"""Wide&Deep recommender — the per-key online-training workload
(BASELINE.json:10: "keyed stream, per-key SGD step").

Wide part: a linear model over (pre-crossed) sparse features, delivered as
a multi-hot float vector.  Deep part: hashed categorical ids -> shared
embedding table -> MLP over [embeddings ++ dense features].  Binary logit
= wide + deep (Cheng et al. 2016).

Online SGD runs as a keyed stream operator whose state IS the params
pytree (SURVEY.md §3.4: the reference keeps variables inside the TF
session; here they are explicit operator state, so checkpoint barriers
snapshot them natively — SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from flink_tensorflow_tpu.models.base import ModelMethod
from flink_tensorflow_tpu.models.zoo.registry import ModelDef, register_model_def
from flink_tensorflow_tpu.tensors.schema import RecordSchema, spec


class WideDeep(nn.Module):
    hash_buckets: int = 100_000
    embed_dim: int = 32
    num_cat_slots: int = 8
    num_dense: int = 13
    num_wide: int = 64
    hidden: tuple = (256, 128, 64)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, wide, dense, cat):
        # Wide: linear over crossed features (float32 — it's one dot).
        wide_logit = nn.Dense(1, dtype=jnp.float32, name="wide")(wide)[..., 0]
        # Deep: shared hashed embedding table + MLP.
        emb = nn.Embed(self.hash_buckets, self.embed_dim,
                       dtype=self.compute_dtype, name="embed")(cat)
        x = jnp.concatenate(
            [emb.reshape((emb.shape[0], -1)), dense.astype(self.compute_dtype)], axis=-1
        )
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.compute_dtype)(x))
        deep_logit = nn.Dense(1, dtype=jnp.float32)(x)[..., 0]
        return wide_logit + deep_logit


@register_model_def("widedeep")
def build(hash_buckets: int = 100_000, embed_dim: int = 32, num_cat_slots: int = 8,
          num_dense: int = 13, num_wide: int = 64, hidden=(256, 128, 64)) -> ModelDef:
    module = WideDeep(hash_buckets=hash_buckets, embed_dim=embed_dim,
                      num_cat_slots=num_cat_slots, num_dense=num_dense,
                      num_wide=num_wide, hidden=tuple(hidden))
    schema = RecordSchema({
        "wide": spec((num_wide,), np.float32),
        "dense": spec((num_dense,), np.float32),
        "cat": spec((num_cat_slots,), np.int32),
    })

    def serve(variables, inputs):
        logit = module.apply(variables, inputs["wide"], inputs["dense"], inputs["cat"])
        return {"logit": logit, "prob": jax.nn.sigmoid(logit)}

    def init_fn(rng):
        return module.init(
            rng,
            jnp.zeros((1, num_wide)),
            jnp.zeros((1, num_dense)),
            jnp.zeros((1, num_cat_slots), jnp.int32),
        )

    def loss_fn(variables, batch, rng):
        import optax

        from flink_tensorflow_tpu.models.zoo._common import weighted_metrics

        logit = module.apply(variables, batch["wide"], batch["dense"], batch["cat"])
        label = batch["label"].astype(jnp.float32)
        per_ex = optax.sigmoid_binary_cross_entropy(logit, label)
        hits = ((logit > 0) == (label > 0.5)).astype(jnp.float32)
        loss, acc = weighted_metrics(per_ex, hits, batch.get("valid"))
        return loss, ({}, {"loss": loss, "accuracy": acc})

    methods = {
        "serve": ModelMethod(
            name="serve",
            input_schema=schema,
            output_names=("logit", "prob"),
            fn=serve,
            compute_dtype=jnp.bfloat16,
        )
    }
    return ModelDef(
        architecture="widedeep",
        config={"hash_buckets": hash_buckets, "embed_dim": embed_dim,
                "num_cat_slots": num_cat_slots, "num_dense": num_dense,
                "num_wide": num_wide, "hidden": list(hidden)},
        module=module,
        input_schema=schema,
        methods=methods,
        init_fn=init_fn,
        loss_fn=loss_fn,
    )
