"""Local multi-subtask executor — the TaskManager equivalent.

The reference runs on Flink's JobManager/TaskManager cluster (SURVEY.md §1
L1); jobs are threads-in-one-process here, one thread per operator CHAIN
(the reference's "task slot" after Flink's operator chaining).  Threads,
not asyncio, because the hot path blocks in XLA device execution which
releases the GIL — a subtask spending its time inside ``jax.jit``-compiled
calls runs truly parallel to the others.

Operator chaining (analysis/chaining.py): forward-partitioned,
same-parallelism neighbors fuse into one subtask and records pass between
them by direct method call through :class:`ChainedOutput` — no queue, no
serialization, no thread wakeup.  Barriers snapshot each chained operator
in stream order before moving on, watermarks traverse the operators' own
``process_watermark`` hooks, and every logical operator keeps its own
metric scope, so exactly-once semantics and per-operator observability
are untouched by fusion.

The record plane between chains is event-driven end to end: the worker
loop blocks on its input gate until a put / wake / close or the chain's
earliest operator deadline — there is no timed idle poll (the 50 ms
``_IDLE_POLL_S`` of BENCH_r05's latency floor is gone).

The mapping to TPU topology (SURVEY.md §7 step 4): subtask index -> local
chip for operator-DP inference; gang operators instead share one
``jax.sharding.Mesh`` spanning all chips (DP training).  Multi-host
execution re-uses this executor per host with jax.distributed providing the
global mesh (see flink_tensorflow_tpu.parallel.multihost).
"""

from __future__ import annotations

import logging
import threading
import time
import typing

from flink_tensorflow_tpu.core import elements as el
from flink_tensorflow_tpu.core.channels import ChannelWriter, InputGate
from flink_tensorflow_tpu.core.graph import CycleError, DataflowGraph, Transformation
from flink_tensorflow_tpu.core.operators import (
    Operator,
    Output,
    SourceOperator,
    SubtaskStats,
)
from flink_tensorflow_tpu.core.partitioning import ForwardPartitioner
from flink_tensorflow_tpu.core.runtime_context import RuntimeContext
from flink_tensorflow_tpu.core.state import KeyedStateStore
from flink_tensorflow_tpu.metrics.registry import MetricRegistry

logger = logging.getLogger(__name__)


class JobFailure(RuntimeError):
    pass


class JobTimeout(JobFailure):
    """join() deadline expired — NOT an operator failure; restart
    strategies must propagate it instead of replaying a healthy job."""


class _ChainedUnit:
    """One logical operator inside a chain's subtask.

    Each unit keeps its own metric scope (records in/out, latency) and
    its own checkpoint identity ``(t.name, index)`` — the inspector and
    the snapshot store see per-operator numbers whether or not the
    operator shares a thread with its neighbors."""

    __slots__ = ("t", "index", "operator", "output", "records_in", "latency")

    def __init__(self, t: Transformation, index: int, operator: Operator):
        self.t = t
        self.index = index
        self.operator = operator
        self.output: typing.Optional[typing.Any] = None
        self.records_in = None   # Meter
        self.latency = None      # Timer

    @property
    def scope(self) -> str:
        return f"{self.t.name}.{self.index}"


class ChainedOutput:
    """Output of a non-tail chained operator: invokes the next operator
    in the chain directly on the same thread — the queue-free hop.

    - records: ``emit`` wraps the value and calls the downstream
      operator's ``process`` inline; per-operator meters/timers still
      tick (latency is INCLUSIVE of the downstream's own chained
      emissions — the chain runs synchronously, like Flink's).
    - barriers: the downstream operator snapshots and acks BEFORE the
      barrier moves further down the chain — everything it processed
      precedes the barrier by construction (synchronous direct calls),
      so aligned exactly-once semantics are byte-identical to the
      channel path.
    - watermarks traverse ``process_watermark`` (operators flush
      event-time state, then forward on their own output).
    - end-of-partition runs the downstream ``finish()`` flush, then
      forwards — the tail's real Output broadcasts to the next chains.
    """

    __slots__ = ("_subtask", "_unit", "_records_out", "_tracer",
                 "_accepts_device")

    def __init__(self, subtask: "_Subtask", unit: _ChainedUnit, records_out,
                 tracer=None, accepts_device: bool = False):
        self._subtask = subtask
        self._unit = unit
        self._records_out = records_out  # upstream operator's out-meter
        self._tracer = tracer
        #: Whether the downstream chained operator consumes DeviceBatch
        #: records directly (device-resident handoff).  False = this hop
        #: is a host boundary: a device batch materializes here (the
        #: deferred d2h forces exactly once) and fans out per record.
        self._accepts_device = accepts_device

    def emit(self, value: typing.Any, timestamp: typing.Optional[float] = None) -> None:
        unit = self._unit
        n = 1
        if getattr(value, "is_device_batch", False):
            if not self._accepts_device:
                ts = timestamp if timestamp is not None else value.timestamp
                for tv in value.materialize():
                    self.emit(tv, ts)
                return
            n = value.num_records  # meters stay per-RECORD under fusion
        t0 = time.monotonic()
        unit.operator.process_record_from(0, el.StreamRecord(value, timestamp))
        t1 = time.monotonic()
        unit.latency.update(t1 - t0)
        unit.records_in.mark(n)
        if self._records_out is not None:
            self._records_out.mark(n)
        tracer = self._tracer
        if tracer is not None:
            tctx = tracer.current()
            if tctx is not None:
                # The chained hop's processing span: inclusive of the
                # member's own downstream emissions, like its latency
                # timer (the chain runs synchronously).
                tracer.span(unit.scope, "process", t0, t1,
                            args={"trace": tctx.trace_id})

    def broadcast_element(self, element: el.StreamElement) -> None:
        unit = self._unit
        if isinstance(element, el.Watermark):
            unit.operator.process_watermark(element)
        elif isinstance(element, el.CheckpointBarrier):
            self._subtask.snapshot_unit(unit, element.checkpoint_id)
            unit.output.broadcast_element(element)
        elif isinstance(element, el.EndOfPartition):
            unit.operator.finish()
            unit.output.broadcast_element(element)
        else:  # pragma: no cover - no other control elements exist
            unit.output.broadcast_element(element)

    @property
    def has_downstream(self) -> bool:
        return True


class _Subtask:
    """One executor thread: a chain of operators sharing one input gate.

    ``chain``/``operators`` hold the fused members head-first; a
    degenerate single-member chain is exactly the pre-chaining subtask.
    Head-centric attributes (``t``, ``operator``, ``output``) refer to
    the chain head — the thread body reads the gate for the head and the
    chain propagates everything else by direct call.
    """

    def __init__(
        self,
        executor: "LocalExecutor",
        chain: typing.Sequence[Transformation],
        index: int,
        operators: typing.Sequence[Operator],
        gate: typing.Optional[InputGate],
        num_input_channels: int,
        edge_of_channel: typing.Optional[typing.List[int]] = None,
    ):
        self.executor = executor
        self.units = [
            _ChainedUnit(t, index, op) for t, op in zip(chain, operators)
        ]
        self.t = chain[0]
        self.index = index
        self.operator = operators[0]
        self.gate = gate
        self.num_input_channels = num_input_channels
        #: channel index -> logical input (edge) index, for two-input
        #: operators (connect/join).
        self.edge_of_channel = edge_of_channel or [0] * num_input_channels
        self.control: "typing.List[int]" = []  # pending checkpoint ids (sources)
        self._control_lock = threading.Lock()
        #: Aborted-checkpoint ids awaiting delivery to this subtask's
        #: thread (coordinator deadline sweeps — see notify_checkpoint_
        #: aborted) and the set already processed: late barriers for an
        #: aborted id are swallowed instead of starting a new alignment
        #: that could never complete.
        self._aborts: "typing.List[int]" = []
        self._aborted_cids: typing.Set[int] = set()
        #: Checkpoint ids this SPLIT-source subtask already cut its
        #: stream at.  A barrier can now reach the reader on three
        #: paths — control drain (trigger), count-based position, and
        #: the freeze-deadlock guard below — and racing paths must not
        #: cut (= snapshot + ack) the same id twice.
        self._barriers_cut: typing.Set[int] = set()
        #: sources.mailbox.SourceMailbox for split-source subtasks (set
        #: by _build) — the ONE wait point of run_split_source; barrier
        #: requests and notifications posted here wake the loop.
        self.mailbox = None
        #: Completed-and-durable checkpoint ids awaiting delivery to the
        #: operators on THEIR thread (single-writer contract; Flink mailbox).
        self._notifications: "typing.List[int]" = []
        self.thread: typing.Optional[threading.Thread] = None
        self.finished = threading.Event()
        # -- instrumentation (wired by the executor in _build) -----------
        #: Single-writer accumulators behind this subtask's pull gauges.
        self.stats = SubtaskStats()
        self.records_in = None      # Meter (workers only; head operator)
        self.latency = None         # Timer: per-record processing/emit time
        self.alignment = None       # Timer: barrier-alignment spans

    @property
    def scope(self) -> str:
        return f"{self.t.name}.{self.index}"

    @property
    def output(self):
        """The chain HEAD's output (a ChainedOutput when fused)."""
        return self.units[0].output

    # --- source control -------------------------------------------------
    def request_checkpoint(self, checkpoint_id: int) -> None:
        with self._control_lock:
            self.control.append(checkpoint_id)
        if self.mailbox is not None:
            self.mailbox.notify()

    def _drain_control(self) -> typing.List[int]:
        with self._control_lock:
            pending, self.control = self.control, []
        return pending

    def add_notification(self, checkpoint_id: int) -> None:
        with self._control_lock:
            self._notifications.append(checkpoint_id)
        if self.mailbox is not None:
            self.mailbox.notify()

    def add_abort(self, checkpoint_id: int) -> None:
        """A checkpoint missed its deadline: deliver the abort to this
        subtask's thread (it drops the id's alignment state and swallows
        its late barriers)."""
        with self._control_lock:
            self._aborts.append(checkpoint_id)
        if self.mailbox is not None:
            self.mailbox.notify()
        elif self.gate is not None:
            self.gate.wake()

    def _drain_aborts(self) -> typing.List[int]:
        with self._control_lock:
            if not self._aborts:
                return []
            pending, self._aborts = self._aborts, []
        self._aborted_cids.update(pending)
        return pending

    def _deliver_notifications(self) -> None:
        with self._control_lock:
            pending, self._notifications = self._notifications, []
        for cid in pending:
            for unit in self.units:
                unit.operator.notify_checkpoint_complete(cid)

    # --- chain helpers ----------------------------------------------------
    def _open_chain(self) -> None:
        """Open tail-to-head so every operator's downstream is live
        before its first record (Flink's chain open order)."""
        for unit in reversed(self.units):
            unit.operator.open()

    def _close_chain(self) -> None:
        for unit in self.units:
            unit.operator.close()

    def _chain_next_deadline(self) -> typing.Optional[float]:
        deadlines = [
            d for d in (u.operator.next_deadline() for u in self.units)
            if d is not None
        ]
        return min(deadlines) if deadlines else None

    def _chain_fire_due(self, now: float) -> None:
        for unit in self.units:
            d = unit.operator.next_deadline()
            if d is not None and now >= d:
                unit.operator.fire_due(now)

    def snapshot_unit(self, unit: _ChainedUnit, checkpoint_id: typing.Optional[int]) -> None:
        """Snapshot + ack ONE chained logical operator (called by
        ChainedOutput as the barrier traverses the chain in order)."""
        san = self.executor.sanitizer
        if san is not None and checkpoint_id is not None:
            # Independent snapshot-order state machine: within this
            # subtask, checkpoint k must snapshot the chain head-to-tail
            # with no gaps (snapshot order == stream order).
            san.chain_snapshot(self.scope, checkpoint_id,
                               self.units.index(unit), len(self.units))
        tracer = self.executor.tracer
        t0 = time.monotonic() if tracer is not None else 0.0
        snapshot = unit.operator.snapshot(checkpoint_id)
        self.executor.coordinator.ack(
            checkpoint_id, unit.t.name, unit.index, snapshot)
        if tracer is not None:
            tracer.span(unit.scope, "snapshot", t0, time.monotonic(),
                        args={"checkpoint": checkpoint_id})
        flight = self.executor.flight
        if flight is not None:
            flight.record(unit.scope, "snapshot",
                          {"checkpoint": checkpoint_id})

    # --- thread bodies ---------------------------------------------------
    def _source_barrier(self, checkpoint_id: int) -> None:
        """Cut a legacy source's stream at a barrier: snapshot + broadcast
        (with a trace instant marking the injection point when traced)."""
        if checkpoint_id in self._aborted_cids:
            return  # deadline-swept checkpoint: do not cut, do not ack
        tracer = self.executor.tracer
        if tracer is not None:
            tracer.instant(self.scope, "barrier.inject",
                           args={"checkpoint": checkpoint_id})
        flight = self.executor.flight
        if flight is not None:
            flight.record(self.scope, "barrier.inject",
                          {"checkpoint": checkpoint_id})
        san = self.executor.sanitizer
        if san is not None:
            san.hb("barrier.inject", self.scope, cid=checkpoint_id)
        self._snapshot_and_ack(checkpoint_id)
        self.output.broadcast_element(el.CheckpointBarrier(checkpoint_id))

    def run_source(self) -> None:
        op = typing.cast(SourceOperator, self.operator)
        try:
            self._open_chain()
            throttle = self.executor.source_throttle_s
            every_n = self.executor.checkpoint_every_n
            tracer = self.executor.tracer
            faults = self.executor.faults
            for value in op.iterate():
                if self.executor.cancelled.is_set():
                    break
                self._deliver_notifications()
                self._drain_aborts()
                for cid in self._drain_control():
                    self._source_barrier(cid)
                if isinstance(value, el.SourceIdle):
                    continue  # idle heartbeat: barriers served, no record
                if tracer is not None:
                    # Head-based admission: the ONE sampling decision for
                    # this record's whole trace is made here.
                    tracer.set_current(tracer.admit(self.scope, value))
                t_emit = time.monotonic()
                self.output.emit(value)
                op.record_emitted()
                if faults is not None:
                    faults.record_point(self.scope, op.offset)
                t_done = time.monotonic()
                # Per-record emit latency: dominated by blocked-put time
                # when downstream backpressures (the source-side signal);
                # for a chained source it covers the fused operators'
                # inline processing.
                self.latency.update(t_done - t_emit)
                if tracer is not None:
                    tctx = tracer.current()
                    if tctx is not None:
                        tracer.span(self.scope, "emit", t_emit, t_done,
                                    args={"trace": tctx.trace_id})
                        tracer.set_current(None)
                # Count-based barriers: checkpoint k cuts the stream after
                # this subtask's k*N-th record — a deterministic position,
                # identical on every host running the same job (the
                # multi-host consistency contract; see CheckpointCoordinator).
                if every_n and op.offset % every_n == 0:
                    cid = op.offset // every_n
                    if self.executor.coordinator.begin_source_checkpoint(cid):
                        self._source_barrier(cid)
                if throttle:
                    time.sleep(throttle)
            # Serve any barrier requests that raced with the last records.
            for cid in self._drain_control():
                self._source_barrier(cid)
            op.finish()
            self.output.broadcast_element(el.EndOfPartition())
            self._close_chain()
        except BaseException as exc:  # noqa: BLE001
            self.executor.fail(self, exc)
        finally:
            self.finished.set()
            self.executor.subtask_finished(self)

    def _split_barrier(self, checkpoint_id: int) -> None:
        """Cut this reader's stream at a barrier: register with the
        split coordinator FIRST (freezing assignment and, for reader 0,
        staging the consistent enumerator-pool snapshot), then snapshot
        this subtask and push the barrier down the chain.  Idempotent
        per id: the same checkpoint may be requested via trigger
        control, reached count-based, AND served by the freeze-deadlock
        guard — only the first cut snapshots and acks."""
        if checkpoint_id in self._barriers_cut or checkpoint_id in self._aborted_cids:
            return
        self._barriers_cut.add(checkpoint_id)
        tracer = self.executor.tracer
        if tracer is not None:
            tracer.instant(self.scope, "barrier.inject",
                           args={"checkpoint": checkpoint_id})
        flight = self.executor.flight
        if flight is not None:
            flight.record(self.scope, "barrier.inject",
                          {"checkpoint": checkpoint_id})
        san = self.executor.sanitizer
        if san is not None:
            san.hb("barrier.inject", self.scope, cid=checkpoint_id)
        op = typing.cast("typing.Any", self.operator)
        op.on_barrier(checkpoint_id)
        self._snapshot_and_ack(checkpoint_id)
        self.output.broadcast_element(el.CheckpointBarrier(checkpoint_id))

    def run_split_source(self) -> None:
        """Mailbox event loop for a split-based source (FLIP-27 model).

        Unlike ``run_source`` — which blocks wherever the user generator
        blocks — this loop owns ALL waiting: every iteration serves
        durable-checkpoint notifications, pending barriers, and chained
        operators' due timers, then asks the operator for one
        non-blocking step (emit a record / park until ``due`` / done).
        Parking happens exclusively on the subtask MAILBOX, bounded by
        the earliest of the next record's due time and the chain's
        earliest operator deadline, and is woken early by barrier
        requests, split availability, notifications, ``ctx.wakeup``, and
        cancellation.  This wakeable wait is why the chaining pass lets
        timer-driven operators fuse into split-source chains.
        """
        from flink_tensorflow_tpu.sources.operator import DONE, RECORD

        op = typing.cast("typing.Any", self.operator)
        executor = self.executor
        stats = self.stats
        try:
            self._open_chain()
            throttle = executor.source_throttle_s
            every_n = executor.checkpoint_every_n
            tracer = executor.tracer
            faults = executor.faults
            while not executor.cancelled.is_set():
                self._deliver_notifications()
                self._drain_aborts()
                for cid in self._drain_control():
                    self._split_barrier(cid)
                now = time.monotonic()
                deadline = self._chain_next_deadline()
                if deadline is not None and now >= deadline:
                    self._chain_fire_due(now)
                    deadline = self._chain_next_deadline()
                kind, payload = op.poll_next()
                if kind == RECORD:
                    if tracer is not None:
                        tracer.set_current(tracer.admit(self.scope, payload))
                    t_emit = time.monotonic()
                    self.output.emit(payload)
                    op.record_emitted()
                    if faults is not None:
                        faults.record_point(self.scope, op.offset)
                    t_done = time.monotonic()
                    self.latency.update(t_done - t_emit)
                    if tracer is not None:
                        tctx = tracer.current()
                        if tctx is not None:
                            tracer.span(self.scope, "emit", t_emit, t_done,
                                        args={"trace": tctx.trace_id})
                            tracer.set_current(None)
                    # Count-based barriers at deterministic PER-SUBTASK
                    # positions (CheckpointCoordinator's every_n mode).
                    if every_n and op.offset % every_n == 0:
                        cid = op.offset // every_n
                        if executor.coordinator.begin_source_checkpoint(cid):
                            self._split_barrier(cid)
                    if throttle:
                        time.sleep(throttle)
                    continue
                if kind == DONE:
                    break
                # Freeze-deadlock guard: a reader parked split-less on a
                # frozen assignment emits no records, so with count-based
                # triggers it would NEVER reach the position that makes
                # it cut the pending barrier — the alignment waits on
                # this reader and this reader on the alignment's freeze.
                # Cut the stream for every pending alignment here, at
                # the wait point (positions are per-run for split
                # sources anyway; sources/operator.py docstring), then
                # re-poll: completing the alignment may unfreeze splits.
                served = False
                for cid in op.pending_alignments():
                    self._split_barrier(cid)
                    served = True
                if served:
                    continue
                # WAIT: nothing to do until `payload` (a record's due
                # time, or None = until an event) / the chain's earliest
                # timer — park on the mailbox, charging idle time.
                due = payload
                now = time.monotonic()
                timeout = None
                for target in (due, deadline):
                    if target is not None:
                        t = max(0.0, target - now)
                        timeout = t if timeout is None else min(timeout, t)
                t0 = now
                self.mailbox.wait(timeout)
                stats.idle_s += time.monotonic() - t0
            # Serve barrier requests that raced with the last records.
            for cid in self._drain_control():
                self._split_barrier(cid)
            if not executor.cancelled.is_set():
                op.finish()
                self.output.broadcast_element(el.EndOfPartition())
            self._close_chain()
        except BaseException as exc:  # noqa: BLE001
            executor.fail(self, exc)
        finally:
            self.finished.set()
            self.executor.subtask_finished(self)

    def run_worker(self) -> None:
        op = self.operator
        gate = self.gate
        n = self.num_input_channels
        eop = [False] * n
        barrier_seen: typing.Dict[int, typing.Set[int]] = {}
        #: checkpoint id -> monotonic time its FIRST barrier arrived here
        #: (alignment span = first barrier -> snapshot).
        barrier_t0: typing.Dict[int, float] = {}
        watermarks = [float("-inf")] * n
        current_wm = float("-inf")
        stats = self.stats
        records_in = self.records_in
        latency = self.latency
        tracer = self.executor.tracer
        faults = self.executor.faults
        processed = 0
        try:
            self._open_chain()
            active = n
            while active > 0 and not self.executor.cancelled.is_set():
                deadline = self._chain_next_deadline()
                now = time.monotonic()
                # Event-driven wait: block until a put/wake/close or the
                # chain's earliest operator deadline — no idle poll
                # quantum (the gate's condition variable replaces the
                # former 50 ms _IDLE_POLL_S re-poll).
                timeout = None if deadline is None else max(0.0, deadline - now)
                poll_start = now
                item = gate.poll(timeout=timeout)
                self._deliver_notifications()
                for cid in self._drain_aborts():
                    # Deadline-swept checkpoint: drop its alignment (a
                    # barrier that never arrives must not wedge the gate
                    # behind blocked channels forever); its stashed
                    # records replay in order.
                    if cid in barrier_seen:
                        del barrier_seen[cid]
                        barrier_t0.pop(cid, None)
                        gate.unblock_all()
                now = time.monotonic()
                if item is None:
                    # Nothing to process: the poll wait was idle time
                    # (with data the dequeue returns ~immediately, so
                    # only empty polls are charged — no extra clock read
                    # either way).
                    stats.idle_s += now - poll_start
                if deadline is not None and now >= deadline:
                    self._chain_fire_due(now)
                if item is None:
                    continue
                idx, element = item
                if isinstance(element, el.StreamRecord):
                    processed += 1
                    if faults is not None:
                        faults.record_point(self.scope, processed)
                    if tracer is None:
                        op.process_record_from(self.edge_of_channel[idx], element)
                        latency.update(time.monotonic() - now)
                    else:
                        tctx = element.trace
                        if tctx is not None:
                            # Queue-wait span (enqueue -> this delivery)
                            # + thread-local continuity for the chain's
                            # downstream emissions.
                            tracer.queue_span(self.scope, tctx, now)
                            tracer.set_current(tctx)
                        op.process_record_from(self.edge_of_channel[idx], element)
                        t1 = time.monotonic()
                        latency.update(t1 - now)
                        if tctx is not None:
                            tracer.span(self.scope, "process", now, t1,
                                        args={"trace": tctx.trace_id})
                            tracer.set_current(None)
                    records_in.mark()
                elif isinstance(element, el.CheckpointBarrier):
                    cid = element.checkpoint_id
                    if cid in self._aborted_cids:
                        # Late barrier of a deadline-swept checkpoint:
                        # swallow it — neither blocking (the alignment
                        # could never complete) nor forwarding (every
                        # downstream received the same abort).
                        continue
                    seen = barrier_seen.setdefault(cid, set())
                    if not seen:
                        barrier_t0[cid] = now
                    seen.add(idx)
                    gate.block_channel(idx)
                    live = {i for i in range(n) if not eop[i]}
                    if live <= seen:
                        t_align = barrier_t0.pop(cid, now)
                        self.alignment.update(now - t_align)
                        if tracer is not None:
                            tracer.span(self.scope, "align", t_align, now,
                                        args={"checkpoint": cid})
                        self._snapshot_and_ack(cid)
                        self.output.broadcast_element(element)
                        del barrier_seen[cid]
                        gate.unblock_all()
                elif isinstance(element, el.Watermark):
                    watermarks[idx] = element.timestamp
                    new_wm = min(
                        watermarks[i] for i in range(n) if not eop[i]
                    )
                    if new_wm > current_wm:
                        current_wm = new_wm
                        if tracer is not None:
                            tracer.instant(self.scope, "watermark", ts=now,
                                           args={"timestamp": current_wm})
                        op.process_watermark(el.Watermark(current_wm))
                elif isinstance(element, el.EndOfPartition):
                    eop[idx] = True
                    active -= 1
                    # A finished channel counts as barriered for all pending
                    # alignments (it can never deliver its barrier).
                    for cid, seen in list(barrier_seen.items()):
                        live = {i for i in range(n) if not eop[i]}
                        if live and live <= seen:
                            t_align = barrier_t0.pop(cid, now)
                            self.alignment.update(now - t_align)
                            if tracer is not None:
                                tracer.span(self.scope, "align", t_align, now,
                                            args={"checkpoint": cid})
                            self._snapshot_and_ack(cid)
                            self.output.broadcast_element(el.CheckpointBarrier(cid))
                            del barrier_seen[cid]
                            gate.unblock_all()
                    # A finished channel no longer holds the combined
                    # watermark back (Flink: finished inputs count as
                    # MAX_WATERMARK) — recompute over the live channels.
                    if active > 0:
                        new_wm = min(
                            watermarks[i] for i in range(n) if not eop[i]
                        )
                        if new_wm > current_wm:
                            current_wm = new_wm
                            op.process_watermark(el.Watermark(current_wm))
            if not self.executor.cancelled.is_set():
                op.finish()
                self.output.broadcast_element(el.EndOfPartition())
            self._close_chain()
        except BaseException as exc:  # noqa: BLE001
            self.executor.fail(self, exc)
        finally:
            self.finished.set()
            self.executor.subtask_finished(self)

    def _snapshot_and_ack(self, checkpoint_id: int) -> None:
        self.snapshot_unit(self.units[0], checkpoint_id)


class LocalExecutor:
    """Builds the physical plan from a DataflowGraph and runs it."""

    def __init__(
        self,
        graph: DataflowGraph,
        *,
        channel_capacity: int = 1024,
        metric_registry: typing.Optional[MetricRegistry] = None,
        device_provider: typing.Optional[typing.Callable[[str, int], typing.Any]] = None,
        mesh: typing.Optional[typing.Any] = None,
        job_config: typing.Optional[dict] = None,
        source_throttle_s: float = 0.0,
        checkpoint_dir: typing.Optional[str] = None,
        checkpoint_every_n: typing.Optional[int] = None,
        checkpoint_timeout_s: float = 60.0,
        checkpoint_retain_last: typing.Optional[int] = None,
        max_parallelism: int = 128,
        chaining: bool = True,
        sanitize: bool = False,
        sanitize_log_path: typing.Optional[str] = None,
        trace: bool = False,
        trace_path: typing.Optional[str] = None,
        trace_sample_rate: float = 1.0,
        flight_recorder: bool = True,
        flight_path: typing.Optional[str] = None,
        device_resident: bool = False,
        wire_dtype: typing.Optional[str] = None,
        wire_flush_bytes: typing.Optional[int] = None,
        wire_flush_ms: typing.Optional[float] = None,
        shm_channels: bool = True,
        flow_control: bool = True,
        faults: typing.Optional[typing.Any] = None,
        restart_epoch: int = 0,
        roofline: typing.Optional[typing.Any] = None,
    ):
        from flink_tensorflow_tpu import tracing
        from flink_tensorflow_tpu.core import sanitizer_rt
        from flink_tensorflow_tpu.core.checkpoint import CheckpointCoordinator
        from flink_tensorflow_tpu.tensors.transfer import (
            env_device_resident,
            env_wire_dtype,
        )

        self.graph = graph
        #: Device-resident dataflow (tensors/transfer.DeviceBatch):
        #: chains of device-capable operators hand HBM-resident batches
        #: between fused members, eliding the d2h/h2d pair per hop; the
        #: first host-only consumer forces the fetch exactly once.
        #: JobConfig.device_resident or FLINK_TPU_DEVICE_RESIDENT=1.
        self.device_resident = device_resident or env_device_resident()
        #: Job-wide compact wire dtype (h2d + remote frames); model
        #: functions/remote sinks default to it at open().
        #: JobConfig.wire_dtype or FLINK_TPU_WIRE_DTYPE.
        self.wire_dtype = wire_dtype if wire_dtype is not None else env_wire_dtype()
        if self.wire_dtype == "f32":
            self.wire_dtype = None
        #: Remote-plane coalescing knobs (JobConfig.wire_flush_bytes /
        #: wire_flush_ms; FLINK_TPU_WIRE_FLUSH_* take precedence inside
        #: the writers) and the same-host shm upgrade.  A LocalExecutor
        #: has no remote edges — these only feed RemoteSink defaults via
        #: the RuntimeContext and the DistributedExecutor's writers.
        self.wire_flush_bytes = wire_flush_bytes
        self.wire_flush_ms = wire_flush_ms
        from flink_tensorflow_tpu.core.shuffle import (
            env_flow_control_enabled,
            env_shm_enabled,
        )

        env_shm = env_shm_enabled()
        self.shm_channels = shm_channels if env_shm is None else env_shm
        #: Credit-based flow control on the cross-process record plane
        #: (JobConfig.flow_control; FLINK_TPU_FLOW_CONTROL overrides).
        #: A LocalExecutor has no remote edges — this only feeds the
        #: DistributedExecutor's writers and RemoteSink defaults.
        env_fc = env_flow_control_enabled()
        self.flow_control = flow_control if env_fc is None else env_fc
        #: Debug-mode concurrency sanitizer (core/sanitizer_rt):
        #: JobConfig.sanitize=True or FLINK_TPU_SANITIZE=1 instruments
        #: every gate/mailbox/coordinator lock and asserts the barrier
        #: protocol invariants; None (the default) leaves the runtime's
        #: production no-op path — plain threading primitives, one
        #: is-None test per hook site.
        self.sanitizer = (
            sanitizer_rt.ConcurrencySanitizer(name="executor")
            if (sanitize or sanitizer_rt.env_enabled()) else None
        )
        #: Happens-before event-log destination (core/sanitizer_stitch
        #: input): JobConfig.sanitize_log_path or FLINK_TPU_SANITIZE_LOG.
        #: Kept even when the sanitizer is off so the distributed layer
        #: can test it unconditionally; no sanitizer → no dump.
        self.sanitize_log_path = (
            sanitize_log_path or sanitizer_rt.env_hb_log_path())
        self.channel_capacity = channel_capacity
        self.metrics = metric_registry or MetricRegistry()
        #: Span tracer (flink_tensorflow_tpu.tracing): JobConfig.trace
        #: or FLINK_TPU_TRACE=1 turns on per-record/per-batch span
        #: recording across sources, chains, channels, the model
        #: runner's h2d/compute/d2h stages, checkpoints, splits and
        #: remote edges; None (the default) keeps the production no-op
        #: path — one is-None test per hook site, zero allocation.
        if trace or tracing.env_enabled():
            self.tracer = tracing.Tracer(
                sample_rate=tracing.env_sample_rate() or trace_sample_rate,
                seed=self.metrics.seed,
            )
        else:
            self.tracer = None
        #: Chrome-trace export destination: written by JobHandle.wait
        #: when the job finishes OR fails (the crash trace is the one
        #: that matters).  None keeps spans in memory (CLI path).
        self.trace_path = trace_path or tracing.env_trace_path()
        #: Flight recorder (tracing/flight.py): the always-on black box —
        #: a bounded ring of control-rate lifecycle/checkpoint/metric-
        #: delta events, dumped to ``flight_path`` on crash, sanitizer
        #: violation, signal, or cancel.  ``flight_recorder=False`` /
        #: FLINK_TPU_FLIGHT=0 is the zero-alloc off path (tier-1
        #: guarded); the ring runs regardless of whether a dump path is
        #: configured.
        from flink_tensorflow_tpu.tracing import flight as flight_mod

        env_flight = flight_mod.env_enabled()
        flight_on = flight_recorder if env_flight is None else env_flight
        self.flight = flight_mod.FlightRecorder() if flight_on else None
        self.flight_path = flight_path or flight_mod.env_flight_path()
        if self.sanitizer is not None and self.tracer is not None:
            # Satellite wiring: sanitizer findings (stall dumps with
            # thread stacks + lock ownership, protocol violations) land
            # as instants on the trace timeline, next to the spans the
            # hang interrupted.
            self.sanitizer.tracer = self.tracer
        #: Zero-arg hooks fired once, at the FIRST subtask failure —
        #: the reporter thread flushes a crash-time snapshot here so the
        #: metrics that explain the failure are published even if the
        #: caller never joins.
        self.failure_listeners: typing.List[typing.Callable[[], None]] = []
        #: Which restart attempt of the job this executor runs (0 = the
        #: first): the fault plan keys its schedule on it, remote-plane
        #: handshakes carry it as the fencing epoch, and the flight
        #: recorder stamps it on lifecycle events.
        self.restart_epoch = restart_epoch
        #: Chaos plane (core/faults.py): a deterministic fault schedule
        #: armed for THIS restart epoch — JobConfig.faults or
        #: FLINK_TPU_FAULTS.  None (the default) keeps the production
        #: path at one is-None test per hook site.
        from flink_tensorflow_tpu.core.faults import FaultInjector, FaultPlan

        injector = None
        plan = FaultPlan.resolve(faults)
        if plan is not None and plan.specs:
            injector = FaultInjector(plan, epoch=restart_epoch,
                                     metrics=self.metrics, flight=self.flight)
            if not injector.active:
                # Nothing armed for THIS epoch (e.g. the restarted run
                # of an epoch-0 schedule): drop back to the zero-cost
                # no-op path.
                injector = None
        self.faults = injector
        #: Roofline attribution plane (metrics/roofline.py):
        #: JobConfig.roofline declares the DeviceSpec peak and carries
        #: the plan's CostTable; model runners mint per-operator probes
        #: off ``ctx.roofline`` and publish ``roofline.*`` gauges +
        #: compile events.  None (the default) keeps the production path
        #: at one is-None test per runner.
        self.roofline = None
        if roofline is not None:
            from flink_tensorflow_tpu.metrics.roofline import RooflinePlane

            self.roofline = RooflinePlane(
                roofline, flight=self.flight, tracer=self.tracer)
        self.device_provider = device_provider
        self.mesh = mesh
        self.job_config = job_config or {}
        self.source_throttle_s = source_throttle_s
        self.checkpoint_every_n = checkpoint_every_n
        self.checkpoint_timeout_s = checkpoint_timeout_s
        self.checkpoint_retain_last = checkpoint_retain_last
        self.max_parallelism = max_parallelism
        self.chaining = chaining
        self.cancelled = threading.Event()
        self._error: typing.Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self.subtasks: typing.List[_Subtask] = []
        self._gates: typing.List[InputGate] = []
        #: One split coordinator per split-source transformation (the
        #: FLIP-27 enumerator host) — shared by that source's readers.
        self._split_coordinators: typing.Dict[str, typing.Any] = {}
        self._split_lock = threading.Lock()
        #: The chaining decision (analysis.chaining.ChainPlan) — the
        #: inspector/analysis CLIs print its topology.
        self.chain_plan = None
        self.coordinator = CheckpointCoordinator(self, checkpoint_dir)
        self.checkpoint_interval_s: typing.Optional[float] = None
        self._finished_count = 0
        self._all_done = threading.Event()
        self._periodic_thread: typing.Optional[threading.Thread] = None
        self._build()
        if self.sanitizer is not None:
            # Observability: the sanitizer reports through the same
            # metric plane as everything else (inspector/reporters show
            # violation counts next to the runtime gauges).
            grp = self.metrics.group("sanitizer")
            grp.gauge("violations", lambda: len(self.sanitizer.violations))
            grp.gauge("tracked_ops", lambda: self.sanitizer.progress_ops)
            # Cross-process happens-before log (PR 15): ring occupancy
            # and drop counts ride the cohort telemetry pushes so the
            # stitcher's truncation caveats are visible live.
            cohort = self.metrics.group("sanitizer.cohort")
            cohort.gauge("hb_events", lambda: self.sanitizer.hb_events)
            cohort.gauge("hb_recorded", lambda: self.sanitizer.hb_recorded)
            cohort.gauge("hb_dropped", lambda: self.sanitizer.hb_dropped)
            cohort.gauge("violations",
                         lambda: len(self.sanitizer.violations))

    # --- plan construction ----------------------------------------------
    def _build(self) -> None:
        by_head: typing.Dict[int, typing.List[_Subtask]] = {}
        gates: typing.Dict[typing.Tuple[int, int], InputGate] = {}

        try:
            order = self.graph.topological_order()
        except CycleError:
            logger.error(
                "cannot build the physical plan: the dataflow graph is "
                "cyclic — run the plan analyzer (env.validate_plan() or "
                "`python -m flink_tensorflow_tpu.analysis <pipeline>`) "
                "for full diagnostics"
            )
            raise

        from flink_tensorflow_tpu.analysis.chaining import compute_chains
        from flink_tensorflow_tpu.core.partitioning import HashPartitioner

        for t in order:
            keyed = any(isinstance(e.partitioner, HashPartitioner) for e in t.inputs)
            if keyed and t.parallelism > self.max_parallelism:
                # Non-keyed operators hold no key-partitioned state and
                # may exceed the bound freely (Flink's rule).
                raise ValueError(
                    f"keyed operator {t.name!r} parallelism {t.parallelism} "
                    f"exceeds max_parallelism {self.max_parallelism} — key "
                    "groups would starve the subtasks above the bound; raise "
                    "JobConfig.max_parallelism"
                )

        # The chaining decision is a pure function of the graph, so every
        # process of a distributed cohort computes the identical plan and
        # channel layouts agree cluster-wide.
        plan = compute_chains(self.graph, enabled=self.chaining)
        self.chain_plan = plan
        chain_by_head = {chain[0].id: chain for chain in plan.chains}
        heads = [t for t in order if t.id in chain_by_head]

        # Pass 1: channel layout per chain HEAD (chained edges pass
        # records by direct call and get no channels at all).  Forward
        # edges contribute 1 channel per gate; others contribute the
        # upstream parallelism.
        channel_base: typing.Dict[typing.Tuple[int, int], int] = {}  # (head_id, edge_idx) -> base
        gate_size: typing.Dict[int, int] = {}
        edge_of_channel: typing.Dict[int, typing.List[int]] = {}  # head id -> per-channel edge idx
        for t in heads:
            base = 0
            channel_edges: typing.List[int] = []
            for edge_idx, edge in enumerate(t.inputs):
                channel_base[(t.id, edge_idx)] = base
                if isinstance(edge.partitioner, ForwardPartitioner):
                    if edge.upstream.parallelism != t.parallelism:
                        raise ValueError(
                            f"forward edge {edge.upstream.name}->{t.name} requires equal "
                            f"parallelism ({edge.upstream.parallelism} vs {t.parallelism})"
                        )
                    span = 1
                else:
                    span = edge.upstream.parallelism
                channel_edges.extend([edge_idx] * span)
                base += span
            gate_size[t.id] = base
            edge_of_channel[t.id] = channel_edges

        # Pass 2: instantiate one subtask per chain per parallel index.
        # A distributed executor owns only the subtasks placed on this
        # process (_owns_subtask); the identical graph AND chain plan are
        # built on every process, so channel layout and subtask indices
        # agree cluster-wide.  Chain members share their head's index —
        # chaining requires equal parallelism, so placement is identical.
        for t in heads:
            chain = chain_by_head[t.id]
            subtasks = []
            for i in range(t.parallelism):
                if not self._owns_subtask(t, i):
                    continue
                operators = [member.operator_factory() for member in chain]
                gate = None
                if not t.is_source:
                    gate = InputGate(gate_size[t.id], capacity=self.channel_capacity,
                                     sanitizer=self.sanitizer,
                                     name=f"{t.name}.{i}.gate")
                    gates[(t.id, i)] = gate
                    self._gates.append(gate)
                st = _Subtask(self, chain, i, operators, gate, gate_size[t.id],
                              edge_of_channel[t.id])
                if t.is_source and getattr(operators[0], "is_split_source", False):
                    from flink_tensorflow_tpu.sources.mailbox import SourceMailbox

                    st.mailbox = SourceMailbox(sanitizer=self.sanitizer,
                                               name=f"{t.name}.{i}.mailbox")
                subtasks.append(st)
            by_head[t.id] = subtasks

        # Pass 3: wire outputs.  Only the chain TAIL talks to channels —
        # every cross-chain edge targets another chain's head gate (a
        # non-head member's sole input is its fused edge).  Within the
        # chain, each operator's output is a ChainedOutput invoking the
        # next member directly.
        for t in heads:
            chain = chain_by_head[t.id]
            tail = chain[-1]
            downstream = [
                (d, edge_idx, edge)
                for d in self.graph.transformations
                for edge_idx, edge in enumerate(d.inputs)
                if edge.upstream.id == tail.id
            ]
            for st in by_head[t.id]:
                edges_for_output = []
                for d, edge_idx, edge in downstream:
                    head_d = plan.head_of[d.id]
                    base = channel_base[(head_d.id, edge_idx)]
                    if isinstance(edge.partitioner, ForwardPartitioner):
                        targets = [(st.index, base)]
                    else:
                        targets = [(j, base + st.index) for j in range(d.parallelism)]
                    # A downstream subtask without a local gate lives on a
                    # peer process: the writer becomes a remote channel of
                    # the record plane (records AND barriers flow through
                    # it — alignment spans processes).
                    writers = [
                        ChannelWriter(gates[(head_d.id, j)], ch)
                        if (head_d.id, j) in gates
                        else self._remote_writer(d, j, ch)
                        for j, ch in targets
                    ]
                    # Stateful partitioners (e.g. rebalance round-robin) must
                    # not be shared across upstream subtask threads.
                    import copy

                    edges_for_output.append((copy.deepcopy(edge.partitioner), writers))

                # Tail gets the real channel Output; every earlier member
                # gets a ChainedOutput onto its successor.
                tail_unit = st.units[-1]
                tail_grp = self.metrics.group(tail_unit.scope)
                tail_unit.output = Output(edges_for_output,
                                          meter=tail_grp.meter("records_out"),
                                          stats=st.stats,
                                          tracer=self.tracer)
                for k in range(len(st.units) - 2, -1, -1):
                    unit = st.units[k]
                    nxt = st.units[k + 1]
                    grp_k = self.metrics.group(unit.scope)
                    accepts = getattr(
                        getattr(nxt.operator, "function", None),
                        "accepts_device_batches", False)
                    unit.output = ChainedOutput(
                        st, nxt, grp_k.meter("records_out"),
                        tracer=self.tracer, accepts_device=accepts)
                    if accepts and self.device_resident:
                        # Emission hint: this member's function may keep
                        # its results HBM-resident — the next chained
                        # operator consumes DeviceBatches directly.
                        up_fn = getattr(unit.operator, "function", None)
                        if getattr(up_fn, "device_capable", False):
                            up_fn._device_chain_hint = True

                self._wire_units(st, gates)
        # Register per-edge record-plane gauges after wiring (the gate
        # and channel layout are both final here).
        for t in heads:
            for st in by_head[t.id]:
                self._register_edge_gauges(st, t, channel_base)

    def _wire_units(self, st: _Subtask, gates) -> None:
        """Per-unit instrumentation + RuntimeContext + operator setup."""
        proc_idx, num_procs = self._process_identity()
        head_gate = st.gate
        chain_len = len(st.units)
        for pos, unit in enumerate(st.units):
            grp = self.metrics.group(unit.scope)
            unit.records_in = grp.meter("records_in")
            unit.latency = grp.timer("process_latency_s")
            # Chain-shape gauges: what got fused where (the inspector's
            # chain column and the CI no-queue-traffic guard read these).
            grp.gauge("chain_length", lambda n=chain_len: n)
            grp.gauge("chained_edges", lambda n=chain_len - 1: n)
            grp.gauge("chain_position", lambda p=pos: p)
            if pos == 0:
                st.records_in = unit.records_in
                st.latency = unit.latency
                st.alignment = grp.timer("checkpoint_alignment_s")
                # Pull-based gauges: the hot path only bumps the plain
                # accumulators; evaluation happens at report time.
                stats = st.stats
                latency = unit.latency
                grp.gauge("idle_s", lambda s=stats: s.idle_s)
                grp.gauge("busy_s", lambda tm=latency: tm.total_s)
                grp.gauge("backpressure_s", lambda s=stats: s.blocked_s)
                if head_gate is not None:
                    grp.gauge("queue_depth",
                              lambda g=head_gate: g.depth)
                    grp.gauge("queue_high_watermark",
                              lambda g=head_gate: g.high_watermark)
                    # Time UPSTREAM writers spent blocked putting into
                    # this subtask's gate — "this operator causes the
                    # backpressure above it".
                    grp.gauge("in_backpressure_s",
                              lambda g=head_gate: g.blocked_put_s)
            state = KeyedStateStore()
            device = (
                self.device_provider(unit.t.name, unit.index)
                if self.device_provider else None
            )
            if device is not None:
                from flink_tensorflow_tpu.utils.profiling import (
                    device_memory_stats,
                )

                grp.gauge(
                    "hbm_bytes_in_use",
                    lambda d=device: device_memory_stats(d).get("bytes_in_use"),
                )
            ctx = RuntimeContext(
                task_name=unit.t.name,
                subtask_index=unit.index,
                parallelism=unit.t.parallelism,
                keyed_state=state,
                metric_group=grp,
                device=device,
                mesh=self.mesh,
                job_config=self.job_config,
                process_index=proc_idx,
                num_processes=num_procs,
            )
            # Span tracer hand-off: model runners / remote sinks read
            # ctx.tracer at open() and record their stage spans
            # (h2d/compute/d2h, serde/wire) on this unit's track.
            ctx.tracer = self.tracer
            # Sanitizer hand-off: remote sinks/sources log cross-process
            # happens-before events (frame send/recv, credit grant/spend)
            # through this at open().
            ctx.sanitizer = self.sanitizer
            # Device-residency hand-off: model functions resolve their
            # emission mode / h2d wire dtype from these at open().
            ctx.device_resident = self.device_resident
            ctx.wire_dtype = self.wire_dtype
            # Remote-plane coalescing defaults (RemoteSink reads these
            # at open() when its own knobs are unset).
            ctx.wire_flush_bytes = self.wire_flush_bytes
            ctx.wire_flush_ms = self.wire_flush_ms
            ctx.flow_control = self.flow_control
            # Chaos-plane hand-off: RemoteSink resolves its per-edge
            # fault hook (sever/blackhole/delay) from this at open().
            ctx.fault_injector = self.faults
            ctx.restart_epoch = self.restart_epoch
            # Roofline hand-off: model runners mint a per-operator probe
            # (static-cost join, roofline.* gauges, compile-event log)
            # from this at open().
            ctx.roofline = self.roofline
            if head_gate is not None:
                # Operator-owned background threads (the model runner's
                # fetch thread) use this to break the CHAIN's event wait
                # when results complete — every fused member wakes the
                # one thread that runs it.
                ctx.wakeup = head_gate.wake
            elif st.mailbox is not None:
                # Split-source chains wait on the mailbox instead of a
                # gate; the same completion wakeup applies to every
                # fused member.
                ctx.wakeup = st.mailbox.notify
            unit.operator.setup(ctx, unit.output, state)
            if pos == 0 and st.mailbox is not None:
                # Wire the reader to its source's coordinator before
                # restore() runs (restored enumerator state flows
                # through the operator into the coordinator).
                coord = self.split_coordinator(unit.t, unit.operator.source)
                unit.operator.attach(coord, unit.index, st.mailbox)
        self.subtasks.append(st)

    def _register_edge_gauges(self, st: _Subtask, head: Transformation,
                              channel_base) -> None:
        """Per-EDGE queue gauges on the record plane: cumulative puts and
        current buffered depth for each input edge of the chain head,
        summed over the edge's channel range.  A chained edge has no
        gate, so its absence from the report IS the zero-queue-traffic
        evidence the latency-floor CI guard asserts."""
        gate = st.gate
        if gate is None:
            return
        grp = self.metrics.group(st.scope)
        for edge_idx, edge in enumerate(head.inputs):
            lo = channel_base[(head.id, edge_idx)]
            span = (1 if isinstance(edge.partitioner, ForwardPartitioner)
                    else edge.upstream.parallelism)
            hi = lo + span
            name = f"edge{edge_idx}_{edge.upstream.name}"
            grp.gauge(f"{name}_queue_puts",
                      lambda g=gate, a=lo, b=hi: sum(g.puts_per_channel[a:b]))
            grp.gauge(f"{name}_queue_depth",
                      lambda g=gate, a=lo, b=hi: sum(
                          max(0, c) for c in g.buffered_per_channel[a:b]))

    def split_coordinator(self, t: Transformation, source):
        """The (lazily created) SplitCoordinator for split source ``t``.
        ``source`` is the shared SplitSource instance (every subtask's
        factory closes over the same one).

        Per-process by construction: a distributed cohort spreading one
        split source's subtasks over several processes would run one
        enumerator per process and double-assign every split — refuse
        rather than duplicate records.
        """
        with self._split_lock:
            coord = self._split_coordinators.get(t.name)
            if coord is None:
                if not all(self._owns_subtask(t, i) for i in range(t.parallelism)):
                    raise ValueError(
                        f"split source {t.name!r}: subtasks are spread over a "
                        "process cohort but the split enumerator is "
                        "per-process — run split sources on a single process "
                        "(or use a legacy SourceFunction for cohort jobs)"
                    )
                from flink_tensorflow_tpu.sources.coordinator import (
                    SplitCoordinator,
                )

                coord = SplitCoordinator(source, t.parallelism,
                                         sanitizer=self.sanitizer, name=t.name)
                self._split_coordinators[t.name] = coord
            return coord

    # --- placement hooks (overridden by DistributedExecutor) -------------
    def _owns_subtask(self, t: Transformation, index: int) -> bool:
        """Whether subtask ``index`` of ``t`` runs in this process."""
        return True

    def _process_identity(self) -> typing.Tuple[int, int]:
        """(process_index, num_processes) of this executor's cohort."""
        return 0, 1

    def _remote_writer(self, t: Transformation, subtask_index: int, channel_idx: int):
        raise RuntimeError(
            f"no gate for {t.name}.{subtask_index} — local executor owns "
            "every subtask, so this is a plan-construction bug"
        )

    # --- restore ---------------------------------------------------------
    def restore(
        self,
        snapshots: typing.Dict[str, typing.Dict[int, typing.Any]],
        from_checkpoint_id: typing.Optional[int] = None,
        *,
        local_shard: bool = False,
    ) -> None:
        """``local_shard=True``: ``snapshots`` holds exactly THIS
        process's subtasks (a distributed same-shape restore from the
        process's own shard — the caller validated the shape against the
        shard's recorded metadata), so each local subtask restores by
        index and the rescale inference must not run (per-task counts
        are local, not the old global parallelism)."""
        if from_checkpoint_id is not None:
            # New checkpoints must never overwrite the restore point.
            self.coordinator.resume_from(from_checkpoint_id)
        job_meta = snapshots.pop("__job__", None)
        if job_meta:
            pinned = job_meta.get(0, {}).get("max_parallelism")
            if pinned is not None and pinned != self.max_parallelism:
                raise ValueError(
                    f"checkpoint was taken with max_parallelism={pinned}; "
                    f"this job uses {self.max_parallelism} — the key-group "
                    "routing would change and orphan keyed state. Restore "
                    "with the original max_parallelism."
                )
        # Restore addresses LOGICAL operators — checkpoints key state by
        # (task name, subtask index), so a job re-planned with a
        # different chaining layout (chaining toggled, escape hatches
        # added) still restores every operator's state correctly.
        by_task: typing.Dict[str, typing.List[_ChainedUnit]] = {}
        for st in self.subtasks:
            for unit in st.units:
                by_task.setdefault(unit.t.name, []).append(unit)
        for task, units in by_task.items():
            task_snaps = snapshots.get(task)
            if task_snaps is None:
                continue
            old_parallelism = len(task_snaps)
            # The NEW parallelism is the transformation's declared one —
            # on a distributed executor the local unit list is only
            # this process's share of it.
            new_parallelism = units[0].t.parallelism
            if local_shard or old_parallelism == new_parallelism:
                for unit in units:
                    snap = task_snaps.get(unit.index)
                    if snap is not None:
                        unit.operator.restore(snap)
            else:
                # Parallelism changed across the restart: redistribute by
                # key group (Flink's rescaling semantics; keyed state only
                # — per-subtask state raises StateNotRescalable).
                for unit in units:
                    unit.operator.restore(
                        unit.operator.rescale(
                            task_snaps, unit.index, new_parallelism,
                            self.max_parallelism,
                        )
                    )
        # Split sources: push restored split/pool state into the
        # per-source coordinators NOW — before any reader thread runs —
        # so the lazily built enumerator always sees it (in-flight
        # splits resume at their offsets; pooled splits redistribute).
        for st in self.subtasks:
            for unit in st.units:
                apply = getattr(unit.operator, "apply_restore", None)
                if apply is not None:
                    apply()

    # --- execution --------------------------------------------------------
    def start(self) -> None:
        if self.flight is not None:
            self.flight.record("job", "start", {
                "subtasks": len(self.subtasks),
                "logical_subtasks": self.total_subtasks,
                "restart_epoch": self.restart_epoch,
            })
            if self.restart_epoch:
                self.flight.record("job", "restart.attempt", {
                    "restart_epoch": self.restart_epoch})
        for st in self.subtasks:
            if not st.t.is_source:
                body = st.run_worker
            elif st.mailbox is not None:
                body = st.run_split_source
            else:
                body = st.run_source
            st.thread = threading.Thread(target=body, name=st.scope, daemon=True)
        for st in self.subtasks:
            st.thread.start()
        if self.checkpoint_interval_s is not None:
            self._periodic_thread = threading.Thread(
                target=self._periodic_checkpoints, name="checkpoint-timer", daemon=True
            )
            self._periodic_thread.start()

    def _periodic_checkpoints(self) -> None:
        """Flink-style periodic snapshots (SURVEY.md §5 "Checkpoint /
        resume"): trigger an aligned checkpoint every interval until the
        job finishes.  Races with completion/cancellation are benign —
        a trigger landing there just fails and is not retried."""
        interval = self.checkpoint_interval_s
        while not self._all_done.wait(interval) and not self.cancelled.is_set():
            try:
                self.coordinator.trigger(timeout=self.checkpoint_timeout_s)
            except Exception:
                # Catch EVERYTHING: an escaping error (serialization bug,
                # disk full, ...) would otherwise kill this daemon thread
                # silently and the job would run on unpersisted, believing
                # it is being checkpointed.
                if self._all_done.is_set() or self.cancelled.is_set():
                    return
                logger.warning("periodic checkpoint failed", exc_info=True)

    def join(self, timeout: typing.Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        for st in self.subtasks:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            st.thread.join(remaining)
            if st.thread.is_alive():
                self.cancel()
                raise JobTimeout(f"timeout waiting for subtask {st.scope}")
        # Completed count-based checkpoints must be durable before the job
        # reports done (a cohort worker exits right after this returns).
        in_flight = self.coordinator.wait_for_persistence(
            None if deadline is None else max(0.1, deadline - time.monotonic())
        )
        if in_flight:
            raise JobTimeout(
                f"{in_flight} checkpoint persist write(s) did not drain — "
                "completed checkpoints are not yet durable"
            )
        # The persist queue fans notifications out via add_notification,
        # but a notification enqueued after a subtask's loop exited would
        # sit undelivered forever (delivery runs on the subtask thread).
        # All threads are joined and all persist jobs drained here, so
        # the join thread can flush the leftovers without violating the
        # single-writer contract — this is what makes "durable before the
        # job reports done" include the final checkpoint's 2PC commit.
        # Best-effort, Flink-style: this late delivery runs AFTER the
        # operator's close(), so a hook that needs close()-released
        # resources may fail — log and keep flushing the remaining
        # subtasks rather than failing a job that already completed.
        if self._error is None:
            for st in self.subtasks:
                try:
                    st._deliver_notifications()
                except Exception:
                    logger.warning(
                        "post-close checkpoint notification failed for %s",
                        st.scope, exc_info=True,
                    )
        if self._error is not None:
            raise JobFailure(f"job failed: {self._error!r}") from self._error
        if self.sanitizer is not None:
            # The job is drained: any recorded violation is a real
            # protocol/lock-discipline bug — surface it as loudly as a
            # failed job (SanitizerError is NOT a JobFailure: restart
            # strategies must not replay over a concurrency bug).
            self.sanitizer.shutdown()
            try:
                self.sanitizer.check()
            except BaseException:
                if self.flight is not None:
                    self.flight.record("job", "sanitizer.violation", {
                        "violations": len(self.sanitizer.violations)})
                    self.flight_dump("sanitizer")
                self.sanitizer_log_dump("violation")
                raise
            # Clean drain: the happens-before log is the stitcher's
            # input — dump it on SUCCESS too, so `flink-tpu-sanitize
            # --cohort` can prove the run conformant (zero violations is
            # an assertion, not an absence of evidence).
            self.sanitizer_log_dump("shutdown")

    def run(self, timeout: typing.Optional[float] = None) -> None:
        self.start()
        self.join(timeout)

    # --- failure / teardown ----------------------------------------------
    def flight_dump(self, reason: str) -> typing.Optional[str]:
        """Dump the flight ring to the configured path (no-op without a
        recorder or a path); returns the written path.  Each artifact
        references the other: the flight dump carries the sanitizer
        event-log path (and vice versa), so whichever one a responder
        finds first points at the rest of the evidence."""
        if self.flight is None or not self.flight_path:
            return None
        extra = ({"sanitizer_log": self.sanitize_log_path}
                 if self.sanitize_log_path else None)
        path = self.flight.dump(self.flight_path, reason,
                                tracer=self.tracer, extra=extra)
        self.sanitizer_log_dump(reason)
        return path

    def sanitizer_log_dump(self, reason: str) -> typing.Optional[str]:
        """Dump the sanitizer's happens-before event log to the
        configured path (no-op without a sanitizer or a path); returns
        the written path.  Idempotent per reason, like flight_dump."""
        if self.sanitizer is None or not self.sanitize_log_path:
            return None
        extra = ({"flight_dump": self.flight_path}
                 if self.flight is not None and self.flight_path else None)
        return self.sanitizer.dump_hb_log(
            self.sanitize_log_path, reason, extra=extra)

    def fail(self, subtask: _Subtask, exc: BaseException) -> None:
        with self._error_lock:
            first = self._error is None
            if first:
                self._error = exc
        logger.error("subtask %s failed", subtask.scope, exc_info=exc)
        self.cancel()
        if first:
            if self.tracer is not None:
                self.tracer.instant(
                    "job", "failure",
                    args={"subtask": subtask.scope, "error": repr(exc)})
            if self.flight is not None:
                # The black box lands BEFORE any teardown runs further:
                # the ring holds the lifecycle that led here.
                self.flight.record("job", "failure", {
                    "subtask": subtask.scope, "error": repr(exc)})
                self.flight_dump("crash")
            # Crash-time observability: flush the reporter (and any other
            # registered listener) NOW, while the gauges still show the
            # state that produced the failure — the final stop() flush
            # runs after teardown and may be too late or never (a caller
            # that crashes before join()).
            for hook in self.failure_listeners:
                try:
                    hook()
                except Exception:  # noqa: BLE001 - observability only
                    logger.warning("failure listener failed", exc_info=True)

    def cancel(self) -> None:
        self.cancelled.set()
        for gate in self._gates:
            gate.close()
        for st in self.subtasks:
            if st.mailbox is not None:
                # close(), not notify(): the sticky shutdown signal is
                # immune to the notify/park race (a one-shot signal
                # consumed by an unrelated wakeup would strand the loop
                # parked between its cancelled-check and its wait).
                st.mailbox.close()
        self.coordinator.cancel_pending()

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """Fan a durable-checkpoint notification out to every subtask
        (delivered to each chained operator on the subtask's own thread)."""
        for st in self.subtasks:
            st.add_notification(checkpoint_id)

    def notify_checkpoint_aborted(self, checkpoint_id: int) -> None:
        """Fan a checkpoint ABORT out: subtasks drop the id's alignment
        state (unblocking gates a missing barrier wedged) and split
        coordinators cancel its assignment freeze — the job keeps
        flowing and sources keep triggering later checkpoints."""
        for st in self.subtasks:
            st.add_abort(checkpoint_id)
        with self._split_lock:
            coords = list(self._split_coordinators.values())
        for coord in coords:
            coord.cancel_alignment(checkpoint_id)

    def subtask_finished(self, subtask: _Subtask) -> None:
        if self.flight is not None:
            self.flight.record(subtask.scope, "subtask.finished")
        self.coordinator.subtask_finished(subtask)
        with self._error_lock:
            self._finished_count += 1
            if self._finished_count >= len(self.subtasks):
                self._all_done.set()

    @property
    def total_subtasks(self) -> int:
        """LOGICAL subtask count (one per operator per parallel index) —
        the checkpoint coordinator expects one ack per logical operator
        regardless of how chains pack them onto threads."""
        return sum(len(st.units) for st in self.subtasks)
