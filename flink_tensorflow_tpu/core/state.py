"""Keyed state — the equivalent of Flink's keyed state backends.

The reference relies on Flink keyed state for the online-training workload
("keyed stream, per-key SGD step", BASELINE.json:9-11): model bookkeeping per
key, with the TF session holding the variables.  The TPU-native design makes
*all* state explicit here — including model parameters, which are pytrees of
(numpy/jax) arrays stored as keyed or operator state so that snapshot
barriers capture them (SURVEY.md §5 "Checkpoint / resume" divergence note).
"""

from __future__ import annotations

import dataclasses
import typing


@dataclasses.dataclass(frozen=True)
class StateDescriptor:
    """Names a piece of keyed state and how to initialize it."""

    name: str
    default_factory: typing.Optional[typing.Callable[[], typing.Any]] = None


class ValueState:
    """Single-value keyed state, scoped to the current key."""

    __slots__ = ("_store", "_descriptor")

    def __init__(self, store: "KeyedStateStore", descriptor: StateDescriptor):
        self._store = store
        self._descriptor = descriptor

    def value(self) -> typing.Any:
        return self._store.get(self._descriptor)

    def update(self, value: typing.Any) -> None:
        self._store.put(self._descriptor, value)

    def clear(self) -> None:
        self._store.remove(self._descriptor)


class KeyedStateStore:
    """Per-subtask store: {state_name: {key: value}}.

    Single-writer by construction — each subtask runs on one thread
    (SURVEY.md §5 "Race detection": keep the single-writer-per-operator
    contract), so no locking is needed on the hot path.
    """

    def __init__(self) -> None:
        self._tables: typing.Dict[str, typing.Dict[typing.Any, typing.Any]] = {}
        self.current_key: typing.Any = None

    # -- access scoped to current_key ---------------------------------
    def get(self, descriptor: StateDescriptor) -> typing.Any:
        table = self._tables.get(descriptor.name)
        if table is None or self.current_key not in table:
            if descriptor.default_factory is not None:
                # Return WITHOUT storing (Flink's ValueState.value rule):
                # storing on read would create a table entry for every
                # key ever probed, bloating snapshots; callers persist a
                # default by calling update() explicitly.
                return descriptor.default_factory()
            return None
        return table[self.current_key]

    def put(self, descriptor: StateDescriptor, value: typing.Any) -> None:
        self._tables.setdefault(descriptor.name, {})[self.current_key] = value

    def remove(self, descriptor: StateDescriptor) -> None:
        table = self._tables.get(descriptor.name)
        if table is not None:
            table.pop(self.current_key, None)

    def value_state(self, descriptor: StateDescriptor) -> ValueState:
        return ValueState(self, descriptor)

    # -- snapshot protocol --------------------------------------------
    def snapshot(self) -> typing.Dict[str, typing.Dict[typing.Any, typing.Any]]:
        """Shallow-copy all tables (values are treated as immutable pytrees)."""
        return {name: dict(table) for name, table in self._tables.items()}

    def restore(self, snap: typing.Dict[str, typing.Dict[typing.Any, typing.Any]]) -> None:
        self._tables = {name: dict(table) for name, table in snap.items()}

    def keys(self, state_name: str) -> typing.Iterable[typing.Any]:
        return self._tables.get(state_name, {}).keys()
