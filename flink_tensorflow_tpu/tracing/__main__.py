"""``python -m flink_tensorflow_tpu.tracing`` — the flink-tpu-trace CLI."""

from flink_tensorflow_tpu.tracing.cli import cli

if __name__ == "__main__":
    cli()
