"""Tensor coercion — host values in, TensorValue records out.

Equivalent of the reference's implicit-conversion layer ("Row<->DeviceArray
marshalling in the tensor-coercion layer", BASELINE.json:5; SURVEY.md §2
"Tensor coercion / injections": Scala arrays / images / Rows -> tensors).
Scala implicits become an explicit, inspectable converter registry — same
capability, but conversions are resolved once per schema (not per record via
implicit search) and the result is always a host numpy record; device
placement is the batcher's job.
"""

from __future__ import annotations

import typing

import numpy as np

from flink_tensorflow_tpu.tensors.schema import RecordSchema, TensorSpec
from flink_tensorflow_tpu.tensors.value import TensorValue

Converter = typing.Callable[[typing.Any, TensorSpec], np.ndarray]

_CONVERTERS: typing.List[typing.Tuple[typing.Callable[[typing.Any], bool], Converter]] = []


def register_converter(predicate: typing.Callable[[typing.Any], bool], converter: Converter) -> None:
    """Register a coercion rule; later registrations win (user overrides)."""
    _CONVERTERS.insert(0, (predicate, converter))


def _convert_array_like(value, spec: TensorSpec) -> np.ndarray:
    arr = np.asarray(value)
    if arr.dtype != spec.dtype:
        arr = arr.astype(spec.dtype)
    # Rank promotion: a flat list reshapes to a fully-static (d, ...) field.
    if arr.ndim != spec.rank:
        target = tuple(d for d in spec.shape if d is not None)
        if len(target) == spec.rank and arr.size == int(np.prod(target)):
            arr = arr.reshape(target)
        else:
            raise TypeError(
                f"cannot coerce array of shape {arr.shape} to spec {spec.shape}"
            )
    spec.validate(arr)
    return arr


def coerce_field(value: typing.Any, spec: TensorSpec) -> np.ndarray:
    for predicate, converter in _CONVERTERS:
        if predicate(value):
            out = converter(value, spec)
            spec.validate(out)
            return out
    return _convert_array_like(value, spec)


def coerce(value: typing.Any, schema: RecordSchema) -> TensorValue:
    """Coerce an arbitrary host value into a schema-conforming TensorValue.

    Accepted inputs (the reference's injection set, SURVEY.md §2):
    - ``TensorValue`` — validated as-is (field subset selected if needed)
    - mapping (a "Row"): field name -> array-like
    - tuple/list matching the schema's field order
    - single array-like, when the schema has exactly one field
    """
    if isinstance(value, TensorValue):
        missing = set(schema.names) - set(value.names)
        if missing:
            raise TypeError(f"record missing fields {missing}")
        return TensorValue(
            {n: coerce_field(value[n], schema[n]) for n in schema.names}, value.meta
        )
    if isinstance(value, typing.Mapping):
        missing = set(schema.names) - set(value)
        if missing:
            raise TypeError(f"row missing fields {missing}")
        return TensorValue({n: coerce_field(value[n], schema[n]) for n in schema.names})
    if isinstance(value, (tuple, list)) and len(schema.names) > 1:
        if len(value) != len(schema.names):
            raise TypeError(
                f"row of {len(value)} columns does not match schema {schema.names}"
            )
        return TensorValue(
            {n: coerce_field(v, schema[n]) for n, v in zip(schema.names, value)}
        )
    if len(schema.names) == 1:
        name = schema.names[0]
        return TensorValue({name: coerce_field(value, schema[name])})
    raise TypeError(f"cannot coerce {type(value).__name__} to {schema}")


# -- image coercion (Inception/MNIST workloads) -----------------------------

def image_to_float(
    image: np.ndarray,
    *,
    scale: float = 1.0 / 255.0,
    offset: float = 0.0,
    dtype=np.float32,
) -> np.ndarray:
    """uint8 HWC image -> scaled float tensor.

    Host-side analogue of the reference's programmatically-built image
    normalization graph in the Inception example (SURVEY.md §2 "Examples").
    The device-side fused version lives in ops.preprocessing; use this one
    only when records arrive as raw bytes and must be normalized per record.
    """
    img = np.asarray(image)
    if img.dtype == np.uint8:
        img = img.astype(dtype)
    return (img * scale + offset).astype(dtype)
