"""Observability plane: metric types, reporters, runtime instrumentation,
inspector CLI.

All tier-1 fast — no TPU, tiny streams, no reporter intervals longer
than a fraction of a second.
"""

import io
import json
import math
import pathlib
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, ".")

from flink_tensorflow_tpu.metrics import (
    ConsoleReporter,
    Gauge,
    Histogram,
    JsonLinesReporter,
    Meter,
    MetricConfig,
    MetricRegistry,
    MetricReporter,
    PrometheusFileReporter,
    ReporterThread,
    Timer,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# metric types
# ---------------------------------------------------------------------------


class TestGauge:
    def test_callback_evaluated_at_read_time(self):
        box = {"v": 1}
        g = Gauge(lambda: box["v"])
        assert g.value() == 1
        box["v"] = 7
        assert g.value() == 7

    def test_raising_callback_yields_none(self):
        g = Gauge(lambda: 1 / 0)
        assert g.value() is None

    def test_reregistration_replaces_callback(self):
        registry = MetricRegistry()
        grp = registry.group("op.0")
        grp.gauge("depth", lambda: 1)
        grp.gauge("depth", lambda: 2)  # operator restart re-binds
        assert registry.snapshot()["op.0"]["depth"] == 2

    def test_registry_snapshot_pulls_gauges(self):
        registry = MetricRegistry()
        state = {"n": 0}
        registry.group("a.0").gauge("n", lambda: state["n"])
        state["n"] = 42
        assert registry.snapshot()["a.0"]["n"] == 42


class TestTimer:
    def test_update_accumulates(self):
        t = Timer()
        t.update(0.5)
        t.update(1.5)
        assert t.count == 2
        assert t.total_s == pytest.approx(2.0)
        assert t.histogram.count == 2

    def test_context_manager_records_elapsed(self):
        t = Timer()
        with t.time():
            time.sleep(0.01)
        assert t.count == 1
        assert 0.005 < t.total_s < 1.0

    def test_summary_includes_total(self):
        t = Timer()
        t.update(1.0)
        s = t.summary()
        assert s["total_s"] == pytest.approx(1.0)
        assert s["p50"] == pytest.approx(1.0)


class TestMeter:
    def test_window_rate_is_pure(self):
        m = Meter()
        m.mark(100)
        r1 = m.window_rate()
        r2 = m.window_rate()
        # Reading must not consume the window (both see the same count;
        # rates differ only by the tiny elapsed-time delta).
        assert r1 > 0 and r2 > 0
        assert m.count == 100

    def test_reset_window_starts_fresh(self):
        m = Meter()
        m.mark(100)
        m.reset_window()
        assert m.window_rate() == 0.0
        m.mark(5)
        assert m.window_rate() > 0.0
        assert m.count == 105  # lifetime count untouched

    def test_thread_safety_smoke(self):
        m = Meter()
        n_threads, per_thread = 8, 5000

        def pound():
            for _ in range(per_thread):
                m.mark()

        threads = [threading.Thread(target=pound) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert m.count == n_threads * per_thread


class TestHistogramReservoir:
    def test_deterministic_under_seed(self):
        a = Histogram(capacity=32, seed=7)
        b = Histogram(capacity=32, seed=7)
        values = list(np.random.RandomState(0).rand(2000))
        for v in values:
            a.record(v)
            b.record(v)
        assert a._samples == b._samples  # identical reservoir decisions

    def test_does_not_touch_global_numpy_state(self):
        np.random.seed(1234)
        before = np.random.get_state()[1].copy()
        h = Histogram(capacity=8, seed=3)
        for v in range(1000):
            h.record(float(v))
        after = np.random.get_state()[1]
        assert np.array_equal(before, after)

    def test_registry_seed_derives_per_metric_seeds(self):
        r1 = MetricRegistry(seed=99)
        r2 = MetricRegistry(seed=99)
        assert r1.metric_seed("op.0", "lat") == r2.metric_seed("op.0", "lat")
        assert r1.metric_seed("op.0", "lat") != r1.metric_seed("op.1", "lat")
        assert MetricRegistry(seed=100).metric_seed("op.0", "lat") != \
            r1.metric_seed("op.0", "lat")

    def test_seeded_registries_sample_identically(self):
        def run(seed):
            reg = MetricRegistry(seed=seed)
            h = reg.group("op.0").histogram("lat")
            h._capacity = 16  # force overflow fast
            for v in range(500):
                h.record(float(v))
            return list(h._samples)

        assert run(5) == run(5)


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------


def _populated_registry() -> MetricRegistry:
    reg = MetricRegistry(seed=1)
    grp = reg.group("op.0")
    grp.counter("events").inc(3)
    grp.meter("records").mark(10)
    grp.histogram("latency_s").record(0.25)
    grp.gauge("depth", lambda: 4)
    grp.timer("span_s").update(0.5)
    return reg


class TestJsonLinesReporter:
    def test_round_trip(self, tmp_path):
        reg = _populated_registry()
        path = tmp_path / "metrics.jsonl"
        rep = JsonLinesReporter(str(path))
        rep.report(reg.snapshot(), timestamp=123.0)
        rep.report(reg.snapshot(), timestamp=124.0)
        rep.close()
        lines = [json.loads(x) for x in path.read_text().splitlines()]
        assert len(lines) == 2
        m = lines[0]["metrics"]["op.0"]
        assert m["events"] == 3
        assert m["records"]["count"] == 10
        assert m["latency_s"]["p50"] == pytest.approx(0.25)
        assert m["depth"] == 4
        assert m["span_s"]["total_s"] == pytest.approx(0.5)

    def test_nan_becomes_null(self, tmp_path):
        reg = MetricRegistry()
        reg.group("a.0").histogram("h")  # empty -> NaN percentiles
        path = tmp_path / "m.jsonl"
        rep = JsonLinesReporter(str(path))
        rep.report(reg.snapshot(), timestamp=0.0)
        rep.close()
        parsed = json.loads(path.read_text())  # must be strict-JSON parseable
        assert parsed["metrics"]["a.0"]["h"]["p50"] is None


class TestPrometheusFileReporter:
    def test_exposition_format_and_atomicity(self, tmp_path):
        reg = _populated_registry()
        path = tmp_path / "metrics.prom"
        rep = PrometheusFileReporter(str(path))
        rep.report(reg.snapshot(), timestamp=1.0)
        text = path.read_text()
        assert 'flink_tpu_events{scope="op.0"} 3' in text
        assert 'flink_tpu_records_count{scope="op.0"} 10' in text
        assert 'flink_tpu_depth{scope="op.0"} 4' in text
        assert "# TYPE flink_tpu_events gauge" in text
        assert not (tmp_path / "metrics.prom.tmp").exists()
        # Second report REPLACES (atomic rewrite, not append).
        rep.report(reg.snapshot(), timestamp=2.0)
        assert path.read_text().count('flink_tpu_events{scope="op.0"}') == 1

    def test_skips_non_finite(self, tmp_path):
        reg = MetricRegistry()
        reg.group("a.0").histogram("h")  # NaN percentiles
        path = tmp_path / "m.prom"
        PrometheusFileReporter(str(path)).report(reg.snapshot(), timestamp=0.0)
        assert "nan" not in path.read_text().lower()


class TestConsoleReporter:
    def test_writes_scope_lines(self):
        reg = _populated_registry()
        buf = io.StringIO()
        ConsoleReporter(stream=buf).report(reg.snapshot(), timestamp=time.time())
        out = buf.getvalue()
        assert "op.0" in out
        assert "events=3" in out


class _RecordingReporter(MetricReporter):
    def __init__(self):
        self.reports = []
        self.closed = False

    def report(self, snapshot, *, timestamp):
        self.reports.append(snapshot)

    def close(self):
        self.closed = True


class TestReporterThread:
    def test_periodic_reports_then_final_on_stop(self):
        reg = _populated_registry()
        sink = _RecordingReporter()
        thread = ReporterThread(reg, [sink], interval_s=0.02)
        thread.start()
        time.sleep(0.15)
        thread.stop()
        assert len(sink.reports) >= 2  # periodic + the final stop() report
        assert sink.closed
        assert sink.reports[-1]["op.0"]["events"] == 3

    def test_stop_idempotent(self):
        thread = ReporterThread(MetricRegistry(), [], interval_s=1.0)
        thread.start()
        thread.stop()
        thread.stop()

    def test_failing_sink_does_not_stop_others(self):
        class Bomb(MetricReporter):
            def report(self, snapshot, *, timestamp):
                raise RuntimeError("boom")

        reg = _populated_registry()
        sink = _RecordingReporter()
        thread = ReporterThread(reg, [Bomb(), sink], interval_s=0.02)
        thread.start()
        time.sleep(0.06)
        thread.stop()
        assert sink.reports

    def test_window_reset_per_report(self):
        reg = MetricRegistry()
        meter = reg.group("a.0").meter("m")
        meter.mark(50)
        thread = ReporterThread(reg, [_RecordingReporter()], interval_s=0.02)
        thread.start()
        time.sleep(0.08)
        thread.stop()
        # The reporter owns the window cadence: after its reports the
        # window no longer carries the initial burst.
        assert meter.window_rate() < meter.rate()


# ---------------------------------------------------------------------------
# runtime integration
# ---------------------------------------------------------------------------


class TestRuntimeInstrumentation:
    def _run_job(self, report_interval_s=None, **metric_kw):
        import dataclasses

        from flink_tensorflow_tpu import StreamExecutionEnvironment

        env = StreamExecutionEnvironment(parallelism=2)
        if metric_kw:
            env.configure(metrics=dataclasses.replace(
                env.config.metrics, **metric_kw))
        (env.from_collection(list(range(64)))
            .rebalance()
            .map(lambda x: x + 1, name="inc", parallelism=2)
            .sink_to_list())
        env.execute("job", timeout=120, report_interval_s=report_interval_s)
        return env

    def test_per_subtask_metrics_populated(self):
        env = self._run_job()
        snap = env.metric_registry.snapshot()
        for scope in ("inc.0", "inc.1"):
            m = snap[scope]
            assert m["records_in"]["count"] == 32
            assert m["records_out"]["count"] == 32
            assert m["process_latency_s"]["count"] == 32
            assert m["queue_depth"] == 0          # drained at job end
            assert m["queue_high_watermark"] >= 1
            assert m["backpressure_s"] >= 0.0
            assert m["idle_s"] >= 0.0
            assert m["busy_s"] > 0.0
        # Source: emit latency + records_out.
        src = snap["collection.0"]
        assert src["records_out"]["count"] == 64
        assert src["process_latency_s"]["count"] == 64

    def test_no_reporter_thread_without_interval(self):
        before = {t.name for t in threading.enumerate()}
        env = self._run_job(report_interval_s=None)
        assert "metric-reporter" not in {
            t.name for t in threading.enumerate()} - before
        assert env is not None

    def test_reporter_sinks_written_during_execution(self, tmp_path):
        jsonl = tmp_path / "m.jsonl"
        prom = tmp_path / "m.prom"
        self._run_job(report_interval_s=0.02,
                      jsonl_path=str(jsonl), prometheus_path=str(prom))
        lines = [json.loads(x) for x in jsonl.read_text().splitlines()]
        assert lines  # at least the final stop() report
        assert any("inc.0" in line["metrics"] for line in lines)
        assert 'scope="inc.0"' in prom.read_text()

    def test_watermark_lag_gauge_on_event_time_pipeline(self):
        from flink_tensorflow_tpu import StreamExecutionEnvironment
        from flink_tensorflow_tpu.core import functions as fn

        class Agg(fn.WindowFunction):
            def process_window(self, key, window, elements, out):
                out.collect((key, len(elements)))

        env = StreamExecutionEnvironment()
        (env.from_collection([("k", float(i)) for i in range(40)])
            .assign_timestamps(lambda e: e[1], watermark_every=4)
            .key_by(lambda e: e[0])
            .time_window(5.0)
            .apply(Agg())
            .sink_to_list())
        env.execute("wm", timeout=120)
        snap = env.metric_registry.snapshot()
        lag = snap["time_window.0"]["watermark_lag_s"]
        assert lag is not None and lag >= 0.0
        assert snap["timestamps.0"]["watermark_lag_s"] is not None

    def test_checkpoint_metrics(self, tmp_path):
        from flink_tensorflow_tpu import StreamExecutionEnvironment
        from flink_tensorflow_tpu.io.sources import CollectionSource

        env = StreamExecutionEnvironment()
        env.enable_checkpointing(str(tmp_path / "chk"), every_n_records=16)
        # disable_chaining keeps the map a real worker with an input
        # gate: a chained operator never aligns (barriers traverse the
        # chain by direct call), so this scope would have no alignment
        # spans at all.
        (env.from_source(CollectionSource(list(range(64))), name="src")
            .map(lambda x: x, name="fwd")
            .disable_chaining()
            .sink_to_list())
        env.execute("chk", timeout=120)
        chk = env.metric_registry.snapshot()["checkpoint"]
        assert chk["completed"] >= 1
        assert chk["duration_s"]["count"] >= 1
        assert chk["last_checkpoint_id"] >= 1
        assert chk["last_size_bytes"] > 0
        # Per-subtask alignment spans recorded on the worker scopes.
        snap = env.metric_registry.snapshot()
        assert snap["fwd.0"]["checkpoint_alignment_s"]["count"] >= 1


# ---------------------------------------------------------------------------
# inspector CLI
# ---------------------------------------------------------------------------

REQUIRED_ROW_KEYS = {
    "operator", "subtask", "records_per_s", "p50_latency_s",
    "p99_latency_s", "queue_depth", "backpressure_fraction",
    "watermark_lag_s",
}


class TestInspector:
    def test_build_rows_shapes(self):
        from flink_tensorflow_tpu.metrics.inspector import build_rows

        snapshot = {
            "op.0": {
                "records_in": {"count": 10, "rate": 5.0, "window_rate": 5.0},
                "records_out": {"count": 10, "rate": 5.0, "window_rate": 5.0},
                "process_latency_s": {"count": 10, "p50": 0.01, "p95": 0.02,
                                      "p99": 0.02, "mean": 0.01,
                                      "total_s": 0.1},
                "queue_depth": 2,
                "queue_high_watermark": 9,
                "backpressure_s": 0.5,
            },
            "checkpoint": {"completed": 1},
        }
        rows = build_rows(snapshot, wall_s=2.0)
        assert len(rows) == 1  # job-level scopes excluded
        row = rows[0]
        assert REQUIRED_ROW_KEYS <= set(row)
        assert row["records_per_s"] == pytest.approx(5.0)
        assert row["backpressure_fraction"] == pytest.approx(0.25)
        assert row["watermark_lag_s"] is None

    def test_cli_on_example(self, capsys):
        from flink_tensorflow_tpu.metrics.inspector import main

        rc = main([str(REPO / "examples/mnist_lenet.py"), "--snapshot-only"])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()
        snap = json.loads(out[-1])
        assert snap["subtasks"], "expected at least one operator subtask"
        for row in snap["subtasks"]:
            assert REQUIRED_ROW_KEYS <= set(row)
            assert row["records_per_s"] is not None
            assert row["backpressure_fraction"] is not None
        # Every operator in the plan shows up with every subtask.
        ops = {(r["operator"], r["subtask"]) for r in snap["subtasks"]}
        assert len(ops) == len(snap["subtasks"])
        assert json.dumps(snap)  # strict-JSON round-trippable

    def test_cli_table_output(self, capsys):
        from flink_tensorflow_tpu.metrics.inspector import main

        rc = main([str(REPO / "examples/mnist_lenet.py")])
        assert rc == 0
        out = capsys.readouterr().out
        assert "rec/s" in out and "p99 ms" in out

    def test_cli_failure_exit_code(self, capsys, tmp_path):
        from flink_tensorflow_tpu.metrics.inspector import main

        bad = tmp_path / "nope.py"
        bad.write_text("def main(argv):\n    return 0\n")
        assert main([str(bad), "--snapshot-only"]) == 2


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


class TestMetricConfig:
    def test_validate_rejects_bad_interval(self):
        with pytest.raises(ValueError):
            MetricConfig(report_interval_s=0).validate()

    def test_validate_rejects_non_reporter(self):
        with pytest.raises(ValueError):
            MetricConfig(reporters=("nope",)).validate()

    def test_build_reporters(self, tmp_path):
        cfg = MetricConfig(jsonl_path=str(tmp_path / "a.jsonl"),
                           prometheus_path=str(tmp_path / "a.prom"),
                           console=True)
        kinds = {type(r) for r in cfg.build_reporters()}
        assert kinds == {JsonLinesReporter, PrometheusFileReporter,
                         ConsoleReporter}

    def test_job_config_carries_metrics(self):
        from flink_tensorflow_tpu.core.config import JobConfig

        cfg = JobConfig(metrics=MetricConfig(report_interval_s=1.0))
        assert cfg.validate().metrics.report_interval_s == 1.0

    def test_seed_flows_into_registry(self):
        import dataclasses

        from flink_tensorflow_tpu import StreamExecutionEnvironment

        env = StreamExecutionEnvironment()
        env.configure(metrics=dataclasses.replace(
            env.config.metrics, seed=17))
        env.from_collection([1, 2, 3]).sink_to_list()
        env.execute("seeded", timeout=60)
        assert env.metric_registry.seed == 17


def test_prometheus_exposition_is_sorted_and_labelled():
    from flink_tensorflow_tpu.metrics.reporters import prometheus_exposition

    text = prometheus_exposition(
        {"b.0": {"x": 1}, "a.0": {"x": 2}}, timestamp=0.0)
    # Scopes render in sorted order; both carry the scope label.
    assert text.index('scope="a.0"') < text.index('scope="b.0"')


def test_gauge_math_watermark_lag_never_negative():
    from flink_tensorflow_tpu.core.event_time import _WatermarkLagMixin

    class Holder(_WatermarkLagMixin):
        ctx = None

    h = Holder()
    assert h._last_lag_s is None
    h._note_event_ts(10.0)
    h._note_watermark(12.0)  # watermark ahead of data (slackless close)
    assert h._last_lag_s == 0.0
    h._note_watermark(math.inf)  # closing watermark must not clobber
    assert h._last_lag_s == 0.0
    h._note_event_ts(20.0)
    h._note_watermark(15.0)
    assert h._last_lag_s == pytest.approx(5.0)
