"""Per-job split coordinator — the enumerator's thread-safe host.

One coordinator exists per split-source transformation per executor
(``LocalExecutor.split_coordinator``); all of that source's reader
subtasks share it.  It owns the :class:`SplitEnumerator` behind a lock
and implements the two protocols the runtime needs:

**Pull-based assignment.**  ``poll_split(reader)`` hands out the next
split on demand.  A reader that drains its split early simply asks
again, so work steals itself: nobody plans a distribution, slow readers
just pull less.  The call never blocks — it answers ``wait`` when
assignment is momentarily impossible and the reader parks on its
mailbox (sources/mailbox.py), to be woken when the state changes.

**Checkpoint consistency.**  The enumerator's unassigned pool must be
snapshotted CONSISTENTLY with every reader's own in-flight-split
snapshot, or a split could restore both into the pool and into a
reader (duplicate records), or into neither (lost records).  Protocol:
the pool snapshot for checkpoint ``k`` is taken when the FIRST reader
cuts its stream at barrier ``k``, and split assignment is FROZEN until
every reader (or finished subtask) has passed ``k``.  With assignment
frozen, a split is in exactly one place at every reader's barrier:
unassigned (in the pool snapshot), in-flight on a reader (in that
reader's snapshot, with offset), or completed (in neither — all its
records pre-date every barrier).  Readers parked on the freeze still
serve their own barriers (the mailbox wait is barrier-wakeable), so the
freeze cannot deadlock the alignment it protects.

The pool snapshot rides in reader 0's operator snapshot, so it lands in
the existing checkpoint store under the source's own (task, subtask)
identity — no new persistence format.
"""

from __future__ import annotations

import threading
import typing

from flink_tensorflow_tpu.sources.api import SourceSplit, SplitEnumerator, SplitSource

if typing.TYPE_CHECKING:
    from flink_tensorflow_tpu.sources.mailbox import SourceMailbox

#: poll_split answers: a split to read, park-and-retry, or end of input.
ASSIGNED = "assigned"
WAIT = "wait"
EXHAUSTED = "exhausted"


class SplitCoordinator:
    def __init__(self, source: SplitSource, num_readers: int, *,
                 sanitizer: typing.Optional[typing.Any] = None,
                 name: str = "split-source"):
        self.source = source
        self.num_readers = num_readers
        #: Debug-mode sanitizer (core/sanitizer_rt): instruments this
        #: lock and asserts the assignment-freeze invariant at every
        #: dispense; None (production) is a plain lock and no checks.
        self._san = sanitizer
        self._name = name
        self._lock = (sanitizer.lock(f"{name}.coordinator")
                      if sanitizer is not None else threading.Lock())
        self._mailboxes: typing.Dict[int, "SourceMailbox"] = {}
        self._enumerator: typing.Optional[SplitEnumerator] = None
        #: Enumerator state delivered by restore() BEFORE the job starts
        #: (reader 0's snapshot carries it); applied at lazy construction.
        self._restored_state: typing.Any = None
        self._has_restored_state = False
        #: In-flight splits of LOST readers (rescale restore pools them
        #: instead of pinning them to dead subtask indices).
        self._returned: typing.List[SourceSplit] = []
        #: checkpoint id -> reader indices that passed its barrier; any
        #: entry here freezes assignment (see module docstring).
        self._aligning: typing.Dict[int, typing.Set[int]] = {}
        #: checkpoint id -> pool snapshot taken at its first barrier.
        self._chk_state: typing.Dict[int, typing.Any] = {}
        #: Readers whose subtask finished: they can no longer pass
        #: barriers and must not hold alignments (or polls) open.
        self._finished: typing.Set[int] = set()
        #: Total splits handed out — the job-level assignment counter
        #: behind the source's splits_assigned metrics.
        self.splits_dispensed = 0

    # -- wiring (executor build/restore time, before any thread runs) ----
    def add_reader(self, index: int, mailbox: "SourceMailbox") -> None:
        self._mailboxes[index] = mailbox

    def deliver_restored_state(self, state: typing.Any) -> None:
        with self._lock:
            if self._enumerator is not None:
                self._enumerator.restore_state(state)
            else:
                self._restored_state = state
                self._has_restored_state = True

    def add_splits_back(self, splits: typing.Sequence[SourceSplit]) -> None:
        if not splits:
            return
        with self._lock:
            if self._enumerator is not None:
                self._enumerator.add_splits_back(list(splits))
            else:
                self._returned.extend(splits)
        self._notify_all()

    # -- assignment (reader threads) -------------------------------------
    def _ensure_enumerator(self) -> SplitEnumerator:
        """Build the enumerator on first use (caller holds the lock).
        Restore state and returned splits were delivered before start()
        (executor.restore runs before any subtask thread), so the lazy
        build always sees them."""
        if self._enumerator is None:
            enum = self.source.create_enumerator()
            if self._has_restored_state:
                enum.restore_state(self._restored_state)
                self._restored_state = None
            if self._returned:
                enum.add_splits_back(self._returned)
                self._returned = []
            self._enumerator = enum
        return self._enumerator

    def poll_split(
        self, reader_index: int
    ) -> typing.Tuple[str, typing.Optional[SourceSplit]]:
        with self._lock:
            if self._aligning:
                # Assignment frozen mid-alignment; the barrier-complete
                # path notifies every mailbox.
                return WAIT, None
            return self._dispense_locked(reader_index)

    def _dispense_locked(
        self, reader_index: int
    ) -> typing.Tuple[str, typing.Optional[SourceSplit]]:
        """Hand the next split to ``reader_index`` (caller holds the lock
        and has honored the alignment freeze).  The sanitizer re-checks
        the freeze here precisely because it does NOT trust the caller —
        a dispense while any alignment is in flight breaks the pool
        snapshot's consistency and is flagged."""
        split = self._ensure_enumerator().next_split(reader_index)
        if split is None:
            return (EXHAUSTED if self.source.bounded else WAIT), None
        self.splits_dispensed += 1
        if self._san is not None:
            self._san.split_dispensed(self._name, frozen=bool(self._aligning))
        return ASSIGNED, split

    # -- checkpoint protocol ---------------------------------------------
    def on_barrier(self, checkpoint_id: int, reader_index: int) -> typing.Optional[typing.Any]:
        """Reader ``reader_index`` is cutting its stream at this barrier.
        Returns the pool snapshot for the checkpoint when THIS reader
        carries it (reader 0 — the snapshot's persistence slot), else
        None."""
        with self._lock:
            passed = self._aligning.get(checkpoint_id)
            if passed is None:
                passed = self._aligning[checkpoint_id] = set()
                self._chk_state[checkpoint_id] = self._pool_state_locked()
            passed.add(reader_index)
            snap = self._chk_state[checkpoint_id] if reader_index == 0 else None
            done = len(passed | self._finished) >= self.num_readers
            if done:
                del self._aligning[checkpoint_id]
                self._chk_state.pop(checkpoint_id, None)
        if done:
            self._notify_all()
        return snap

    def pending_alignments(self, reader_index: int) -> typing.List[int]:
        """Checkpoint ids whose alignment is frozen on this coordinator
        and which ``reader_index`` has NOT passed yet, ascending.

        Exists for the runtime's freeze-deadlock guard: a reader parked
        split-less on the freeze emits no records, so with count-based
        triggers it can never reach the stream position that would make
        it cut the pending barrier — the alignment would wait on the
        reader and the reader on the alignment, forever.  (Found by the
        PR 5 sanitizer's stall watchdog; see _Subtask.run_split_source.)
        The runtime serves these barriers at the wait point instead."""
        with self._lock:
            return sorted(cid for cid, passed in self._aligning.items()
                          if reader_index not in passed)

    def cancel_alignment(self, checkpoint_id: int) -> None:
        """The coordinator declined ``checkpoint_id`` at its deadline:
        drop its alignment freeze and staged pool snapshot so assignment
        thaws and readers stop seeing it as pending — the stuck barrier
        must not freeze split dispensing forever."""
        with self._lock:
            dropped = self._aligning.pop(checkpoint_id, None)
            self._chk_state.pop(checkpoint_id, None)
        if dropped is not None:
            self._notify_all()

    def reader_finished(self, reader_index: int) -> None:
        """A reader's subtask ended (bounded input drained or failure
        teardown): it counts as passed for every current and future
        alignment — its final snapshot stands in for barrier acks
        (mirroring CheckpointCoordinator._seed_finished)."""
        with self._lock:
            self._finished.add(reader_index)
            complete = [
                cid for cid, passed in self._aligning.items()
                if len(passed | self._finished) >= self.num_readers
            ]
            for cid in complete:
                del self._aligning[cid]
                self._chk_state.pop(cid, None)
        self._notify_all()

    def live_pool_state(self) -> typing.Any:
        """Current pool snapshot, outside any barrier — the job-end final
        snapshot path (checkpoint races with completion)."""
        with self._lock:
            return self._pool_state_locked()

    def _pool_state_locked(self) -> typing.Any:
        if self._enumerator is not None:
            return self._enumerator.snapshot_state()
        # Nothing dispensed yet: the pool is whatever restore delivered
        # (None = the source's fresh split set).
        return self._restored_state if self._has_restored_state else None

    def _notify_all(self) -> None:
        for mailbox in self._mailboxes.values():
            mailbox.notify()
