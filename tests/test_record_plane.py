"""High-throughput record plane (ISSUE 8): frame coalescing, the
selectors-based reactor, the columnar serde fast path, and same-host
shared-memory channels.

The framing edge cases the coalesced plane must pin:

- stream order and barrier alignment are byte-identical to the
  per-record wire (control elements force a flush ahead of themselves);
- peer death mid-coalesced-frame raises (no silent truncation — a lost
  half-frame must never pass as a clean close);
- decoded out-of-band buffers stay WRITABLE (in-place user code must
  not break only in distributed runs);
- the shm ring carries exactly the TCP frames and cleans up its tmpfs
  file;
- the sanitizer reports zero violations on the reactor paths.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from flink_tensorflow_tpu.core import elements as el
from flink_tensorflow_tpu.core.channels import InputGate
from flink_tensorflow_tpu.core.shuffle import (
    ColumnarFrame,
    RemoteChannelWriter,
    ShuffleServer,
    _send_obj,
    encode_obj_frame,
)
from flink_tensorflow_tpu.metrics.registry import MetricRegistry
from flink_tensorflow_tpu.native.ring import ShmByteRing, shm_dir
from flink_tensorflow_tpu.tensors import TensorValue


def _tv(i, n=16):
    return TensorValue({"x": np.full(n, i, np.float32)}, {"i": i})


def _server(gate, metrics=None, **kw):
    server = ShuffleServer("127.0.0.1", metrics=metrics, **kw)
    server.register_gate("op", 0, gate)
    server.start()
    return server


def _writer(port, metrics=None, **kw):
    return RemoteChannelWriter("127.0.0.1", port, "op", 0, 0,
                               connect_timeout_s=10.0, metrics=metrics, **kw)


def _drain(gate, n, timeout=15.0):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        item = gate.poll(timeout=0.5)
        if item is not None:
            out.append(item[1])
    return out


def _await_metric(reg, key, want, timeout=5.0):
    """Sender-side counters tick right AFTER the send; the receiver can
    deliver first — wait the metric out instead of racing it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if reg.report().get(key) == want:
            return reg.report()
        time.sleep(0.01)
    return reg.report()


class TestCoalescing:
    def test_barrier_forces_flush_order_preserved(self):
        """Acceptance: barrier-through-coalesced-frame.  Records buffered
        ahead of a barrier flush BEFORE it; records after it stay after —
        alignment sees exactly the per-record wire's stream order."""
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=1 << 20, flush_ms=10_000.0)
        try:
            for i in range(5):
                w.write(el.StreamRecord(_tv(i)))
            w.write(el.CheckpointBarrier(1))
            for i in range(5, 8):
                w.write(el.StreamRecord(_tv(i)))
            w.write(el.EndOfPartition())
            got = _drain(gate, 10)
            kinds = [type(e) for e in got]
            assert kinds == [el.StreamRecord] * 5 + [el.CheckpointBarrier] \
                + [el.StreamRecord] * 3 + [el.EndOfPartition]
            assert [e.value.meta["i"] for e in got[:5]] == list(range(5))
            assert got[5].checkpoint_id == 1
            assert [e.value.meta["i"] for e in got[6:9]] == [5, 6, 7]
        finally:
            w.close()
            server.close()

    def test_flush_reason_attribution(self):
        reg = MetricRegistry()
        gate = InputGate(1, capacity=256)
        server = _server(gate, metrics=reg)
        w = _writer(server.port, metrics=reg,
                    flush_bytes=2_000, flush_ms=10_000.0)
        try:
            # ~16*4+64 bytes estimated per record: >= 2000 flushes on size.
            for i in range(40):
                w.write(el.StreamRecord(_tv(i)))
            w.write(el.CheckpointBarrier(1))
            w.write(el.EndOfPartition())
            got = _drain(gate, 42)
            assert len(got) == 42
            scope = "shuffle.out.op.0.ch0"
            report = _await_metric(reg, f"{scope}.records", 40)
            assert report[f"{scope}.flush_size"] >= 1
            assert report[f"{scope}.flush_barrier"] >= 1
            assert report[f"{scope}.records"] == 40
            assert report["shuffle.in.op.0.ch0.records"] == 40
            assert (report[f"{scope}.bytes"]
                    == report["shuffle.in.op.0.ch0.bytes"] > 0)
            assert report["wire.flush_total"]["count"] >= 2
        finally:
            w.close()
            server.close()

    def test_timeout_flush(self):
        reg = MetricRegistry()
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, metrics=reg,
                    flush_bytes=1 << 20, flush_ms=20.0)
        try:
            for i in range(3):
                w.write(el.StreamRecord(_tv(i)))
            # Nothing else forces a flush: only the buffer timeout can
            # deliver these.
            got = _drain(gate, 3)
            assert [e.value.meta["i"] for e in got] == [0, 1, 2]
            report = _await_metric(
                reg, "shuffle.out.op.0.ch0.flush_timeout", 1)
            assert report["shuffle.out.op.0.ch0.flush_timeout"] == 1
        finally:
            w.close()
            server.close()

    def test_timeout_flush_rearms_across_idle_gaps(self):
        """The buffer timer is ONE re-arming deadline per writer (not
        one per epoch): after a timeout flush disarms it, the next first
        buffered record must re-arm it — a record written after an idle
        gap still flushes within ~flush_ms, repeatedly."""
        reg = MetricRegistry()
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, metrics=reg,
                    flush_bytes=1 << 20, flush_ms=10.0)
        try:
            for i in range(3):
                w.write(el.StreamRecord(_tv(i)))
                got = _drain(gate, 1)
                assert [e.value.meta["i"] for e in got] == [i]
                time.sleep(0.05)  # idle past the deadline between writes
            report = _await_metric(
                reg, "shuffle.out.op.0.ch0.flush_timeout", 3)
            assert report["shuffle.out.op.0.ch0.flush_timeout"] == 3
        finally:
            w.close()
            server.close()

    def test_coalescing_disabled_is_frame_per_record(self):
        reg = MetricRegistry()
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, metrics=reg, flush_bytes=0)
        try:
            for i in range(4):
                w.write(el.StreamRecord(_tv(i)))
            w.write(el.EndOfPartition())
            got = _drain(gate, 5)
            assert len(got) == 5
        finally:
            w.close()
            server.close()

    def test_columnar_roundtrip_with_timestamps(self):
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=1 << 20, flush_ms=10_000.0)
        try:
            for i in range(6):
                w.write(el.StreamRecord(_tv(i), timestamp=0.5 * i))
            # White-box: a homogeneous run coalesces columnar.
            assert isinstance(w._coalesce(
                [el.StreamRecord(_tv(i)) for i in range(3)]), ColumnarFrame)
            w.write(el.EndOfPartition())
            got = _drain(gate, 7)
            recs = got[:6]
            assert all(isinstance(e, el.StreamRecord) for e in recs)
            for i, e in enumerate(recs):
                assert e.timestamp == 0.5 * i
                assert e.value.meta["i"] == i
                np.testing.assert_array_equal(
                    e.value["x"], np.full(16, i, np.float32))
        finally:
            w.close()
            server.close()

    def test_heterogeneous_run_falls_back_to_list(self):
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=1 << 20, flush_ms=10_000.0)
        try:
            # Mixed shapes + a plain-int record: not columnar-eligible.
            assert not isinstance(w._coalesce(
                [el.StreamRecord(_tv(0)), el.StreamRecord(7)]), ColumnarFrame)
            w.write(el.StreamRecord(_tv(0)))
            w.write(el.StreamRecord(7))
            w.write(el.StreamRecord(TensorValue(
                {"y": np.ones((2, 2), np.float64)}, {"i": 2})))
            w.write(el.EndOfPartition())
            got = _drain(gate, 4)
            assert got[0].value == _tv(0)
            assert got[1].value == 7
            assert got[2].value.meta["i"] == 2
        finally:
            w.close()
            server.close()

    def test_columnar_narrowed_wire_dtype(self):
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=1 << 20, flush_ms=10_000.0,
                    wire_dtype="bf16")
        try:
            vals = [TensorValue(
                {"x": (np.arange(16, dtype=np.float32) - 8) * (i + 1)},
                {"i": i}) for i in range(4)]
            for v in vals:
                w.write(el.StreamRecord(v))
            w.write(el.EndOfPartition())
            got = _drain(gate, 5)
            for v, e in zip(vals, got[:4]):
                assert e.value["x"].dtype == np.float32
                np.testing.assert_allclose(e.value["x"], v["x"],
                                           rtol=2 ** -7, atol=1e-6)
        finally:
            w.close()
            server.close()

    def test_decoded_oob_buffers_are_writable(self):
        """The mutable-buffer guarantee survives coalescing: numpy
        payloads reconstructed from a coalesced pickle frame's
        out-of-band buffers must be writable (in-place user code)."""
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=1 << 20, flush_ms=10_000.0)
        try:
            # Plain dict values (NOT TensorValue, whose contract is
            # immutability): arrays ride pickle-5 out-of-band.
            w.write(el.StreamRecord({"x": np.arange(1000, dtype=np.float32)}))
            w.write(el.StreamRecord({"x": np.ones(500, np.float32)}))
            w.write(el.EndOfPartition())
            got = _drain(gate, 3)
            for e in got[:2]:
                arr = e.value["x"]
                assert arr.flags.writeable
                arr += 1.0  # must not raise
        finally:
            w.close()
            server.close()


class TestTruncation:
    def _raw_conn(self, port):
        s = socket.create_connection(("127.0.0.1", port), timeout=10.0)
        _send_obj(s, ("op", 0, 0))
        return s

    def test_peer_death_mid_coalesced_frame_raises(self):
        """EOF inside a half-received coalesced frame is a loud
        transport error, never a silently truncated stream."""
        errors = []
        gate = InputGate(1)
        server = _server(gate, on_error=errors.append)
        try:
            s = self._raw_conn(server.port)
            parts, _ = encode_obj_frame(
                [el.StreamRecord(_tv(i)) for i in range(8)])
            frame = b"".join(bytes(p) for p in parts)
            s.sendall(frame[: len(frame) - 11])  # die mid-frame
            s.close()
            deadline = time.monotonic() + 10.0
            while not errors and time.monotonic() < deadline:
                time.sleep(0.02)
            assert errors, "mid-frame truncation was not reported"
            assert "truncat" in str(errors[0]) or "mid-frame" in str(errors[0])
        finally:
            server.close()

    def test_clean_eof_without_eop_is_peer_loss(self):
        errors = []
        gate = InputGate(1)
        server = _server(gate, on_error=errors.append)
        try:
            s = self._raw_conn(server.port)
            _send_obj(s, el.StreamRecord(_tv(1)))
            s.close()  # frame boundary, but no EndOfPartition
            deadline = time.monotonic() + 10.0
            while not errors and time.monotonic() < deadline:
                time.sleep(0.02)
            assert errors and "EndOfPartition" in str(errors[0])
        finally:
            server.close()


class TestBackpressure:
    def test_full_gate_pauses_and_resumes_lossless(self):
        """A tiny gate forces the reactor through its pause/resume path
        hundreds of times; every record must arrive exactly once, in
        order (the event-driven resume must not lose or reorder)."""
        gate = InputGate(1, capacity=4)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=600, flush_ms=2.0)
        n = 300

        def produce():
            for i in range(n):
                w.write(el.StreamRecord(_tv(i, n=8)))
            w.write(el.EndOfPartition())

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            got = []
            deadline = time.monotonic() + 30.0
            while len(got) < n + 1 and time.monotonic() < deadline:
                item = gate.poll(timeout=0.5)
                if item is None:
                    continue
                got.append(item[1])
                time.sleep(0.0005)  # slow consumer: keeps the gate full
            assert len(got) == n + 1
            ids = [e.value.meta["i"] for e in got[:-1]]
            assert ids == list(range(n))
            assert isinstance(got[-1], el.EndOfPartition)
        finally:
            t.join(timeout=5)
            w.close()
            server.close()


class TestShmChannel:
    def test_same_host_edge_rides_the_ring(self):
        gate = InputGate(1, capacity=256)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=4_000, flush_ms=5.0, shm=True)
        try:
            for i in range(100):
                w.write(el.StreamRecord(_tv(i)))
            w.write(el.EndOfPartition())
            got = _drain(gate, 101)
            assert len(got) == 101
            assert [e.value.meta["i"] for e in got[:-1]] == list(range(100))
            # The transport really was the ring, and its tmpfs file is
            # unlinked once the receiver saw the clean EOF after EOP.
            assert w._ring is not None
            path = w._ring.path
            assert os.path.exists(path)
            w.close()
            deadline = time.monotonic() + 5.0
            while os.path.exists(path) and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not os.path.exists(path)
        finally:
            w.close()
            server.close()

    def test_barriers_and_watermarks_cross_the_ring(self):
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=1 << 20, flush_ms=10_000.0,
                    shm=True)
        try:
            w.write(el.StreamRecord(_tv(0)))
            w.write(el.Watermark(1.5))
            w.write(el.CheckpointBarrier(3))
            w.write(el.StreamRecord(_tv(1)))
            w.write(el.EndOfPartition())
            got = _drain(gate, 5)
            assert [type(e) for e in got] == [
                el.StreamRecord, el.Watermark, el.CheckpointBarrier,
                el.StreamRecord, el.EndOfPartition]
            assert got[1].timestamp == 1.5 and got[2].checkpoint_id == 3
        finally:
            w.close()
            server.close()

    def test_shm_requires_local_host(self):
        w = RemoteChannelWriter("198.51.100.7", 1, "op", 0, 0, shm=True)
        assert not w.shm  # non-local peer: silently stays on TCP

    def test_doorbell_suppressed_wakes_after_idle_gaps(self):
        """Doorbell suppression: the sender rings the socket only for a
        PARKED consumer.  Bursts separated by idle gaps (consumer parks
        between them) must each wake the receiver — and a burst landing
        while the consumer drains must arrive without its own doorbell
        (suppressed count observable via the parked flag protocol)."""
        gate = InputGate(1, capacity=256)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=64, flush_ms=2.0, shm=True)
        try:
            total = 0
            for burst in range(5):
                for i in range(10):
                    w.write(el.StreamRecord(_tv(total + i)))
                total += 10
                got = _drain(gate, 10)
                assert [e.value.meta["i"] for e in got] == list(
                    range(total - 10, total))
                # Consumer drained dry -> it parked itself; the next
                # burst's first frame must ring the doorbell (or the
                # reactor poller backstop must catch it).
                time.sleep(0.03)
                assert w._ring is not None and w._ring.consumer_parked()
            w.write(el.EndOfPartition())
            assert len(_drain(gate, 1)) == 1
        finally:
            w.close()
            server.close()


class TestShmByteRing:
    def test_wraparound_parity(self):
        path = os.path.join(shm_dir(), f"ftt-test-ring-{os.getpid()}-a")
        prod = ShmByteRing.create(path, 1 << 12)
        cons = ShmByteRing.attach(path)
        try:
            rng = np.random.RandomState(3)
            frames = [bytes(rng.randint(0, 256, rng.randint(1, 900),
                                        dtype=np.uint8)) for _ in range(300)]
            got, pending, it = [], None, iter(frames)
            while len(got) < len(frames):
                if pending is None:
                    pending = next(it, None)
                if pending is not None and prod.try_write(pending):
                    pending = None
                frame = cons.read()
                if frame is not None:
                    got.append(bytes(frame))
            assert got == frames
        finally:
            cons.close(unlink=True)
            prod.close()
        assert not os.path.exists(path)

    def test_oversized_frame_rejected(self):
        path = os.path.join(shm_dir(), f"ftt-test-ring-{os.getpid()}-b")
        ring = ShmByteRing.create(path, 1 << 10)
        try:
            with pytest.raises(ValueError, match="exceeds"):
                ring.try_write(b"x" * (1 << 11))
        finally:
            ring.close(unlink=True)

    def test_full_ring_reports_false(self):
        path = os.path.join(shm_dir(), f"ftt-test-ring-{os.getpid()}-c")
        ring = ShmByteRing.create(path, 1 << 10)
        try:
            writes = 0
            while ring.try_write(b"y" * 100):
                writes += 1
            assert 0 < writes <= (1 << 10) // 104 + 1
            ring.read()
            assert ring.try_write(b"y" * 100)  # space reclaimed
        finally:
            ring.close(unlink=True)


from flink_tensorflow_tpu.core import functions as fn  # noqa: E402


class _Doubler(fn.ProcessFunction):
    def process_element(self, value, ctx, out):
        out.collect(TensorValue({"v": value["v"] * 2},
                                {"key": int(value.meta["key"])}))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class TestSanitizerCleanOnReactorPaths:
    def test_two_process_cohort_in_threads_zero_violations(self):
        """Acceptance: FLINK_TPU_SANITIZE semantics (JobConfig.sanitize)
        report zero violations with the reactor receive path feeding
        instrumented gates.  Two cohort 'processes' run as threads in
        this process — real TCP/shm channels, real barriers."""
        from flink_tensorflow_tpu import (
            DistributedConfig,
            StreamExecutionEnvironment,
        )

        ports = _free_ports(2)
        peers = tuple(f"127.0.0.1:{p}" for p in ports)
        n, num_keys = 120, 4
        outs = {0: [], 1: []}
        errors = []

        def run(proc):
            try:
                env = StreamExecutionEnvironment(parallelism=1)
                env.set_distributed(DistributedConfig(proc, 2, peers))
                env.configure(sanitize=True)
                records = [
                    TensorValue({"v": np.int64(i)}, {"key": i % num_keys})
                    for i in range(n)
                ]
                collected = (
                    env.from_collection(records, parallelism=1)
                    .key_by(lambda r: int(r.meta["key"]))
                    .process(_Doubler(), name="bump", parallelism=2)
                    .sink_to_list(parallelism=2)
                )
                env.execute(timeout=90)
                outs[proc].extend(collected)
            except BaseException as exc:  # noqa: BLE001
                errors.append((proc, exc))

        threads = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, f"cohort failed under sanitizer: {errors}"
        got = sorted(int(v["v"]) for v in outs[0] + outs[1])
        assert got == sorted(2 * i for i in range(n))


class TestRemoteSinkCoalescing:
    def _pipe(self, sink_kwargs, n=60):
        from flink_tensorflow_tpu import StreamExecutionEnvironment
        from flink_tensorflow_tpu.io.remote import RemoteSink, RemoteSource

        source = RemoteSource(bind="127.0.0.1")
        records = [
            TensorValue({"x": np.full(8, i, np.float32)}, {"i": i})
            for i in range(n)
        ]

        def upstream():
            env = StreamExecutionEnvironment(parallelism=1)
            (
                env.from_collection(records)
                .add_sink(RemoteSink("127.0.0.1", source.port, **sink_kwargs))
            )
            env.execute(timeout=60)

        t = threading.Thread(target=upstream)
        t.start()
        env2 = StreamExecutionEnvironment(parallelism=1)
        out = env2.from_source(source).sink_to_list()
        env2.execute(timeout=60)
        t.join()
        assert [r.meta["i"] for r in out] == list(range(n))
        return out, records

    def test_coalesced_columnar_pipe(self):
        out, records = self._pipe(dict(flush_bytes=2_000, flush_ms=50.0))
        for got, want in zip(out, records):
            np.testing.assert_array_equal(got["x"], want["x"])

    def test_flush_ms_zero_is_per_record(self):
        self._pipe(dict(flush_ms=0.0))

    def test_close_flushes_partial_buffer(self):
        # Huge thresholds: ONLY the sink's close() can deliver these.
        self._pipe(dict(flush_bytes=1 << 30, flush_ms=10_000.0), n=10)

    def test_narrowed_columnar_pipe(self):
        out, records = self._pipe(
            dict(flush_bytes=2_000, flush_ms=50.0, wire_dtype="bf16"))
        for got, want in zip(out, records):
            np.testing.assert_allclose(got["x"], want["x"], rtol=2 ** -7,
                                       atol=1e-6)


class TestFlowControl:
    """Credit-based flow control on the shuffle plane (ISSUE 14): a
    stalled consumer must park the producer within one credit window —
    bounded sender memory — while preserving lossless in-order delivery,
    barrier/EOS bypass, and replenish-on-drain.

    TCP credit mode needs a reactor (the grant lane rides the event
    loop, exactly as in the distributed executor); the shm path needs
    none (grants ride the ring's credit cell)."""

    @pytest.fixture()
    def reactor(self):
        from flink_tensorflow_tpu.core.reactor import Reactor

        r = Reactor()
        r.start()
        yield r
        r.close()

    def test_stalled_consumer_bounds_sender_queue(self, reactor):
        """Acceptance: with flow control on, a stalled consumer bounds
        the sender's send-queue high-water mark at the credit window;
        the producer thread demonstrably parks instead of buffering."""
        from flink_tensorflow_tpu.core.shuffle import credit_window

        reg = MetricRegistry()
        gate = InputGate(1, capacity=64)
        window = credit_window(64)
        server = _server(gate, metrics=reg)
        w = _writer(server.port, metrics=reg, flush_bytes=1024,
                    flush_ms=0.0, flow_control=True, reactor=reactor)
        n = 300
        written = [0]

        def produce():
            for i in range(n):
                w.write(el.StreamRecord(_tv(i)))
                written[0] += 1
            w.write(el.EndOfPartition())

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            time.sleep(1.0)  # consumer fully stalled
            assert written[0] < n, "producer must park at zero credit"
            bound = window * (1024 + 1024)
            assert w._conn is not None
            assert w._conn.peak_send_queue_bytes <= bound
            got = _drain(gate, n + 1, timeout=30.0)
            assert len(got) == n + 1
            assert [e.value.meta["i"] for e in got[:-1]] == list(range(n))
            assert isinstance(got[-1], el.EndOfPartition)
            # The bound held for the WHOLE run, not just the stall.
            assert w._conn.peak_send_queue_bytes <= bound
            report = reg.report()
            scope = "shuffle.out.op.0.ch0"
            assert report[f"{scope}.credit_starved_s"] > 0.3
            assert report["shuffle.in.op.0.ch0.credit_grants"] > 0
        finally:
            t.join(timeout=10)
            w.close()
            server.close()

    def test_flow_control_off_queue_grows_unbounded(self, reactor):
        """The control arm: same stall WITHOUT credits — the producer
        never parks and the sender queue grows far past the window."""
        gate = InputGate(1, capacity=64)
        server = _server(gate)
        w = _writer(server.port, flush_bytes=1024, flush_ms=0.0,
                    reactor=reactor)
        n = 1500
        try:
            for i in range(n):  # ~1KB records: ~1.5MB total, no parking
                w.write(el.StreamRecord(_tv(i, n=256)))
            # Producer finished with the consumer fully stalled: the
            # backlog lives in the sender queue + kernel buffers.
            assert w._conn is not None
            assert w._conn.peak_send_queue_bytes > 50_000
            got = _drain(gate, n, timeout=30.0)
            assert len(got) == n
        finally:
            w.close()
            server.close()

    def test_barrier_bypasses_zero_credit_and_drain_replenishes(
            self, reactor):
        """A zero-credit edge must never wedge alignment: with the
        window exhausted and replenish withheld (gate at high water),
        barrier + EOP still go through; draining the gate replenishes
        the window."""
        from flink_tensorflow_tpu.core.shuffle import credit_window

        reg = MetricRegistry()
        gate = InputGate(1, capacity=4)  # low_water 2, window 2
        assert credit_window(4) == 2
        server = _server(gate, metrics=reg)
        w = _writer(server.port, metrics=reg, flush_bytes=1,
                    flush_ms=0.0, flow_control=True, reactor=reactor)
        try:
            # Sequenced writes so the credit ledger is deterministic:
            # rec0 drains below low water -> replenished (back to 2);
            # rec1/rec2 put the gate AT/OVER low water -> withheld.
            # Net: 3 spent, 1 granted, window 2 -> exactly zero left.
            for i in range(3):
                w.write(el.StreamRecord(_tv(i)))
                deadline = time.monotonic() + 5.0
                while gate.depth < i + 1 and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert gate.depth == i + 1
                time.sleep(0.05)  # let the grant (if any) land
            assert w._fc_credits_now() == 0

            done = threading.Event()

            def control_plane():
                w.write(el.CheckpointBarrier(7))
                w.write(el.EndOfPartition())
                done.set()

            t = threading.Thread(target=control_plane, daemon=True)
            t.start()
            # Bypass/overdraw: control elements cross a zero-credit
            # edge without waiting for the consumer.
            assert done.wait(timeout=5.0), \
                "barrier/EOS wedged on a zero-credit edge"
            got = _drain(gate, 5)
            assert [type(e) for e in got] == [
                el.StreamRecord, el.StreamRecord, el.StreamRecord,
                el.CheckpointBarrier, el.EndOfPartition]
            assert got[3].checkpoint_id == 7
            t.join(timeout=5)
        finally:
            w.close()
            server.close()

    def test_shm_ring_credits_park_and_recover(self):
        """Same-host shm edge: credits ride the ring's cumulative grant
        cell instead of grant frames; a stalled consumer parks the
        producer, draining recovers it losslessly."""
        reg = MetricRegistry()
        gate = InputGate(1, capacity=64)
        server = _server(gate, metrics=reg)
        w = _writer(server.port, metrics=reg, flush_bytes=1024,
                    flush_ms=0.0, shm=True, flow_control=True)
        n = 300
        written = [0]

        def produce():
            for i in range(n):
                w.write(el.StreamRecord(_tv(i)))
                written[0] += 1
            w.write(el.CheckpointBarrier(3))
            w.write(el.EndOfPartition())

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            time.sleep(1.0)
            assert w._ring is not None, "same-host edge must ride the ring"
            assert written[0] < n, "producer must park on ring credits"
            got = _drain(gate, n + 2, timeout=30.0)
            assert len(got) == n + 2
            assert [e.value.meta["i"] for e in got[:n]] == list(range(n))
            assert isinstance(got[n], el.CheckpointBarrier)
            assert isinstance(got[n + 1], el.EndOfPartition)
            assert reg.report()["shuffle.out.op.0.ch0.credit_starved_s"] > 0.3
        finally:
            t.join(timeout=10)
            w.close()
            server.close()

    def test_stale_generation_grants_dropped(self):
        """Fault plane: a zombie connection's grant arriving after the
        writer reconnected (its generation retired) must be dropped —
        stale credits can never be spent against the new transport."""
        from flink_tensorflow_tpu.core.shuffle import CREDIT_GRANT

        w = RemoteChannelWriter("127.0.0.1", 1, "op", 0, 0)
        with w._fc_cv:
            w._fc_gen = 3
            w._fc_credits = 0
        # Grant carrying the CURRENT generation: credited.
        w._on_grant(((CREDIT_GRANT, 5),), 3)
        assert w._fc_credits == 5
        # Zombie grant from the torn-down generation: dropped.
        w._on_grant(((CREDIT_GRANT, 100),), 2)
        assert w._fc_credits == 5
