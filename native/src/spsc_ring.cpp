// SPSC ring arena — the native data plane for tensor records.
//
// The reference's data plane is Flink's Netty shuffle (C/JVM native,
// SURVEY.md §2 "Distributed communication backend"); this is the
// TPU-framework equivalent for the in-process hop between a stream
// subtask and a model operator: a lock-free single-producer /
// single-consumer ring of fixed-size record slots backed by one
// contiguous arena.
//
// The point is zero-copy batch assembly (BASELINE.json north_star:
// "zero-copy Row<->DeviceArray marshalling"): the producer writes each
// record's tensor bytes directly into its slot; the consumer claims N
// CONTIGUOUS slots at once, and the Python side wraps them as one
// [N, ...] numpy view — the batch that jax.device_put ships to HBM with
// no intermediate stacking copy.
//
// Memory model: standard C++11 acquire/release SPSC queue.  head_ is
// only written by the consumer, tail_ only by the producer.  Slot
// payloads are published by the release store to tail_ and observed via
// the acquire load in ring_poppable().

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

namespace {

struct Ring {
  uint64_t slot_size;   // bytes per record slot
  uint64_t n_slots;     // power of two
  uint64_t mask;        // n_slots - 1
  uint8_t* arena;       // slot_size * n_slots bytes
  alignas(64) std::atomic<uint64_t> head;  // next slot to consume
  alignas(64) std::atomic<uint64_t> tail;  // next slot to produce
};

}  // namespace

extern "C" {

// Create a ring with n_slots (rounded up to a power of two) of slot_size
// bytes.  Returns nullptr on allocation failure.
Ring* ring_create(uint64_t slot_size, uint64_t n_slots) {
  uint64_t pow2 = 1;
  while (pow2 < n_slots) pow2 <<= 1;
  Ring* r = new (std::nothrow) Ring();
  if (!r) return nullptr;
  r->slot_size = slot_size;
  r->n_slots = pow2;
  r->mask = pow2 - 1;
  // 64-byte alignment: slot 0 starts cacheline-aligned, and typical
  // record shapes keep rows well-aligned for the numpy views.  The
  // SIZE must also be a 64-multiple — aligned_alloc with a size that is
  // not a multiple of the alignment is UB per C11/C++17 (NULL on
  // conforming allocators); the Python layout always 64-rounds slot
  // sizes, but the C ABI must not depend on that.
  uint64_t bytes = (slot_size * pow2 + 63u) & ~uint64_t{63};
  r->arena = static_cast<uint8_t*>(aligned_alloc(64, bytes));
  if (!r->arena) {
    delete r;
    return nullptr;
  }
  r->head.store(0, std::memory_order_relaxed);
  r->tail.store(0, std::memory_order_relaxed);
  return r;
}

void ring_destroy(Ring* r) {
  if (!r) return;
  free(r->arena);
  delete r;
}

uint8_t* ring_arena(Ring* r) { return r->arena; }
uint64_t ring_slot_size(Ring* r) { return r->slot_size; }
uint64_t ring_capacity(Ring* r) { return r->n_slots; }

// Producer: reserve the next slot for writing.  Returns the slot index
// (0..n_slots-1) or -1 if the ring is full.  The producer must write the
// payload into the slot and then call ring_push_commit exactly once.
int64_t ring_push_reserve(Ring* r) {
  uint64_t tail = r->tail.load(std::memory_order_relaxed);
  uint64_t head = r->head.load(std::memory_order_acquire);
  if (tail - head >= r->n_slots) return -1;  // full
  return static_cast<int64_t>(tail & r->mask);
}

// Producer: publish the reserved slot (payload must be fully written).
void ring_push_commit(Ring* r) {
  r->tail.fetch_add(1, std::memory_order_release);
}

// Consumer: how many records are ready.
uint64_t ring_poppable(Ring* r) {
  uint64_t tail = r->tail.load(std::memory_order_acquire);
  uint64_t head = r->head.load(std::memory_order_relaxed);
  return tail - head;
}

// (No pop_claim in the C ABI: overlapping claims — several dispatched
// batches in flight — need a claim cursor independent of head, which
// lives in the Python TensorRing layer; a head-based claim here would
// silently double-claim on repeated calls.)

// Consumer: free the OLDEST claimed slots for reuse (releases are
// strictly FIFO with respect to the TensorRing layer's claims).
void ring_pop_release(Ring* r, uint64_t count) {
  r->head.fetch_add(count, std::memory_order_release);
}

}  // extern "C"
