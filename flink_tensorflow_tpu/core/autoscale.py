"""Autoscaling — the health plane's first actuator.

Closes the loop the observability stack built: the process-0
:class:`~flink_tensorflow_tpu.metrics.health.HealthEvaluator` rolls the
cohort's merged metric feed into OK/WARN/BREACH states, and on a
SUSTAINED breach of a scaling rule this module drives the existing
recovery machinery end to end:

    breach sustained -> decision recorded -> cohort stop (rescale exit
    code) -> supervisor respawns at the new worker count (attempt
    threaded into ``restart_epoch`` per the zombie-fencing contract) ->
    workers restore from the latest COMMON checkpoint, keyed state
    redistributing by key group.

Two halves, two processes:

- :class:`AutoscaleActuator` runs INSIDE the process-0 worker (wired by
  ``execute_async`` when ``JobConfig.health.autoscale`` is set, or
  hand-held by a worker script).  Level-triggered on evaluator ticks,
  it picks the worst active breach with a scaling action, applies
  cooldown + min/max bounds + the completed-checkpoint gate (acting
  before a restore point exists would lose records), writes one
  decision file atomically, records the decision (inputs, rule,
  verdict) on the flight recorder, and invokes ``on_decision`` —
  typically "cancel the job and exit with the rescale code".

- :class:`AutoscaleSupervisor` runs in the PARENT (a
  ``parallel.CohortSupervisor`` subclass): a worker exiting with
  ``rescale_exit_code`` (or a fresh decision file appearing — the peers
  of the deciding worker die with ordinary codes when the cohort stops)
  is a rescale request, not a failure; the supervisor clamps the target
  again (defense in depth — the decision file crossed a process
  boundary), respawns the cohort at the new shape with a fresh restart
  budget, and books every consumed decision into its outcome.

Every decision is explainable post-hoc: the decision file carries the
rule, the observed value, the health rollup at decision time, and the
restore point; the same facts land on the flight ring, so
``flink-tpu-doctor`` can correlate "what breached" with "what the
supervisor did about it".
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
import typing

from flink_tensorflow_tpu.parallel.supervisor import (
    CohortFailed,
    CohortSupervisor,
)

logger = logging.getLogger(__name__)

#: EX_TEMPFAIL: the conventional "stopped on purpose, run me again"
#: exit — distinguishable from crashes (tracebacks exit 1, signals
#: negative) without colliding with shell/errno codes.
RESCALE_EXIT_CODE = 75

DECISION_KIND = "flink-tpu-autoscale-decision"


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Actuator policy knobs (``JobConfig.health.autoscale``)."""

    #: Worker-count bounds the actuator may decide within.
    min_workers: int = 1
    max_workers: int = 4
    #: Workers added (scale_up) / removed (scale_down) per decision.
    step: int = 1
    #: Seconds from actuator start before it may act — the warmup after
    #: a (re)spawn AND the cooldown between consecutive rescales, since
    #: every rescale restarts the actuator with the cohort.
    cooldown_s: float = 10.0
    #: Where the decision file lands (the supervisor reads it back);
    #: None keeps decisions in memory/flight only — fine for tests and
    #: for integrations that act through ``on_decision`` alone.
    decision_path: typing.Optional[str] = None
    #: Refuse to act until a completed checkpoint exists: stopping a
    #: cohort with no restore point would replay from scratch (or lose
    #: exactly-once output entirely).
    require_checkpoint: bool = True
    rescale_exit_code: int = RESCALE_EXIT_CODE

    def validate(self) -> "AutoscaleConfig":
        if self.min_workers < 1:
            raise ValueError(
                f"autoscale.min_workers must be >= 1, got {self.min_workers}")
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"autoscale.max_workers must be >= min_workers, got "
                f"{self.max_workers} < {self.min_workers}")
        if self.step < 1:
            raise ValueError(f"autoscale.step must be >= 1, got {self.step}")
        if self.cooldown_s < 0:
            raise ValueError(
                f"autoscale.cooldown_s must be >= 0, got {self.cooldown_s}")
        return self


@dataclasses.dataclass(frozen=True)
class AutoscaleDecision:
    """One scaling verdict, fully explainable: the rule that breached,
    the value it saw, the shape change, the restore point, and the
    health rollup at decision time."""

    rule_id: str
    target: str
    action: str
    value: float
    from_workers: int
    to_workers: int
    ts: float
    checkpoint_id: typing.Optional[int] = None
    health: typing.Mapping[str, typing.Any] = dataclasses.field(
        default_factory=dict)

    def to_dict(self) -> typing.Dict[str, typing.Any]:
        d = dataclasses.asdict(self)
        d["kind"] = DECISION_KIND
        return d


def write_decision(path: str, decision: AutoscaleDecision) -> str:
    """Atomic decision-file write (the supervisor may poll mid-write)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(decision.to_dict(), f)
    os.replace(tmp, path)
    return path


def read_decision(path: str) -> typing.Optional[typing.Dict[str, typing.Any]]:
    """The decision dict at ``path``, or None (absent / torn / not a
    decision file — the supervisor treats all three as 'no request')."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("kind") != DECISION_KIND:
        return None
    return doc


def checkpoint_gate(checkpoint_dir: typing.Optional[str]
                    ) -> typing.Callable[[], typing.Optional[int]]:
    """The default ``checkpoint_ready`` probe: latest COMPLETED id in
    this process's checkpoint dir (None before the first one lands)."""
    def probe() -> typing.Optional[int]:
        if checkpoint_dir is None:
            return None
        from flink_tensorflow_tpu.checkpoint.store import latest_checkpoint_id

        try:
            return latest_checkpoint_id(checkpoint_dir)
        except OSError:
            return None
    return probe


class AutoscaleActuator:
    """In-job half: turns sustained breaches into ONE decision.

    Subscribe it to the evaluator (``evaluator.subscribe_ticks(
    actuator.on_tick)``): level-triggered re-evaluation means a
    decision deferred by the cooldown or the checkpoint gate fires on a
    later tick while the breach holds, instead of being lost with the
    transition edge.  One decision per actuator life — after deciding,
    the process's job is to stop; the respawned cohort gets a fresh
    actuator (and the cooldown starts over, damping rescale cascades).
    """

    def __init__(
        self,
        config: AutoscaleConfig,
        num_workers: int,
        *,
        checkpoint_ready: typing.Optional[
            typing.Callable[[], typing.Optional[int]]] = None,
        on_decision: typing.Optional[
            typing.Callable[[AutoscaleDecision], None]] = None,
        flight: typing.Optional[typing.Any] = None,
        clock: typing.Callable[[], float] = time.monotonic,
    ):
        self.config = config.validate()
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.num_workers = num_workers
        self.checkpoint_ready = checkpoint_ready
        self.on_decision = on_decision
        self.flight = flight
        self._clock = clock
        self._ready_at = clock() + self.config.cooldown_s
        #: The one decision this actuator made (None until then).
        self.decision: typing.Optional[AutoscaleDecision] = None
        #: Why the last tick did NOT act ("cooldown", "no-checkpoint",
        #: "at-bounds", "no-breach", or "decided") — test/doctor visibility.
        self.last_verdict = "no-breach"

    def _target_workers(self, action: str) -> int:
        cfg = self.config
        delta = cfg.step if action == "scale_up" else -cfg.step
        return max(cfg.min_workers, min(cfg.max_workers,
                                        self.num_workers + delta))

    def on_tick(self, evaluator) -> None:
        if self.decision is not None:
            self.last_verdict = "decided"
            return
        breaches = [(rule, target, value)
                    for rule, target, value in evaluator.active_breaches()
                    if rule.action in ("scale_up", "scale_down")
                    and value is not None]
        if not breaches:
            self.last_verdict = "no-breach"
            return
        # Worst first: scale_up outranks scale_down (saturation beats
        # thrift), then by how far past the breach threshold.
        def severity(b):
            rule, _target, value = b
            over = (value - rule.breach) if rule.cmp == ">" else (rule.breach - value)
            return (rule.action == "scale_up", over)

        rule, target, value = max(breaches, key=severity)
        if self._clock() < self._ready_at:
            self.last_verdict = "cooldown"
            return
        cid = self.checkpoint_ready() if self.checkpoint_ready else None
        if self.config.require_checkpoint and cid is None:
            self.last_verdict = "no-checkpoint"
            return
        to_workers = self._target_workers(rule.action)
        if to_workers == self.num_workers:
            self.last_verdict = "at-bounds"
            return
        decision = AutoscaleDecision(
            rule_id=rule.id, target=target, action=rule.action,
            value=value, from_workers=self.num_workers,
            to_workers=to_workers, ts=time.time(), checkpoint_id=cid,
            health=evaluator.health(),
        )
        self.decision = decision
        self.last_verdict = "decided"
        if self.config.decision_path is not None:
            try:
                write_decision(self.config.decision_path, decision)
            except OSError:
                logger.warning("autoscale decision write to %s failed",
                               self.config.decision_path, exc_info=True)
        if self.flight is not None:
            self.flight.record("autoscale", "decision", {
                "rule": rule.id, "target": target, "action": rule.action,
                "value": value, "from_workers": decision.from_workers,
                "to_workers": to_workers, "checkpoint_id": cid})
        logger.warning(
            "autoscale decision: %s breached on %s (value=%.4g) — "
            "%d -> %d workers (restore from checkpoint %s)",
            rule.id, target, value, decision.from_workers, to_workers, cid)
        if self.on_decision is not None:
            self.on_decision(decision)


@dataclasses.dataclass(frozen=True)
class AutoscaleOutcome:
    """Result of supervising an autoscaling cohort to completion."""

    attempts: int
    returncode: int
    num_workers: int
    #: Decision dicts consumed, oldest first — ``len`` is the rescale
    #: count the closed-loop tests assert on.
    rescales: typing.Tuple[typing.Dict[str, typing.Any], ...] = ()


class AutoscaleSupervisor(CohortSupervisor):
    """Parent half: a :class:`~flink_tensorflow_tpu.parallel.supervisor.
    CohortSupervisor` whose restart loop understands rescale requests.

    ``command(worker_id, num_workers, attempt)`` must thread ``attempt``
    into ``DistributedConfig.restart_epoch`` (the PR-11 fencing
    contract) and have workers restore from the latest COMMON
    checkpoint on ``attempt > 0`` — the same contract as plain cohort
    supervision; the only new behavior is the shape change.
    """

    def __init__(
        self,
        command: typing.Callable[[int, int, int], typing.Sequence[str]],
        num_workers: int,
        *,
        decision_path: str,
        min_workers: int = 1,
        max_workers: typing.Optional[int] = None,
        max_rescales: int = 3,
        rescale_exit_code: int = RESCALE_EXIT_CODE,
        env: typing.Optional[typing.Callable[
            [int, int, int], typing.Mapping[str, str]]] = None,
        max_restarts: int = 2,
        poll_s: float = 0.1,
        kill_grace_s: float = 5.0,
        attempt_timeout_s: typing.Optional[float] = None,
    ):
        super().__init__(
            command, num_workers, env=env, max_restarts=max_restarts,
            poll_s=poll_s, kill_grace_s=kill_grace_s,
            attempt_timeout_s=attempt_timeout_s,
            min_workers=min_workers,
        )
        self.decision_path = decision_path
        self.max_workers = max_workers if max_workers is not None else num_workers
        if self.max_workers < num_workers:
            raise ValueError(
                f"max_workers must be >= num_workers, got "
                f"{self.max_workers} < {num_workers}")
        self.max_rescales = max_rescales
        self.rescale_exit_code = rescale_exit_code

    def _fresh_decision(self, after_ts: float) -> typing.Optional[dict]:
        doc = read_decision(self.decision_path)
        if doc is None or float(doc.get("ts", 0.0)) <= after_ts:
            return None
        return doc

    def run(self) -> AutoscaleOutcome:  # type: ignore[override]
        shape = self.num_workers
        attempt = 0
        budget = self.max_restarts + 1
        rescales: typing.List[dict] = []
        consumed_ts = 0.0
        last_rc = -1
        while True:
            rc = self._run_attempt(attempt, shape)
            attempt += 1
            if rc == 0:
                return AutoscaleOutcome(
                    attempts=attempt, returncode=0, num_workers=shape,
                    rescales=tuple(rescales))
            last_rc = rc
            # A rescale request: the deciding worker's exit code, or —
            # when a peer's teardown code surfaced first — the fresh
            # decision file on its own.  Either way the decision is the
            # authority; its target is re-clamped here because it
            # crossed a process boundary.
            decision = self._fresh_decision(consumed_ts)
            if decision is not None and len(rescales) < self.max_rescales:
                consumed_ts = float(decision.get("ts", 0.0))
                target = max(self.min_workers,
                             min(self.max_workers,
                                 int(decision.get("to_workers", shape))))
                rescales.append(decision)
                logger.warning(
                    "autoscale: consuming decision (%s on %s) — respawning "
                    "cohort at %d workers (was %d), attempt %d",
                    decision.get("rule_id"), decision.get("target"),
                    target, shape, attempt)
                shape = target
                budget = self.max_restarts + 1
                continue
            if rc == self.rescale_exit_code:
                # Rescale exit with no readable decision: the file was
                # lost/torn.  Respawn at the same shape (costs restart
                # budget) rather than guessing a target.
                logger.warning(
                    "autoscale: worker requested rescale but no decision "
                    "file at %s — respawning unchanged", self.decision_path)
            budget -= 1
            if budget <= 0:
                raise CohortFailed(attempt, last_rc)
