"""Training-operator tests: per-key online SGD (Wide&Deep shape,
BASELINE.json:10) and the DP gang operator (ResNet shape, BASELINE.json:11)
on the virtual 8-device mesh."""

import numpy as np
import pytest

import jax
import optax

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.functions import DPTrainWindowFunction, OnlineTrainFunction
from flink_tensorflow_tpu.models import get_model_def
from flink_tensorflow_tpu.parallel import make_mesh
from flink_tensorflow_tpu.tensors import RecordSchema, TensorValue, spec


def widedeep_tiny():
    return get_model_def("widedeep", hash_buckets=50, embed_dim=4,
                         num_cat_slots=2, num_dense=3, num_wide=8, hidden=(8,))


def widedeep_train_schema():
    return RecordSchema({
        "wide": spec((8,)),
        "dense": spec((3,)),
        "cat": spec((2,), np.int32),
        "label": spec((), np.int32),
    })


def make_records(n, seed=0, users=("a", "b")):
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        user = users[i % len(users)]
        label = 1 if user == "a" else 0  # separable by user -> loss must fall
        recs.append(TensorValue({
            "wide": (rng.rand(8) * (1 + label)).astype(np.float32),
            "dense": rng.rand(3).astype(np.float32),
            "cat": rng.randint(0, 50, (2,)).astype(np.int32),
            "label": np.int32(label),
        }, meta={"user": user}))
    return recs


class _StubMetrics:
    @staticmethod
    def meter(name):
        class M:
            @staticmethod
            def mark(n):
                pass
        return M

    @staticmethod
    def counter(name):
        class C:
            @staticmethod
            def inc(n=1):
                pass
        return C


class _StubCtx:
    subtask_index = 0
    metrics = _StubMetrics


class _StubPCtx:
    current_key = "a"


class _ListOut:
    def __init__(self):
        self.items = []

    def collect(self, v, ts=None):
        self.items.append(v)


class TestOnlineTrain:
    def test_keyed_online_sgd_loss_decreases(self):
        env = StreamExecutionEnvironment(parallelism=1)
        f = OnlineTrainFunction(
            widedeep_tiny(), optax.adam(5e-2),
            train_schema=widedeep_train_schema(), mini_batch=4,
        )
        out = (
            env.from_collection(make_records(80))
            .key_by(lambda r: r.meta["user"])
            .process(f, name="train")
            .sink_to_list()
        )
        env.execute(timeout=300)
        losses = [float(r["loss"]) for r in out]
        assert len(losses) == 20  # 80 records / mini_batch 4
        assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses

    def test_per_key_scope_independent_models(self):
        env = StreamExecutionEnvironment(parallelism=1)
        f = OnlineTrainFunction(
            widedeep_tiny(), optax.sgd(1e-2),
            train_schema=widedeep_train_schema(), scope="key", mini_batch=2,
        )
        out = (
            env.from_collection(make_records(12, users=("a", "b", "c")))
            .key_by(lambda r: r.meta["user"])
            .process(f, name="train")
            .sink_to_list()
        )
        env.execute(timeout=300)
        # 3 keys x 4 records each / mini_batch 2 = 6 steps; per-key step
        # counters advance independently (each reaches 2).
        by_key = {}
        for r in out:
            by_key.setdefault(r.meta["key"], []).append(int(r["step"]))
        assert set(by_key) == {"a", "b", "c"}
        for steps in by_key.values():
            assert steps == [1, 2]

    def test_snapshot_restore_roundtrip(self):
        f = OnlineTrainFunction(
            widedeep_tiny(), optax.sgd(1e-2),
            train_schema=widedeep_train_schema(), mini_batch=2,
        )
        f.open(_StubCtx())
        out = _ListOut()
        for r in make_records(4, users=("a",)):
            f.process_element(r, _StubPCtx, out)
        # Metric emission is pipelined (dispatch-and-go); the snapshot
        # flushes everything in flight before capturing state.
        snap = f.snapshot_state()
        assert len(out.items) == 2
        assert [int(r["step"]) for r in out.items] == [1, 2]

        g = OnlineTrainFunction(
            widedeep_tiny(), optax.sgd(1e-2),
            train_schema=widedeep_train_schema(), mini_batch=2,
        )
        g.restore_state(snap)
        g.open(_StubCtx())
        leaves_f = jax.tree.leaves(f.current_params())
        leaves_g = jax.tree.leaves(g.current_params())
        for a, b in zip(leaves_f, leaves_g):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_disk_checkpoint_roundtrip_with_adam(self, tmp_path):
        """Persistence regression (ADVICE.md r1): a training snapshot must
        survive write_checkpoint → pickle → read_checkpoint with (a) the
        typed PRNG key and (b) optax's namedtuple optimizer state intact,
        and the restored function must complete a post-restore adam step."""
        from flink_tensorflow_tpu.checkpoint.store import read_checkpoint, write_checkpoint

        def make():
            return OnlineTrainFunction(
                widedeep_tiny(), optax.adam(1e-2),
                train_schema=widedeep_train_schema(), mini_batch=2,
            )

        f = make()
        f.open(_StubCtx())
        out = _ListOut()
        for r in make_records(4, users=("a",)):
            f.process_element(r, _StubPCtx, out)
        snap = f.snapshot_state()

        write_checkpoint(str(tmp_path), 1, {"train": {0: snap}})
        cid, snapshots = read_checkpoint(str(tmp_path))
        assert cid == 1

        g = make()
        g.restore_state(snapshots["train"][0])
        g.open(_StubCtx())
        # Params identical after the disk round trip...
        for a, b in zip(jax.tree.leaves(f.current_params()),
                        jax.tree.leaves(g.current_params())):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # ...and a further adam step works (namedtuple opt state preserved).
        out2 = _ListOut()
        for r in make_records(2, seed=1, users=("a",)):
            g.process_element(r, _StubPCtx, out2)
        g.on_finish(out2)
        assert len(out2.items) == 1
        # Step numbering continues from the restored state (2 steps done).
        assert int(out2.items[0]["step"]) == 3
        assert np.isfinite(float(out2.items[0]["loss"]))


class TestDPTrainGang:
    def test_gang_dp_training_loss_decreases(self):
        mesh = make_mesh({"data": 8})
        mdef = get_model_def("lenet")
        schema = RecordSchema({
            "image": spec((28, 28, 1)),
            "label": spec((), np.int32),
        })
        rng = np.random.RandomState(0)
        # Learnable mapping: label = brightness bucket
        recs = []
        for i in range(128):
            label = i % 4
            img = rng.rand(28, 28, 1).astype(np.float32) * 0.2 + label * 0.25
            recs.append(TensorValue({"image": img, "label": np.int32(label)}))

        env = StreamExecutionEnvironment(parallelism=1)
        env.set_mesh(mesh)
        f = DPTrainWindowFunction(
            mdef, optax.adam(1e-2), train_schema=schema, global_batch=32,
        )
        out = (
            env.from_collection(recs * 2)  # 256 records -> 8 steps
            .count_window(32)
            .apply(f, name="dp_train")
            .sink_to_list()
        )
        result = env.execute(timeout=600)
        losses = [float(r["loss"]) for r in out]
        assert len(losses) == 8
        assert losses[-1] < losses[0], losses
        assert result.metrics["dp_train.0.train_steps"] == 8

    def test_gang_requires_mesh(self):
        env = StreamExecutionEnvironment(parallelism=1)
        f = DPTrainWindowFunction(
            get_model_def("lenet"), train_schema=RecordSchema({
                "image": spec((28, 28, 1)), "label": spec((), np.int32)}),
            global_batch=8,
        )
        env.from_collection([TensorValue({
            "image": np.zeros((28, 28, 1), np.float32), "label": np.int32(0)
        })]).count_window(8).apply(f).sink_to_list()
        from flink_tensorflow_tpu.core.runtime import JobFailure

        with pytest.raises(JobFailure):
            env.execute(timeout=60)


class TestFusedOnlineSteps:
    """steps_per_dispatch fuses K SGD steps into one lax.scan dispatch;
    the step sequence must match the unfused path (float rounding may
    differ across executables) and partial chunks must flush."""

    def _run(self, k, n=24):
        env = StreamExecutionEnvironment(parallelism=1)
        out = (
            env.from_collection(make_records(n, users=("a",)))
            .key_by(lambda r: r.meta["user"])
            .process(
                OnlineTrainFunction(
                    widedeep_tiny(), optax.sgd(5e-2),
                    train_schema=widedeep_train_schema(), mini_batch=2,
                    steps_per_dispatch=k,
                ),
                name="train", parallelism=1,
            )
            .sink_to_list()
        )
        env.execute(timeout=300)
        return out

    def test_fused_matches_sequential(self):
        a, b = self._run(1), self._run(4)
        assert [int(r["step"]) for r in a] == [int(r["step"]) for r in b] \
            == list(range(1, 13))
        np.testing.assert_allclose([float(r["loss"]) for r in a],
                                   [float(r["loss"]) for r in b], rtol=1e-5)

    def test_partial_chunk_flushes_at_finish(self):
        # 24 records / mini_batch 2 = 12 steps; with k=5 the last fused
        # chunk holds only 2 staged steps — on_finish must run them.
        out = self._run(5)
        assert [int(r["step"]) for r in out] == list(range(1, 13))
