"""Event-time windows, watermarks, keyed reduce — the Flink streaming
semantics the reference jobs build on (SURVEY.md §1 L1)."""

import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.core import functions as fn


class CollectWindow(fn.WindowFunction):
    def process_window(self, key, window, elements, out):
        out.collect((key, window.start, sorted(elements, key=str)))


class TestEventTimeWindows:
    def test_keyed_tumbling_windows(self):
        env = StreamExecutionEnvironment(parallelism=1)
        # (key, event_time): out-of-order within 1s slack
        events = [("a", 0.5), ("b", 0.7), ("b", 0.2), ("a", 1.2),
                  ("a", 0.9), ("b", 2.1), ("a", 2.6)]
        out = (
            env.from_collection(events)
            .assign_timestamps(lambda e: e[1], out_of_orderness_s=1.0)
            .key_by(lambda e: e[0])
            .time_window(1.0)
            .apply(CollectWindow())
            .sink_to_list()
        )
        env.execute(timeout=60)
        got = {(key, start): [t for _, t in elems] for key, start, elems in out}
        assert got == {
            ("a", 0.0): [0.5, 0.9],
            ("a", 1.0): [1.2],
            ("a", 2.0): [2.6],
            ("b", 0.0): [0.2, 0.7],
            ("b", 2.0): [2.1],
        }

    def test_late_records_beyond_slack_dropped(self):
        env = StreamExecutionEnvironment(parallelism=1)
        events = [("a", 0.1), ("a", 5.0), ("a", 0.2)]  # 0.2 arrives after wm=5-0=5
        out = (
            env.from_collection(events)
            .assign_timestamps(lambda e: e[1], out_of_orderness_s=0.0,
                               watermark_every=1)
            .key_by(lambda e: e[0])
            .time_window(1.0)
            .apply(CollectWindow())
            .sink_to_list()
        )
        env.execute(timeout=60)
        all_ts = [t for _, _, elems in out for _, t in elems]
        assert 0.2 not in all_ts and 0.1 in all_ts and 5.0 in all_ts

    def test_global_time_window(self):
        env = StreamExecutionEnvironment(parallelism=1)
        out = (
            env.from_collection([(i, float(i)) for i in range(10)])
            .assign_timestamps(lambda e: e[1])
            .time_window_all(4.0)
            .apply(CollectWindow())
            .sink_to_list()
        )
        env.execute(timeout=60)
        sizes = sorted(len(elems) for _, _, elems in out)
        assert sizes == [2, 4, 4]  # [0..3], [4..7], [8..9]

    def test_missing_timestamps_fail_loud(self):
        from flink_tensorflow_tpu.core.runtime import JobFailure

        env = StreamExecutionEnvironment(parallelism=1)
        (
            env.from_collection([1, 2, 3])
            .key_by(lambda x: x)
            .time_window(1.0)
            .apply(CollectWindow())
            .sink_to_list()
        )
        with pytest.raises(JobFailure):
            env.execute(timeout=60)


class TestKeyedReduce:
    def test_running_reduce(self):
        env = StreamExecutionEnvironment(parallelism=2)
        out = (
            env.from_collection([("a", 1), ("b", 10), ("a", 2), ("b", 20), ("a", 3)])
            .key_by(lambda e: e[0])
            .reduce(lambda acc, v: (acc[0], acc[1] + v[1]))
            .sink_to_list()
        )
        env.execute(timeout=60)
        finals = {}
        for key, total in out:
            finals[key] = max(finals.get(key, 0), total)
        assert finals == {"a": 6, "b": 30}
