"""Model layer — abstraction, loaders, and the workload zoo.

TPU-native replacement for the reference's model-loading path
(``GraphLoader``/``SavedModelLoader`` + ``Model``/``GraphMethod``,
SURVEY.md §2 rows 4-6, BASELINE.json:5).
"""

from flink_tensorflow_tpu.models.base import Model, ModelMethod
from flink_tensorflow_tpu.models.loaders import (
    GraphLoader,
    SavedModelLoader,
    freeze_method,
    save_bundle,
)
from flink_tensorflow_tpu.models.tf_loader import TFGraphDefLoader, TFSavedModelLoader
from flink_tensorflow_tpu.models.zoo.registry import ModelDef, get_model_def

__all__ = [
    "GraphLoader",
    "Model",
    "ModelDef",
    "ModelMethod",
    "SavedModelLoader",
    "TFGraphDefLoader",
    "TFSavedModelLoader",
    "freeze_method",
    "get_model_def",
    "save_bundle",
]
