"""Local multi-subtask executor — the TaskManager equivalent.

The reference runs on Flink's JobManager/TaskManager cluster (SURVEY.md §1
L1); jobs are threads-in-one-process here, one thread per operator subtask
(the reference's "task slot").  Threads, not asyncio, because the hot path
blocks in XLA device execution which releases the GIL — a subtask spending
its time inside ``jax.jit``-compiled calls runs truly parallel to the others.

The mapping to TPU topology (SURVEY.md §7 step 4): subtask index -> local
chip for operator-DP inference; gang operators instead share one
``jax.sharding.Mesh`` spanning all chips (DP training).  Multi-host
execution re-uses this executor per host with jax.distributed providing the
global mesh (see flink_tensorflow_tpu.parallel.multihost).
"""

from __future__ import annotations

import logging
import threading
import time
import typing

from flink_tensorflow_tpu.core import elements as el
from flink_tensorflow_tpu.core.channels import ChannelWriter, InputGate
from flink_tensorflow_tpu.core.graph import CycleError, DataflowGraph, Transformation
from flink_tensorflow_tpu.core.operators import (
    Operator,
    Output,
    SourceOperator,
    SubtaskStats,
)
from flink_tensorflow_tpu.core.partitioning import ForwardPartitioner
from flink_tensorflow_tpu.core.runtime_context import RuntimeContext
from flink_tensorflow_tpu.core.state import KeyedStateStore
from flink_tensorflow_tpu.metrics.registry import MetricRegistry

logger = logging.getLogger(__name__)

_IDLE_POLL_S = 0.05


class JobFailure(RuntimeError):
    pass


class JobTimeout(JobFailure):
    """join() deadline expired — NOT an operator failure; restart
    strategies must propagate it instead of replaying a healthy job."""


class _Subtask:
    def __init__(
        self,
        executor: "LocalExecutor",
        transformation: Transformation,
        index: int,
        operator: Operator,
        gate: typing.Optional[InputGate],
        num_input_channels: int,
        edge_of_channel: typing.Optional[typing.List[int]] = None,
    ):
        self.executor = executor
        self.t = transformation
        self.index = index
        self.operator = operator
        self.gate = gate
        self.num_input_channels = num_input_channels
        #: channel index -> logical input (edge) index, for two-input
        #: operators (connect/join).
        self.edge_of_channel = edge_of_channel or [0] * num_input_channels
        self.output: typing.Optional[Output] = None
        self.control: "typing.List[int]" = []  # pending checkpoint ids (sources)
        self._control_lock = threading.Lock()
        #: Completed-and-durable checkpoint ids awaiting delivery to the
        #: operator on ITS thread (single-writer contract; Flink mailbox).
        self._notifications: "typing.List[int]" = []
        self.thread: typing.Optional[threading.Thread] = None
        self.finished = threading.Event()
        # -- instrumentation (wired by the executor in _build) -----------
        #: Single-writer accumulators behind this subtask's pull gauges.
        self.stats = SubtaskStats()
        self.records_in = None      # Meter (workers only)
        self.latency = None         # Timer: per-record processing/emit time
        self.alignment = None       # Timer: barrier-alignment spans

    @property
    def scope(self) -> str:
        return f"{self.t.name}.{self.index}"

    # --- source control -------------------------------------------------
    def request_checkpoint(self, checkpoint_id: int) -> None:
        with self._control_lock:
            self.control.append(checkpoint_id)

    def _drain_control(self) -> typing.List[int]:
        with self._control_lock:
            pending, self.control = self.control, []
        return pending

    def add_notification(self, checkpoint_id: int) -> None:
        with self._control_lock:
            self._notifications.append(checkpoint_id)

    def _deliver_notifications(self) -> None:
        with self._control_lock:
            pending, self._notifications = self._notifications, []
        for cid in pending:
            self.operator.notify_checkpoint_complete(cid)

    # --- thread bodies ---------------------------------------------------
    def run_source(self) -> None:
        op = typing.cast(SourceOperator, self.operator)
        try:
            op.open()
            throttle = self.executor.source_throttle_s
            every_n = self.executor.checkpoint_every_n
            for value in op.iterate():
                if self.executor.cancelled.is_set():
                    break
                self._deliver_notifications()
                for cid in self._drain_control():
                    self._snapshot_and_ack(cid)
                    self.output.broadcast_element(el.CheckpointBarrier(cid))
                if isinstance(value, el.SourceIdle):
                    continue  # idle heartbeat: barriers served, no record
                t_emit = time.monotonic()
                self.output.emit(value)
                op.record_emitted()
                # Per-record emit latency: dominated by blocked-put time
                # when downstream backpressures (the source-side signal).
                self.latency.update(time.monotonic() - t_emit)
                # Count-based barriers: checkpoint k cuts the stream after
                # this subtask's k*N-th record — a deterministic position,
                # identical on every host running the same job (the
                # multi-host consistency contract; see CheckpointCoordinator).
                if every_n and op.offset % every_n == 0:
                    cid = op.offset // every_n
                    if self.executor.coordinator.begin_source_checkpoint(cid):
                        self._snapshot_and_ack(cid)
                        self.output.broadcast_element(el.CheckpointBarrier(cid))
                if throttle:
                    time.sleep(throttle)
            # Serve any barrier requests that raced with the last records.
            for cid in self._drain_control():
                self._snapshot_and_ack(cid)
                self.output.broadcast_element(el.CheckpointBarrier(cid))
            op.finish()
            self.output.broadcast_element(el.EndOfPartition())
            op.close()
        except BaseException as exc:  # noqa: BLE001
            self.executor.fail(self, exc)
        finally:
            self.finished.set()
            self.executor.subtask_finished(self)

    def run_worker(self) -> None:
        op = self.operator
        gate = self.gate
        n = self.num_input_channels
        eop = [False] * n
        barrier_seen: typing.Dict[int, typing.Set[int]] = {}
        #: checkpoint id -> monotonic time its FIRST barrier arrived here
        #: (alignment span = first barrier -> snapshot).
        barrier_t0: typing.Dict[int, float] = {}
        watermarks = [float("-inf")] * n
        current_wm = float("-inf")
        stats = self.stats
        records_in = self.records_in
        latency = self.latency
        try:
            op.open()
            active = n
            while active > 0 and not self.executor.cancelled.is_set():
                deadline = op.next_deadline()
                now = time.monotonic()
                timeout = _IDLE_POLL_S if deadline is None else max(0.0, min(deadline - now, _IDLE_POLL_S))
                poll_start = now
                item = gate.poll(timeout=timeout)
                self._deliver_notifications()
                now = time.monotonic()
                if item is None:
                    # Nothing to process: the poll wait was idle time
                    # (with data the dequeue returns ~immediately, so
                    # only empty polls are charged — no extra clock read
                    # either way).
                    stats.idle_s += now - poll_start
                if deadline is not None and now >= deadline:
                    op.fire_due(now)
                if item is None:
                    continue
                idx, element = item
                if isinstance(element, el.StreamRecord):
                    op.process_record_from(self.edge_of_channel[idx], element)
                    latency.update(time.monotonic() - now)
                    records_in.mark()
                elif isinstance(element, el.CheckpointBarrier):
                    cid = element.checkpoint_id
                    seen = barrier_seen.setdefault(cid, set())
                    if not seen:
                        barrier_t0[cid] = now
                    seen.add(idx)
                    gate.block_channel(idx)
                    live = {i for i in range(n) if not eop[i]}
                    if live <= seen:
                        self.alignment.update(now - barrier_t0.pop(cid, now))
                        self._snapshot_and_ack(cid)
                        self.output.broadcast_element(element)
                        del barrier_seen[cid]
                        gate.unblock_all()
                elif isinstance(element, el.Watermark):
                    watermarks[idx] = element.timestamp
                    new_wm = min(
                        watermarks[i] for i in range(n) if not eop[i]
                    )
                    if new_wm > current_wm:
                        current_wm = new_wm
                        op.process_watermark(el.Watermark(current_wm))
                elif isinstance(element, el.EndOfPartition):
                    eop[idx] = True
                    active -= 1
                    # A finished channel counts as barriered for all pending
                    # alignments (it can never deliver its barrier).
                    for cid, seen in list(barrier_seen.items()):
                        live = {i for i in range(n) if not eop[i]}
                        if live and live <= seen:
                            self.alignment.update(now - barrier_t0.pop(cid, now))
                            self._snapshot_and_ack(cid)
                            self.output.broadcast_element(el.CheckpointBarrier(cid))
                            del barrier_seen[cid]
                            gate.unblock_all()
                    # A finished channel no longer holds the combined
                    # watermark back (Flink: finished inputs count as
                    # MAX_WATERMARK) — recompute over the live channels.
                    if active > 0:
                        new_wm = min(
                            watermarks[i] for i in range(n) if not eop[i]
                        )
                        if new_wm > current_wm:
                            current_wm = new_wm
                            op.process_watermark(el.Watermark(current_wm))
            if not self.executor.cancelled.is_set():
                op.finish()
                self.output.broadcast_element(el.EndOfPartition())
            op.close()
        except BaseException as exc:  # noqa: BLE001
            self.executor.fail(self, exc)
        finally:
            self.finished.set()
            self.executor.subtask_finished(self)

    def _snapshot_and_ack(self, checkpoint_id: int) -> None:
        snapshot = self.operator.snapshot(checkpoint_id)
        self.executor.coordinator.ack(checkpoint_id, self.t.name, self.index, snapshot)


class LocalExecutor:
    """Builds the physical plan from a DataflowGraph and runs it."""

    def __init__(
        self,
        graph: DataflowGraph,
        *,
        channel_capacity: int = 1024,
        metric_registry: typing.Optional[MetricRegistry] = None,
        device_provider: typing.Optional[typing.Callable[[str, int], typing.Any]] = None,
        mesh: typing.Optional[typing.Any] = None,
        job_config: typing.Optional[dict] = None,
        source_throttle_s: float = 0.0,
        checkpoint_dir: typing.Optional[str] = None,
        checkpoint_every_n: typing.Optional[int] = None,
        checkpoint_timeout_s: float = 60.0,
        checkpoint_retain_last: typing.Optional[int] = None,
        max_parallelism: int = 128,
    ):
        from flink_tensorflow_tpu.core.checkpoint import CheckpointCoordinator

        self.graph = graph
        self.channel_capacity = channel_capacity
        self.metrics = metric_registry or MetricRegistry()
        self.device_provider = device_provider
        self.mesh = mesh
        self.job_config = job_config or {}
        self.source_throttle_s = source_throttle_s
        self.checkpoint_every_n = checkpoint_every_n
        self.checkpoint_timeout_s = checkpoint_timeout_s
        self.checkpoint_retain_last = checkpoint_retain_last
        self.max_parallelism = max_parallelism
        self.cancelled = threading.Event()
        self._error: typing.Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self.subtasks: typing.List[_Subtask] = []
        self._gates: typing.List[InputGate] = []
        self.coordinator = CheckpointCoordinator(self, checkpoint_dir)
        self.checkpoint_interval_s: typing.Optional[float] = None
        self._finished_count = 0
        self._all_done = threading.Event()
        self._periodic_thread: typing.Optional[threading.Thread] = None
        self._build()

    # --- plan construction ----------------------------------------------
    def _build(self) -> None:
        by_transformation: typing.Dict[int, typing.List[_Subtask]] = {}
        gates: typing.Dict[typing.Tuple[int, int], InputGate] = {}

        try:
            order = self.graph.topological_order()
        except CycleError:
            logger.error(
                "cannot build the physical plan: the dataflow graph is "
                "cyclic — run the plan analyzer (env.validate_plan() or "
                "`python -m flink_tensorflow_tpu.analysis <pipeline>`) "
                "for full diagnostics"
            )
            raise

        from flink_tensorflow_tpu.core.partitioning import HashPartitioner

        for t in order:
            keyed = any(isinstance(e.partitioner, HashPartitioner) for e in t.inputs)
            if keyed and t.parallelism > self.max_parallelism:
                # Non-keyed operators hold no key-partitioned state and
                # may exceed the bound freely (Flink's rule).
                raise ValueError(
                    f"keyed operator {t.name!r} parallelism {t.parallelism} "
                    f"exceeds max_parallelism {self.max_parallelism} — key "
                    "groups would starve the subtasks above the bound; raise "
                    "JobConfig.max_parallelism"
                )

        # Pass 1: channel layout per downstream transformation.
        # Forward edges contribute 1 channel per gate; others contribute
        # the upstream parallelism.
        channel_base: typing.Dict[typing.Tuple[int, int], int] = {}  # (down_id, edge_idx) -> base
        gate_size: typing.Dict[int, int] = {}
        edge_of_channel: typing.Dict[int, typing.List[int]] = {}  # t.id -> per-channel edge idx
        for t in order:
            base = 0
            channel_edges: typing.List[int] = []
            for edge_idx, edge in enumerate(t.inputs):
                channel_base[(t.id, edge_idx)] = base
                if isinstance(edge.partitioner, ForwardPartitioner):
                    if edge.upstream.parallelism != t.parallelism:
                        raise ValueError(
                            f"forward edge {edge.upstream.name}->{t.name} requires equal "
                            f"parallelism ({edge.upstream.parallelism} vs {t.parallelism})"
                        )
                    span = 1
                else:
                    span = edge.upstream.parallelism
                channel_edges.extend([edge_idx] * span)
                base += span
            gate_size[t.id] = base
            edge_of_channel[t.id] = channel_edges

        # Pass 2: instantiate subtasks and gates.  A distributed executor
        # owns only the subtasks placed on this process (_owns_subtask);
        # the identical graph is built on every process, so channel
        # layout and subtask indices agree cluster-wide.
        for t in order:
            subtasks = []
            for i in range(t.parallelism):
                if not self._owns_subtask(t, i):
                    continue
                operator = t.operator_factory()
                gate = None
                if not t.is_source:
                    gate = InputGate(gate_size[t.id], capacity=self.channel_capacity)
                    gates[(t.id, i)] = gate
                    self._gates.append(gate)
                st = _Subtask(self, t, i, operator, gate, gate_size[t.id],
                              edge_of_channel[t.id])
                subtasks.append(st)
            by_transformation[t.id] = subtasks

        # Pass 3: wire outputs.
        for t in order:
            downstream = [
                (d, edge_idx, edge)
                for d in self.graph.transformations
                for edge_idx, edge in enumerate(d.inputs)
                if edge.upstream.id == t.id
            ]
            for st in by_transformation[t.id]:
                edges_for_output = []
                for d, edge_idx, edge in downstream:
                    base = channel_base[(d.id, edge_idx)]
                    if isinstance(edge.partitioner, ForwardPartitioner):
                        targets = [(st.index, base)]
                    else:
                        targets = [(j, base + st.index) for j in range(d.parallelism)]
                    # A downstream subtask without a local gate lives on a
                    # peer process: the writer becomes a remote channel of
                    # the record plane (records AND barriers flow through
                    # it — alignment spans processes).
                    writers = [
                        ChannelWriter(gates[(d.id, j)], ch)
                        if (d.id, j) in gates
                        else self._remote_writer(d, j, ch)
                        for j, ch in targets
                    ]
                    # Stateful partitioners (e.g. rebalance round-robin) must
                    # not be shared across upstream subtask threads.
                    import copy

                    edges_for_output.append((copy.deepcopy(edge.partitioner), writers))
                grp = self.metrics.group(st.scope)
                st.output = Output(edges_for_output,
                                   meter=grp.meter("records_out"),
                                   stats=st.stats)
                st.records_in = grp.meter("records_in")
                st.latency = grp.timer("process_latency_s")
                st.alignment = grp.timer("checkpoint_alignment_s")
                # Pull-based gauges: the hot path only bumps the plain
                # accumulators above; evaluation happens at report time.
                stats = st.stats
                latency = st.latency
                grp.gauge("idle_s", lambda s=stats: s.idle_s)
                grp.gauge("busy_s", lambda tm=latency: tm.total_s)
                grp.gauge("backpressure_s", lambda s=stats: s.blocked_s)
                gate_for_metrics = st.gate
                if gate_for_metrics is not None:
                    grp.gauge("queue_depth",
                              lambda g=gate_for_metrics: g.depth)
                    grp.gauge("queue_high_watermark",
                              lambda g=gate_for_metrics: g.high_watermark)
                    # Time UPSTREAM writers spent blocked putting into
                    # this subtask's gate — "this operator causes the
                    # backpressure above it".
                    grp.gauge("in_backpressure_s",
                              lambda g=gate_for_metrics: g.blocked_put_s)
                state = KeyedStateStore()
                device = (
                    self.device_provider(t.name, st.index) if self.device_provider else None
                )
                if device is not None:
                    from flink_tensorflow_tpu.utils.profiling import (
                        device_memory_stats,
                    )

                    grp.gauge(
                        "hbm_bytes_in_use",
                        lambda d=device: device_memory_stats(d).get("bytes_in_use"),
                    )
                proc_idx, num_procs = self._process_identity()
                ctx = RuntimeContext(
                    task_name=t.name,
                    subtask_index=st.index,
                    parallelism=t.parallelism,
                    keyed_state=state,
                    metric_group=self.metrics.group(st.scope),
                    device=device,
                    mesh=self.mesh,
                    job_config=self.job_config,
                    process_index=proc_idx,
                    num_processes=num_procs,
                )
                gate = getattr(st, "gate", None)
                if gate is not None:
                    # Operator-owned background threads (the model
                    # runner's fetch thread) use this to break the
                    # subtask loop's poll sleep when results complete.
                    ctx.wakeup = gate.wake
                st.operator.setup(ctx, st.output, state)
                self.subtasks.append(st)

    # --- placement hooks (overridden by DistributedExecutor) -------------
    def _owns_subtask(self, t: Transformation, index: int) -> bool:
        """Whether subtask ``index`` of ``t`` runs in this process."""
        return True

    def _process_identity(self) -> typing.Tuple[int, int]:
        """(process_index, num_processes) of this executor's cohort."""
        return 0, 1

    def _remote_writer(self, t: Transformation, subtask_index: int, channel_idx: int):
        raise RuntimeError(
            f"no gate for {t.name}.{subtask_index} — local executor owns "
            "every subtask, so this is a plan-construction bug"
        )

    # --- restore ---------------------------------------------------------
    def restore(
        self,
        snapshots: typing.Dict[str, typing.Dict[int, typing.Any]],
        from_checkpoint_id: typing.Optional[int] = None,
        *,
        local_shard: bool = False,
    ) -> None:
        """``local_shard=True``: ``snapshots`` holds exactly THIS
        process's subtasks (a distributed same-shape restore from the
        process's own shard — the caller validated the shape against the
        shard's recorded metadata), so each local subtask restores by
        index and the rescale inference must not run (per-task counts
        are local, not the old global parallelism)."""
        if from_checkpoint_id is not None:
            # New checkpoints must never overwrite the restore point.
            self.coordinator.resume_from(from_checkpoint_id)
        job_meta = snapshots.pop("__job__", None)
        if job_meta:
            pinned = job_meta.get(0, {}).get("max_parallelism")
            if pinned is not None and pinned != self.max_parallelism:
                raise ValueError(
                    f"checkpoint was taken with max_parallelism={pinned}; "
                    f"this job uses {self.max_parallelism} — the key-group "
                    "routing would change and orphan keyed state. Restore "
                    "with the original max_parallelism."
                )
        by_task: typing.Dict[str, typing.List[_Subtask]] = {}
        for st in self.subtasks:
            by_task.setdefault(st.t.name, []).append(st)
        for task, sts in by_task.items():
            task_snaps = snapshots.get(task)
            if task_snaps is None:
                continue
            old_parallelism = len(task_snaps)
            # The NEW parallelism is the transformation's declared one —
            # on a distributed executor the local subtask list is only
            # this process's share of it.
            new_parallelism = sts[0].t.parallelism
            if local_shard or old_parallelism == new_parallelism:
                for st in sts:
                    snap = task_snaps.get(st.index)
                    if snap is not None:
                        st.operator.restore(snap)
            else:
                # Parallelism changed across the restart: redistribute by
                # key group (Flink's rescaling semantics; keyed state only
                # — per-subtask state raises StateNotRescalable).
                for st in sts:
                    st.operator.restore(
                        st.operator.rescale(
                            task_snaps, st.index, new_parallelism,
                            self.max_parallelism,
                        )
                    )

    # --- execution --------------------------------------------------------
    def start(self) -> None:
        for st in self.subtasks:
            body = st.run_source if st.t.is_source else st.run_worker
            st.thread = threading.Thread(target=body, name=st.scope, daemon=True)
        for st in self.subtasks:
            st.thread.start()
        if self.checkpoint_interval_s is not None:
            self._periodic_thread = threading.Thread(
                target=self._periodic_checkpoints, name="checkpoint-timer", daemon=True
            )
            self._periodic_thread.start()

    def _periodic_checkpoints(self) -> None:
        """Flink-style periodic snapshots (SURVEY.md §5 "Checkpoint /
        resume"): trigger an aligned checkpoint every interval until the
        job finishes.  Races with completion/cancellation are benign —
        a trigger landing there just fails and is not retried."""
        interval = self.checkpoint_interval_s
        while not self._all_done.wait(interval) and not self.cancelled.is_set():
            try:
                self.coordinator.trigger(timeout=self.checkpoint_timeout_s)
            except Exception:
                # Catch EVERYTHING: an escaping error (serialization bug,
                # disk full, ...) would otherwise kill this daemon thread
                # silently and the job would run on unpersisted, believing
                # it is being checkpointed.
                if self._all_done.is_set() or self.cancelled.is_set():
                    return
                logger.warning("periodic checkpoint failed", exc_info=True)

    def join(self, timeout: typing.Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        for st in self.subtasks:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            st.thread.join(remaining)
            if st.thread.is_alive():
                self.cancel()
                raise JobTimeout(f"timeout waiting for subtask {st.scope}")
        # Completed count-based checkpoints must be durable before the job
        # reports done (a cohort worker exits right after this returns).
        in_flight = self.coordinator.wait_for_persistence(
            None if deadline is None else max(0.1, deadline - time.monotonic())
        )
        if in_flight:
            raise JobTimeout(
                f"{in_flight} checkpoint persist write(s) did not drain — "
                "completed checkpoints are not yet durable"
            )
        # The persist queue fans notifications out via add_notification,
        # but a notification enqueued after a subtask's loop exited would
        # sit undelivered forever (delivery runs on the subtask thread).
        # All threads are joined and all persist jobs drained here, so
        # the join thread can flush the leftovers without violating the
        # single-writer contract — this is what makes "durable before the
        # job reports done" include the final checkpoint's 2PC commit.
        # Best-effort, Flink-style: this late delivery runs AFTER the
        # operator's close(), so a hook that needs close()-released
        # resources may fail — log and keep flushing the remaining
        # subtasks rather than failing a job that already completed.
        if self._error is None:
            for st in self.subtasks:
                try:
                    st._deliver_notifications()
                except Exception:
                    logger.warning(
                        "post-close checkpoint notification failed for %s",
                        st.scope, exc_info=True,
                    )
        if self._error is not None:
            raise JobFailure(f"job failed: {self._error!r}") from self._error

    def run(self, timeout: typing.Optional[float] = None) -> None:
        self.start()
        self.join(timeout)

    # --- failure / teardown ----------------------------------------------
    def fail(self, subtask: _Subtask, exc: BaseException) -> None:
        with self._error_lock:
            if self._error is None:
                self._error = exc
        logger.error("subtask %s failed", subtask.scope, exc_info=exc)
        self.cancel()

    def cancel(self) -> None:
        self.cancelled.set()
        for gate in self._gates:
            gate.close()
        self.coordinator.cancel_pending()

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """Fan a durable-checkpoint notification out to every subtask
        (delivered on each subtask's own thread)."""
        for st in self.subtasks:
            st.add_notification(checkpoint_id)

    def subtask_finished(self, subtask: _Subtask) -> None:
        self.coordinator.subtask_finished(subtask)
        with self._error_lock:
            self._finished_count += 1
            if self._finished_count >= len(self.subtasks):
                self._all_done.set()

    @property
    def total_subtasks(self) -> int:
        return len(self.subtasks)
