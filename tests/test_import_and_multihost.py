"""Weight import (name-mapped) and multi-host formation paths."""

import numpy as np
import pytest

import jax

from flink_tensorflow_tpu.models import get_model_def
from flink_tensorflow_tpu.models.import_tf import assign_by_name, read_savedmodel_variables


class TestAssignByName:
    def test_lenet_import_by_names(self):
        """External checkpoint dict (TF-style naming) maps onto the flax
        tree by normalized path + shape."""
        mdef = get_model_def("lenet")
        template = jax.jit(mdef.init_fn)(jax.random.key(0))
        external = {}
        # Build a fake external checkpoint with the same paths (TF-style
        # separators/casing) and recognizable values.
        from flink_tensorflow_tpu.models.import_tf import _flatten

        for i, (path, leaf) in enumerate(_flatten(template)):
            tf_name = "/".join(path).replace("_", "_")
            external[tf_name] = np.full(np.shape(leaf), float(i), np.float32)

        merged = assign_by_name(template, external)
        leaves = list(_flatten(merged))
        for i, (path, leaf) in enumerate(leaves):
            assert float(np.ravel(leaf)[0]) == float(i), path

    def test_strict_reports_missing(self):
        mdef = get_model_def("lenet")
        template = jax.jit(mdef.init_fn)(jax.random.key(0))
        with pytest.raises(ValueError, match="unmatched model variables"):
            assign_by_name(template, {"nope/kernel": np.zeros((1,))})

    def test_rules_rewrite_names(self):
        mdef = get_model_def("widedeep", hash_buckets=10, embed_dim=2,
                             hidden=(4,))
        template = jax.jit(mdef.init_fn)(jax.random.key(0))
        from flink_tensorflow_tpu.models.import_tf import _flatten

        external = {
            "model/" + "/".join(path): np.asarray(leaf)
            for path, leaf in _flatten(template)
        }
        merged = assign_by_name(template, external, rules=[(r"^model/", "")])
        assert jax.tree.structure(merged) == jax.tree.structure(template)

    def test_read_savedmodel_variables_roundtrip(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        path = str(tmp_path / "sm")

        class M(tf.Module):
            def __init__(self):
                self.w = tf.Variable(tf.fill((2, 3), 5.0), name="w")

            @tf.function(input_signature=[tf.TensorSpec([None, 2], tf.float32)])
            def serve(self, x):
                return {"y": x @ self.w}

        m = M()
        tf.saved_model.save(m, path, signatures={"serving_default": m.serve})
        variables = read_savedmodel_variables(path)
        (name, value), = variables.items()
        assert value.shape == (2, 3) and float(value[0, 0]) == 5.0


class TestMultihost:
    def test_initialize_single_host_noop(self):
        from flink_tensorflow_tpu.parallel.multihost import initialize

        topo = initialize()
        assert topo.process_id == 0 and topo.num_processes == 1
        assert topo.global_devices == 8  # virtual CPU mesh

    def test_global_mesh_single_slice(self):
        from flink_tensorflow_tpu.parallel.multihost import global_mesh

        mesh = global_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_global_mesh_wrong_size(self):
        from flink_tensorflow_tpu.parallel.multihost import global_mesh

        with pytest.raises(ValueError):
            global_mesh({"data": 3})
