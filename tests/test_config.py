"""Typed JobConfig validation (SURVEY.md §5 "Config / flag system":
"single typed config dataclass per job; no global flags")."""

import dataclasses

import pytest

from flink_tensorflow_tpu import CheckpointConfig, JobConfig, StreamExecutionEnvironment


def test_jobconfig_defaults_validate():
    JobConfig().validate()


def test_jobconfig_is_frozen():
    cfg = JobConfig()
    with pytest.raises(dataclasses.FrozenInstanceError):
        cfg.parallelism = 4


@pytest.mark.parametrize(
    "changes",
    [
        {"parallelism": 0},
        {"channel_capacity": 0},
        {"source_throttle_s": -1.0},
        {"device_provider": "not-callable"},
        {"mesh": object()},
        {"checkpoint": CheckpointConfig(interval_s=1.0)},  # interval without dir
        {"checkpoint": CheckpointConfig(dir="/tmp/x", interval_s=0.0)},
        {"checkpoint": CheckpointConfig(timeout_s=0.0)},
    ],
)
def test_jobconfig_rejects_bad_values(changes):
    with pytest.raises(ValueError):
        dataclasses.replace(JobConfig(), **changes).validate()


def test_invalid_config_rejected_at_execute():
    env = StreamExecutionEnvironment()
    env.configure(channel_capacity=0)
    env.from_collection([1, 2, 3]).sink_to_list()
    with pytest.raises(ValueError, match="channel_capacity"):
        env.execute(timeout=5)


def test_env_setters_rebuild_config():
    env = StreamExecutionEnvironment(parallelism=3)
    assert env.config.parallelism == 3
    env.channel_capacity = 7
    env.enable_checkpointing("/tmp/ck", interval_s=2.0)
    assert env.config.channel_capacity == 7
    assert env.config.checkpoint == CheckpointConfig(dir="/tmp/ck", interval_s=2.0)
    # Legacy attribute reads still work.
    assert env.checkpoint_dir == "/tmp/ck"
    assert env.default_parallelism == 3


def test_env_accepts_config_instance():
    cfg = JobConfig(parallelism=2, channel_capacity=16, user_params={"model": "x"})
    env = StreamExecutionEnvironment(config=cfg)
    assert env.config is cfg
    out = env.from_collection([1, 2, 3]).map(lambda x: x + 1).sink_to_list()
    env.execute(timeout=30)
    assert sorted(out) == [2, 3, 4]


def test_job_config_dict_is_deprecated_alias():
    env = StreamExecutionEnvironment()
    with pytest.deprecated_call():
        env.job_config["model_path"] = "/m"
    assert env.config.user_params == {"model_path": "/m"}
