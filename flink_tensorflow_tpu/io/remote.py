"""Remote record plane — cross-process/host stream channels over TCP.

The reference's record plane is Flink's Netty shuffle between
TaskManagers (SURVEY.md §2 "Distributed communication backend").  In the
TPU framework, *gradients* never touch this layer (they ride XLA
collectives over ICI/DCN inside the compiled step); the host-side record
plane only carries stream records between processes/hosts — job-to-job
pipes, ingestion from feeders, multi-host source fan-in.

``RemoteSink`` streams length-prefixed codec frames (tensors/serde.py)
to a peer; ``RemoteSource`` accepts one connection and yields records.
Delivery is at-least-once only if the upstream replays on failure — TCP
sources are non-replayable, so exactly-once jobs should front them with
a durable log, exactly as Flink treats raw socket sources.
"""

from __future__ import annotations

import socket
import struct
import typing

from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.tensors.serde import decode_record, encode_record
from flink_tensorflow_tpu.tensors.value import TensorValue

_LEN = struct.Struct("<Q")


class RemoteSink(fn.SinkFunction):
    """Ships records (TensorValue) to a RemoteSource over TCP."""

    def __init__(self, host: str, port: int, *, connect_timeout_s: float = 30.0):
        self.host = host
        self.port = port
        self.connect_timeout_s = connect_timeout_s
        self._sock: typing.Optional[socket.socket] = None

    def clone(self):
        return RemoteSink(self.host, self.port, connect_timeout_s=self.connect_timeout_s)

    def open(self, ctx) -> None:
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def invoke(self, value) -> None:
        if not isinstance(value, TensorValue):
            raise TypeError("RemoteSink carries TensorValue records")
        payload = encode_record(value)
        self._sock.sendall(_LEN.pack(len(payload)) + payload)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            self._sock.close()
            self._sock = None


class RemoteSource(fn.SourceFunction):
    """Accepts ONE RemoteSink connection and yields its records.

    Bind with port=0 to pick a free port; read it from :attr:`port`
    after construction (the listener opens eagerly so the peer can
    connect before the job starts).
    """

    def __init__(self, bind: str = "0.0.0.0", port: int = 0,
                 *, accept_timeout_s: float = 60.0):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((bind, port))
        self._listener.listen(1)
        self.port = self._listener.getsockname()[1]
        self.accept_timeout_s = accept_timeout_s

    def clone(self):
        return self  # the listener is the identity; parallelism must be 1

    def open(self, ctx) -> None:
        if ctx.parallelism != 1:
            raise RuntimeError(
                "RemoteSource accepts exactly one connection — run it with "
                f"parallelism=1 (got {ctx.parallelism})"
            )

    def run(self) -> typing.Iterator[typing.Any]:
        self._listener.settimeout(self.accept_timeout_s)
        conn, _ = self._listener.accept()
        conn.settimeout(None)
        try:
            buf = b""

            def read_exact(n: int, *, mid_frame: bool) -> typing.Optional[bytes]:
                nonlocal buf
                while len(buf) < n:
                    chunk = conn.recv(1 << 20)
                    if not chunk:
                        if buf or mid_frame:
                            # EOF inside a frame = peer died mid-send; a
                            # silent stop would pass truncation off as a
                            # clean close.
                            raise ConnectionError(
                                "remote peer closed mid-frame (stream truncated)"
                            )
                        return None
                    buf += chunk
                out, buf = buf[:n], buf[n:]
                return out

            while True:
                head = read_exact(_LEN.size, mid_frame=False)
                if head is None:
                    return  # clean shutdown between frames
                (length,) = _LEN.unpack(head)
                payload = read_exact(length, mid_frame=True)
                yield decode_record(payload)
        finally:
            conn.close()

    def close(self) -> None:
        self._listener.close()
