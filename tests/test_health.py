"""Health plane + autoscale actuator + doctor (ISSUE 12).

The closed observability loop: declarative SLO rules over the merged
metric feed, hysteresis that a flapping metric cannot oscillate, the
actuator's cooldown / checkpoint-gate / bounds policy, the parent
supervisor's rescale protocol, and the doctor's evidence correlation —
plus the slow 2-process soak where a sustained induced breach drives
exactly one checkpoint -> rescale -> restore cycle with byte-identical
committed output.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from flink_tensorflow_tpu.core.autoscale import (
    RESCALE_EXIT_CODE,
    AutoscaleActuator,
    AutoscaleConfig,
    AutoscaleDecision,
    AutoscaleSupervisor,
    read_decision,
    write_decision,
)
from flink_tensorflow_tpu.metrics.health import (
    BREACH,
    OK,
    WARN,
    HealthConfig,
    HealthEvaluator,
    SloRule,
    default_rules,
)
from flink_tensorflow_tpu.metrics.registry import MetricRegistry

# ---------------------------------------------------------------------------
# fixtures: deterministic snapshot sequences
# ---------------------------------------------------------------------------

EDGE_RULE = SloRule("edge-queue", "edge*_queue_depth", warn=4.0, breach=6.0,
                    sustain=2, clear_after=2, action="scale_up")


def snap(depth):
    return {"slow.0": {"edge0_src_queue_depth": float(depth)}}


def feed(evaluator, depths, t0=100.0, dt=1.0):
    fired = []
    for i, d in enumerate(depths):
        fired.extend(evaluator.evaluate_once(snap(d), now=t0 + i * dt))
    return fired


# ---------------------------------------------------------------------------
# SloRule selection + validation
# ---------------------------------------------------------------------------


class TestSloRule:
    def test_validation(self):
        with pytest.raises(ValueError, match="metric or expr"):
            SloRule("x", "", warn=1, breach=2).validate()
        with pytest.raises(ValueError, match="cmp"):
            SloRule("x", "m", warn=1, breach=2, cmp=">=").validate()
        with pytest.raises(ValueError, match="sustain"):
            SloRule("x", "m", warn=1, breach=2, sustain=0).validate()
        with pytest.raises(ValueError, match="breach threshold"):
            SloRule("x", "m", warn=2, breach=1).validate()
        with pytest.raises(ValueError, match="field"):
            SloRule("x", "m", warn=1, breach=2, field="p97").validate()
        SloRule("x", "m", warn=1, breach=2).validate()

    def test_subtasks_roll_up_to_worst(self):
        rule = SloRule("bp", "queue_depth", warn=4, breach=6)
        got = rule.observe({"op.0": {"queue_depth": 2.0},
                            "op.1": {"queue_depth": 9.0},
                            "checkpoint": {"queue_depth": 99.0}})
        # Job-level scopes stay out of the default "*" selector.
        assert got == {"op": 9.0}

    def test_metric_pattern_yields_per_edge_targets(self):
        got = EDGE_RULE.observe({
            "op.0": {"edge0_a_queue_depth": 3.0, "edge1_b_queue_depth": 7.0}})
        assert got == {"op/edge0_a_queue_depth": 3.0,
                       "op/edge1_b_queue_depth": 7.0}

    def test_scope_and_field_selection(self):
        rule = SloRule("ckpt", "duration_s", scope="checkpoint",
                       field="p95", warn=5, breach=30)
        got = rule.observe({"checkpoint": {"duration_s": {"p95": 12.0}},
                            "op.0": {"duration_s": {"p95": 50.0}}})
        assert got == {"checkpoint": 12.0}

    def test_expr_scalar_lands_on_job(self):
        rule = SloRule("free", "", warn=1, breach=2,
                       expr=lambda s: len(s))
        assert rule.observe({"a.0": {}, "b.0": {}}) == {"job": 2.0}

    def test_default_catalogue_validates_and_scales(self):
        rules = default_rules(channel_capacity=100)
        by_id = {r.id: r for r in rules}
        assert by_id["edge-queue"].warn == 50.0
        assert by_id["edge-queue"].breach == 90.0
        for r in rules:
            r.validate()


# ---------------------------------------------------------------------------
# hysteresis: sustained vs flapping
# ---------------------------------------------------------------------------


class TestHysteresis:
    def test_sustained_breach_escalates_after_sustain(self):
        ev = HealthEvaluator([EDGE_RULE])
        fired = feed(ev, [9, 9])
        assert [(t.old, t.new) for t in fired] == [(OK, BREACH)]
        assert ev.job_state() == BREACH

    def test_warn_band_escalates_to_warn_only(self):
        ev = HealthEvaluator([EDGE_RULE])
        fired = feed(ev, [5, 5, 5, 5])
        assert [(t.old, t.new) for t in fired] == [(OK, WARN)]

    def test_flapping_never_transitions(self):
        ev = HealthEvaluator([EDGE_RULE])
        fired = feed(ev, [9, 0] * 10)
        assert fired == []
        assert ev.job_state() == OK

    def test_flapping_cannot_deescalate_a_breach_either(self):
        ev = HealthEvaluator([EDGE_RULE])
        feed(ev, [9, 9])  # BREACH
        fired = feed(ev, [0, 9] * 10, t0=200.0)
        assert fired == []
        assert ev.job_state() == BREACH

    def test_deescalation_steps_one_level_per_clear_window(self):
        ev = HealthEvaluator([EDGE_RULE])
        feed(ev, [9, 9])
        fired = feed(ev, [0, 0, 0, 0], t0=200.0)
        assert [(t.old, t.new) for t in fired] == [(BREACH, WARN), (WARN, OK)]

    def test_rate_mode_differentiates_and_skips_first_sight(self):
        rule = SloRule("bp", "backpressure_s", warn=0.5, breach=0.85,
                       mode="rate", sustain=2, action="scale_up")
        ev = HealthEvaluator([rule])
        # Cumulative gauge: +0.9s of blocked time per 1s interval.
        fired = []
        for i, raw in enumerate([0.0, 0.9, 1.8, 2.7]):
            fired.extend(ev.evaluate_once(
                {"op.0": {"backpressure_s": raw}}, now=100.0 + i))
        # First sight yields no rate; breaches at ticks 2 and 3 sustain.
        assert [(t.old, t.new) for t in fired] == [(OK, BREACH)]
        assert fired[0].value == pytest.approx(0.9)

    def test_transitions_carry_rule_action(self):
        ev = HealthEvaluator([EDGE_RULE])
        (t,) = feed(ev, [9, 9])
        assert t.action == "scale_up"
        assert "edge-queue" in t.describe()


# ---------------------------------------------------------------------------
# credit-starvation SLO (flow-control plane)
# ---------------------------------------------------------------------------


class TestCreditStarvationRule:
    """The flow-control SLO: the starved clocks are CUMULATIVE
    seconds-parked-at-zero-credit gauges, so the rules run mode="rate" —
    the fraction of wall time the edge spent parked.  Two scope
    families carry them: RemoteSink edges publish
    ``edge.credit_starved_s`` under their operator scope, shuffle-plane
    writers publish ``credit_starved_s`` under
    ``shuffle.out.{task}.{n}.ch{k}``."""

    def _rule(self, rid):
        return next(r for r in default_rules() if r.id == rid)

    def test_catalogue_carries_both_scope_families(self):
        for rid in ("credit-starvation", "credit-starvation-shuffle"):
            rule = self._rule(rid)
            rule.validate()
            assert rule.mode == "rate"
            assert rule.action == "scale_up"

    def test_operator_scope_starved_clock_breaches_on_rate(self):
        # A RemoteSink edge parked 0.9s of every second: rate 0.9 >
        # breach 0.85, sustained 3 ticks (first sight yields no rate).
        ev = HealthEvaluator([self._rule("credit-starvation")])
        fired = []
        for i, raw in enumerate([0.0, 0.9, 1.8, 2.7]):
            fired.extend(ev.evaluate_once(
                {"rsink.0": {"edge.credit_starved_s": raw}}, now=100.0 + i))
        assert [(t.old, t.new) for t in fired] == [(OK, BREACH)]
        assert fired[0].action == "scale_up"
        assert fired[0].value == pytest.approx(0.9)

    def test_shuffle_scope_starved_clock_breaches_on_rate(self):
        ev = HealthEvaluator([self._rule("credit-starvation-shuffle")])
        fired = []
        for i, raw in enumerate([0.0, 0.9, 1.8, 2.7]):
            fired.extend(ev.evaluate_once(
                {"shuffle.out.op.0.ch0": {"credit_starved_s": raw}},
                now=100.0 + i))
        assert [(t.old, t.new) for t in fired] == [(OK, BREACH)]

    def test_briefly_parked_edge_stays_ok(self):
        # 10% of wall time at zero credit is normal coalescing weather —
        # well under warn (0.5), neither rule may fire.
        rules = [self._rule("credit-starvation"),
                 self._rule("credit-starvation-shuffle")]
        ev = HealthEvaluator(rules)
        fired = []
        for i, raw in enumerate([0.0, 0.1, 0.2, 0.3, 0.4]):
            fired.extend(ev.evaluate_once(
                {"rsink.0": {"edge.credit_starved_s": raw},
                 "shuffle.out.op.0.ch0": {"credit_starved_s": raw}},
                now=100.0 + i))
        assert fired == []


# ---------------------------------------------------------------------------
# paged KV economy SLOs (ISSUE 19): pool pressure + tier thrash
# ---------------------------------------------------------------------------


class TestKvEconomyRules:
    @staticmethod
    def _rule(rule_id):
        return {r.id: r for r in default_rules()}[rule_id]

    def test_catalogue_carries_both_kv_rules(self):
        by_id = {r.id: r for r in default_rules()}
        assert by_id["kv-pool-pressure"].action == "scale_up"
        assert by_id["kv-tier-thrash"].mode == "rate"

    def test_pool_pressure_breaches_on_sustained_occupancy(self):
        ev = HealthEvaluator([self._rule("kv-pool-pressure")])
        fired = []
        for i, pct in enumerate([96.0, 97.0]):
            fired.extend(ev.evaluate_once(
                {"serve.0": {"kv_page_occupancy_pct": pct}}, now=100.0 + i))
        assert [(t.old, t.new) for t in fired] == [(OK, BREACH)]
        assert fired[0].action == "scale_up"

    def test_pool_pressure_warn_band(self):
        ev = HealthEvaluator([self._rule("kv-pool-pressure")])
        fired = []
        for i in range(4):
            fired.extend(ev.evaluate_once(
                {"serve.0": {"kv_page_occupancy_pct": 88.0}}, now=100.0 + i))
        assert [(t.old, t.new) for t in fired] == [(OK, WARN)]

    def test_tier_thrash_rates_the_cumulative_move_counter(self):
        ev = HealthEvaluator([self._rule("kv-tier-thrash")])
        fired = []
        # 60 demote/revive transitions per second, sustained: thrash.
        for i, raw in enumerate([0.0, 60.0, 120.0, 180.0]):
            fired.extend(ev.evaluate_once(
                {"serve.0": {"kv_tier_moves": raw}}, now=100.0 + i))
        assert [(t.old, t.new) for t in fired] == [(OK, BREACH)]
        assert fired[0].value == pytest.approx(60.0)

    def test_slow_tier_movement_stays_ok(self):
        ev = HealthEvaluator([self._rule("kv-tier-thrash")])
        fired = []
        for i, raw in enumerate([0.0, 2.0, 4.0, 6.0]):
            fired.extend(ev.evaluate_once(
                {"serve.0": {"kv_tier_moves": raw}}, now=100.0 + i))
        assert fired == []

    def test_dense_plan_without_kv_metrics_never_fires(self):
        ev = HealthEvaluator([self._rule("kv-pool-pressure"),
                              self._rule("kv-tier-thrash")])
        fired = []
        for i in range(3):
            fired.extend(ev.evaluate_once(
                {"serve.0": {"active_seqs": 4.0}}, now=100.0 + i))
        assert fired == []


# ---------------------------------------------------------------------------
# evaluator publication: gauges, flight, rollups
# ---------------------------------------------------------------------------


class TestEvaluatorPublication:
    def test_health_gauges_land_in_registry(self):
        reg = MetricRegistry()
        ev = HealthEvaluator([EDGE_RULE], registry=reg)
        feed(ev, [9, 9])
        health = reg.snapshot()["health"]
        assert health["slow"] == BREACH
        assert health["job"] == BREACH

    def test_gauges_track_deescalation(self):
        reg = MetricRegistry()
        ev = HealthEvaluator([EDGE_RULE], registry=reg)
        feed(ev, [9, 9])
        feed(ev, [0, 0, 0, 0], t0=200.0)
        assert reg.snapshot()["health"]["slow"] == OK

    def test_per_edge_targets_fold_to_operator(self):
        ev = HealthEvaluator([EDGE_RULE])
        feed(ev, [9, 9])
        assert ev.target_states() == {"slow": BREACH}
        assert [(r.id, t) for r, t, _v in ev.active_breaches()] == \
            [("edge-queue", "slow/edge0_src_queue_depth")]

    def test_flight_records_every_transition(self):
        from flink_tensorflow_tpu.tracing.flight import FlightRecorder

        flight = FlightRecorder()
        ev = HealthEvaluator([EDGE_RULE], flight=flight)
        feed(ev, [9, 9])
        events = [e for e in flight.events() if e[0] == "health"]
        assert len(events) == 1
        assert events[0][5]["to"] == "BREACH"

    def test_health_view_shape(self):
        ev = HealthEvaluator([EDGE_RULE])
        feed(ev, [9, 9])
        view = ev.health()
        assert view["job"] == "BREACH"
        assert view["targets"] == {"slow": "BREACH"}
        assert view["transitions"]

    def test_config_validation(self):
        HealthConfig(rules=(EDGE_RULE,),
                     autoscale=AutoscaleConfig()).validate()
        with pytest.raises(ValueError, match="interval_s"):
            HealthConfig(interval_s=0.0).validate()
        with pytest.raises(ValueError, match="max_workers"):
            HealthConfig(autoscale=AutoscaleConfig(
                min_workers=3, max_workers=2)).validate()


# ---------------------------------------------------------------------------
# actuator policy
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _actuator(tmp_path, clock, *, num_workers=2, max_workers=3,
              cooldown_s=5.0, checkpoint_ready=lambda: 7):
    cfg = AutoscaleConfig(
        min_workers=1, max_workers=max_workers, cooldown_s=cooldown_s,
        decision_path=str(tmp_path / "decision.json"))
    return AutoscaleActuator(cfg, num_workers, clock=clock,
                             checkpoint_ready=checkpoint_ready)


class TestActuator:
    def test_cooldown_defers_then_level_trigger_decides(self, tmp_path):
        clock = _Clock()
        act = _actuator(tmp_path, clock)
        ev = HealthEvaluator([EDGE_RULE])
        ev.subscribe_ticks(act.on_tick)
        feed(ev, [9, 9])
        # BREACH is active but the cooldown is running: deferred.
        assert act.last_verdict == "cooldown"
        assert act.decision is None
        clock.t = 6.0
        # No new transition edge — the next tick alone must decide.
        feed(ev, [9], t0=300.0)
        assert act.last_verdict == "decided"
        assert act.decision.action == "scale_up"
        assert act.decision.from_workers == 2
        assert act.decision.to_workers == 3
        assert act.decision.checkpoint_id == 7

    def test_checkpoint_gate_blocks_until_a_checkpoint_exists(self, tmp_path):
        clock = _Clock(10.0)
        cid = {"v": None}
        act = _actuator(tmp_path, clock, cooldown_s=0.0,
                        checkpoint_ready=lambda: cid["v"])
        ev = HealthEvaluator([EDGE_RULE])
        ev.subscribe_ticks(act.on_tick)
        feed(ev, [9, 9])
        assert act.last_verdict == "no-checkpoint"
        cid["v"] = 3
        feed(ev, [9], t0=300.0)
        assert act.decision is not None
        assert act.decision.checkpoint_id == 3

    def test_at_bounds_never_decides(self, tmp_path):
        clock = _Clock(10.0)
        act = _actuator(tmp_path, clock, num_workers=3, max_workers=3,
                        cooldown_s=0.0)
        ev = HealthEvaluator([EDGE_RULE])
        ev.subscribe_ticks(act.on_tick)
        feed(ev, [9, 9, 9, 9])
        assert act.decision is None
        assert act.last_verdict == "at-bounds"

    def test_one_decision_per_actuator_life(self, tmp_path):
        clock = _Clock(10.0)
        act = _actuator(tmp_path, clock, cooldown_s=0.0)
        ev = HealthEvaluator([EDGE_RULE])
        ev.subscribe_ticks(act.on_tick)
        feed(ev, [9] * 10)
        assert act.decision.to_workers == 3
        assert act.last_verdict == "decided"

    def test_flapping_fixture_never_actuates(self, tmp_path):
        clock = _Clock(10.0)
        act = _actuator(tmp_path, clock, cooldown_s=0.0)
        ev = HealthEvaluator([EDGE_RULE])
        ev.subscribe_ticks(act.on_tick)
        feed(ev, [9, 0] * 10)
        assert act.decision is None
        assert act.last_verdict == "no-breach"

    def test_scale_up_outranks_scale_down(self, tmp_path):
        idle = SloRule("idle", "idle_s", warn=4, breach=6, sustain=2,
                       clear_after=2, action="scale_down")
        clock = _Clock(10.0)
        act = _actuator(tmp_path, clock, cooldown_s=0.0)
        ev = HealthEvaluator([EDGE_RULE, idle])
        ev.subscribe_ticks(act.on_tick)
        for i in range(2):
            ev.evaluate_once({"slow.0": {"edge0_src_queue_depth": 9.0},
                              "lazy.0": {"idle_s": 9.0}}, now=100.0 + i)
        assert act.decision.action == "scale_up"
        assert act.decision.rule_id == "edge-queue"

    def test_decision_file_round_trip(self, tmp_path):
        clock = _Clock(10.0)
        act = _actuator(tmp_path, clock, cooldown_s=0.0)
        ev = HealthEvaluator([EDGE_RULE])
        ev.subscribe_ticks(act.on_tick)
        feed(ev, [9, 9])
        doc = read_decision(str(tmp_path / "decision.json"))
        assert doc is not None
        assert doc["to_workers"] == 3
        assert doc["rule_id"] == "edge-queue"
        assert doc["health"]["job"] == "BREACH"

    def test_read_decision_rejects_garbage(self, tmp_path):
        path = str(tmp_path / "d.json")
        assert read_decision(path) is None
        with open(path, "w") as f:
            f.write("{not json")
        assert read_decision(path) is None
        with open(path, "w") as f:
            json.dump({"kind": "something-else"}, f)
        assert read_decision(path) is None


# ---------------------------------------------------------------------------
# supervisor protocol (no record plane: trivial worker commands)
# ---------------------------------------------------------------------------


def _decision_writer_code(path, to_workers, exit_code=RESCALE_EXIT_CODE):
    decision = AutoscaleDecision(
        rule_id="edge-queue", target="slow", action="scale_up", value=9.0,
        from_workers=2, to_workers=to_workers, ts=0.0)
    doc = decision.to_dict()
    return (
        "import json, sys, time\n"
        f"doc = {doc!r}\n"
        "doc['ts'] = time.time()\n"
        f"json.dump(doc, open({path!r}, 'w'))\n"
        f"sys.exit({exit_code})\n"
    )


class TestAutoscaleSupervisor:
    def test_rescale_request_respawns_at_decision_target(self, tmp_path):
        path = str(tmp_path / "decision.json")

        def command(w, num_workers, attempt):
            if attempt == 0 and w == 0:
                return [sys.executable, "-S", "-c",
                        _decision_writer_code(path, 3)]
            if attempt == 0:
                # The deciding worker's peer: killed by the supervisor.
                return [sys.executable, "-S", "-c",
                        "import time; time.sleep(60)"]
            return [sys.executable, "-S", "-c",
                    f"import sys; sys.exit(0 if {num_workers} == 3 else 9)"]

        sup = AutoscaleSupervisor(command, 2, decision_path=path,
                                  max_workers=3, poll_s=0.02)
        outcome = sup.run()
        assert outcome.returncode == 0
        assert outcome.attempts == 2
        assert outcome.num_workers == 3
        assert len(outcome.rescales) == 1
        assert outcome.rescales[0]["to_workers"] == 3

    def test_decision_target_is_reclamped(self, tmp_path):
        path = str(tmp_path / "decision.json")

        def command(w, num_workers, attempt):
            if attempt == 0 and w == 0:
                # A decision demanding more than the parent allows.
                return [sys.executable, "-S", "-c",
                        _decision_writer_code(path, 99)]
            return [sys.executable, "-S", "-c",
                    f"import sys; sys.exit(0 if {num_workers} == 3 else 9)"]

        sup = AutoscaleSupervisor(command, 2, decision_path=path,
                                  max_workers=3, poll_s=0.02)
        outcome = sup.run()
        assert outcome.num_workers == 3

    def test_rescale_exit_without_decision_burns_budget(self, tmp_path):
        path = str(tmp_path / "decision.json")  # never written
        attempts = []

        def command(w, num_workers, attempt):
            attempts.append((attempt, num_workers))
            rc = RESCALE_EXIT_CODE if attempt == 0 else 0
            return [sys.executable, "-S", "-c",
                    f"import sys; sys.exit({rc})"]

        sup = AutoscaleSupervisor(command, 2, decision_path=path,
                                  max_workers=3, max_restarts=2,
                                  poll_s=0.02)
        outcome = sup.run()
        # Respawned UNCHANGED: a lost decision file must not guess.
        assert outcome.num_workers == 2
        assert outcome.rescales == ()

    def test_stale_decision_is_not_reconsumed(self, tmp_path):
        path = str(tmp_path / "decision.json")
        write_decision(path, AutoscaleDecision(
            rule_id="old", target="x", action="scale_up", value=1.0,
            from_workers=2, to_workers=3, ts=time.time()))
        sup = AutoscaleSupervisor(lambda w, n, a: [], 2,
                                  decision_path=path, max_workers=3)
        # A decision consumed at ts must not match afterwards.
        doc = sup._fresh_decision(0.0)
        assert doc is not None
        assert sup._fresh_decision(float(doc["ts"])) is None

    def test_max_workers_below_start_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="max_workers"):
            AutoscaleSupervisor(lambda w, n, a: [], 3,
                                decision_path=str(tmp_path / "d"),
                                max_workers=2)


# ---------------------------------------------------------------------------
# doctor: evidence correlation
# ---------------------------------------------------------------------------


class TestDoctor:
    SNAP = {
        "slow.0": {"in_backpressure_s": 4.0, "queue_depth": 7.0,
                   "edge0_src_queue_depth": 8.0, "backpressure_s": 0.2,
                   "idle_s": 0.1},
        "sink.0": {"idle_s": 5.0, "queue_depth": 0.0},
        "health": {"slow": 2.0, "job": 2.0},
    }
    EVENTS = [
        ("slow.0", "compute", "X", 0.00, 0.040, None),
        ("slow.0", "compute", "X", 0.10, 0.050, None),
        ("slow.0", "h2d", "X", 0.05, 0.001, None),
    ]

    def test_health_findings_rank_breach_first(self):
        from flink_tensorflow_tpu.tracing.doctor import health_findings

        findings = health_findings(self.SNAP, channel_capacity=8)
        assert findings[0]["severity"] == 2
        assert findings[0]["target"].startswith("slow")

    def test_bottleneck_ranking_leads_with_blocked_upstream(self):
        from flink_tensorflow_tpu.tracing.doctor import bottleneck_ranking

        ranked = bottleneck_ranking(self.SNAP)
        assert ranked[0]["operator"] == "slow"
        assert ranked[0]["in_backpressure_s"] == 4.0

    def test_stage_dominance(self):
        from flink_tensorflow_tpu.tracing.doctor import stage_dominance

        stages = stage_dominance(self.EVENTS)
        assert stages["slow"]["stage"] == "compute"
        assert stages["slow"]["share"] > 0.9

    def test_diagnose_names_operator_stage_and_action(self):
        from flink_tensorflow_tpu.tracing.doctor import diagnose

        decision = AutoscaleDecision(
            rule_id="edge-queue", target="slow", action="scale_up",
            value=8.0, from_workers=2, to_workers=3, ts=1.0,
            checkpoint_id=4).to_dict()
        report = diagnose(self.SNAP, events=self.EVENTS,
                          decision=decision, channel_capacity=8)
        head = report["findings"][0]
        assert "#1 bottleneck slow" in head
        assert "dominant stage compute" in head
        assert any("scale_up 2 -> 3" in f for f in report["findings"])

    def test_diagnose_notes_missing_actuation_on_breach(self):
        from flink_tensorflow_tpu.tracing.doctor import diagnose

        report = diagnose(self.SNAP, channel_capacity=8)
        assert any("no autoscale decision" in f for f in report["findings"])

    CREDIT_SNAP = {
        # The sender is hot (blocked upstream writers) AND its shuffle
        # out-edge spent 2.5s parked at zero credit; the RemoteSink edge
        # on "pipe" carries the operator-scope flavour of the clock.
        "up.0": {"in_backpressure_s": 4.0, "backpressure_s": 3.0,
                 "idle_s": 0.0},
        "shuffle.out.up.0.ch2": {"credit_starved_s": 2.5,
                                 "credits_available": 0.0},
        "shuffle.out.up.0.ch1": {"credit_starved_s": 0.4},
        "pipe.0": {"edge.credit_starved_s": 1.2, "idle_s": 0.1},
        "down.0": {"idle_s": 5.0},
    }

    def test_bottleneck_ranking_carries_credit_evidence(self):
        from flink_tensorflow_tpu.tracing.doctor import bottleneck_ranking

        ranked = {r["operator"]: r
                  for r in bottleneck_ranking(self.CREDIT_SNAP)}
        # Shuffle-plane scopes fold onto their SENDING operator; the
        # worst-starved edge is named so the report can point at the
        # exact link.
        assert ranked["up"]["credit_starved_s"] == pytest.approx(2.9)
        assert ranked["up"]["credit_edge"] == "shuffle.out.up.0.ch2"
        # RemoteSink edges book under their own operator scope.
        assert ranked["pipe"]["credit_starved_s"] == pytest.approx(1.2)
        assert ranked["pipe"]["credit_edge"] == "pipe.0"
        assert ranked["down"]["credit_starved_s"] == 0.0
        assert ranked["down"]["credit_edge"] is None

    def test_diagnose_names_credit_starved_edge(self):
        from flink_tensorflow_tpu.tracing.doctor import diagnose

        report = diagnose(self.CREDIT_SNAP, channel_capacity=8)
        head = report["findings"][0]
        assert "#1 bottleneck up" in head
        assert "credit-starved 2.90s on edge shuffle.out.up.0.ch2" in head
        assert "the jam is below this operator" in head

    def test_cli_round_trip(self, tmp_path):
        from flink_tensorflow_tpu.tracing.doctor import main

        snap_path = str(tmp_path / "snap.json")
        with open(snap_path, "w") as f:
            json.dump(self.SNAP, f)
        out = str(tmp_path / "report.json")
        assert main(["--snapshot", snap_path, "--out", out,
                     "--channel-capacity", "8", "--report-only"]) == 0
        with open(out) as f:
            report = json.load(f)
        assert report["kind"] == "flink-tpu-doctor-report"
        assert report["bottlenecks"][0]["operator"] == "slow"

    def test_cli_unreadable_evidence_exits_2(self, tmp_path):
        from flink_tensorflow_tpu.tracing.doctor import main

        assert main(["--snapshot", str(tmp_path / "absent.json")]) == 2
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            f.write("ceci n'est pas une decision")
        assert main(["--decision", bad]) == 2


# ---------------------------------------------------------------------------
# the closed-loop soak
# ---------------------------------------------------------------------------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.mark.slow
class TestAutoscaleSoak:
    def test_sustained_breach_drives_one_rescale_byte_identical(
            self, tmp_path):
        """The PR's acceptance demo: a 2-process cohort with a slow keyed
        stage saturates its input queues; the health plane sustains an
        edge-queue BREACH, the actuator (after a completed checkpoint)
        decides 2 -> 3, the supervisor respawns the cohort at 3 with the
        attempt threaded into the fencing epoch, the workers restore
        from the highest complete cohort checkpoint — and the committed
        output equals the fault-free expectation exactly, with exactly
        ONE rescale cycle (max_workers=3 makes a second decision
        at-bounds; hysteresis keeps flapping out)."""
        from flink_tensorflow_tpu.io.files import read_committed

        sys.path.insert(0, os.path.dirname(__file__))
        from _autoscale_worker import NUM_KEYS  # noqa: E402

        worker = os.path.join(os.path.dirname(__file__),
                              "_autoscale_worker.py")
        n, every, par = 1200, 60, 3
        out = str(tmp_path / "out")
        chk = str(tmp_path / "chk")
        decision_path = str(tmp_path / "decision.json")
        ports_by_shape = {2: _free_ports(2), 3: _free_ports(3)}
        pythonpath = os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__)),
             os.environ.get("PYTHONPATH", "")])

        def command(w, num_workers, attempt):
            return [
                sys.executable, worker, "--index", str(w),
                "--ports", ",".join(map(str, ports_by_shape[num_workers])),
                "--out", out, "--chk", chk, "--n", str(n),
                "--every", str(every), "--par", str(par),
                "--delay", "0.01", "--cap", "8",
                "--epoch", str(attempt),
                "--restore-id", "-1" if attempt == 0 else "-2",
                "--decision", decision_path,
                "--min-workers", "1", "--max-workers", "3",
                "--cooldown", "2.0",
            ]

        sup = AutoscaleSupervisor(
            command, 2, decision_path=decision_path,
            min_workers=1, max_workers=3, max_rescales=2,
            env=lambda w, p, a: {"PYTHONPATH": pythonpath},
            max_restarts=2, poll_s=0.05, kill_grace_s=8.0,
            attempt_timeout_s=150.0,
        )
        outcome = sup.run()

        # Exactly one checkpoint -> rescale -> restore cycle.
        assert outcome.returncode == 0
        assert outcome.attempts == 2
        assert outcome.num_workers == 3
        assert len(outcome.rescales) == 1
        decision = outcome.rescales[0]
        assert decision["action"] == "scale_up"
        assert decision["from_workers"] == 2
        assert decision["to_workers"] == 3
        assert decision["checkpoint_id"] is not None
        assert decision["target"].startswith("slow_sum")

        # Byte-identical exactly-once output: one (key, i, running sum)
        # per record, exactly once, despite the mid-stream rescale.
        sums = {k: 0 for k in range(NUM_KEYS)}
        expected = []
        for i in range(n):
            k = i % NUM_KEYS
            sums[k] += i
            expected.append((k, i, sums[k]))
        got = sorted(
            (int(r.meta["key"]), int(r.meta["i"]), int(r["v"]))
            for r in read_committed(out)
        )
        assert got == sorted(expected)

        # The doctor, fed the supervisor's decision, names the breached
        # rule, the injected bottleneck, and what the supervisor did.
        from flink_tensorflow_tpu.tracing.doctor import diagnose

        report = diagnose(decision["health"].get("targets") and {
            "health": {t: {"OK": 0, "WARN": 1, "BREACH": 2}[s]
                       for t, s in decision["health"]["targets"].items()},
        } or {}, decision=decision, channel_capacity=8)
        assert any("slow_sum" in f for f in report["findings"])
        assert any("scale_up 2 -> 3" in f for f in report["findings"])
