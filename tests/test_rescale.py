"""Checkpoint rescaling — keyed state redistributes across parallelism.

VERDICT r1 missing #4: restore mapped snapshots by (task, subtask_index),
so changing parallelism silently dropped/misassigned keyed state.  Flink
(whose runtime the reference inherits, SURVEY.md §1 L1) redistributes key
groups; these tests pin the same semantics here.
"""

import time

import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core.operators import StateNotRescalable
from flink_tensorflow_tpu.core.partitioning import (
    DEFAULT_MAX_PARALLELISM,
    key_group,
    subtask_for_key,
    subtask_for_key_group,
)
from flink_tensorflow_tpu.core.state import StateDescriptor


class TestKeyGroups:
    def test_groups_partition_contiguously(self):
        maxp = 128
        for p in (1, 2, 3, 7, 128):
            owners = [subtask_for_key_group(g, p, maxp) for g in range(maxp)]
            assert set(owners) <= set(range(p))
            assert owners == sorted(owners)  # contiguous ranges
            assert set(owners) == set(range(p))  # every subtask owns some

    def test_routing_agrees_with_state_assignment(self):
        # The HashPartitioner and the rescale path must use the same
        # key -> subtask mapping, else state lands where records don't.
        from flink_tensorflow_tpu.core.partitioning import HashPartitioner

        part = HashPartitioner(lambda v: v, DEFAULT_MAX_PARALLELISM)
        for p in (1, 2, 3, 5):
            for key in ["a", "b", 7, 42, (1, "x")]:
                assert part.select(key, p) == (
                    subtask_for_key(key, p, DEFAULT_MAX_PARALLELISM),
                )

    def test_group_stable_across_processes(self):
        # FNV hash, not PYTHONHASHSEED-dependent builtin hash.
        assert key_group("user-17", 128) == key_group("user-17", 128)
        assert key_group(17, 128) == 17 % 128


class _KeyedSum(fn.ProcessFunction):
    def open(self, ctx):
        self._desc = StateDescriptor("sum")

    def process_element(self, value, ctx, out):
        state = ctx.state(self._desc)
        total = (state.value() or 0) + value["amount"]
        state.update(total)
        out.collect({"key": ctx.current_key, "sum": total})


def _build(env, records, parallelism):
    out = (
        env.from_collection(records, parallelism=1)
        .key_by(lambda r: r["key"])
        .process(_KeyedSum(), name="keyed_sum", parallelism=parallelism)
        .sink_to_list()
    )
    return out


def _records(n, keys=10):
    return [{"key": f"k{i % keys}", "amount": i} for i in range(n)]


def _expected_sums(records):
    sums = {}
    for r in records:
        sums[r["key"]] = sums.get(r["key"], 0) + r["amount"]
    return sums


class TestRescaleRestore:
    @pytest.mark.parametrize("old_p,new_p", [(2, 3), (3, 1), (1, 4), (4, 2)])
    def test_keyed_state_redistributes(self, tmp_path, old_p, new_p):
        records = _records(300)
        d = str(tmp_path / "chk")

        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(d)
        env.source_throttle_s = 0.002
        _build(env, records, old_p)
        h = env.execute_async("rescale")
        time.sleep(0.2)
        h.trigger_checkpoint()
        h.cancel()

        env2 = StreamExecutionEnvironment(parallelism=1)
        env2.enable_checkpointing(d)
        out2 = _build(env2, records, new_p)
        env2.execute("rescale", restore_from=d, timeout=120)

        # Per-key final sums equal the uninterrupted run: state followed
        # its keys to the new subtasks, replayed records found it there.
        finals = {}
        for r in out2:
            finals[r["key"]] = max(finals.get(r["key"], 0), r["sum"])
        assert finals == _expected_sums(records)

    def test_source_rescale_raises(self, tmp_path):
        records = _records(200)
        d = str(tmp_path / "chk")
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(d)
        env.source_throttle_s = 0.002
        (
            env.from_collection(records, parallelism=2)
            .key_by(lambda r: r["key"])
            .process(_KeyedSum(), name="keyed_sum", parallelism=2)
            .sink_to_list()
        )
        h = env.execute_async("src")
        time.sleep(0.2)
        h.trigger_checkpoint()
        h.cancel()

        env2 = StreamExecutionEnvironment(parallelism=1)
        env2.enable_checkpointing(d)
        (
            env2.from_collection(records, parallelism=4)  # changed!
            .key_by(lambda r: r["key"])
            .process(_KeyedSum(), name="keyed_sum", parallelism=2)
            .sink_to_list()
        )
        with pytest.raises(StateNotRescalable, match="source"):
            env2.execute("src", restore_from=d, timeout=120)

    def test_online_training_rescales_by_key(self, tmp_path):
        """Wide&Deep-style per-key models (scope='key') follow their keys
        to the new subtasks."""
        import numpy as np
        import optax

        from flink_tensorflow_tpu.functions import OnlineTrainFunction
        from flink_tensorflow_tpu.models import get_model_def
        from flink_tensorflow_tpu.tensors import RecordSchema, TensorValue, spec

        mdef = get_model_def("widedeep", hash_buckets=50, embed_dim=2,
                             num_cat_slots=2, num_dense=2, num_wide=4,
                             hidden=(8,))
        schema = RecordSchema({
            "wide": spec((4,)),
            "dense": spec((2,)),
            "cat": spec((2,), np.int32),
            "label": spec((), np.int32),
        })
        rng = np.random.RandomState(0)
        records = [
            TensorValue({
                "wide": rng.rand(4).astype(np.float32),
                "dense": rng.rand(2).astype(np.float32),
                "cat": rng.randint(0, 50, (2,)).astype(np.int32),
                "label": np.int32(i % 2),
            }, meta={"user": i % 6})
            for i in range(120)
        ]

        def build(env, parallelism):
            return (
                env.from_collection(records, parallelism=1)
                .key_by(lambda r: r.meta["user"])
                .process(
                    OnlineTrainFunction(mdef, optax.sgd(0.05), train_schema=schema,
                                        scope="key", mini_batch=4),
                    name="train", parallelism=parallelism,
                )
                .sink_to_list()
            )

        d = str(tmp_path / "chk")
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(d)
        env.source_throttle_s = 0.02  # 120 records ~= 2.4s: the trigger
        build(env, 2)                 # below lands mid-stream
        h = env.execute_async("train")
        time.sleep(0.5)
        h.trigger_checkpoint()
        h.cancel()

        env2 = StreamExecutionEnvironment(parallelism=1)
        env2.enable_checkpointing(d)
        out = build(env2, 3)
        env2.execute("train", restore_from=d, timeout=300)
        # Every key's model trained through its full (replayed) stream:
        # 120 records / 6 users / mini_batch 4 = 5 steps per user.
        steps = {}
        for r in out:
            steps[int(r.meta["key"])] = max(
                steps.get(int(r.meta["key"]), 0), int(r["step"])
            )
        assert set(steps) == set(range(6))
        assert all(s == 5 for s in steps.values()), steps
