"""flink_tensorflow_tpu — a TPU-native streaming-ML framework.

A from-scratch rebuild of the capabilities of the reference project
``sirpkt/flink-tensorflow`` (a Scala library embedding TensorFlow sessions in
Apache Flink stream operators), redesigned for TPU hardware and the JAX/XLA
compilation model rather than translated from the JVM/JNI/CUDA original.

Reference parity map (see SURVEY.md for the full reconstruction; the
reference mount was empty this round, so citations are to the capability
contract in BASELINE.json):

- Flink DataStream runtime        -> :mod:`flink_tensorflow_tpu.core`
  (typed streams, operator graph, multi-subtask scheduler, keyed state,
  windows, snapshot barriers — BASELINE.json:4 "windowed micro-batching")
- TensorValue + TypeInformation   -> :mod:`flink_tensorflow_tpu.tensors`
  (pytree record schemas, host<->HBM marshalling — BASELINE.json:4
  "zero-copy Row<->DeviceArray marshalling in the tensor-coercion layer")
- GraphLoader / SavedModelLoader  -> :mod:`flink_tensorflow_tpu.models.loaders`
  (model bundles lowered to jax.jit-compiled callables — BASELINE.json:4)
- ModelFunction / GraphFunction   -> :mod:`flink_tensorflow_tpu.functions`
  (stream operators invoking XLA executables on HBM-resident arrays)
- ClusterSpec + NCCL allreduce    -> :mod:`flink_tensorflow_tpu.parallel`
  (jax.sharding.Mesh whose axes map to task slots; allreduce over ICI)
"""

from flink_tensorflow_tpu.version import __version__

from flink_tensorflow_tpu.core.config import CheckpointConfig, JobConfig
from flink_tensorflow_tpu.core.distributed import DistributedConfig
from flink_tensorflow_tpu.core.environment import StreamExecutionEnvironment
from flink_tensorflow_tpu.core.faults import FaultPlan, FaultSpec
from flink_tensorflow_tpu.core.stream import DataStream, KeyedStream, WindowedStream

__all__ = [
    "__version__",
    "CheckpointConfig",
    "DistributedConfig",
    "FaultPlan",
    "FaultSpec",
    "JobConfig",
    "StreamExecutionEnvironment",
    "DataStream",
    "KeyedStream",
    "WindowedStream",
]
