"""Micro-batch assembly: stack, pad, bucket.

This is the bridge from the window operator's fired element list (SURVEY.md
§3.2 — "stack B records -> one batched input tensor") to an XLA-friendly
``[B, ...]`` pytree.  Two TPU constraints shape the design (SURVEY.md §7
hard part 2):

1. **Static shapes only.** Streaming batch sizes vary per window fire, and
   BiLSTM-style records vary in length.  Every dynamic dimension — batch and
   sequence alike — is padded up to a bucket from a fixed ladder, so the
   jit compile cache stays small and warm (one executable per bucket tuple).
2. **One transfer per batch.** Records are stacked into a single contiguous
   host buffer per field and shipped to HBM in one ``device_put`` — never
   per record (the reference's per-record JNI copy is the hot-loop cost its
   own micro-batching exists to amortize, SURVEY.md §3.1).

A ``Batch`` carries ``valid`` (rows that are real records, not batch pad)
and per-field length arrays for sequence fields, so downstream unbatching
drops padding losslessly.
"""

from __future__ import annotations

import bisect
import dataclasses
import typing

import numpy as np

from flink_tensorflow_tpu.tensors.schema import RecordSchema
from flink_tensorflow_tpu.tensors.value import TensorValue


class BucketLadder:
    """Monotone ladder of sizes; values round up to the next rung.

    Defaults to powers of two — the geometric ladder bounds both padding
    waste (<2x) and the number of compiled executables (log2(max)).
    """

    def __init__(self, sizes: typing.Optional[typing.Sequence[int]] = None, *, max_size: int = 4096):
        if sizes is None:
            sizes, s = [], 1
            while s <= max_size:
                sizes.append(s)
                s *= 2
        self.sizes = sorted(set(int(s) for s in sizes))
        if not self.sizes:
            raise ValueError("bucket ladder must be non-empty")

    def round_up(self, n: int) -> int:
        i = bisect.bisect_left(self.sizes, n)
        if i == len(self.sizes):
            raise ValueError(f"size {n} exceeds largest bucket {self.sizes[-1]}")
        return self.sizes[i]

    @classmethod
    def up_to(cls, cap: int) -> "BucketLadder":
        """Powers of two up to ``cap``, with ``cap`` itself as the top
        rung even when it isn't a power of two — the capped micro-batch
        ladder (transparent map batching, open-loop service buckets)."""
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap}")
        sizes, s = [], 1
        while s < cap:
            sizes.append(s)
            s *= 2
        sizes.append(cap)
        return cls(sizes)


@dataclasses.dataclass(frozen=True)
class BucketPolicy:
    """How a model operator resolves dynamic dims to static shapes."""

    batch: BucketLadder = dataclasses.field(default_factory=BucketLadder)
    #: Ladder for every dynamic (non-batch) dim, e.g. sequence length.
    lengths: BucketLadder = dataclasses.field(default_factory=lambda: BucketLadder(max_size=8192))
    #: If set, batches are always padded to exactly this size (no ladder).
    fixed_batch: typing.Optional[int] = None

    def batch_bucket(self, n: int) -> int:
        return self.fixed_batch if self.fixed_batch is not None else self.batch.round_up(n)


@dataclasses.dataclass
class Batch:
    """One assembled micro-batch (host side, pre-transfer).

    ``arrays``: field -> ``[B, ...]`` numpy array (B = bucketed batch).
    ``valid``: ``[B]`` bool — False rows are batch padding.
    ``lengths``: field -> ``[B]`` int32 true lengths, for fields whose
    leading record dim was dynamic (sequence fields).
    ``metas``: per-record metadata from the source TensorValues.
    """

    arrays: typing.Dict[str, np.ndarray]
    valid: np.ndarray
    lengths: typing.Dict[str, np.ndarray]
    metas: typing.List[typing.Mapping[str, typing.Any]]

    @property
    def num_records(self) -> int:
        return int(self.valid.sum())

    @property
    def padded_size(self) -> int:
        return int(self.valid.shape[0])

    def bucket_key(self) -> typing.Tuple:
        """Compile-cache key: every static shape the jitted call sees."""
        return tuple(sorted((n, a.shape, str(a.dtype)) for n, a in self.arrays.items()))

    def unbatch(
        self, outputs: typing.Mapping[str, np.ndarray]
    ) -> typing.List[TensorValue]:
        """Split a model's ``[B, ...]`` outputs back into per-record values,
        dropping batch-pad rows and re-attaching each record's metadata."""
        out_host = {n: np.asarray(a) for n, a in outputs.items()}
        records = []
        for i in range(self.padded_size):
            if not self.valid[i]:
                continue
            records.append(
                TensorValue({n: a[i] for n, a in out_host.items()}, self.metas[len(records)])
            )
        return records


def assemble(
    records: typing.Sequence[TensorValue],
    schema: RecordSchema,
    policy: typing.Optional[BucketPolicy] = None,
) -> Batch:
    """Stack records into one bucketed, padded micro-batch.

    Dynamic dims (``None`` in the schema) are padded per the policy's length
    ladder; the batch dim is padded per the batch ladder.  Pad rows replay
    the first record's values so the padded computation hits no NaN/inf
    paths — ``valid`` masks them out downstream.
    """
    if not records:
        raise ValueError("cannot assemble an empty batch")
    policy = policy or BucketPolicy()
    n = len(records)
    b = policy.batch_bucket(n)
    if b < n:
        raise ValueError(
            f"{n} records exceed fixed_batch={b}; chunk the window upstream"
        )

    arrays: typing.Dict[str, np.ndarray] = {}
    lengths: typing.Dict[str, np.ndarray] = {}
    for name, spec in schema:
        parts = [np.asarray(r[name]) for r in records]
        dyn_axes = [ax for ax, d in enumerate(spec.shape) if d is None]
        if dyn_axes:
            # Bucket every dynamic axis to the max length's rung.
            target = list(parts[0].shape)
            for ax in dyn_axes:
                target[ax] = policy.lengths.round_up(max(p.shape[ax] for p in parts))
            # True length on the first dynamic axis (the sequence axis).
            # Batch-pad rows replay record 0's LENGTH as well as its data:
            # a zero length with real data would hit 0/0 in any masked-
            # mean style computation — exactly the NaN path padding is
            # meant to avoid (pad rows are excluded via `valid` anyway).
            pad_len = parts[0].shape[dyn_axes[0]]
            lengths[name] = np.array(
                [p.shape[dyn_axes[0]] for p in parts] + [pad_len] * (b - n),
                dtype=np.int32,
            )
            padded = np.zeros((b, *target), dtype=spec.dtype)
            for i, p in enumerate(parts):
                padded[(i, *(slice(0, s) for s in p.shape))] = p
            if b > n:  # batch pad replays record 0
                padded[n:] = padded[0]
            arrays[name] = padded
        else:
            # Single preallocated contiguous buffer, one row-copy per record
            # — this fill IS the batch's host-side memory traffic, keep it 1x.
            out = np.empty((b, *parts[0].shape), dtype=spec.dtype)
            for i, p in enumerate(parts):
                out[i] = p
            if b > n:  # batch pad replays record 0
                out[n:] = out[0]
            arrays[name] = out

    valid = np.zeros((b,), dtype=bool)
    valid[:n] = True
    return Batch(arrays=arrays, valid=valid, lengths=lengths, metas=[r.meta for r in records])
