"""Pallas kernel tests (interpreter mode on CPU — same code path that
compiles on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from flink_tensorflow_tpu.ops import flash_attention, flash_attention_decode
from flink_tensorflow_tpu.parallel import full_attention


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        rng = np.random.RandomState(0)
        b, t, h, d = 2, 64, 2, 16
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal)
        got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal, block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_odd_block_sizes_shrink(self):
        rng = np.random.RandomState(1)
        b, t, h, d = 1, 24, 1, 8  # 24 not divisible by 128 -> gcd blocks
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_bfloat16_inputs(self):
        rng = np.random.RandomState(2)
        b, t, h, d = 1, 32, 2, 16
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16) for _ in range(3))
        want = full_attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32), atol=3e-2)

    def test_lse_residual_recombines_split_kv(self):
        """The returned log-sum-exp must be exactly the residual needed to
        fold two half-K/V flash calls into full attention — the contract
        the seq-axis ring relies on."""
        from flink_tensorflow_tpu.parallel.ring_attention import _combine_blocks

        rng = np.random.RandomState(3)
        b, t, h, d = 2, 32, 2, 8
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

        o1, lse1 = flash_attention(jnp.asarray(q), jnp.asarray(k[:, :16]),
                                   jnp.asarray(v[:, :16]), return_lse=True)
        o2, lse2 = flash_attention(jnp.asarray(q), jnp.asarray(k[:, 16:]),
                                   jnp.asarray(v[:, 16:]), return_lse=True)
        assert lse1.shape == (b, h, t)
        got, _ = _combine_blocks(o1, lse1, o2, lse2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_tpu_matches_interpret(self):
        """Compiled-TPU vs interpret-mode equivalence (VERDICT r1 #7).
        Skips unless a real TPU is attached (the conftest pins tests to
        the virtual CPU mesh; the driver's bench path exercises this)."""
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("needs a real TPU; interpret-only backend here")
        rng = np.random.RandomState(5)
        b, t, h, d = 2, 256, 4, 64
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16) for _ in range(3))
        for causal in (False, True):
            o_t, lse_t = flash_attention(q, k, v, causal=causal,
                                         interpret=False, return_lse=True)
            o_i, lse_i = flash_attention(q, k, v, causal=causal,
                                         interpret=True, return_lse=True)
            np.testing.assert_allclose(np.asarray(o_t, np.float32),
                                       np.asarray(o_i, np.float32), atol=3e-3)
            np.testing.assert_allclose(np.asarray(lse_t), np.asarray(lse_i), atol=1e-4)

    def test_lse_fully_masked_rows_are_neg_inf(self):
        """Causal first row attends only to itself; a fully-masked block
        (k entirely after q in a later ring step) must yield lse=-inf —
        exercised here via the ring's skip branch shape contract."""
        rng = np.random.RandomState(4)
        b, t, h, d = 1, 16, 1, 8
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        _, lse = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                 causal=True, return_lse=True)
        assert np.all(np.isfinite(np.asarray(lse)))


class TestFlashAttentionDecode:
    """Single-query decode step (the serving plane's per-token path):
    must equal the full-prefix kernel at the last valid position."""

    def test_single_step_equals_full_prefix(self):
        rng = np.random.RandomState(0)
        b, c, h, d = 3, 32, 2, 16
        lengths = np.array([32, 20, 7], np.int32)
        k = rng.randn(b, c, h, d).astype(np.float32)
        v = rng.randn(b, c, h, d).astype(np.float32)
        q1 = rng.randn(b, 1, h, d).astype(np.float32)
        got = flash_attention_decode(jnp.asarray(q1), jnp.asarray(k),
                                     jnp.asarray(v), jnp.asarray(lengths))
        # Reference: per row, full (non-causal) attention of the single
        # query over exactly the valid prefix.
        for i in range(b):
            n = lengths[i]
            want = full_attention(jnp.asarray(q1[i:i + 1]),
                                  jnp.asarray(k[i:i + 1, :n]),
                                  jnp.asarray(v[i:i + 1, :n]))
            np.testing.assert_allclose(np.asarray(got[i]),
                                       np.asarray(want[0]), atol=1e-5)

    def test_matches_causal_prefill_last_position(self):
        """Decode over a cache built by causal prefill == the causal
        kernel's output at the final position — the incremental/full
        consistency the KV cache relies on."""
        rng = np.random.RandomState(1)
        b, t, h, d = 2, 24, 2, 8
        q = rng.randn(b, t, h, d).astype(np.float32)
        k = rng.randn(b, t, h, d).astype(np.float32)
        v = rng.randn(b, t, h, d).astype(np.float32)
        full = flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=True,
                               block_q=8, block_k=8)
        step = flash_attention_decode(
            jnp.asarray(q[:, -1:]), jnp.asarray(k), jnp.asarray(v),
            jnp.full((b,), t, np.int32))
        np.testing.assert_allclose(np.asarray(step[:, 0]),
                                   np.asarray(full[:, -1]), atol=1e-5)

    def test_squeezed_3d_query_and_zero_length_rows(self):
        rng = np.random.RandomState(2)
        b, c, h, d = 2, 16, 2, 8
        q = rng.randn(b, h, d).astype(np.float32)
        k = rng.randn(b, c, h, d).astype(np.float32)
        v = rng.randn(b, c, h, d).astype(np.float32)
        lengths = np.array([10, 0], np.int32)  # row 1: inactive pool slot
        out, lse = flash_attention_decode(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(lengths), return_lse=True)
        assert out.shape == (b, h, d)
        assert np.all(np.isfinite(np.asarray(out)))
        assert np.all(np.asarray(out)[1] == 0.0)       # masked row -> zeros
        assert np.all(np.isneginf(np.asarray(lse)[1]))  # lse residual -inf

    def test_lse_recombines_split_cache_ring_style(self):
        """Two half-cache decode calls fold into the full answer via the
        ring's _combine_blocks — the sharded-decode contract."""
        from flink_tensorflow_tpu.parallel.ring_attention import _combine_blocks

        rng = np.random.RandomState(3)
        b, c, h, d = 2, 32, 2, 8
        q = rng.randn(b, 1, h, d).astype(np.float32)
        k = rng.randn(b, c, h, d).astype(np.float32)
        v = rng.randn(b, c, h, d).astype(np.float32)
        lengths = np.array([28, 11], np.int32)
        want = flash_attention_decode(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), jnp.asarray(lengths))
        half = c // 2
        lo = np.clip(lengths, 0, half).astype(np.int32)
        hi = np.clip(lengths - half, 0, half).astype(np.int32)
        o1, l1 = flash_attention_decode(jnp.asarray(q), jnp.asarray(k[:, :half]),
                                        jnp.asarray(v[:, :half]),
                                        jnp.asarray(lo), return_lse=True)
        o2, l2 = flash_attention_decode(jnp.asarray(q), jnp.asarray(k[:, half:]),
                                        jnp.asarray(v[:, half:]),
                                        jnp.asarray(hi), return_lse=True)
        # _combine_blocks wants lse as [B, H, T]; decode returns [B, H, 1].
        got, _ = _combine_blocks(o1.astype(jnp.float32), l1,
                                 o2.astype(jnp.float32), l2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


class TestShardedDecode:
    """Ring/Ulysses decode paths, smoke-tested on the virtual CPU mesh."""

    def _case(self, seed=5, b=2, c=32, h=4, d=8):
        rng = np.random.RandomState(seed)
        q = rng.randn(b, 1, h, d).astype(np.float32)
        k = rng.randn(b, c, h, d).astype(np.float32)
        v = rng.randn(b, c, h, d).astype(np.float32)
        lengths = np.array([c, 13], np.int32)[:b]
        want = flash_attention_decode(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), jnp.asarray(lengths))
        return q, k, v, lengths, want

    def test_ring_decode_matches_unsharded(self):
        from flink_tensorflow_tpu.parallel import make_mesh, ring_decode_attention

        import jax

        q, k, v, lengths, want = self._case()
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        got = ring_decode_attention(mesh, jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), jnp.asarray(lengths))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_ulysses_decode_matches_unsharded(self):
        from flink_tensorflow_tpu.parallel import (
            make_mesh,
            ulysses_decode_attention,
        )

        import jax

        q, k, v, lengths, want = self._case()
        # Shards the 4 heads over a 4-device slice of the virtual mesh.
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        got = ulysses_decode_attention(mesh, jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), jnp.asarray(lengths))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_ulysses_decode_indivisible_heads_rejected(self):
        from flink_tensorflow_tpu.parallel import (
            make_mesh,
            ulysses_decode_attention,
        )

        mesh = make_mesh({"seq": 8})
        q = jnp.zeros((1, 1, 6, 8))
        kv = jnp.zeros((1, 16, 6, 8))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_decode_attention(mesh, q, kv, kv,
                                     jnp.full((1,), 16, jnp.int32))

    def test_ring_decode_indivisible_capacity_rejected(self):
        from flink_tensorflow_tpu.parallel import make_mesh, ring_decode_attention

        mesh = make_mesh({"seq": 8})
        q = jnp.zeros((1, 1, 4, 8))
        kv = jnp.zeros((1, 30, 4, 8))  # 30 % 8 != 0
        with pytest.raises(ValueError, match="divide"):
            ring_decode_attention(mesh, q, kv, kv,
                                  jnp.full((1,), 30, jnp.int32))


class TestTileableBlocks:
    def test_block_selection_is_mosaic_legal(self):
        """Mosaic requires a block's sublane dim divisible by 8 OR equal
        to the whole array dim; the old gcd picked sizes like 4 for
        t=100, which crashed only on the real chip (interpret mode can't
        catch it)."""
        from flink_tensorflow_tpu.ops.flash_attention import _tileable_block

        for t in [8, 12, 64, 100, 128, 136, 200, 264, 1000, 1001, 4096]:
            b = _tileable_block(t, 128)
            assert t % b == 0, (t, b)
            assert b % 8 == 0 or b == t, (t, b)
            assert b <= 128 or b == t, (t, b)

    def test_non_divisible_lengths_match_reference(self):
        """Shapes that used to crash Mosaic (t=100, 264, mixed) run the
        same kernel path in interpret mode and match full attention."""
        import jax.numpy as jnp

        from flink_tensorflow_tpu.ops.flash_attention import flash_attention
        from flink_tensorflow_tpu.parallel import full_attention

        rng = np.random.RandomState(3)
        for t, tk in [(100, 100), (264, 136), (12, 200)]:
            q = rng.randn(1, t, 2, 16).astype(np.float32)
            k = rng.randn(1, tk, 2, 16).astype(np.float32)
            v = rng.randn(1, tk, 2, 16).astype(np.float32)
            got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
            want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-5)
