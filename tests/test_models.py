"""Model layer tests: zoo forward passes, loader round-trips, frozen
functions — the reference's loader-behavior unit tests (SURVEY.md §4)
recast for bundles and jax-export artifacts.

Everything is jitted: eager per-op dispatch is pathologically slow in this
environment, and the framework's production path is always-compiled anyway
(the model runner jits per batch bucket)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_tensorflow_tpu.models import (
    GraphLoader,
    SavedModelLoader,
    freeze_method,
    get_model_def,
    save_bundle,
)


@pytest.fixture(scope="module")
def rng():
    return jax.random.key(0)


def init_jit(mdef, rng):
    return jax.jit(mdef.init_fn)(rng)


class TestZoo:
    def test_lenet_serve(self, rng):
        mdef = get_model_def("lenet")
        params = init_jit(mdef, rng)
        out = jax.jit(mdef.methods["serve"].fn)(params, {"image": jnp.zeros((4, 28, 28, 1))})
        assert out["logits"].shape == (4, 10)
        assert out["label"].shape == (4,) and out["label"].dtype == jnp.int32
        np.testing.assert_allclose(np.sum(np.asarray(out["prob"]), -1), 1.0, rtol=1e-3)

    def test_resnet_tiny_serve_and_loss(self, rng):
        mdef = get_model_def("resnet50", num_classes=7, image_size=32, width=8,
                             stage_sizes=(1, 1))
        params = init_jit(mdef, rng)
        out = jax.jit(mdef.methods["serve"].fn)(params, {"image": jnp.zeros((2, 32, 32, 3))})
        assert out["logits"].shape == (2, 7)
        batch = {"image": jnp.zeros((2, 32, 32, 3)),
                 "label": jnp.array([1, 2], jnp.int32)}
        loss, (new_state, metrics) = jax.jit(mdef.loss_fn)(params, batch, rng)
        assert np.isfinite(float(loss)) and "batch_stats" in new_state
        assert 0.0 <= float(metrics["accuracy"]) <= 1.0

    def test_inception_v3_serve(self, rng):
        mdef = get_model_def("inception_v3", num_classes=10)
        params = init_jit(mdef, rng)
        out = jax.jit(mdef.methods["serve"].fn)(
            params, {"image": jnp.zeros((1, 299, 299, 3))}
        )
        assert out["logits"].shape == (1, 10)
        assert float(out["score"][0]) <= 1.0

    def test_inception_uint8_matches_prescaled_float(self, rng):
        """uint8 ingestion + on-device normalize == float ingestion of the
        same normalized pixels (the 4x-transfer-saving path is lossless
        up to bf16 rounding)."""
        mdef8 = get_model_def("inception_v3", num_classes=5, uint8_input=True)
        mdeff = get_model_def("inception_v3", num_classes=5)
        params = init_jit(mdef8, rng)
        img8 = np.random.RandomState(0).randint(0, 256, (1, 299, 299, 3)).astype(np.uint8)
        imgf = img8.astype(np.float32) / 127.5 - 1.0
        out8 = jax.jit(mdef8.methods["serve"].fn)(params, {"image": jnp.asarray(img8)})
        outf = jax.jit(mdeff.methods["serve"].fn)(params, {"image": jnp.asarray(imgf)})
        np.testing.assert_allclose(np.asarray(out8["logits"]),
                                   np.asarray(outf["logits"]), atol=0.25)

    def test_bilstm_padding_invariance(self, rng):
        """Same sequence padded to different buckets -> same logits: the
        masking contract dynamic batching relies on (BASELINE.json:9)."""
        mdef = get_model_def("bilstm", vocab_size=50, hidden_dim=16, embed_dim=8)
        params = init_jit(mdef, rng)
        tokens = np.array([3, 7, 11, 2], np.int32)
        fn = jax.jit(mdef.methods["serve"].fn)
        out8 = fn(params,
                  {"tokens": jnp.asarray(np.pad(tokens, (0, 4))[None])},
                  {"tokens": jnp.array([4], jnp.int32)})
        out16 = fn(params,
                   {"tokens": jnp.asarray(np.pad(tokens, (0, 12))[None])},
                   {"tokens": jnp.array([4], jnp.int32)})
        np.testing.assert_allclose(np.asarray(out8["logits"]),
                                   np.asarray(out16["logits"]), atol=2e-2)

    def test_widedeep_serve_and_loss(self, rng):
        mdef = get_model_def("widedeep", hash_buckets=100, embed_dim=4,
                             hidden=(16, 8))
        params = init_jit(mdef, rng)
        inputs = {
            "wide": jnp.ones((3, 64)),
            "dense": jnp.ones((3, 13)),
            "cat": jnp.zeros((3, 8), jnp.int32),
        }
        out = jax.jit(mdef.methods["serve"].fn)(params, inputs)
        assert out["prob"].shape == (3,)
        batch = dict(inputs, label=jnp.array([0, 1, 1], jnp.int32))
        loss, (_, metrics) = jax.jit(mdef.loss_fn)(params, batch, rng)
        assert np.isfinite(float(loss))

    def test_unknown_architecture(self):
        with pytest.raises(KeyError):
            get_model_def("alexnet")


class TestLoaders:
    def test_bundle_roundtrip(self, rng, tmp_path):
        mdef = get_model_def("lenet")
        params = init_jit(mdef, rng)
        path = str(tmp_path / "lenet_bundle")
        save_bundle(mdef, params, path)

        model = SavedModelLoader(path).load()
        assert model.metadata["architecture"] == "lenet"
        x = {"image": jnp.ones((2, 28, 28, 1))}
        serve = jax.jit(mdef.methods["serve"].fn)
        want = serve(params, x)
        got = serve(model.params, x)
        np.testing.assert_allclose(np.asarray(want["logits"]),
                                   np.asarray(got["logits"]), atol=1e-6)

    def test_bundle_bad_format(self, tmp_path):
        import json

        (tmp_path / "model.json").write_text(json.dumps({"format": "other"}))
        with pytest.raises(ValueError):
            SavedModelLoader(str(tmp_path)).manifest()

    def test_frozen_graph_roundtrip(self, rng, tmp_path):
        mdef = get_model_def("lenet")
        model = mdef.to_model(init_jit(mdef, rng))
        frozen_bytes = freeze_method(model, "serve", batch=2)
        path = tmp_path / "lenet.stablehlo"
        path.write_bytes(frozen_bytes)

        fn = GraphLoader(str(path)).load()
        x = {"image": jnp.ones((2, 28, 28, 1))}
        got = fn(x)
        want = jax.jit(model.method("serve").fn)(model.params, x)
        np.testing.assert_allclose(np.asarray(want["logits"]),
                                   np.asarray(got["logits"]), atol=1e-6)

    def test_missing_method(self, rng):
        mdef = get_model_def("lenet")
        model = mdef.to_model(init_jit(mdef, rng))
        with pytest.raises(KeyError):
            model.method("nope")
