"""Multi-host execution: count-based checkpoints, fan-in remote plane,
cohort supervision, and a REAL 2-process jax.distributed job.

VERDICT r1 "What's missing" #2 / next-round #5: multi-host was formation
code with no end-to-end proof.  These tests spawn actual processes —
the 2-process DP train forms a global 8-device mesh over jax.distributed
(gloo collectives on CPU), streams records between processes over the
remote record plane, and survives killing one process mid-training.
"""

import os
import sys
import threading

import numpy as np
import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.checkpoint.store import checkpoint_ids, read_checkpoint
from flink_tensorflow_tpu.io.remote import RemoteSink, RemoteSource
from flink_tensorflow_tpu.parallel import (
    CohortFailed,
    CohortSupervisor,
    latest_common_checkpoint,
)
from flink_tensorflow_tpu.tensors import TensorValue


class TestCountBasedCheckpoints:
    """Barrier positions must be a pure function of the stream — the
    cross-process consistency contract (CheckpointCoordinator docs)."""

    def _job(self, d, n=35, every=10):
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(d, every_n_records=every)
        out = (
            env.from_collection(list(range(n)), parallelism=1)
            .map(lambda x: x * 2)
            .sink_to_list()
        )
        return env, out

    def test_deterministic_positions(self, tmp_path):
        d = str(tmp_path / "chk")
        env, out = self._job(d)
        env.execute("count-chk", timeout=60)
        # Durable on return: join() drains the persistence queue.
        assert checkpoint_ids(d) == [1, 2, 3]
        for cid in (1, 2, 3):
            _, snaps = read_checkpoint(d, cid)
            # Checkpoint k cuts the source exactly after record k*N.
            assert snaps["collection"][0]["operator"]["offset"] == cid * 10

    def test_restore_from_deterministic_position(self, tmp_path):
        d = str(tmp_path / "chk")
        env, _ = self._job(d)
        env.execute("count-chk", timeout=60)
        assert len(checkpoint_ids(d)) == 3
        env2, out2 = self._job(d)
        env2.execute("count-chk", restore_from=d, restore_checkpoint_id=2, timeout=60)
        assert sorted(out2) == [x * 2 for x in range(20, 35)]

    def test_manual_trigger_rejected(self, tmp_path):
        env, _ = self._job(str(tmp_path / "chk"), n=200)
        env.source_throttle_s = 0.005
        h = env.execute_async("count-chk")
        with pytest.raises(RuntimeError, match="every_n_records"):
            h.trigger_checkpoint()
        h.wait(60)

    def test_interval_and_count_mutually_exclusive(self, tmp_path):
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path), interval_s=1.0)
        with pytest.raises(ValueError, match="mutually"):
            env.enable_checkpointing(str(tmp_path), interval_s=1.0,
                                     every_n_records=4)
            env.config.validate()


class TestRemoteFanIn:
    def test_merges_multiple_peers(self):
        n_peers, per_peer = 3, 20
        source = RemoteSource("127.0.0.1", 0, fan_in=n_peers)
        env = StreamExecutionEnvironment(parallelism=1)
        out = env.from_source(source, name="fanin", parallelism=1).sink_to_list()

        def ship(worker):
            senv = StreamExecutionEnvironment(parallelism=1)
            data = [
                TensorValue({"x": np.float32(i)}, meta={"w": worker, "i": i})
                for i in range(per_peer)
            ]
            senv.from_collection(data, parallelism=1).add_sink(
                RemoteSink("127.0.0.1", source.port)
            )
            senv.execute(f"ship-{worker}", timeout=60)

        threads = [threading.Thread(target=ship, args=(w,)) for w in range(n_peers)]
        for t in threads:
            t.start()
        env.execute("fanin", timeout=60)
        for t in threads:
            t.join(timeout=10)
        assert len(out) == n_peers * per_peer
        by_worker = {}
        for r in out:
            by_worker.setdefault(int(r.meta["w"]), []).append(int(r.meta["i"]))
        # Per-peer order preserved; cross-peer interleaving unordered.
        assert set(by_worker) == set(range(n_peers))
        for ids in by_worker.values():
            assert ids == sorted(ids)

    def test_fan_in_validates(self):
        with pytest.raises(ValueError):
            RemoteSource("127.0.0.1", 0, fan_in=0)


class TestCohortSupervisor:
    def _worker_cmd(self, marker_dir, fail_on_attempt_0):
        def command(worker, num_workers, attempt):
            fail = fail_on_attempt_0 and attempt == 0 and worker == 1
            body = (
                f"import sys, pathlib;"
                f"pathlib.Path(r'{marker_dir}', f'w{worker}_a{attempt}').touch();"
                f"sys.exit({1 if fail else 0})"
            )
            return [sys.executable, "-c", body]

        return command

    def test_restarts_cohort_on_failure(self, tmp_path):
        sup = CohortSupervisor(
            self._worker_cmd(tmp_path, fail_on_attempt_0=True), 2,
            max_restarts=2, poll_s=0.05,
        )
        outcome = sup.run()
        assert outcome.attempts == 2
        assert (tmp_path / "w0_a1").exists() and (tmp_path / "w1_a1").exists()

    def test_gives_up_after_max_restarts(self, tmp_path):
        def always_fail(worker, num_workers, attempt):
            return [sys.executable, "-c", "import sys; sys.exit(3)"]

        sup = CohortSupervisor(always_fail, 2, max_restarts=1, poll_s=0.05)
        with pytest.raises(CohortFailed):
            sup.run()

    def test_latest_common_checkpoint(self, tmp_path):
        from flink_tensorflow_tpu.checkpoint.store import write_checkpoint

        d0, d1 = str(tmp_path / "w0"), str(tmp_path / "w1")
        for cid in (1, 2, 3):
            write_checkpoint(d0, cid, {"t": {0: {"x": cid}}})
        for cid in (1, 2):  # w1 died before checkpoint 3 completed
            write_checkpoint(d1, cid, {"t": {0: {"x": cid}}})
        assert latest_common_checkpoint([d0, d1]) == 2
        assert latest_common_checkpoint([d0, str(tmp_path / "missing")]) is None


@pytest.mark.slow
class TestTwoProcessDPTrain:
    """The end-to-end cluster proof: 2 OS processes, global mesh, remote
    record plane, injected failure, cohort restart from a common
    checkpoint.  (~60s: spawns 4 worker processes total, each compiling
    the train step.)"""

    def test_two_process_train_with_failure_recovery(self, tmp_path):
        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        from examples import multihost_dp_train

        summary = multihost_dp_train.main([
            "--records-per-worker", "32",
            "--global-batch", "8",
            "--ckpt-every-steps", "2",
            "--fail-at-step", "4",
            "--work-dir", str(tmp_path),
        ])
        assert summary["workers"] == 2
        assert summary["global_devices"] == 8  # 2 processes x 4 devices
        assert summary["cohort_attempts"] == 2  # one injected failure
        # Restored from the checkpoint BOTH workers completed, then
        # replayed to the end: 8 total steps, restore at step 2*2=4 -> 4
        # replayed + 4 new... steps_final_attempt counts post-restore only.
        assert summary["restored_checkpoint"] is not None
        assert summary["losses_agree_across_workers"]
        assert summary["aggregate"]["workers_reporting"] == [0, 1]
        # Total stream fully processed on the final attempt.
        total_steps = 32 // (8 // 2)
        restored_steps = summary["restored_checkpoint"] * 2
        assert summary["steps_final_attempt"] == total_steps - restored_steps
