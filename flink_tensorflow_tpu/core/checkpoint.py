"""Checkpoint coordinator — aligned snapshots with params included.

The reference inherits Flink's Chandy-Lamport barrier snapshots, but TF
session variables live OUTSIDE Flink state, so its training path risks
losing model progress on failover (SURVEY.md §5 "Checkpoint / resume").
The rebuild fixes that by construction: model parameters are explicit
operator state (pytrees), so every snapshot captures them natively.

Disk format: one directory per checkpoint, one file per subtask, written
with the tensor-aware serializer (numpy/jax arrays -> npz-style payloads,
the rest pickled) — see flink_tensorflow_tpu.checkpoint.store.
"""

from __future__ import annotations

import threading
import time
import typing

if typing.TYPE_CHECKING:
    from flink_tensorflow_tpu.core.runtime import LocalExecutor, _Subtask


class _PendingCheckpoint:
    def __init__(self, checkpoint_id: int, expected: int, *, source_initiated: bool = False):
        self.checkpoint_id = checkpoint_id
        self.expected = expected
        self.snapshots: typing.Dict[str, typing.Dict[int, typing.Any]] = {}
        self.acks = 0
        self.done = threading.Event()
        self.failed = False
        #: Set by the deadline sweeper: the checkpoint was declined
        #: (missed checkpoint_timeout_s) and its state discarded.
        self.aborted = False
        #: Count-based checkpoints have no trigger() caller waiting on
        #: them — persistence happens on completion, off the ack thread.
        self.source_initiated = source_initiated
        #: Trigger time — end-to-end duration (through persistence) is
        #: measured from here for the checkpoint.duration_s timer.
        self.created_s = time.monotonic()


class CheckpointCoordinator:
    """Collects one snapshot per subtask per aligned checkpoint.

    Two trigger modes:

    - ``trigger()`` (timer/manual): the coordinator allocates an id and
      asks every source to inject a barrier at its CURRENT position.
      One such checkpoint runs at a time.
    - **source-initiated** (``begin_source_checkpoint``): with
      ``CheckpointConfig.every_n_records``, each source injects barrier
      ``k`` deterministically after its ``k*N``-th record.  Barrier
      positions are then a pure function of the stream — the property
      multi-host cohorts need, since each host checkpoints independently
      and can only restore a checkpoint all hosts cut at the SAME stream
      position (see parallel/supervisor.latest_common_checkpoint).
      Several such checkpoints may be in flight when source subtasks run
      at different speeds; per-gate channel blocking still serializes
      alignment within each gate.
    """

    def __init__(self, executor: "LocalExecutor", checkpoint_dir: typing.Optional[str] = None):
        self.executor = executor
        self.checkpoint_dir = checkpoint_dir
        #: Job-level checkpoint metrics under the "checkpoint" scope:
        #: duration_s timer (trigger -> durable), completed counter, and
        #: last-id/last-size gauges.  Per-subtask ALIGNMENT time lives on
        #: each subtask's own scope (checkpoint_alignment_s, core/runtime).
        #: Executor doubles without a registry get a private one — the
        #: coordinator must work against the bare protocol it documents.
        registry = getattr(executor, "metrics", None)
        if registry is None:
            from flink_tensorflow_tpu.metrics.registry import MetricRegistry

            registry = MetricRegistry()
        self.metrics = registry.group("checkpoint")
        #: Span tracer (tracing plane): checkpoint-lifecycle events land
        #: on the job-level "checkpoint" track — trigger instants and a
        #: span per completed checkpoint (trigger -> durable).  None on
        #: untraced jobs (and bare-protocol executor doubles).
        self.tracer = getattr(executor, "tracer", None)
        self._last_checkpoint_id: typing.Optional[int] = None
        self._last_size_bytes: typing.Optional[int] = None
        self.metrics.gauge("last_checkpoint_id", lambda: self._last_checkpoint_id)
        self.metrics.gauge("last_size_bytes", lambda: self._last_size_bytes)
        #: Checkpoint ids declined at their deadline (the recovery
        #: observability catalogue's ``checkpoints_aborted``): a stuck
        #: barrier no longer wedges the job — the sweeper discards the
        #: expired checkpoint and sources keep triggering later ones.
        self.aborted_ids: typing.List[int] = []
        registry.group("recovery").gauge(
            "checkpoints_aborted", lambda: len(self.aborted_ids))
        #: Distributed record plane: barriers may originate at sources on
        #: PEER processes, so the first local sighting of checkpoint k is
        #: an ack from a worker subtask, not begin_source_checkpoint —
        #: register the pending checkpoint lazily at that ack.
        self.lazy_register = False
        #: Distributed commit point: called with the checkpoint id after
        #: the LOCAL shard is durable and before notifications fire.  A
        #: False return withholds the 2PC commit signal (the checkpoint
        #: is not yet durable on every process); staged sink transactions
        #: then promote via a later checkpoint, clean finish, or restore.
        self.commit_gate: typing.Optional[typing.Callable[[int], bool]] = None
        #: Extra fields persisted in the __job__ snapshot entry (and the
        #: shard's METADATA.json) — the distributed executor records the
        #: cohort shape here so restore can validate shard-set
        #: completeness instead of inferring it from a directory listing.
        self.job_meta_extra: typing.Dict[str, typing.Any] = {}
        self._next_id = 1
        #: Debug-mode sanitizer: the ack/trigger lock joins the
        #: happens-before record so its ordering against the gate /
        #: split-coordinator / mailbox locks is checked (the observed
        #: legal order is checkpoint.lock -> split.lock -> mailbox —
        #: any reverse acquisition is a lock-order inversion finding).
        san = getattr(executor, "sanitizer", None)
        self._lock = (san.lock("checkpoint.lock") if san is not None
                      else threading.Lock())
        #: Serializes whole trigger() calls: a trigger arriving while one
        #: is in flight (manual colliding with the periodic timer) queues
        #: behind it instead of failing.
        self._trigger_lock = threading.Lock()
        self._pending: typing.Dict[int, _PendingCheckpoint] = {}
        self._completed: typing.List[int] = []
        #: Final snapshots of subtasks that finished (bounded jobs): used to
        #: complete checkpoints racing with job completion.
        self._final_snapshots: typing.Dict[typing.Tuple[str, int], typing.Any] = {}
        #: Serializes source-initiated checkpoint persistence (one write at
        #: a time, in completion order) and lets join() drain it so a
        #: completed checkpoint is durable before the job reports done.
        self._persist_pool = None
        self._persist_futures: typing.List[typing.Any] = []
        #: Deadline sweeper for SOURCE-INITIATED checkpoints (trigger()
        #: callers enforce their own timeout): started lazily at the
        #: first registration, it declines any pending checkpoint older
        #: than ``executor.checkpoint_timeout_s`` — late acks land in
        #: the void, subtasks drop the alignment, and the job keeps
        #: flowing instead of wedging behind a barrier that never
        #: arrives (dead subtask, severed edge, stalled operator).
        self._abort_thread: typing.Optional[threading.Thread] = None
        self._abort_stop = threading.Event()

    def resume_from(self, checkpoint_id: int) -> None:
        """Continue numbering after a restored checkpoint so new snapshots
        never overwrite the restore point."""
        with self._lock:
            self._next_id = max(self._next_id, checkpoint_id + 1)

    # -- trigger ----------------------------------------------------------
    def trigger(self, timeout: float = 60.0) -> typing.Dict[str, typing.Dict[int, typing.Any]]:
        """Run one aligned checkpoint; returns {task: {subtask: snapshot}}.

        Concurrent callers queue: if a checkpoint is already in flight
        (e.g. a manual ``trigger_checkpoint`` colliding with the periodic
        timer), the second call waits for the first to drain — within the
        same ``timeout`` budget — and then runs its own checkpoint.
        """
        if self.executor.checkpoint_every_n:
            raise RuntimeError(
                "manual/timer checkpoints are disabled when "
                "checkpoint.every_n_records is set — barrier positions must "
                "stay a deterministic function of the stream"
            )
        if self.lazy_register or self.commit_gate is not None:
            # A manual trigger reaches only LOCAL sources and would
            # commit without the global durability gate — on a cohort
            # that is a divergent, gate-bypassing checkpoint.
            raise RuntimeError(
                "manual checkpoints are not available on distributed jobs — "
                "configure checkpoint.every_n_records (deterministic "
                "cohort-wide barrier positions)"
            )
        deadline = time.monotonic() + timeout
        if not self._trigger_lock.acquire(timeout=timeout):
            raise TimeoutError(
                f"another checkpoint did not drain within {timeout}s"
            )
        try:
            return self._trigger_locked(max(0.05, deadline - time.monotonic()))
        finally:
            self._trigger_lock.release()

    def _with_job_meta(self, snapshots):
        """Persisted checkpoints pin the key-group count: restoring under
        a different max_parallelism would silently orphan keyed state
        (the hash routing changes; Flink pins maxParallelism the same way)."""
        return {
            **snapshots,
            "__job__": {0: {"max_parallelism": self.executor.max_parallelism,
                            **self.job_meta_extra}},
        }

    def _seed_finished(self, pending: _PendingCheckpoint) -> None:
        """Subtasks already finished ack immediately with their final state
        (caller holds the lock)."""
        for (task, idx), snap in self._final_snapshots.items():
            pending.snapshots.setdefault(task, {})[idx] = snap
            pending.acks += 1
        if pending.acks >= pending.expected:
            pending.done.set()

    def _trigger_locked(self, timeout: float) -> typing.Dict[str, typing.Dict[int, typing.Any]]:
        with self._lock:
            cid = self._next_id
            self._next_id += 1
            pending = _PendingCheckpoint(cid, self.executor.total_subtasks)
            self._pending[cid] = pending
            self._seed_finished(pending)
        if self.tracer is not None:
            self.tracer.instant("checkpoint", "trigger",
                                args={"checkpoint": cid})
        sources = [st for st in self.executor.subtasks if st.t.is_source]
        for st in sources:
            st.request_checkpoint(cid)
        if not pending.done.wait(timeout):
            with self._lock:
                self._pending.pop(cid, None)
                self.aborted_ids.append(cid)
            self._announce_abort(cid, "trigger timeout")
            raise TimeoutError(f"checkpoint {cid} did not complete within {timeout}s")
        with self._lock:
            self._pending.pop(cid, None)
        if pending.failed:
            raise RuntimeError(f"checkpoint {cid} failed (job cancelled)")
        self._completed.append(cid)
        chk_path = None
        if self.checkpoint_dir is not None:
            from flink_tensorflow_tpu.checkpoint.store import write_checkpoint

            faults = getattr(self.executor, "faults", None)
            if faults is not None:
                faults.store_point(cid)
            chk_path = write_checkpoint(
                self.checkpoint_dir, cid, self._with_job_meta(pending.snapshots))
        self._record_completed(pending, chk_path)
        # Durable (or in-memory-complete): fire the commit signal for
        # two-phase sinks.  Durability-before-notify is the 2PC order.
        self.executor.notify_checkpoint_complete(cid)
        self._prune()
        return pending.snapshots

    def begin_source_checkpoint(self, checkpoint_id: int) -> bool:
        """Register a count-based checkpoint (idempotent across the source
        subtasks that reach the trigger position).  Returns True when the
        calling source should snapshot+broadcast its barrier, False when
        the id belongs to an already-completed/restored checkpoint."""
        with self._lock:
            if checkpoint_id in self._pending:
                return True
            if checkpoint_id < self._next_id:
                return False  # restored past it, or already completed
            pending = _PendingCheckpoint(
                checkpoint_id, self.executor.total_subtasks, source_initiated=True
            )
            self._pending[checkpoint_id] = pending
            self._next_id = max(self._next_id, checkpoint_id + 1)
            self._seed_finished(pending)
            self._ensure_abort_sweeper_locked()
        return True

    # -- deadline abort ----------------------------------------------------
    def _ensure_abort_sweeper_locked(self) -> None:
        """Start the deadline sweeper lazily (caller holds ``_lock``)."""
        if self._abort_thread is not None or self._abort_stop.is_set():
            return
        self._abort_thread = threading.Thread(
            target=self._abort_loop, name="checkpoint-abort-sweeper",
            daemon=True,
        )
        self._abort_thread.start()

    def _abort_loop(self) -> None:
        timeout = getattr(self.executor, "checkpoint_timeout_s", 60.0)
        interval = max(0.02, min(timeout / 4.0, 1.0))
        cancelled = getattr(self.executor, "cancelled", None)
        all_done = getattr(self.executor, "_all_done", None)
        while not self._abort_stop.wait(interval):
            if ((cancelled is not None and cancelled.is_set())
                    or (all_done is not None and all_done.is_set())):
                return
            now = time.monotonic()
            expired: typing.List[_PendingCheckpoint] = []
            with self._lock:
                for cid, pending in list(self._pending.items()):
                    if (pending.source_initiated
                            and now - pending.created_s > timeout):
                        pending.failed = True
                        pending.aborted = True
                        pending.done.set()
                        del self._pending[cid]
                        self.aborted_ids.append(cid)
                        expired.append(pending)
            for pending in expired:
                self._announce_abort(
                    pending.checkpoint_id,
                    f"missed deadline ({timeout:.1f}s) with "
                    f"{pending.acks}/{pending.expected} acks",
                )

    def _announce_abort(self, checkpoint_id: int, why: str) -> None:
        """Log/trace/flight one declined checkpoint and fan the abort out
        to the subtasks (they drop the id's alignment state)."""
        import logging

        logging.getLogger(__name__).warning(
            "checkpoint %d aborted: %s — discarded; sources keep "
            "triggering later checkpoints", checkpoint_id, why)
        if self.tracer is not None:
            self.tracer.instant("checkpoint", "abort",
                                args={"checkpoint": checkpoint_id,
                                      "why": why})
        flight = getattr(self.executor, "flight", None)
        if flight is not None:
            flight.record("checkpoint", "abort",
                          {"checkpoint": checkpoint_id, "why": why})
        notify = getattr(self.executor, "notify_checkpoint_aborted", None)
        if notify is not None:
            notify(checkpoint_id)

    def _complete_locked(self, pending: _PendingCheckpoint) -> None:
        """Finish a source-initiated checkpoint (no trigger() caller).

        MUST be called while holding ``self._lock``: the persist/notify
        job is enqueued to the single-worker pool in the same critical
        section that decided completion, so jobs are strictly ordered by
        checkpoint id.  Submitting after releasing the lock let two acking
        threads race — checkpoint k+1's notify could run before k was
        durable, and a 2PC sink would promote k-bound transactions on a
        checkpoint whose write might still fail.  join() /
        wait_for_persistence drain the queue, so completed checkpoints
        (and, without a checkpoint_dir, their notifications) land before
        the job reports done."""
        self._completed.append(pending.checkpoint_id)

        if self.checkpoint_dir is None:
            def job():
                self._record_completed(pending, None)
                if self.commit_gate is not None and not self.commit_gate(
                        pending.checkpoint_id):
                    return
                self.executor.notify_checkpoint_complete(pending.checkpoint_id)
        else:
            def job():
                from flink_tensorflow_tpu.checkpoint.store import write_checkpoint

                try:
                    faults = getattr(self.executor, "faults", None)
                    if faults is not None:
                        # Chaos plane: a scheduled store_fail raises here
                        # and takes the same decline path a real disk
                        # failure would — NOT durable, no commit signal.
                        faults.store_point(pending.checkpoint_id)
                    chk_path = write_checkpoint(
                        self.checkpoint_dir, pending.checkpoint_id,
                        self._with_job_meta(pending.snapshots))
                except Exception:
                    import logging

                    logging.getLogger(__name__).warning(
                        "persisting checkpoint %d failed", pending.checkpoint_id,
                        exc_info=True,
                    )
                    with self._lock:
                        self.aborted_ids.append(pending.checkpoint_id)
                    self._announce_abort(
                        pending.checkpoint_id, "checkpoint-store write failed")
                    return  # NOT durable: the 2PC commit signal must not fire
                self._record_completed(pending, chk_path)
                # Distributed jobs gate the commit signal on the checkpoint
                # being durable on EVERY process — a locally-durable shard
                # of a globally-incomplete checkpoint must not promote 2PC
                # transactions (a cohort restore would rewind past it).
                if self.commit_gate is not None and not self.commit_gate(
                        pending.checkpoint_id):
                    return
                self.executor.notify_checkpoint_complete(pending.checkpoint_id)
                # Retention runs only behind a durable-and-notified newer
                # checkpoint (on a cohort: behind its GLOBAL commit).
                self._prune()

        if self._persist_pool is None:
            import concurrent.futures

            self._persist_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="chk-persist"
            )
        self._persist_futures.append(self._persist_pool.submit(job))

    def _record_completed(self, pending: _PendingCheckpoint,
                          chk_path: typing.Optional[str]) -> None:
        """Checkpoint bookkeeping metrics — once per completed checkpoint,
        off the record path (trigger caller or persist worker)."""
        if self.tracer is not None:
            # The whole checkpoint lifecycle as one span on the job
            # track: barrier inject instants and per-subtask align /
            # snapshot spans nest visually inside it in Perfetto.
            self.tracer.span(
                "checkpoint", "checkpoint", pending.created_s,
                time.monotonic(),
                args={"checkpoint": pending.checkpoint_id,
                      "path": chk_path})
        self.metrics.timer("duration_s").update(
            time.monotonic() - pending.created_s)
        self.metrics.counter("completed").inc()
        self._last_checkpoint_id = pending.checkpoint_id
        if chk_path is not None:
            from flink_tensorflow_tpu.checkpoint.store import (
                checkpoint_size_bytes,
            )

            self._last_size_bytes = checkpoint_size_bytes(chk_path)

    def _prune(self) -> None:
        """Apply the retained-checkpoints policy (keep the newest N on
        disk) — called only after a newer checkpoint is durable AND its
        notifications fired, so nothing a 2PC sink still depends on can
        disappear."""
        retain = getattr(self.executor, "checkpoint_retain_last", None)
        if retain is None or self.checkpoint_dir is None:
            return
        from flink_tensorflow_tpu.checkpoint.store import prune_checkpoints

        prune_checkpoints(self.checkpoint_dir, retain)

    def wait_for_persistence(self, timeout: typing.Optional[float] = 60.0) -> int:
        """Block until every completed checkpoint has landed on disk.

        Returns the number of writes STILL in flight after ``timeout``
        (0 = fully durable); unfinished futures stay queued so a later
        call can drain them — they are never silently dropped."""
        import concurrent.futures

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                futures = list(self._persist_futures)
            if not futures:
                return 0
            budget = None if deadline is None else deadline - time.monotonic()
            if budget is not None and budget <= 0:
                return len(futures)
            done, _ = concurrent.futures.wait(futures, timeout=budget)
            with self._lock:
                # Remove only what finished; checkpoints completing DURING
                # the wait re-enter the loop and are drained too.
                self._persist_futures = [
                    f for f in self._persist_futures if f not in done
                ]

    # -- subtask callbacks -------------------------------------------------
    def ack(self, checkpoint_id: int, task: str, subtask_index: int, snapshot: typing.Any) -> None:
        with self._lock:
            pending = self._pending.get(checkpoint_id)
            if (pending is None and self.lazy_register
                    and checkpoint_id >= self._next_id):
                pending = _PendingCheckpoint(
                    checkpoint_id, self.executor.total_subtasks,
                    source_initiated=True,
                )
                self._pending[checkpoint_id] = pending
                self._next_id = checkpoint_id + 1
                self._seed_finished(pending)
                self._ensure_abort_sweeper_locked()
            if pending is None:
                return
            pending.snapshots.setdefault(task, {})[subtask_index] = snapshot
            pending.acks += 1
            finished = pending.acks >= pending.expected
            if finished:
                pending.done.set()
                if pending.source_initiated:
                    del self._pending[checkpoint_id]
                    if not pending.failed:
                        self._complete_locked(pending)

    def subtask_finished(self, subtask: "_Subtask") -> None:
        # One final snapshot per LOGICAL operator: a chained subtask
        # carries several fused operators (core/runtime._ChainedUnit),
        # each with its own (task, index) checkpoint identity.
        with self._lock:
            for unit in subtask.units:
                key = (unit.t.name, unit.index)
                try:
                    snap = unit.operator.snapshot()
                except Exception:  # pragma: no cover - state already released
                    snap = None
                self._final_snapshots[key] = snap
                for cid, pending in list(self._pending.items()):
                    if unit.index not in pending.snapshots.get(unit.t.name, {}):
                        pending.snapshots.setdefault(unit.t.name, {})[unit.index] = snap
                        pending.acks += 1
                        if pending.acks >= pending.expected:
                            pending.done.set()
                            if pending.source_initiated:
                                del self._pending[cid]
                                if not pending.failed:
                                    self._complete_locked(pending)

    def cancel_pending(self) -> None:
        self._abort_stop.set()
        with self._lock:
            for pending in self._pending.values():
                pending.failed = True
                pending.done.set()
            self._pending.clear()

    @property
    def completed_ids(self) -> typing.List[int]:
        return list(self._completed)
