"""Snapshot persistence — tensor-aware, atomic, resumable.

Device arrays are pulled to host (one ``jax.device_get`` per snapshot, off
the hot path — snapshots happen at barrier alignment, never inside a jitted
step, SURVEY.md §7 hard part 5) and stored as numpy inside a pickle.  A
checkpoint directory is only visible under its final name after a full
write + fsync-rename, so a crash mid-write can never yield a torn restore
point.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import shutil
import typing


class _PRNGKeyData:
    """Picklable stand-in for a typed PRNG key (extended dtypes cannot be
    np.asarray'd).  Stores the raw counter words + impl name; rebuilt with
    ``jax.random.wrap_key_data`` on read."""

    __slots__ = ("impl", "data")

    def __init__(self, impl: str, data) -> None:
        self.impl = impl
        self.data = data

    def __eq__(self, other) -> bool:
        import numpy as np

        return (
            isinstance(other, _PRNGKeyData)
            and self.impl == other.impl
            and np.array_equal(self.data, other.data)
        )


def _to_host(obj: typing.Any) -> typing.Any:
    """Convert jax arrays to numpy so snapshots pickle portably.

    Manual recursion rather than ``jax.tree.map``: tree flattening sorts
    dict keys, which raises on the mixed-type keys keyed state legally
    contains (int and str user keys in one table).  Namedtuples — optax's
    ScaleByAdamState et al. — are rebuilt as their own type, and typed
    PRNG keys become picklable :class:`_PRNGKeyData` markers."""
    import jax
    import numpy as np

    if isinstance(obj, jax.Array):
        if jax.dtypes.issubdtype(obj.dtype, jax.dtypes.prng_key):
            return _PRNGKeyData(
                str(jax.random.key_impl(obj)),
                np.asarray(jax.random.key_data(obj)),
            )
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        converted = [_to_host(v) for v in obj]
        if hasattr(obj, "_fields"):  # namedtuple: keep the type
            return type(obj)(*converted)
        return type(obj)(converted)
    return obj


def _rebuild_keys(obj: typing.Any) -> typing.Any:
    """Inverse of the PRNG-key marker in :func:`_to_host`."""
    import jax

    if isinstance(obj, _PRNGKeyData):
        return jax.random.wrap_key_data(jax.numpy.asarray(obj.data), impl=obj.impl)
    if isinstance(obj, dict):
        return {k: _rebuild_keys(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        converted = [_rebuild_keys(v) for v in obj]
        if hasattr(obj, "_fields"):
            return type(obj)(*converted)
        return type(obj)(converted)
    return obj


def _chk_dir(base: str, checkpoint_id: int) -> str:
    return os.path.join(base, f"chk-{checkpoint_id:06d}")


def write_checkpoint(
    base_dir: str,
    checkpoint_id: int,
    snapshots: typing.Dict[str, typing.Dict[int, typing.Any]],
) -> str:
    os.makedirs(base_dir, exist_ok=True)
    final = _chk_dir(base_dir, checkpoint_id)
    tmp = final + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    # fsync data AND directories before the rename: the rename alone is
    # journaled, the data blocks are not — without this a crash right
    # after os.replace can expose chk-N with a truncated state.pkl, and
    # restore then fails on the "latest" checkpoint instead of falling
    # back (the torn-restore-point this layout exists to prevent).
    with open(os.path.join(tmp, "state.pkl"), "wb") as f:
        pickle.dump(_to_host(snapshots), f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    meta = {
        "checkpoint_id": checkpoint_id,
        "tasks": {task: sorted(per_sub.keys()) for task, per_sub in snapshots.items()},
        # Cohort shape (distributed shards): lets restore validate the
        # shard set and pick same-shape fast paths WITHOUT unpickling
        # the state payloads.
        "job": snapshots.get("__job__", {}).get(0, {}),
    }
    with open(os.path.join(tmp, "METADATA.json"), "w") as f:
        json.dump(meta, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(tmp)
    if os.path.exists(final):
        shutil.rmtree(final)
    os.replace(tmp, final)
    _fsync_dir(base_dir)
    return final


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def checkpoint_size_bytes(chk_path: str) -> int:
    """On-disk footprint of one written checkpoint directory (state +
    metadata) — feeds the coordinator's ``checkpoint.last_size_bytes``
    gauge.  Called once per completed checkpoint, never on the record
    path.  0 when the directory vanished (pruned concurrently)."""
    total = 0
    try:
        for root, _, files in os.walk(chk_path):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(root, name))
                except OSError:
                    continue
    except OSError:
        return 0
    return total


def checkpoint_ids(base_dir: str) -> typing.List[int]:
    """All completed checkpoint ids under ``base_dir``, ascending."""
    if not os.path.isdir(base_dir):
        return []
    ids = []
    for name in os.listdir(base_dir):
        if name.startswith("chk-") and not name.endswith(".tmp"):
            try:
                ids.append(int(name[4:]))
            except ValueError:
                continue
    return sorted(ids)


def latest_checkpoint_id(base_dir: str) -> typing.Optional[int]:
    ids = checkpoint_ids(base_dir)
    return ids[-1] if ids else None


def read_checkpoint(
    base_dir: str, checkpoint_id: typing.Optional[int] = None
) -> typing.Tuple[int, typing.Dict[str, typing.Dict[int, typing.Any]]]:
    if checkpoint_id is None:
        checkpoint_id = latest_checkpoint_id(base_dir)
        if checkpoint_id is None:
            raise FileNotFoundError(f"no checkpoints under {base_dir}")
    with open(os.path.join(_chk_dir(base_dir, checkpoint_id), "state.pkl"), "rb") as f:
        return checkpoint_id, _rebuild_keys(pickle.load(f))


def prune_checkpoints(base_dir: str, keep_last: int) -> typing.List[int]:
    """Delete all but the newest ``keep_last`` completed checkpoints
    under ``base_dir``; returns the deleted ids (Flink's retained-
    checkpoints policy).

    Deletion is oldest-first, best-effort, and ATOMIC with respect to
    ``checkpoint_ids``: the directory is renamed to ``.pruning`` (one
    journaled operation that removes it from the completed set) before
    the recursive delete, so a partially-failed rmtree can never leave a
    torn ``chk-N`` that restore would select and then fail on — the
    same either-absent-or-complete invariant the fsync+rename write
    path guarantees."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    # Reap .pruning orphans first: a crash between the rename and the
    # recursive delete leaves a directory checkpoint_ids no longer
    # lists, so nothing else would ever reclaim it.
    if os.path.isdir(base_dir):
        for name in os.listdir(base_dir):
            if name.endswith(".pruning"):
                try:
                    shutil.rmtree(os.path.join(base_dir, name))
                except OSError:  # pragma: no cover - retried next prune
                    pass
    ids = checkpoint_ids(base_dir)
    deleted = []
    for cid in ids[:-keep_last]:
        final = _chk_dir(base_dir, cid)
        doomed = final + ".pruning"
        try:
            if os.path.exists(doomed):
                shutil.rmtree(doomed)
            os.rename(final, doomed)
        except OSError:  # pragma: no cover - fs race/permissions
            logging.getLogger(__name__).warning(
                "could not prune checkpoint %d under %s", cid, base_dir,
                exc_info=True,
            )
            continue
        deleted.append(cid)
        try:
            shutil.rmtree(doomed)
        except OSError:  # pragma: no cover - reaped by a later prune
            pass
    return deleted


def cohort_process_dirs(base_dir: str) -> typing.List[str]:
    """The per-process shard directories a distributed cohort wrote under
    one shared checkpoint base (``proc-00000``, ``proc-00001``, ...)."""
    if not os.path.isdir(base_dir):
        return []
    return sorted(
        os.path.join(base_dir, name)
        for name in os.listdir(base_dir)
        if name.startswith("proc-") and os.path.isdir(os.path.join(base_dir, name))
    )


def read_shard_meta(shard_dir: str, checkpoint_id: int) -> typing.Optional[dict]:
    """A shard's METADATA.json for one checkpoint (no state unpickling);
    None when the checkpoint or the metadata file is absent (pre-r3
    shards carry no metadata for the cohort fields)."""
    path = os.path.join(_chk_dir(shard_dir, checkpoint_id), "METADATA.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _complete_shard_set(
    dirs: typing.Sequence[str], checkpoint_id: int,
    ids_by_dir: typing.Optional[typing.Mapping[str, typing.Set[int]]] = None,
) -> typing.Optional[typing.List[str]]:
    """The shard directories forming a COMPLETE cohort snapshot of
    ``checkpoint_id``, or None.

    Completeness comes from the cohort shape each shard RECORDED at
    write time (num_processes + participants + process_index in
    METADATA.json): the shards holding the id must all agree on the
    shape and cover the recorded PARTICIPANT set exactly.  Participants
    — the processes owning >= 1 subtask — rather than {0..P-1}, because
    an over-provisioned cohort (num_processes > max operator
    parallelism) legally has idle processes that never write a shard;
    requiring every index would deem each of its checkpoints incomplete
    forever (ADVICE r3 medium).  A directory listing alone cannot
    distinguish "cohort of 2" from "cohort of 3 minus a lost shard" —
    and a stale shard from a previous cohort shape (which simply lacks
    this id) must not veto the id.  Shards that recorded num_processes
    but no participant set (r3) imply participants = {0..P-1}; shards
    written before any shape was recorded fall back to the oldest rule:
    the id must be present in EVERY proc-* directory.
    """
    if ids_by_dir is None:
        ids_by_dir = {d: set(checkpoint_ids(d)) for d in dirs}
    having = [d for d in dirs if checkpoint_id in ids_by_dir[d]]
    if not having:
        return None
    metas = [read_shard_meta(d, checkpoint_id) for d in having]
    jobs = [(m or {}).get("job", {}) for m in metas]
    shapes = [j.get("num_processes") for j in jobs]
    if any(p is None for p in shapes):
        # Legacy shards: no recorded shape — complete iff universal.
        return having if len(having) == len(dirs) else None
    if len(set(shapes)) != 1:
        return None
    expected_participants = {
        tuple(j["participants"]) if j.get("participants") is not None
        else tuple(range(shapes[0]))
        for j in jobs
    }
    if len(expected_participants) != 1:
        return None
    expected = set(expected_participants.pop())
    indices = {j.get("process_index") for j in jobs}
    if len(having) == len(expected) and indices == expected:
        return having
    return None


def select_cohort_checkpoint(
    base_dir: str, checkpoint_id: typing.Optional[int] = None
) -> typing.Tuple[int, typing.List[str]]:
    """Pick ``(checkpoint_id, complete shard dirs)`` under a shared
    cohort base — metadata-only (no state unpickling).  With
    ``checkpoint_id=None``: the highest id with a complete shard set;
    an explicit id with an incomplete set raises loudly."""
    dirs = cohort_process_dirs(base_dir)
    if not dirs:
        raise FileNotFoundError(f"no proc-* shard directories under {base_dir}")
    # One directory listing per shard, shared across candidate ids.
    ids_by_dir = {d: set(checkpoint_ids(d)) for d in dirs}
    if checkpoint_id is None:
        candidates: typing.Set[int] = set()
        for ids in ids_by_dir.values():
            candidates.update(ids)
        for cid in sorted(candidates, reverse=True):
            shard_set = _complete_shard_set(dirs, cid, ids_by_dir)
            if shard_set is not None:
                return cid, shard_set
        raise FileNotFoundError(
            f"no checkpoint under {base_dir} has a complete cohort shard set"
        )
    shard_set = _complete_shard_set(dirs, checkpoint_id, ids_by_dir)
    if shard_set is None:
        raise ValueError(
            f"checkpoint {checkpoint_id} under {base_dir} has an INCOMPLETE "
            "cohort shard set (a process's shard is missing or shards "
            "disagree on the cohort shape) — restoring it would silently "
            "drop that shard's state"
        )
    return checkpoint_id, shard_set


def read_cohort_checkpoint(
    base_dir: str, checkpoint_id: typing.Optional[int] = None
) -> typing.Tuple[int, typing.Dict[str, typing.Dict[int, typing.Any]]]:
    """Merge the per-process shards of checkpoint ``checkpoint_id`` under
    a SHARED cohort base directory into one global snapshot mapping.

    Every process of a distributed job persists only its own subtasks'
    state (``proc-NNNNN/chk-XXXXXX``); merging the shards reconstructs
    the full {task: {subtask: state}} view — what cohort RESCALING needs
    (restoring with a different process count or operator parallelism
    redistributes keyed state by key group, which requires every old
    subtask's shard, not just the local one).

    ``checkpoint_id=None`` selects the HIGHEST id whose shard set is
    complete per the cohort shape recorded in the shards themselves
    (see ``_complete_shard_set`` — a lost shard makes an id ineligible
    rather than silently restoring partial state, and stale shards from
    a previous cohort shape neither veto nor pollute newer ids).  An
    explicit id with an incomplete shard set raises loudly.
    """
    checkpoint_id, shard_set = select_cohort_checkpoint(base_dir, checkpoint_id)
    merged: typing.Dict[str, typing.Dict[int, typing.Any]] = {}
    for d in shard_set:
        _, snapshots = read_checkpoint(d, checkpoint_id)
        for task, subtasks in snapshots.items():
            into = merged.setdefault(task, {})
            for idx, snap in subtasks.items():
                if task != "__job__" and idx in into:
                    raise ValueError(
                        f"checkpoint {checkpoint_id}: subtask {task}.{idx} "
                        f"appears in more than one shard under {base_dir} — "
                        "shards from different cohort shapes are mixed; "
                        "use a fresh checkpoint base per job lineage"
                    )
                into[idx] = snap
    return checkpoint_id, merged
