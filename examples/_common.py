"""Shared plumbing for the example jobs (platform selection, data gen,
reporting).  Each example mirrors one reference workload (BASELINE.json:6-12)
as a runnable job script — the reference ships its workloads as Flink job
mains (SURVEY.md §1 L6)."""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--records", type=int, default=256, help="stream length")
    p.add_argument("--batch", type=int, default=32, help="micro-batch / window size")
    p.add_argument("--parallelism", type=int, default=1)
    p.add_argument("--cpu", action="store_true",
                   help="force CPU with 8 virtual devices (default: real TPU if present)")
    p.add_argument("--smoke", action="store_true", help="tiny sizes for CI")
    return p


def select_platform(force_cpu: bool, virtual_devices: int = 8) -> None:
    """Must run before jax touches a backend."""
    if force_cpu:
        from flink_tensorflow_tpu.utils.platform import force_cpu as _force

        _force(virtual_devices)


def synthetic_images(n: int, size: int, channels: int = 3, seed: int = 0):
    """Deterministic fake image records (the examples are about the
    streaming+model path, not datasets — reference examples fetch
    Inception inputs at run time too, SURVEY.md §4 fixtures note)."""
    from flink_tensorflow_tpu.tensors import TensorValue

    rng = np.random.RandomState(seed)
    return [
        TensorValue(
            {"image": rng.rand(size, size, channels).astype(np.float32)},
            {"id": i},
        )
        for i in range(n)
    ]


def report(job: str, metrics: dict, t0: float, records: int, extra: dict = None):
    """One human-readable summary + one machine-readable JSON line."""
    wall = time.time() - t0
    out = {
        "job": job,
        "records": records,
        "wall_s": round(wall, 3),
        "records_per_s": round(records / wall, 2) if wall > 0 else None,
    }
    out.update(extra or {})
    # One latency histogram per SUBTASK: report the worst across them
    # (overwriting per key would report whichever subtask iterates last).
    p50s, p99s = [], []
    for key, value in metrics.items():
        if key.endswith("record_latency_s") and isinstance(value, dict):
            p50s.append(value["p50"])
            p99s.append(value["p99"])
    if p50s:
        out["p50_latency_ms"] = round(max(p50s) * 1e3, 3)
        out["p99_latency_ms"] = round(max(p99s) * 1e3, 3)
        if len(p50s) > 1:
            out["latency_aggregation"] = f"max over {len(p50s)} subtasks"
    print(json.dumps(out))
    return out
