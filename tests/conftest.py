"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's MiniCluster strategy (SURVEY.md §4): Flink projects
test "multi-node" in one JVM; we test multi-chip sharding on virtual CPU
devices.  Env vars must be set before jax initializes its backends, hence
at conftest import time.
"""

import os

# Force CPU even when the environment preselects a TPU platform (e.g.
# JAX_PLATFORMS=axon tunneling to one real chip): tests need the virtual
# 8-device mesh, and must not monopolize/depend on bench hardware.  The
# env var alone is not enough — the axon PJRT plugin re-registers itself
# as default — so pin the platform via jax.config too.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def env():
    from flink_tensorflow_tpu import StreamExecutionEnvironment

    return StreamExecutionEnvironment(parallelism=2)
