"""End-to-end span tracing + latency attribution (Perfetto-exportable).

Enable with ``JobConfig(trace=True)`` (optionally ``trace_path=...``,
``trace_sample_rate=...``) or ``FLINK_TPU_TRACE=1`` /
``FLINK_TPU_TRACE_PATH`` / ``FLINK_TPU_TRACE_SAMPLE``.  The CLI twin is
``flink-tpu-trace`` (``python -m flink_tensorflow_tpu.tracing``): run a
captured pipeline under tracing and print the per-operator stage
attribution table.  See ``tracer.py`` for the span model and
``attribution.py`` for the profiler.
"""

from flink_tensorflow_tpu.tracing.attribution import (
    STAGES,
    attribution,
    events_from_chrome,
    format_attribution_table,
)
from flink_tensorflow_tpu.tracing.clocksync import OffsetEstimator
from flink_tensorflow_tpu.tracing.flight import (
    FlightRecorder,
    load_flight_dump,
)
from flink_tensorflow_tpu.tracing.stitch import (
    cross_process_traces,
    merge_cohort_trace_files,
    merge_cohort_traces,
)
from flink_tensorflow_tpu.tracing.tracer import (
    TraceContext,
    Tracer,
    env_enabled,
    env_sample_rate,
    env_trace_path,
    events_to_chrome,
)

__all__ = [
    "STAGES",
    "FlightRecorder",
    "OffsetEstimator",
    "TraceContext",
    "Tracer",
    "attribution",
    "cross_process_traces",
    "env_enabled",
    "env_sample_rate",
    "env_trace_path",
    "events_from_chrome",
    "events_to_chrome",
    "format_attribution_table",
    "load_flight_dump",
    "merge_cohort_trace_files",
    "merge_cohort_traces",
]
