"""Two-input operators: connect (CoMap/CoFlatMap/CoProcess), window
join, interval join.

VERDICT r1 missing #5: two-input operators (connect/join) absent; the
reference inherits Flink's full DataStream surface (SURVEY.md §1 L1).
Barrier alignment across BOTH inputs comes from the runtime's channel-
level alignment (all channels, regardless of edge) — the checkpoint test
pins that.
"""

import time

import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core.state import StateDescriptor


class Tag(fn.CoMapFunction):
    def map1(self, value):
        return ("left", value)

    def map2(self, value):
        return ("right", value)


class TestConnect:
    def test_co_map_routes_by_input(self):
        env = StreamExecutionEnvironment(parallelism=1)
        s1 = env.from_collection([1, 2, 3], parallelism=1)
        s2 = env.from_collection(["a", "b"], parallelism=1)
        out = s1.connect(s2).map(Tag(), parallelism=1).sink_to_list()
        env.execute("co-map", timeout=60)
        assert sorted(v for t, v in out if t == "left") == [1, 2, 3]
        assert sorted(v for t, v in out if t == "right") == ["a", "b"]

    def test_co_flat_map(self):
        class Dup(fn.CoFlatMapFunction):
            def flat_map1(self, value):
                return [value, value]

            def flat_map2(self, value):
                return [value]

        env = StreamExecutionEnvironment(parallelism=1)
        s1 = env.from_collection([1], parallelism=1)
        s2 = env.from_collection([9], parallelism=1)
        out = s1.connect(s2).flat_map(Dup(), parallelism=1).sink_to_list()
        env.execute("co-flat", timeout=60)
        assert sorted(out) == [1, 1, 9]

    def test_keyed_co_process_shares_state_across_inputs(self):
        """Control-stream pattern: input 2 sets a per-key factor, input 1
        multiplies by it — state written by one input is visible to the
        other (same key space, same subtask)."""

        class Scale(fn.CoProcessFunction):
            def open(self, ctx):
                self._factor = StateDescriptor("factor")

            def process_element1(self, value, ctx, out):
                factor = ctx.state(self._factor).value() or 1
                out.collect((ctx.current_key, value["v"] * factor))

            def process_element2(self, value, ctx, out):
                ctx.state(self._factor).update(value["factor"])

        env = StreamExecutionEnvironment(parallelism=1)
        control = [{"k": "a", "factor": 10}]
        data = [{"k": "a", "v": i} for i in range(1, 4)] + [{"k": "b", "v": 5}]

        c = env.from_collection(control, parallelism=1)
        d_env = env.from_collection(data, parallelism=1)
        # Delay the data source so the control record lands first.
        env.source_throttle_s = 0.01
        out = (
            d_env.key_by(lambda r: r["k"])
            .connect(c.key_by(lambda r: r["k"]))
            .process(Scale(), parallelism=2)
            .sink_to_list()
        )
        env.execute("keyed-co", timeout=60)
        got = dict()
        for k, v in out:
            got.setdefault(k, []).append(v)
        assert sorted(got["b"]) == [5]
        # key "a": each value is v or v*10 depending on whether the
        # control record beat it (two independent sources = no order
        # guarantee); the base values must come through exactly once,
        # and at least the state plumbing must not crash.
        assert sorted(v if v < 10 else v // 10 for v in got["a"]) == [1, 2, 3]

    def test_broadcast_control_reaches_every_subtask(self):
        """The broadcast-state pattern: a control stream broadcast to ALL
        subtasks of a two-input operator, updating per-subtask function
        state that the (rebalanced) data stream reads."""
        import threading

        seen_controls = []
        lock = threading.Lock()

        class Gate(fn.CoProcessFunction):
            def open(self, ctx):
                self._factor = 1
                self._subtask = ctx.subtask_index

            def process_element1(self, value, ctx, out):
                out.collect(value * self._factor)

            def process_element2(self, value, ctx, out):
                self._factor = value
                with lock:
                    seen_controls.append(self._subtask)

        env = StreamExecutionEnvironment(parallelism=1)
        env.source_throttle_s = 0.01  # let the broadcast land first
        data = env.from_collection(list(range(1, 9)), parallelism=1)
        control = env.from_collection([100], parallelism=1)
        out = (
            data.rebalance()
            .connect(control.broadcast())
            .process(Gate(), parallelism=3)
            .sink_to_list()
        )
        env.execute("broadcast-state", timeout=60)
        # Every subtask received the broadcast control record...
        assert sorted(seen_controls) == [0, 1, 2]
        # ...and each data record was scaled by whichever factor its
        # subtask had at processing time (all = 100 once control landed).
        assert len(out) == 8
        assert all(v % 100 == 0 or v < 9 for v in out)

    def test_unkeyed_mixed_with_keyed_rejected(self):
        env = StreamExecutionEnvironment(parallelism=1)
        s1 = env.from_collection([1], parallelism=1).key_by(lambda v: v)
        s2 = env.from_collection([2], parallelism=1)
        with pytest.raises(TypeError):
            s1.connect(s2)


class TestWindowJoin:
    def test_joins_within_tumbling_window(self):
        env = StreamExecutionEnvironment(parallelism=1)
        orders = [
            {"user": "u1", "t": 1.0, "order": "A"},
            {"user": "u1", "t": 7.0, "order": "B"},
            {"user": "u2", "t": 2.0, "order": "C"},
        ]
        clicks = [
            {"uid": "u1", "t": 2.0, "page": "x"},
            {"uid": "u1", "t": 8.0, "page": "y"},
            {"uid": "u2", "t": 9.0, "page": "z"},  # different window than C
        ]
        s1 = (
            env.from_collection(orders, parallelism=1)
            .assign_timestamps(lambda r: r["t"], watermark_every=1)
        )
        s2 = (
            env.from_collection(clicks, parallelism=1)
            .assign_timestamps(lambda r: r["t"], watermark_every=1)
        )
        out = (
            s1.join(s2)
            .where(lambda r: r["user"])
            .equal_to(lambda r: r["uid"])
            .window(5.0)
            .apply(lambda l, r: (l["order"], r["page"]), parallelism=2)
            .sink_to_list()
        )
        env.execute("window-join", timeout=60)
        # Window [0,5): (A, x); window [5,10): (B, y); u2's C@2 and z@9
        # fall in different windows -> no pair.
        assert sorted(out) == [("A", "x"), ("B", "y")]

    def test_builder_validation(self):
        env = StreamExecutionEnvironment(parallelism=1)
        s1 = env.from_collection([1], parallelism=1)
        s2 = env.from_collection([2], parallelism=1)
        with pytest.raises(ValueError, match="where"):
            s1.join(s2).window(5.0).apply(lambda l, r: None)
        with pytest.raises(ValueError, match="window"):
            s1.join(s2).where(lambda v: v).equal_to(lambda v: v).apply(
                lambda l, r: None
            )


class TestIntervalJoin:
    def test_pairs_within_interval(self):
        env = StreamExecutionEnvironment(parallelism=1)
        lefts = [{"k": "a", "t": 10.0, "v": "L10"}, {"k": "a", "t": 20.0, "v": "L20"}]
        rights = [
            {"k": "a", "t": 11.0, "v": "R11"},   # within [10-2, 10+2] of L10
            {"k": "a", "t": 19.0, "v": "R19"},   # within L20's interval
            {"k": "a", "t": 30.0, "v": "R30"},   # matches nothing
        ]
        s1 = (
            env.from_collection(lefts, parallelism=1)
            .assign_timestamps(lambda r: r["t"], watermark_every=1)
            .key_by(lambda r: r["k"])
        )
        s2 = (
            env.from_collection(rights, parallelism=1)
            .assign_timestamps(lambda r: r["t"], watermark_every=1)
            .key_by(lambda r: r["k"])
        )
        out = (
            s1.interval_join(s2, lower_s=-2.0, upper_s=2.0)
            .apply(lambda l, r: (l["v"], r["v"]), parallelism=1)
            .sink_to_list()
        )
        env.execute("interval-join", timeout=60)
        assert sorted(out) == [("L10", "R11"), ("L20", "R19")]

    def test_eviction_mirrors_acceptance_bound(self):
        """A buffered element must survive as long as an opposite-side
        record the operator would still ACCEPT could match it (driven at
        the operator level — watermark interleaving across two real
        sources is nondeterministic)."""
        from flink_tensorflow_tpu.core import elements as el
        from flink_tensorflow_tpu.core.joins import IntervalJoinOperator, as_join_function
        from flink_tensorflow_tpu.core.operators import Output
        from flink_tensorflow_tpu.core.state import KeyedStateStore

        op = IntervalJoinOperator(
            "ij", as_join_function(lambda l, r: (l, r)), -2.0, 2.0,
            lambda v: "k", lambda v: "k",
        )
        emitted = []

        class _Writer:
            def write(self, e):
                if isinstance(e, el.StreamRecord):
                    emitted.append(e.value)

        op.setup(None, Output([(None, [])]), KeyedStateStore())
        op.output.emit = lambda v, ts=None: emitted.append(v)
        op.output.broadcast_element = lambda e: None

        op.process_record_from(1, el.StreamRecord("R7.5", 7.5))
        op.process_watermark(el.Watermark(10.0))
        # Left at 8.5 is still accepted (8.5 + upper >= wm) and its match
        # at 7.5 must still be buffered.
        op.process_record_from(0, el.StreamRecord("L8.5", 8.5))
        assert emitted == [("L8.5", "R7.5")]

    def test_checkpoint_survives_midstream(self, tmp_path):
        """Two-input barrier alignment: a checkpoint cut mid-join must
        restore to the same final join results."""
        d = str(tmp_path / "chk")
        lefts = [{"k": i % 4, "t": float(i), "v": f"L{i}"} for i in range(40)]
        rights = [{"k": i % 4, "t": float(i) + 0.5, "v": f"R{i}"} for i in range(40)]

        def build(env):
            s1 = (
                env.from_collection(lefts, parallelism=1)
                .assign_timestamps(lambda r: r["t"], watermark_every=4)
                .key_by(lambda r: r["k"])
            )
            s2 = (
                env.from_collection(rights, parallelism=1)
                .assign_timestamps(lambda r: r["t"], watermark_every=4)
                .key_by(lambda r: r["k"])
            )
            return (
                s1.interval_join(s2, lower_s=0.0, upper_s=1.0)
                .apply(lambda l, r: (l["v"], r["v"]), parallelism=2)
                .sink_to_list()
            )

        envA = StreamExecutionEnvironment(parallelism=1)
        outA = build(envA)
        envA.execute("ij-clean", timeout=60)
        expected = set(outA)
        assert expected  # the clean run must actually produce pairs

        env1 = StreamExecutionEnvironment(parallelism=1)
        env1.enable_checkpointing(d)
        env1.source_throttle_s = 0.004
        out1 = build(env1)
        h = env1.execute_async("ij")
        time.sleep(0.1)
        h.trigger_checkpoint()
        h.cancel()

        env2 = StreamExecutionEnvironment(parallelism=1)
        env2.enable_checkpointing(d)
        out2 = build(env2)
        env2.execute("ij", restore_from=d, timeout=60)
        # Join STATE is exactly-once: pre-cancel emissions plus the
        # replayed run cover every pair (sink emissions themselves are
        # at-least-once — standard non-transactional sink semantics).
        assert set(out1) | set(out2) == expected
