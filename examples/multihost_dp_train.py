"""Multi-host data-parallel training over the transparent record plane.

The reference's cluster story (SURVEY.md §1 L1, §3.5): a JobManager
schedules subtasks onto TaskManagers; DP training crosses processes via
TF ClusterSpec + NCCL; records cross via Flink's network shuffle.  The
TPU-native cohort (SURVEY.md §7 step 8) — ONE job graph, built
identically on every process:

- a **CohortSupervisor** (parent mode, the JobManager analogue) spawns N
  identical worker processes and restarts the whole cohort from the last
  COMMON checkpoint on any worker loss (XLA meshes cannot shrink live);
- each worker joins the jax.distributed cohort, forms the global mesh,
  and executes the SAME job with ``env.set_distributed``: the
  parallelism-N source partitions the logical stream (subtask w on
  process w), count windows of ``global_batch/N`` feed the gang
  **DPTrainWindowFunction** (parallelism N = one subtask per process, so
  every process participates in the pjit-ed step; gradient allreduce
  compiled by XLA, zero communication code here), and the loss stream
  REBALANCES down to a parallelism-1 aggregation sink on process 0 —
  the cross-host edge rides the record plane's barrier-carrying
  channels, no RemoteSink/RemoteSource anywhere;
- checkpoints use **count-based barriers** (``every_n_records``) into a
  SHARED checkpoint directory (per-process shards are namespaced by the
  framework); barriers cross processes through the shuffle channels and
  the 2PC commit point is global durability.

Run (2 processes, 8 virtual CPU devices total, one injected failure):
  python examples/multihost_dp_train.py --records-per-worker 48
Clean run:  python examples/multihost_dp_train.py --no-failure
"""

import argparse
import json
import os
import sys
import tempfile
import typing
import time

sys.path.insert(0, ".")


def build_parser():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--devices-per-worker", type=int, default=4)
    p.add_argument("--records-per-worker", type=int, default=48)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--ckpt-every-steps", type=int, default=2)
    p.add_argument("--base-port", type=int, default=0,
                   help="0 = pick free ports automatically")
    p.add_argument("--no-failure", action="store_true",
                   help="skip the injected worker failure")
    p.add_argument("--fail-worker", type=int, default=1)
    p.add_argument("--fail-at-step", type=int, default=5)
    p.add_argument("--work-dir", default=None)
    # worker-mode internals (set by the parent)
    p.add_argument("--worker", type=int, default=None)
    p.add_argument("--attempt", type=int, default=0)
    p.add_argument("--coordinator-port", type=int, default=None)
    p.add_argument("--shuffle-ports", default=None,
                   help="comma-separated record-plane ports, one per worker")
    return p


def _model_and_schema():
    import numpy as np

    from flink_tensorflow_tpu.models import get_model_def
    from flink_tensorflow_tpu.tensors import RecordSchema, spec

    cfg = dict(hash_buckets=200, embed_dim=4, num_cat_slots=2,
               num_dense=4, num_wide=8, hidden=(16,))
    mdef = get_model_def("widedeep", **cfg)
    schema = RecordSchema({
        "wide": spec((cfg["num_wide"],)),
        "dense": spec((cfg["num_dense"],)),
        "cat": spec((cfg["num_cat_slots"],), np.int32),
        "label": spec((), np.int32),
    })
    return mdef, schema, cfg


def _stream_records(n, cfg):
    """The ONE logical stream, generated identically on every process —
    the parallelism-W source partitions it (subtask w emits w::W), and
    replay after a cohort restart regenerates identical records."""
    import numpy as np

    from flink_tensorflow_tpu.tensors import TensorValue

    rng = np.random.RandomState(1000)
    records = []
    for i in range(n):
        x_wide = rng.rand(cfg["num_wide"]).astype(np.float32)
        records.append(TensorValue({
            "wide": x_wide,
            "dense": rng.rand(cfg["num_dense"]).astype(np.float32),
            "cat": rng.randint(0, cfg["hash_buckets"], (cfg["num_cat_slots"],)).astype(np.int32),
            "label": np.int32(x_wide[0] > 0.5),
        }, meta={"id": i}))
    return records


# ---------------------------------------------------------------------------
# worker mode
# ---------------------------------------------------------------------------

class _LossProbe:
    """Per-process map stage behind the gang op: records this process's
    loss sequence (for the cohort-agreement check), tags each record
    with its gang subtask + step for the downstream aggregator, and
    injects the TaskManager-loss failure mid-round."""

    def __init__(self, args):
        self.args = args
        self.losses = []
        self.subtask = 0

    def make(self):
        from flink_tensorflow_tpu.core import functions as fn

        probe = self

        class Probe(fn.MapFunction):
            def clone(self):
                return self  # one subtask per process: keep the handle

            def open(self, ctx):
                probe.subtask = ctx.subtask_index

            def map(self, record):
                probe.losses.append(float(record["loss"]))
                step = len(probe.losses)
                a = probe.args
                if (not a.no_failure and a.attempt == 0
                        and probe.subtask == a.fail_worker
                        and step >= a.fail_at_step):
                    # Injected TaskManager loss: die mid-round, off a
                    # checkpoint boundary, taking the cohort's
                    # collectives AND its shuffle channels down with us.
                    os._exit(1)
                return record.with_meta(gang_subtask=probe.subtask, step=step)

        return Probe()


def run_worker(args) -> int:
    from flink_tensorflow_tpu.utils.platform import force_cpu

    force_cpu(args.devices_per_worker)
    import optax

    from flink_tensorflow_tpu import DistributedConfig, StreamExecutionEnvironment
    from flink_tensorflow_tpu.functions import DPTrainWindowFunction
    from flink_tensorflow_tpu.parallel import latest_common_checkpoint, multihost

    topo = multihost.initialize(
        f"localhost:{args.coordinator_port}",
        num_processes=args.workers,
        process_id=args.worker,
    )
    mesh = multihost.global_mesh({"data": topo.global_devices})

    mdef, schema, cfg = _model_and_schema()
    W = args.workers
    local_batch = args.global_batch // W
    records = _stream_records(W * args.records_per_worker, cfg)
    total_steps = args.records_per_worker // local_batch

    shared_ckpt = os.path.join(args.work_dir, "ckpt")
    shuffle_ports = [int(x) for x in args.shuffle_ports.split(",")]
    dist = DistributedConfig(
        args.worker, W, tuple(f"127.0.0.1:{p}" for p in shuffle_ports),
    )
    # The framework namespaces per-process shards under the shared dir;
    # ask the config for the paths instead of duplicating the format.
    worker_dirs = [dist.process_checkpoint_dir(shared_ckpt, w) for w in range(W)]

    env = StreamExecutionEnvironment(parallelism=1)
    env.set_mesh(mesh)
    env.set_distributed(dist)
    # Aligned-across-hosts barriers: checkpoint k lands after every
    # source subtask's k * (ckpt_every_steps * local_batch)-th record,
    # and the barriers cross processes through the record plane.
    env.enable_checkpointing(
        shared_ckpt, every_n_records=args.ckpt_every_steps * local_batch
    )

    probe = _LossProbe(args)
    received = []

    def agg_sink(record):
        received.append((int(record.meta["gang_subtask"]),
                         int(record.meta["step"]), float(record["loss"])))

    (
        env.from_collection(records, parallelism=W)
        .count_window(local_batch)
        .apply(
            DPTrainWindowFunction(mdef, optax.adam(1e-2), train_schema=schema,
                                  global_batch=args.global_batch),
            name="dp_train", parallelism=W,
        )
        .map(probe.make(), name="loss_probe", parallelism=W)
        # W -> 1 rebalance: worker 1's losses cross to process 0 over
        # the record plane (the old RemoteSink/RemoteSource fan-in,
        # now just an edge in the job graph).
        .sink_to_callable(agg_sink, name="loss_agg", parallelism=1)
    )

    restored_id = None
    if args.attempt > 0:
        restored_id = latest_common_checkpoint(worker_dirs)
    env.execute(
        "multihost-dp-train",
        timeout=600,
        restore_from=shared_ckpt if restored_id is not None else None,
        restore_checkpoint_id=restored_id,
    )

    result = {
        "worker": args.worker,
        "attempt": args.attempt,
        "global_devices": topo.global_devices,
        "num_processes": topo.num_processes,
        "restored_checkpoint": restored_id,
        "steps_this_attempt": len(probe.losses),
        "total_steps": total_steps,
        "losses": [round(l, 6) for l in probe.losses],
    }
    with open(os.path.join(args.work_dir, f"result_w{args.worker}.json"), "w") as f:
        json.dump(result, f)

    if args.worker == 0:
        import numpy as np

        by_worker = {}
        for subtask, step, loss in received:
            by_worker.setdefault(subtask, []).append((step, loss))
        summary = {
            "workers_reporting": sorted(by_worker),
            "records_received": len(received),
            "mean_final_loss": round(
                float(np.mean([sorted(v)[-1][1] for v in by_worker.values()])), 6
            ) if by_worker else None,
        }
        with open(os.path.join(args.work_dir, "aggregate.json"), "w") as f:
            json.dump(summary, f)
    return 0


# ---------------------------------------------------------------------------
# parent mode (the JobManager analogue)
# ---------------------------------------------------------------------------

def _free_ports(n: int) -> typing.List[int]:
    """n DISTINCT free ports: all sockets bind simultaneously before any
    closes, so the kernel cannot hand the same port out twice (bind-then-
    close one at a time can — a coordinator/agg-port collision crashes a
    worker with EADDRINUSE and burns a cohort restart attempt)."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def run_parent(args) -> dict:
    from flink_tensorflow_tpu.parallel import CohortSupervisor

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="multihost_dp_")
    # Fresh ports per attempt (1 coordinator + W shuffle endpoints): a
    # dead attempt's sockets may linger in TIME_WAIT.
    per_attempt = 1 + args.workers
    if args.base_port:
        ports = {
            a: tuple(args.base_port + a * per_attempt + i for i in range(per_attempt))
            for a in range(4)
        }
    else:
        flat = _free_ports(4 * per_attempt)
        ports = {
            a: tuple(flat[a * per_attempt: (a + 1) * per_attempt])
            for a in range(4)
        }

    def command(worker, num_workers, attempt):
        cport, *shuffle = ports[attempt]
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--worker", str(worker),
            "--workers", str(num_workers),
            "--attempt", str(attempt),
            "--coordinator-port", str(cport),
            "--shuffle-ports", ",".join(map(str, shuffle)),
            "--devices-per-worker", str(args.devices_per_worker),
            "--records-per-worker", str(args.records_per_worker),
            "--global-batch", str(args.global_batch),
            "--ckpt-every-steps", str(args.ckpt_every_steps),
            "--fail-worker", str(args.fail_worker),
            "--fail-at-step", str(args.fail_at_step),
            "--work-dir", work_dir,
        ]
        if args.no_failure:
            cmd.append("--no-failure")
        return cmd

    supervisor = CohortSupervisor(
        command, args.workers, max_restarts=2, attempt_timeout_s=600
    )
    t0 = time.time()
    outcome = supervisor.run()

    results = []
    for w in range(args.workers):
        with open(os.path.join(work_dir, f"result_w{w}.json")) as f:
            results.append(json.load(f))
    with open(os.path.join(work_dir, "aggregate.json")) as f:
        aggregate = json.load(f)

    summary = {
        "job": "multihost_dp_train",
        "workers": args.workers,
        "cohort_attempts": outcome.attempts,
        "wall_s": round(time.time() - t0, 1),
        "global_devices": results[0]["global_devices"],
        "restored_checkpoint": results[0]["restored_checkpoint"],
        "steps_final_attempt": results[0]["steps_this_attempt"],
        "loss_first": results[0]["losses"][0] if results[0]["losses"] else None,
        "loss_last": results[0]["losses"][-1] if results[0]["losses"] else None,
        "losses_agree_across_workers": all(
            r["losses"] == results[0]["losses"] for r in results
        ),
        "aggregate": aggregate,
        "work_dir": work_dir,
    }
    print(json.dumps(summary))
    return summary


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.global_batch % args.workers:
        raise SystemExit("global-batch must divide by workers")
    if args.worker is not None:
        sys.exit(run_worker(args))
    return run_parent(args)


if __name__ == "__main__":
    main()
