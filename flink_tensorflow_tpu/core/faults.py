"""Chaos plane — deterministic fault injection at the runtime's seams.

Flink earns its exactly-once claims by *surviving* faults: checkpoint
decline/abort, fixed-delay restart strategies, netty channel
re-establishment.  This module is the instrument that proves the same
for this runtime: a :class:`FaultPlan` is a deterministic schedule of
faults over ``(restart epoch, stream position)``, and the
:class:`FaultInjector` fires them at injection points that already
exist as seams in the runtime:

- ``kill`` — raise :class:`InjectedFault` inside a subtask's record
  loop after its K-th record (``_Subtask.run_source`` /
  ``run_split_source`` / ``run_worker``), exactly like a user-code
  crash: the job fails and the restart strategy / cohort supervisor
  recovers it from the last checkpoint.
- ``stall`` — sleep ``duration_s`` inside the record loop at record K:
  the wedged-operator shape that used to block barrier alignment (and
  therefore checkpointing) forever; the checkpoint ABORT machinery
  (core/checkpoint.py) is what this fault forces into existence.
- ``sever`` — tear down a remote edge's transport and raise a
  connection error at the K-th frame sent on that edge
  (``RemoteChannelWriter`` / ``RemoteSink``): exercises the
  exponential-backoff reconnect + restart-epoch fencing.
- ``blackhole`` — silently swallow that edge's frames for
  ``duration_s`` after the K-th: a hung-but-alive peer, the shape only
  heartbeat death-detection catches.
- ``delay`` — sleep ``duration_s`` before each of the next ``count``
  sends on the edge: degraded-link latency.
- ``store_fail`` — fail the checkpoint-store write of checkpoint id K
  (``CheckpointCoordinator``): the checkpoint must be declined (no 2PC
  commit signal) and a LATER checkpoint must succeed.

Determinism: every fault is pinned to a stream position (a subtask's
own record count / an edge's own frame count / a checkpoint id) and a
restart epoch, so the same plan over the same job produces the same
run, byte for byte — which is what lets tests assert
``read_committed()`` equals the fault-free run's output exactly.
``seed`` feeds only magnitude jitter on ``delay`` faults.

Zero-cost when off (the sanitizer's contract): without a plan the
executor keeps ``faults=None`` and every hook site is one is-None
test.  Enable via ``JobConfig.faults`` (a :class:`FaultPlan`, a spec
string, or a list of specs) or the ``FLINK_TPU_FAULTS`` env var.

Spec-string grammar (``;``-separated entries)::

    kill:<task>.<index>@<record>            crash the subtask
    stall:<task>.<index>@<record>~<secs>    wedge the subtask
    sever:<task>.<index>@<frame>            cut the edge INTO task.index
    blackhole:<task>.<index>@<frame>~<secs> drop that edge's frames
    delay:<task>.<index>@<frame>~<secs>[x<count>]
    store_fail@<checkpoint_id>

An entry may carry ``#<epoch>`` to fire on a specific restart epoch
(default 0 — the first attempt only, so a restarted run replays
cleanly instead of crash-looping into its restart budget).  Example::

    FLINK_TPU_FAULTS="kill:count.0@50;store_fail@2;stall:count.1@80~0.5#1"

Every fired fault lands on the flight recorder (``faults`` track) and
ticks a ``faults.<kind>`` meter, so a chaos run's black box shows the
schedule that produced it.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import re
import threading
import time
import typing

logger = logging.getLogger(__name__)

KINDS = ("kill", "stall", "sever", "blackhole", "delay", "store_fail")
#: Edge-directed kinds (fire inside a remote writer's send path).
EDGE_KINDS = ("sever", "blackhole", "delay")


class InjectedFault(RuntimeError):
    """A scheduled ``kill`` fired.  Deliberately an ordinary runtime
    error: the job must fail exactly as it would for a user-code crash,
    and restart strategies must recover it."""


class InjectedStoreFailure(OSError):
    """A scheduled ``store_fail`` fired inside a checkpoint persist."""


class InjectedConnectionError(ConnectionError):
    """A scheduled ``sever`` fired inside a remote edge's send path."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault.  ``task``/``index`` target a subtask scope
    (record faults) or the subtask an edge feeds (edge faults);
    ``at`` is the 1-based record/frame count (or the checkpoint id for
    ``store_fail``) at which the fault fires on restart ``epoch``."""

    kind: str
    task: str = ""
    index: int = 0
    at: int = 1
    duration_s: float = 0.0
    count: int = 1
    epoch: int = 0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (one of {KINDS})")
        if self.at < 1:
            raise ValueError(f"fault position must be >= 1, got {self.at}")
        if self.duration_s < 0:
            raise ValueError(f"duration_s must be >= 0, got {self.duration_s}")
        if self.count < 1:
            raise ValueError(f"count must be >= 1, got {self.count}")
        if self.epoch < 0:
            raise ValueError(f"epoch must be >= 0, got {self.epoch}")

    @property
    def scope(self) -> str:
        return f"{self.task}.{self.index}"


_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)"
    r"(?::(?P<task>.+?)\.(?P<index>\d+))?"
    r"@(?P<at>\d+)"
    r"(?:~(?P<duration>[0-9.]+))?"
    r"(?:x(?P<count>\d+))?"
    r"(?:#(?P<epoch>\d+))?$"
)


def parse_fault_spec(text: str) -> FaultSpec:
    m = _SPEC_RE.match(text.strip())
    if m is None:
        raise ValueError(
            f"unparseable fault spec {text!r} — expected "
            "kind[:task.index]@at[~duration][xcount][#epoch]"
        )
    kind = m.group("kind")
    if kind != "store_fail" and m.group("task") is None:
        raise ValueError(f"fault spec {text!r}: kind {kind!r} needs a task.index target")
    return FaultSpec(
        kind=kind,
        task=m.group("task") or "",
        index=int(m.group("index") or 0),
        at=int(m.group("at")),
        duration_s=float(m.group("duration") or 0.0),
        count=int(m.group("count") or 1),
        epoch=int(m.group("epoch") or 0),
    )


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An immutable, deterministic fault schedule for one job."""

    specs: typing.Tuple[FaultSpec, ...] = ()
    seed: int = 0

    @classmethod
    def parse(cls, text: str, *, seed: int = 0) -> "FaultPlan":
        specs = tuple(
            parse_fault_spec(entry)
            for entry in text.split(";") if entry.strip()
        )
        return cls(specs=specs, seed=seed)

    @classmethod
    def resolve(cls, value: typing.Any) -> typing.Optional["FaultPlan"]:
        """Normalize a JobConfig.faults value (plan / spec string / spec
        sequence / None), then let ``FLINK_TPU_FAULTS`` override."""
        env = os.environ.get("FLINK_TPU_FAULTS")
        if env:
            return cls.parse(env)
        if value is None:
            return None
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            return cls.parse(value)
        return cls(specs=tuple(
            parse_fault_spec(s) if isinstance(s, str) else s for s in value
        ))


class _Armed:
    """Mutable firing state of one spec (remaining count / window)."""

    __slots__ = ("spec", "remaining", "window_until")

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.remaining = spec.count
        #: blackhole: monotonic time its drop window closes (armed at
        #: the first frame past ``at``).
        self.window_until: typing.Optional[float] = None


class FaultInjector:
    """Runtime half of the chaos plane: owns the armed specs for ONE
    executor (one restart epoch) and fires them at the hook sites.

    Thread-safety: each subtask/edge has its own position counter keyed
    by scope; arming state is guarded by one lock (hook sites are
    record-rate at most, and only while a plan is active)."""

    def __init__(self, plan: FaultPlan, *, epoch: int = 0,
                 metrics: typing.Optional[typing.Any] = None,
                 flight: typing.Optional[typing.Any] = None):
        self.plan = plan
        self.epoch = epoch
        self.flight = flight
        self._lock = threading.Lock()
        self._rng = random.Random(plan.seed)
        #: scope -> armed record-faults (kill/stall), sorted by position.
        self._record_specs: typing.Dict[str, typing.List[_Armed]] = {}
        #: scope -> armed edge-faults (sever/blackhole/delay).
        self._edge_specs: typing.Dict[str, typing.List[_Armed]] = {}
        #: checkpoint id -> armed store_fail.
        self._store_specs: typing.Dict[int, _Armed] = {}
        #: per-edge frame counters (record counters live in the subtask).
        self._edge_sent: typing.Dict[str, int] = {}
        #: every firing, for tests/post-mortems: (kind, scope, position).
        self.fired: typing.List[typing.Tuple[str, str, int]] = []
        self._meters: typing.Dict[str, typing.Any] = {}
        if metrics is not None:
            grp = metrics.group("faults")
            for kind in KINDS:
                self._meters[kind] = grp.meter(kind)
            grp.gauge("fired_total", lambda: len(self.fired))
        for spec in plan.specs:
            if spec.epoch != epoch:
                continue
            armed = _Armed(spec)
            if spec.kind in ("kill", "stall"):
                self._record_specs.setdefault(spec.scope, []).append(armed)
            elif spec.kind in EDGE_KINDS:
                self._edge_specs.setdefault(spec.scope, []).append(armed)
            else:
                self._store_specs[spec.at] = armed

    @property
    def active(self) -> bool:
        return bool(self._record_specs or self._edge_specs or self._store_specs)

    # -- firing ----------------------------------------------------------
    def _fire(self, spec: FaultSpec, position: int) -> None:
        self.fired.append((spec.kind, spec.scope or "store", position))
        meter = self._meters.get(spec.kind)
        if meter is not None:
            meter.mark()
        if self.flight is not None:
            self.flight.record("faults", spec.kind, {
                "target": spec.scope or "store",
                "at": position,
                "epoch": self.epoch,
                "duration_s": spec.duration_s,
            })
        logger.warning("fault injected: %s at %s@%d (epoch %d)",
                       spec.kind, spec.scope or "store", position, self.epoch)

    # -- hook: subtask record loops --------------------------------------
    def record_point(self, scope: str, offset: int) -> None:
        """Called after a subtask processed/emitted its ``offset``-th
        record; raises InjectedFault for a due ``kill``, sleeps for a
        due ``stall``."""
        armed_list = self._record_specs.get(scope)
        if not armed_list:
            return
        stall_s = 0.0
        kill: typing.Optional[FaultSpec] = None
        with self._lock:
            for armed in armed_list:
                if armed.remaining <= 0 or offset < armed.spec.at:
                    continue
                armed.remaining -= 1
                self._fire(armed.spec, offset)
                if armed.spec.kind == "kill":
                    kill = armed.spec
                else:
                    stall_s += armed.spec.duration_s
        if stall_s > 0:
            time.sleep(stall_s)
        if kill is not None:
            raise InjectedFault(
                f"injected kill: {scope} at record {offset} "
                f"(epoch {self.epoch})"
            )

    # -- hook: remote edges ----------------------------------------------
    def edge_hook(self, task: str, index: int) -> typing.Optional[
            typing.Callable[[], typing.Optional[str]]]:
        """A per-edge send hook for the writer feeding ``task.index``, or
        None when no spec targets that edge (the writer then keeps its
        zero-cost path).  The hook is called once per frame send and
        returns ``"drop"`` to blackhole the frame, raises
        :class:`InjectedConnectionError` for a sever, sleeps for a
        delay, and returns None to proceed."""
        scope = f"{task}.{index}"
        if scope not in self._edge_specs:
            return None

        def hook() -> typing.Optional[str]:
            return self._edge_point(scope)

        return hook

    def _edge_point(self, scope: str) -> typing.Optional[str]:
        now = time.monotonic()
        delay_s = 0.0
        action: typing.Optional[str] = None
        sever: typing.Optional[FaultSpec] = None
        with self._lock:
            sent = self._edge_sent.get(scope, 0) + 1
            self._edge_sent[scope] = sent
            for armed in self._edge_specs.get(scope, ()):
                spec = armed.spec
                if spec.kind == "blackhole":
                    if armed.window_until is not None:
                        if now < armed.window_until:
                            action = "drop"
                        continue
                    if armed.remaining > 0 and sent >= spec.at:
                        armed.remaining -= 1
                        armed.window_until = now + spec.duration_s
                        self._fire(spec, sent)
                        action = "drop"
                    continue
                if armed.remaining <= 0 or sent < spec.at:
                    continue
                armed.remaining -= 1
                self._fire(spec, sent)
                if spec.kind == "sever":
                    sever = spec
                else:  # delay
                    jitter = 1.0 + 0.1 * (2.0 * self._rng.random() - 1.0)
                    delay_s += spec.duration_s * jitter
        if delay_s > 0:
            time.sleep(delay_s)
        if sever is not None:
            raise InjectedConnectionError(
                f"injected sever: edge into {scope} at frame "
                f"{self._edge_sent[scope]} (epoch {self.epoch})"
            )
        return action

    # -- hook: checkpoint store ------------------------------------------
    def store_point(self, checkpoint_id: int) -> None:
        """Called before a checkpoint-store write; raises
        InjectedStoreFailure when checkpoint ``checkpoint_id``'s write
        is scheduled to fail."""
        with self._lock:
            armed = self._store_specs.get(checkpoint_id)
            if armed is None or armed.remaining <= 0:
                return
            armed.remaining -= 1
            self._fire(armed.spec, checkpoint_id)
        raise InjectedStoreFailure(
            f"injected store failure: checkpoint {checkpoint_id} "
            f"(epoch {self.epoch})"
        )
