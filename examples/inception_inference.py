"""Inception-v3 streaming image labeling — the flagship workload.

Reference: the Inception demo job, a bounded DataStream of images mapped
through a ``ModelFunction`` running a frozen Inception-v3 graph in an
embedded TF session (BASELINE.json:7; SURVEY.md §3.1).  This job is the
north-star measurement path (BASELINE.json:2): records/sec/chip and p50
per-record latency.

TPU-native shape: images arrive as records, a count-or-timeout window
micro-batches them, and each fired window is ONE jitted bfloat16 forward
on a ``[B, 299, 299, 3]`` HBM-resident batch.

Run:  python examples/inception_inference.py --records 512 --batch 32
      python examples/inception_inference.py --smoke --cpu   # CI-safe
      python examples/inception_inference.py --bundle-dir /tmp/incep  # artifact path
"""

import os
import sys
import time

sys.path.insert(0, ".")  # repo-root invocation
from examples._common import base_parser, report, select_platform, synthetic_images


def main(argv=None):
    p = base_parser(__doc__)
    p.add_argument("--bundle-dir", default=None,
                   help="serve from a saved model bundle (exported on first "
                        "run) — the reference's load-an-artifact deployment "
                        "shape, instead of in-process init")
    p.add_argument("--output-dir", default=None,
                   help="also write results through the exactly-once "
                        "two-phase-commit file sink (committed on durable "
                        "checkpoints)")
    args = p.parse_args(argv)
    select_platform(args.cpu)
    if args.smoke:
        args.records, args.batch = 16, 8

    import jax

    from flink_tensorflow_tpu import StreamExecutionEnvironment
    from flink_tensorflow_tpu.functions import ModelWindowFunction
    from flink_tensorflow_tpu.models import SavedModelLoader, get_model_def, save_bundle
    from flink_tensorflow_tpu.tensors import BucketPolicy

    num_classes = 10 if args.smoke else 1000
    mdef = get_model_def("inception_v3", num_classes=num_classes)
    if args.bundle_dir:
        # The reference's flagship job LOADS its model (frozen graph /
        # SavedModel) rather than building it in-process (SURVEY.md §3.3).
        # Export once, then every operator replica loads the bundle at
        # open() — the artifact-deployment shape.
        if not os.path.isdir(args.bundle_dir):
            params = jax.jit(mdef.init_fn)(jax.random.key(0))
            save_bundle(mdef, params, args.bundle_dir)
        else:
            print(f"serving EXISTING bundle {args.bundle_dir} as-is "
                  "(its architecture config wins over this run's flags)",
                  file=sys.stderr)
        model = SavedModelLoader(args.bundle_dir)
    else:
        model = mdef.to_model(jax.jit(mdef.init_fn)(jax.random.key(0)))
    records = synthetic_images(args.records, 299)

    env = StreamExecutionEnvironment(parallelism=args.parallelism)
    if args.output_dir:
        # Deterministic barriers + the 2PC sink: committed output files
        # hold each result exactly once even across failover.
        env.enable_checkpointing(args.output_dir + ".chk",
                                 every_n_records=4 * args.batch)
    labeled = (
        # Source schema declaration — plan-time validation against the
        # model contract (see flink_tensorflow_tpu.analysis).
        env.from_collection(records, parallelism=1, schema=mdef.input_schema)
        .rebalance()
        .count_window(args.batch, timeout_s=0.05)
        .apply(
            ModelWindowFunction(
                model,
                policy=BucketPolicy(fixed_batch=args.batch),
                warmup_batches=(args.batch,),
            ),
            name="inception",
            parallelism=args.parallelism,
        )
    )
    results = labeled.sink_to_list()
    if args.output_dir:
        from flink_tensorflow_tpu.io import ExactlyOnceRecordFileSink

        labeled.add_sink(ExactlyOnceRecordFileSink(args.output_dir),
                         name="committed_results", parallelism=args.parallelism)
    t0 = time.time()
    job = env.execute("inception-v3-labeling", timeout=3600)
    assert len(results) == args.records, (len(results), args.records)
    labels = [int(r["label"]) for r in results[:5]]
    extra = {"sample_labels": labels}
    if args.output_dir:
        from flink_tensorflow_tpu.io import read_committed

        extra["committed_records"] = len(read_committed(args.output_dir))
    return report("inception_v3_streaming_inference", job.metrics, t0,
                  args.records, extra)


if __name__ == "__main__":
    main()
