from flink_tensorflow_tpu.io.sources import (
    CollectionSource,
    GeneratorSource,
    ThrottledSource,
)

__all__ = ["CollectionSource", "GeneratorSource", "ThrottledSource"]
