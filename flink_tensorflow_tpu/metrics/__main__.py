"""``python -m flink_tensorflow_tpu.metrics <pipeline.py>`` — job inspector."""

import sys

from flink_tensorflow_tpu.metrics.inspector import main

if __name__ == "__main__":
    sys.exit(main())
