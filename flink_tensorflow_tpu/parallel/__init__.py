"""Parallelism layer — meshes, shardings, DP training, ring attention,
multi-host formation.

TPU-native replacement for the reference's ClusterSpec+NCCL distributed
path (BASELINE.json:5; SURVEY.md §2 "Distributed communication backend"):
collectives are emitted by XLA from sharding annotations and ride ICI/DCN.
"""

from flink_tensorflow_tpu.parallel.dp import (
    init_train_state,
    make_dp_train_step,
    make_train_step,
)
from flink_tensorflow_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    TP_AXIS,
    MeshSpec,
    abstract_mesh,
    batch_sharding,
    is_abstract_mesh,
    make_mesh,
    named_sharding,
    replicate,
    replicated,
    shard_batch,
    spans_processes,
)
from flink_tensorflow_tpu.parallel.supervisor import (
    CohortFailed,
    CohortOutcome,
    CohortSupervisor,
    latest_common_checkpoint,
)
from flink_tensorflow_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ring_attention_sharded,
    ring_decode_attention,
)
from flink_tensorflow_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_sharded,
    ulysses_decode_attention,
)

__all__ = [
    "DATA_AXIS",
    "EXPERT_AXIS",
    "FSDP_AXIS",
    "MODEL_AXIS",
    "MeshSpec",
    "PIPE_AXIS",
    "SEQ_AXIS",
    "TP_AXIS",
    "CohortFailed",
    "CohortOutcome",
    "CohortSupervisor",
    "abstract_mesh",
    "batch_sharding",
    "full_attention",
    "init_train_state",
    "is_abstract_mesh",
    "latest_common_checkpoint",
    "make_dp_train_step",
    "make_mesh",
    "make_train_step",
    "named_sharding",
    "replicate",
    "replicated",
    "ring_attention",
    "ring_attention_sharded",
    "ring_decode_attention",
    "shard_batch",
    "spans_processes",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "ulysses_decode_attention",
]
