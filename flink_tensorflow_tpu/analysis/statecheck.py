"""flink-tpu-statecheck — exact-resume, RNG-stream & rescale-safety
static analyzer.

The reference system's defining flaw is state hidden inside the TF
session: variables the checkpoint barriers never see (SURVEY.md §3.4,
§5 "Checkpoint / resume").  This repo's whole next arc stakes the
opposite guarantee — barrier snapshot = exact resume, for params,
optimizer moments, RNG streams and paged KV state alike — and until now
that guarantee was only tested dynamically, per workload.  Like
``flink-tpu-shardcheck`` (PR 16) lets a CPU box reject a broken TPU
layout at plan time, this module lets a CPU box reject a plan whose
snapshot is *incomplete* or whose rescale is *unsafe*, before first run.

Four verdict families, emitted through the PR-1 rule registry with
operator/edge provenance:

- ``statecheck-hidden-state`` (ERROR) — walk every user function's
  closure cells, instance ``__dict__`` and referenced module globals
  for device arrays, TrainState/optimizer pytrees, PRNG keys and
  mutable containers holding any of those, *outside* declared operator
  state (``snapshot_state``/keyed state): exactly the reference's
  state-outside-snapshots failure.
- ``statecheck-train-state`` — abstract-eval ``init_train_state`` for
  every ``OnlineTrainFunction``/``DPTrainWindowFunction``: optimizer
  moments must shard WITH their params under the declared
  :class:`~flink_tensorflow_tpu.analysis.shardcheck.SpecLayout`
  (closing PR 16's optimizer-state deferral), dtype drift between
  params and moments is flagged, and a large TrainState not donated
  through the step is the 2x-HBM trap.
- ``statecheck-rescale`` — rescale-safety: a subtask-scoped TrainState
  under a checkpointed (worse: autoscaled) plan raises
  ``StateNotRescalable`` at the restore nobody tests; a gang's
  ``global_batch`` must divide the whole p→p′ reshard ladder up to
  ``max_parallelism``, not just today's mesh.
- ``statecheck-rng-stream`` — per-session/per-key RNG must derive via
  ``jax.random.fold_in`` from keyed state, never from constant seeds in
  the record path or process-global ``numpy.random``/``random`` — so
  PR 5 replay-purity's "a restored session re-samples the identical
  continuation" holds by construction.
- ``exactly-once-boundary`` (promoted from the PR-1 local lint) — a
  dataflow pass: classify every source (replayable / WAL-fronted /
  non-replayable), propagate the delivery guarantee along every edge,
  and ERROR with the full offending path when at-least-once provenance
  reaches a sink declaring ``idempotent = False``.
- ``statecheck-page-keygroup`` (WARN; closes the PR-19 deferral) — the
  paged KV pool must partition along key groups so a p→p′ rescale
  moves whole key-group page sets, not sessions.

Everything is fail-soft (an abstract eval that raises becomes a note,
never a crashed analysis).  Front doors: ``analyze(graph)`` /
``env.validate_plan()`` (rules register via analysis/rules.py's bottom
import), the ``flink-tpu-statecheck`` console script (JSON report that
``flink-tpu-doctor --statecheck`` folds in, exit codes 0/1/2 matching
the shardcheck CLI family), and ``audit_plan()`` for tests/tools.
"""

from __future__ import annotations

import dataclasses
import dis
import math
import types
import typing

from flink_tensorflow_tpu.analysis.diagnostics import Severity
from flink_tensorflow_tpu.analysis.sanitizer import (
    _classify_chain,
    _is_user_code,
    _iter_code_objects,
    _MISSING,
    _MUTABLE_TYPES,
    _resolve_chain,
    _unwrap,
    collect_user_functions,
)
from flink_tensorflow_tpu.analysis.shardcheck import (
    DONATION_MIN_BYTES,
    Finding,
    SpecLayout,
    _leaf_shape_dtype,
    _param_paths,
)

if typing.TYPE_CHECKING:
    from flink_tensorflow_tpu.analysis.rules import AnalysisContext

#: Operator attributes that may host user-authored callables.
_SCAN_ATTRS = ("function", "key_selector", "key_selector1", "key_selector2",
               "ts_fn", "source")

#: Methods that run OUTSIDE the record path — a constant seed there is
#: the sanctioned pattern (seed once, fold per key/step afterwards).
_LIFECYCLE = frozenset({"open", "close", "clone", "__init__",
                        "snapshot_state", "restore_state", "rescale_state"})

#: jax.random samplers: consuming a key on the record path is fine *if*
#: the key derives via fold_in; re-seeding per record is not.
_RNG_SEEDERS = frozenset({"PRNGKey", "key"})
_RNG_FOLDS = frozenset({"fold_in"})


# ---------------------------------------------------------------------------
# audit data model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OpStateAudit:
    """Everything statecheck derived about one operator."""

    node: str
    kind: str  # source | train | serving | operator
    #: sources only: replayable | wal-fronted | non-replayable.
    source_class: typing.Optional[str] = None
    #: delivery guarantee arriving at / leaving this node.
    guarantee: typing.Optional[str] = None
    #: hidden-state symbol descriptions (ERROR provenance).
    hidden_state: typing.List[str] = dataclasses.field(default_factory=list)
    #: abstract-evaluated TrainState footprint (train ops only).
    train_state_bytes: typing.Optional[int] = None
    #: why parts of the audit were skipped (fail-soft provenance).
    notes: typing.List[str] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "node": self.node, "kind": self.kind,
            "source_class": self.source_class,
            "guarantee": self.guarantee,
            "hidden_state": list(self.hidden_state),
            "train_state_bytes": self.train_state_bytes,
            "notes": list(self.notes),
        }


@dataclasses.dataclass
class PlanStateAudit:
    """The full statecheck result for one captured plan."""

    findings: typing.List[Finding]
    ops: typing.List[OpStateAudit]

    def op(self, node: str) -> typing.Optional[OpStateAudit]:
        for a in self.ops:
            if a.node == node:
                return a
        return None

    def to_json(self) -> dict:
        return {
            "operators": [a.to_json() for a in self.ops],
            "findings": [f.to_json() for f in self.findings],
        }


# ---------------------------------------------------------------------------
# hidden-state classification
# ---------------------------------------------------------------------------


def _classify_state(value: typing.Any, _depth: int = 0) -> typing.Optional[str]:
    """Human description when ``value`` is checkpoint-relevant state —
    a device array, PRNG key, TrainState/optimizer pytree, or a mutable
    container holding any of those.  None for inert values (plain
    numbers, configs, numpy constants): the audit must stay quiet about
    everything a snapshot does not need."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep here
        return None
    if isinstance(value, jax.Array):
        try:
            if jax.dtypes.issubdtype(value.dtype, jax.dtypes.prng_key):
                return f"a PRNG key array (shape {tuple(value.shape)})"
        except Exception:  # noqa: BLE001 - exotic dtypes stay arrays
            pass
        return f"a device array {value.dtype}{list(value.shape)}"
    if isinstance(value, dict):
        keys = set(value.keys())
        if {"variables", "opt_state"} <= keys or {"params", "opt_state"} <= keys:
            return "a TrainState pytree (params + optimizer moments)"
    tmod = (type(value).__module__ or "").split(".")[0]
    if tmod == "optax" and isinstance(value, tuple) and _depth < 4:
        for item in value:
            inner = _classify_state(item, _depth + 1)
            if inner:
                return (f"optimizer state {type(value).__name__} "
                        f"(holding {inner})")
        return None  # GradientTransformation etc: functions, not state
    if isinstance(value, _MUTABLE_TYPES) and _depth < 4:
        items = value.values() if isinstance(value, dict) else value
        for i, item in enumerate(items):
            if i >= 64:
                break
            inner = _classify_state(item, _depth + 1)
            if inner:
                return f"a {type(value).__name__} holding {inner}"
    return None


def _referenced_global_names(code: types.CodeType) -> typing.Set[str]:
    names: typing.Set[str] = set()
    for co in _iter_code_objects(code):
        for instr in dis.get_instructions(co):
            if instr.opname in ("LOAD_GLOBAL", "LOAD_NAME"):
                names.add(instr.argval)
    return names


def _snapshot_covered_attrs(obj: typing.Any) -> typing.Optional[typing.Set[str]]:
    """Attribute names the object's USER-authored snapshot/restore
    methods touch, or None when it declares no user snapshot protocol
    at all (every stateful attr is then hidden by definition)."""
    covered: typing.Optional[typing.Set[str]] = None
    for mname in ("snapshot_state", "restore_state"):
        fn = _unwrap(getattr(type(obj), mname, None))
        if fn is None or not _is_user_code(fn.__code__):
            continue
        if covered is None:
            covered = set()
        for co in _iter_code_objects(fn.__code__):
            for instr in dis.get_instructions(co):
                if instr.opname in ("LOAD_ATTR", "LOAD_METHOD", "STORE_ATTR"):
                    covered.add(instr.argval)
    return covered


_HIDDEN_TAIL = (
    "outside declared operator state — checkpoint barriers never see it, "
    "so a restored job resumes with stale (or doubly-applied) state: the "
    "reference's state-outside-snapshots failure; move it into "
    "snapshot_state()/keyed state"
)


def _hidden_state_findings(
    t, op, findings: typing.List[Finding], audit_syms: typing.List[str],
) -> None:
    seen: typing.Set[typing.Tuple[str, str]] = set()

    def hit(where: str, symbol: str, desc: str, how: str) -> None:
        if (where, symbol) in seen:
            return
        seen.add((where, symbol))
        audit_syms.append(f"{where}: {symbol}")
        findings.append(Finding(
            rule="statecheck-hidden-state", severity=Severity.ERROR,
            message=f"{where} {how} {symbol!r} — {desc} {_HIDDEN_TAIL}",
            node=t.name))

    for attr in _SCAN_ATTRS:
        target = getattr(op, attr, None)
        if target is None:
            continue
        for name, fn in collect_user_functions(target):
            for var, cell in zip(fn.__code__.co_freevars, fn.__closure__ or ()):
                try:
                    captured = cell.cell_contents
                except ValueError:  # pragma: no cover - empty cell
                    continue
                desc = _classify_state(captured)
                if desc:
                    hit(name, var, desc, "captures by closure")
            for gname in sorted(_referenced_global_names(fn.__code__)):
                val = fn.__globals__.get(gname, _MISSING)
                if val is _MISSING or isinstance(
                        val, (types.ModuleType, types.FunctionType, type)):
                    continue
                desc = _classify_state(val)
                if desc:
                    hit(name, gname, desc, "references module global")
        _instance_state_findings(target, hit)


def _instance_state_findings(obj: typing.Any, hit) -> None:
    """Stateful attrs in a USER class instance's ``__dict__`` that its
    snapshot protocol never touches.  Framework functions keep their
    state by construction (their snapshot methods are the contract) and
    are skipped wholesale."""
    if _unwrap(obj) is not None or not hasattr(obj, "__dict__"):
        return
    cls = type(obj)
    if cls.__module__.startswith("flink_tensorflow_tpu.") or cls.__module__ in (
            "builtins", "functools"):
        return
    covered = _snapshot_covered_attrs(obj)
    for aname, val in vars(obj).items():
        desc = _classify_state(val)
        if desc is None:
            continue
        if covered is not None and aname in covered:
            continue
        hit(f"{cls.__qualname__}", f"self.{aname}", desc,
            "keeps instance attribute" if covered is None else
            "keeps snapshot-omitted instance attribute")


# ---------------------------------------------------------------------------
# RNG-stream discipline
# ---------------------------------------------------------------------------


def _classify_rng_chain(
    chain: typing.Sequence[str], globals_ns: typing.Optional[dict],
) -> typing.Optional[typing.Tuple[str, str]]:
    """('seed' | 'fold' | 'global-draw', symbol) for RNG-relevant
    attribute chains; None otherwise."""
    symbol = ".".join(chain)
    resolved = _resolve_chain(chain, globals_ns)
    if resolved is not _MISSING:
        mod = getattr(resolved, "__module__", "") or ""
        if "random" in mod and (mod == "jax" or mod.startswith(("jax.", "jax_"))):
            rname = getattr(resolved, "__name__", chain[-1])
            if rname in _RNG_SEEDERS:
                return "seed", symbol
            if rname in _RNG_FOLDS:
                return "fold", symbol
    elif len(chain) >= 3 and chain[-2] == "random" and chain[0] == "jax":
        if chain[-1] in _RNG_SEEDERS:
            return "seed", symbol
        if chain[-1] in _RNG_FOLDS:
            return "fold", symbol
    # Process-global numpy.random / random draws: the purity scanner's
    # classification, re-judged here under the fold_in discipline.
    purity = _classify_chain(chain, globals_ns)
    if purity is not None and purity[0] == "unseeded-random":
        return "global-draw", symbol
    return None


def _rng_uses(
    code: types.CodeType, globals_ns: typing.Optional[dict],
) -> typing.List[typing.Tuple[str, str, typing.Optional[int]]]:
    uses: typing.List[typing.Tuple[str, str, typing.Optional[int]]] = []
    for co in _iter_code_objects(code):
        chain: typing.List[str] = []
        chain_line: typing.Optional[int] = None
        line: typing.Optional[int] = None

        def flush() -> None:
            if not chain:
                return
            hitc = _classify_rng_chain(chain, globals_ns)
            if hitc is not None:
                uses.append((hitc[0], hitc[1], chain_line))

        for instr in dis.get_instructions(co):
            if instr.starts_line is not None:
                line = instr.starts_line
            if instr.opname in ("LOAD_GLOBAL", "LOAD_NAME"):
                flush()
                chain = [instr.argval]
                chain_line = line
            elif instr.opname in ("LOAD_ATTR", "LOAD_METHOD") and chain:
                chain.append(instr.argval)
            else:
                flush()
                chain = []
        flush()
    return uses


def _rng_stream_findings(
    t, op, keyed: bool, findings: typing.List[Finding],
) -> None:
    severity = Severity.ERROR if keyed else Severity.WARN
    target = getattr(op, "function", None)
    if target is None:
        return
    for name, fn in collect_user_functions(target):
        if set(name.replace(" -> ", ".").split(".")) & _LIFECYCLE:
            continue  # seed-in-open is the sanctioned pattern
        uses = _rng_uses(fn.__code__, fn.__globals__)
        has_fold = any(cat == "fold" for cat, _, _ in uses)
        for cat, symbol, line in uses:
            loc = f"{name}" + (f":{line}" if line else "")
            if cat == "seed" and not has_fold:
                findings.append(Finding(
                    rule="statecheck-rng-stream", severity=severity,
                    message=(
                        f"{symbol} in {loc} re-seeds from a constant in the "
                        "record path with no jax.random.fold_in in sight — "
                        "every record (and every restored replica) draws "
                        "the SAME stream instead of a per-session one; "
                        "seed once in open() and derive per-key/per-step "
                        "keys via jax.random.fold_in from keyed state so a "
                        "restored session re-samples the identical "
                        "continuation"),
                    node=t.name))
            elif cat == "global-draw":
                findings.append(Finding(
                    rule="statecheck-rng-stream", severity=severity,
                    message=(
                        f"{symbol} in {loc} draws from a process-global RNG "
                        "stream — after a restore the replayed records "
                        "re-sample a DIFFERENT continuation, so keyed state "
                        "rebuilt by replay diverges byte-for-byte from the "
                        "original run; derive per-key randomness via "
                        "jax.random.fold_in from keyed state instead"),
                    node=t.name))


# ---------------------------------------------------------------------------
# train-state audit (closes PR 16's optimizer-state sharding deferral)
# ---------------------------------------------------------------------------


def _flat_leaves(pytree) -> typing.List[typing.Tuple[str, tuple, typing.Any]]:
    out = []
    for path, leaf in _param_paths(pytree):
        shape, dtype = _leaf_shape_dtype(leaf)
        out.append((path, shape, dtype))
    return out


def _match_param(
    params: typing.List[typing.Tuple[str, tuple, typing.Any]],
    mpath: str, mshape: tuple,
) -> typing.Optional[typing.Tuple[str, tuple, typing.Any]]:
    """The param leaf an optimizer-moment leaf mirrors: optax keeps the
    param tree nested inside its states, so a path-suffix match wins;
    a same-leaf-name shape match next; a UNIQUE shape match last (the
    renamed-slot case the placement check exists for)."""
    same_shape = [p for p in params if p[1] == mshape]
    if not same_shape:
        return None
    for p in same_shape:
        if mpath == p[0] or mpath.endswith("/" + p[0]):
            return p
    mleaf = mpath.rsplit("/", 1)[-1]
    named = [p for p in same_shape if p[0].rsplit("/", 1)[-1] == mleaf]
    if named:
        def suffix_len(p):  # longest shared path suffix wins
            msegs, psegs = mpath.split("/")[::-1], p[0].split("/")[::-1]
            return sum(1 for a, b in zip(msegs, psegs) if a == b)
        return max(named, key=suffix_len)
    if len(same_shape) == 1:
        return same_shape[0]
    return None


def _train_state_findings(
    t, function, layout: SpecLayout,
    mesh_axes: typing.Optional[typing.Dict[str, int]],
    findings: typing.List[Finding], audit: OpStateAudit,
) -> None:
    try:
        import jax
        import optax

        from flink_tensorflow_tpu.parallel.dp import init_train_state

        optimizer = function.optimizer or optax.sgd(0.01)
        state = jax.eval_shape(
            lambda: init_train_state(function.model_def, optimizer,
                                     jax.random.PRNGKey(0)))
    except Exception as ex:  # noqa: BLE001 - fail-soft by contract
        audit.notes.append(f"abstract train-state eval failed: {ex!r}")
        return
    params = _flat_leaves(state["variables"])
    moments = _flat_leaves(state["opt_state"])
    total = 0
    for _, shape, dtype in params + moments:
        total += int(math.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
    audit.train_state_bytes = total
    sharded_layout = (mesh_axes is not None
                      and (layout.fsdp_axis or layout.tp_axis))
    for mpath, mshape, mdtype in moments:
        match = _match_param(params, mpath, mshape)
        if match is None:
            continue  # counts/steps/factored slots: no mirrored param
        ppath, pshape, pdtype = match
        if mdtype != pdtype:
            findings.append(Finding(
                rule="statecheck-train-state", severity=Severity.WARN,
                message=(
                    f"optimizer moment {mpath!r} is {mdtype} but its param "
                    f"{ppath!r} is {pdtype} — dtype drift between params "
                    "and moments: the snapshot round-trips the moment at a "
                    "different precision than the param it updates, so "
                    "resumed training follows a different trajectory than "
                    "uninterrupted training; align mu_dtype with the param "
                    "dtype (or declare the drift deliberately)"),
                node=t.name))
        if sharded_layout:
            pspec = layout.param_spec(ppath, pshape)
            mspec = layout.param_spec(mpath, mshape)
            if pspec != mspec:
                findings.append(Finding(
                    rule="statecheck-train-state", severity=Severity.ERROR,
                    message=(
                        f"optimizer moment {mpath!r} would place as "
                        f"{mspec} but its param {ppath!r} places as "
                        f"{pspec} under the declared SpecLayout — the "
                        "moment does not shard WITH its param, so every "
                        "update step pays a reshard (and a rescale-restore "
                        "redistributes the two differently); keep the "
                        "param tree's leaf names inside the optimizer "
                        "state or adjust the SpecLayout hints"),
                    node=t.name))
    donates = getattr(function, "donates_train_state", None)
    if donates is False and total >= DONATION_MIN_BYTES:
        findings.append(Finding(
            rule="statecheck-train-state", severity=Severity.WARN,
            message=(
                f"TrainState ({total / 2**20:.1f} MiB params+moments) is "
                "not donated through the jitted step — the previous state "
                "stays live across every update (2x HBM for the whole "
                "TrainState); use the gang DP path (make_dp_train_step "
                "donates the state) or keep the model small enough that "
                "double-buffering is acceptable"),
            node=t.name))


# ---------------------------------------------------------------------------
# rescale-safety (the p -> p' ladder)
# ---------------------------------------------------------------------------


def _rescale_findings(
    t, function, cfg, findings: typing.List[Finding],
) -> None:
    checkpoint = getattr(cfg, "checkpoint", None) if cfg is not None else None
    checkpointed = checkpoint is not None and getattr(checkpoint, "dir", None)
    health = getattr(cfg, "health", None) if cfg is not None else None
    autoscaled = health is not None and getattr(health, "autoscale", None)
    max_p = getattr(cfg, "max_parallelism", 128) if cfg is not None else 128
    scope = getattr(function, "scope", None)
    if scope == "subtask" and checkpointed:
        findings.append(Finding(
            rule="statecheck-rescale",
            severity=Severity.ERROR if autoscaled else Severity.WARN,
            message=(
                f"{type(function).__name__}(scope='subtask') keeps one "
                "independent model replica per subtask: a p→p′ "
                "rescale-restore raises StateNotRescalable at the restore "
                "nobody tests"
                + (" — and health.autoscale WILL rescale this plan on a "
                   "sustained breach, so the actuator's restore kills the "
                   "job; use scope='key' (state redistributes by key "
                   "group) or remove the train operator from the "
                   "autoscaled plan" if autoscaled else
                   "; pin the operator's parallelism across restores or "
                   "use scope='key' so state redistributes by key group")),
            node=t.name))
    elif scope == "key" and checkpointed:
        findings.append(Finding(
            rule="statecheck-rescale", severity=Severity.INFO,
            message=(
                "per-key TrainState redistributes by key group on rescale "
                f"(max_parallelism={max_p} key groups) — exact-resume "
                "holds for any p′ <= max_parallelism"),
            node=t.name))
    if getattr(function, "is_gang", False):
        batch = getattr(function, "global_batch", None)
        if not batch:
            return
        ladder: typing.List[int] = []
        p = 1
        while p <= min(max_p, batch):
            ladder.append(p)
            p *= 2
        bad = [p for p in ladder if batch % p]
        if bad:
            findings.append(Finding(
                rule="statecheck-rescale", severity=Severity.WARN,
                message=(
                    f"global_batch {batch} does not divide the "
                    f"data-parallel reshard ladder at p′={bad[0]} "
                    f"(powers of two up to max_parallelism={max_p}): a "
                    f"p→p′ rescale to {bad[0]} processes leaves ragged "
                    "per-process shards and the gang's open() rejects the "
                    "batch after the restore already happened; pick a "
                    "global_batch divisible through the ladder"),
                node=t.name))
        else:
            findings.append(Finding(
                rule="statecheck-rescale", severity=Severity.INFO,
                message=(
                    f"data-parallel reshard ladder divides cleanly: "
                    f"global_batch {batch} across p′ ∈ {{1..{ladder[-1]}}} "
                    f"(powers of two, max_parallelism={max_p})"),
                node=t.name))


# ---------------------------------------------------------------------------
# exactly-once dataflow pass (promoted from the PR-1 local lint)
# ---------------------------------------------------------------------------


def _source_feed(op) -> typing.Optional[typing.Any]:
    for attr in ("function", "source"):
        feed = getattr(op, attr, None)
        if feed is not None:
            return feed
    return None


def _classify_source(feed) -> str:
    if getattr(feed, "replayable", True) is False:
        return "non-replayable"
    if getattr(feed, "wal_fronted", False):
        return "wal-fronted"
    return "replayable"


def _sink_idempotent(op) -> typing.Optional[bool]:
    for holder in (getattr(op, "function", None), op):
        val = getattr(holder, "idempotent", None)
        if val is not None:
            return bool(val)
    return None


def _exactly_once_findings(
    ctx: "AnalysisContext", findings: typing.List[Finding],
    ops: typing.List[OpStateAudit],
) -> None:
    cfg = ctx.config
    if cfg is None:
        return  # bare graph: no checkpoint/restart story claimed
    checkpoint = getattr(cfg, "checkpoint", None)
    if checkpoint is None or getattr(checkpoint, "dir", None) is None:
        return
    children: typing.Dict[int, list] = {}
    for t in ctx.order:
        for e in t.inputs:
            children.setdefault(e.upstream.id, []).append(t)
    for t in ctx.order:
        if not t.is_source:
            continue
        op = ctx.operators.get(t.id)
        feed = _source_feed(op) if op is not None else None
        if feed is None:
            continue
        source_class = _classify_source(feed)
        audit = OpStateAudit(
            node=t.name, kind="source", source_class=source_class,
            guarantee=("at-least-once" if source_class == "non-replayable"
                       else "exactly-once"))
        ops.append(audit)
        if source_class != "non-replayable":
            continue
        findings.append(Finding(
            rule="exactly-once-boundary", severity=Severity.WARN,
            message=(
                f"source {t.name!r} ({type(feed).__name__}) is not "
                "replayable: after a restart-from-checkpoint its "
                "stream cannot be rewound, so delivery through this "
                "job is at-least-once (or lossy for in-flight "
                "records) regardless of sink transactionality — "
                "front it with a durable FileSplitSource-backed "
                "write-ahead log for end-to-end exactly-once"),
            node=t.name))
        # Propagate the degraded guarantee along every edge; judge it
        # where it terminates.
        parent: typing.Dict[int, typing.Optional[typing.Any]] = {t.id: None}
        frontier = [t]
        terminals = []
        while frontier:
            cur = frontier.pop()
            downs = children.get(cur.id, [])
            if not downs:
                terminals.append(cur)
            for child in downs:
                if child.id not in parent:
                    parent[child.id] = cur
                    frontier.append(child)
        for term in terminals:
            hops = []
            walk: typing.Optional[typing.Any] = term
            while walk is not None:
                hops.append(walk.name)
                walk = parent.get(walk.id)
            path = " -> ".join(reversed(hops))
            idem = _sink_idempotent(ctx.operators.get(term.id))
            if idem is False:
                findings.append(Finding(
                    rule="exactly-once-boundary", severity=Severity.ERROR,
                    message=(
                        "at-least-once provenance reaches a non-idempotent "
                        f"sink: the delivery guarantee degrades along "
                        f"{path} (source {t.name!r} is non-replayable) and "
                        f"sink {term.name!r} declares idempotent=False — "
                        "replayed records after a restore DUPLICATE its "
                        "side effect while in-flight records are lost "
                        "outright; front the source with a durable "
                        "FileSplitSource write-ahead log or make the sink "
                        "transactional (ExactlyOnceRecordFileSink)"),
                    node=term.name))
            elif idem is True:
                findings.append(Finding(
                    rule="exactly-once-boundary", severity=Severity.INFO,
                    message=(
                        f"at-least-once provenance along {path} is "
                        f"absorbed: sink {term.name!r} declares itself "
                        "idempotent/transactional, so replay duplicates "
                        "collapse (records lost in flight at the source "
                        "remain lost)"),
                    node=term.name))


# ---------------------------------------------------------------------------
# paged-KV key-group partition (closes the PR-19 deferral)
# ---------------------------------------------------------------------------


def _page_keygroup_findings(
    t, op, cfg, findings: typing.List[Finding],
) -> None:
    scfg = getattr(op, "serving_config", None)
    if scfg is None or not getattr(scfg, "paged_kv", False):
        return
    key_groups = getattr(cfg, "max_parallelism", 128) if cfg is not None else 128
    per_group, rem = scfg.page_partition(key_groups)
    pages = scfg.resolved_hbm_pages()
    if rem:
        findings.append(Finding(
            rule="statecheck-page-keygroup", severity=Severity.WARN,
            message=(
                f"PagedKVPool ({pages} pages x page_tokens="
                f"{scfg.page_tokens}) does not partition along the "
                f"{key_groups} key groups ({rem} pages left over): a "
                "p→p′ rescale must then move SESSIONS (drop their pages "
                "and re-prefill on the new owner) instead of handing "
                "whole key-group page sets over — size hbm_pages to a "
                "multiple of max_parallelism so pages migrate with their "
                "key groups"),
            node=t.name))
    else:
        findings.append(Finding(
            rule="statecheck-page-keygroup", severity=Severity.INFO,
            message=(
                f"paged KV pool partitions along key groups: {per_group} "
                f"pages per key group x {key_groups} key groups "
                f"(page_tokens={scfg.page_tokens}) — a rescale moves "
                "pages, not sessions"),
            node=t.name))


# ---------------------------------------------------------------------------
# the plan walk
# ---------------------------------------------------------------------------


def _layout_of(op, function) -> SpecLayout:
    for holder in (function, op):
        layout = getattr(holder, "spec_layout", None)
        if layout is not None:
            return layout
    return SpecLayout()


def audit_plan(ctx: "AnalysisContext") -> PlanStateAudit:
    """Run the full statecheck pass over an analysis context."""
    cfg = ctx.config
    mesh = getattr(cfg, "mesh", None) if cfg is not None else None
    mesh_axes = dict(mesh.shape) if mesh is not None else None
    findings: typing.List[Finding] = []
    ops: typing.List[OpStateAudit] = []
    for t in ctx.order:
        op = ctx.operators.get(t.id)
        if op is None:
            continue
        function = getattr(op, "function", None)
        hidden: typing.List[str] = []
        _hidden_state_findings(t, op, findings, hidden)
        _rng_stream_findings(t, op, ctx.is_keyed(t), findings)
        if hasattr(function, "model_def") and hasattr(function, "train_schema"):
            audit = OpStateAudit(node=t.name, kind="train",
                                 hidden_state=hidden)
            _train_state_findings(t, function, _layout_of(op, function),
                                  mesh_axes, findings, audit)
            _rescale_findings(t, function, cfg, findings)
            ops.append(audit)
        elif getattr(op, "is_continuous_batching", False):
            audit = OpStateAudit(node=t.name, kind="serving",
                                 hidden_state=hidden)
            _page_keygroup_findings(t, op, cfg, findings)
            ops.append(audit)
        elif hidden:
            ops.append(OpStateAudit(node=t.name, kind="operator",
                                    hidden_state=hidden))
    _exactly_once_findings(ctx, findings, ops)
    return PlanStateAudit(findings=findings, ops=ops)


def audit_of(ctx: "AnalysisContext") -> PlanStateAudit:
    """The per-context cached audit — the registered rules (and the
    CLI/report path) share ONE analysis pass."""
    cached = ctx.__dict__.get("_statecheck_audit")
    if cached is None:
        cached = audit_plan(ctx)
        ctx.__dict__["_statecheck_audit"] = cached
    return cached


# ---------------------------------------------------------------------------
# lint registry wiring (via the bottom import in analysis/rules.py)
# ---------------------------------------------------------------------------


def _emit_family(ctx, emit, rule_id: str) -> None:
    for f in audit_of(ctx).findings:
        if f.rule == rule_id:
            emit(f.message, node=f.node, edge=f.edge, severity=f.severity)


def _register_rules() -> None:
    from flink_tensorflow_tpu.analysis.rules import rule

    @rule("statecheck-hidden-state", Severity.ERROR)
    def _statecheck_hidden_state(ctx, emit) -> None:
        """Hidden-state audit: device arrays, TrainState/optimizer
        pytrees, PRNG keys, or mutable containers holding them, living
        in closure cells, instance attrs, or module globals OUTSIDE
        declared operator state — the snapshot is incomplete and resume
        is not exact (the reference's state-outside-snapshots failure,
        caught before first run)."""
        _emit_family(ctx, emit, "statecheck-hidden-state")

    @rule("statecheck-train-state", Severity.WARN)
    def _statecheck_train_state(ctx, emit) -> None:
        """Train-state audit over the abstract-evaluated TrainState:
        optimizer moments must shard WITH their params under the
        declared SpecLayout (ERROR on placement mismatch — closes the
        PR-16 optimizer-state deferral), param/moment dtype drift, and
        a large TrainState not donated through the step (2x HBM)."""
        _emit_family(ctx, emit, "statecheck-train-state")

    @rule("statecheck-rescale", Severity.WARN)
    def _statecheck_rescale(ctx, emit) -> None:
        """Rescale-safety: subtask-scoped TrainState under a
        checkpointed plan dies at a p→p′ rescale-restore
        (StateNotRescalable; ERROR when health.autoscale will drive
        that rescale), and a gang's global_batch must divide the whole
        power-of-two reshard ladder up to max_parallelism."""
        _emit_family(ctx, emit, "statecheck-rescale")

    @rule("statecheck-rng-stream", Severity.WARN)
    def _statecheck_rng_stream(ctx, emit) -> None:
        """RNG-stream discipline: per-session/per-key randomness must
        derive via jax.random.fold_in from keyed state — not constant
        seeds in the record path, not process-global numpy.random —
        so a restored session re-samples the identical continuation
        (ERROR on keyed-state paths)."""
        _emit_family(ctx, emit, "statecheck-rng-stream")

    @rule("statecheck-page-keygroup", Severity.WARN)
    def _statecheck_page_keygroup(ctx, emit) -> None:
        """Paged-KV rescale economics: the HBM page pool must partition
        along key groups (hbm_pages % max_parallelism == 0) so a p→p′
        rescale hands whole key-group page sets over instead of
        dropping sessions for re-prefill — closes the PR-19 deferral."""
        _emit_family(ctx, emit, "statecheck-page-keygroup")

    @rule("exactly-once-boundary", Severity.WARN)
    def _exactly_once_boundary(ctx, emit) -> None:
        """Exactly-once dataflow pass (promoted from the PR-1 local
        lint): classify every source (replayable / WAL-fronted /
        non-replayable), propagate the delivery guarantee along every
        edge of a checkpointed plan, WARN at the non-replayable
        boundary, and ERROR with the full offending path when
        at-least-once provenance reaches a sink declaring
        ``idempotent = False``."""
        _emit_family(ctx, emit, "exactly-once-boundary")


# ---------------------------------------------------------------------------
# report + CLI
# ---------------------------------------------------------------------------


def report_for_env(env, pipeline: typing.Optional[str] = None) -> dict:
    """The JSON statecheck report for one captured plan — the format
    ``flink-tpu-doctor --statecheck`` folds into its diagnosis."""
    from flink_tensorflow_tpu.analysis.analyzer import analyze  # noqa: F401 - registers rules
    from flink_tensorflow_tpu.analysis.rules import AnalysisContext
    from flink_tensorflow_tpu.analysis.schema_prop import propagate

    graph = env.graph
    order = graph.topological_order()
    operators = {}
    for t in graph.transformations:
        try:
            operators[t.id] = t.operator_factory()
        except Exception:  # noqa: BLE001 - factory-error is the analyzer's finding
            operators[t.id] = None
    flow = propagate(graph, order, operators)
    ctx = AnalysisContext(graph=graph, order=order, operators=operators,
                          schemas=flow.out, schema_sets=flow.out_sets,
                          config=env.config)
    audit = audit_of(ctx)
    report = audit.to_json()
    report["pipeline"] = pipeline
    report["errors"] = sum(
        1 for f in audit.findings if f.severity == Severity.ERROR)
    return report


def main(argv=None) -> int:
    """``flink-tpu-statecheck`` — the console script."""
    import argparse
    import dataclasses as dc
    import json
    import sys

    parser = argparse.ArgumentParser(
        prog="flink-tpu-statecheck",
        description="Exact-resume, RNG-stream & rescale-safety static "
                    "analyzer: audits a captured plan's snapshot "
                    "completeness, train-state placement, RNG discipline "
                    "and delivery guarantees — no devices, no execution.",
    )
    parser.add_argument("pipelines", nargs="+", metavar="pipeline.py",
                        help="pipeline script(s) defining main(argv)")
    parser.add_argument("--job-args", default="--smoke --cpu",
                        help="argv passed to each pipeline's main() while "
                             "building its graph (default: '--smoke --cpu')")
    parser.add_argument("--mesh", metavar="data=4,fsdp=2",
                        help="override the job's mesh with an ABSTRACT mesh "
                             "of these axes (enables the optimizer-state "
                             "placement audit on a CPU box)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON report per pipeline")
    parser.add_argument("--out", metavar="REPORT.json",
                        help="also write the (last) JSON report here — the "
                             "file flink-tpu-doctor --statecheck reads")
    args = parser.parse_args(argv)

    from flink_tensorflow_tpu.analysis.capture import capture_pipeline_file

    job_args = args.job_args.split()
    exit_code = 0
    report = None
    for path in args.pipelines:
        try:
            env = capture_pipeline_file(path, job_args)
        except Exception as ex:  # noqa: BLE001 - report and keep going
            print(f"{path}: capture failed: {ex}", file=sys.stderr)
            exit_code = max(exit_code, 2)
            continue
        if args.mesh:
            from flink_tensorflow_tpu.analysis.shardcheck import _parse_mesh
            from flink_tensorflow_tpu.parallel.mesh import abstract_mesh

            env.config = dc.replace(
                env.config, mesh=abstract_mesh(_parse_mesh(args.mesh)))
        report = report_for_env(env, pipeline=path)
        if args.json:
            print(json.dumps(report))
        else:
            print(f"== {path} ==")
            for a in report["operators"]:
                line = f"  [{a['kind']}] {a['node']}"
                if a.get("source_class"):
                    line += f"  source={a['source_class']}"
                if a.get("guarantee"):
                    line += f"  guarantee={a['guarantee']}"
                if a.get("train_state_bytes"):
                    line += (f"  train_state="
                             f"{a['train_state_bytes'] / 2**20:.1f}MiB")
                print(line)
                for sym in a["hidden_state"]:
                    print(f"      hidden: {sym}")
                for note in a["notes"]:
                    print(f"      note: {note}")
            for f in report["findings"]:
                where = f" [{f['edge'] or f['node'] or 'plan'}]"
                print(f"  {f['severity']:5s} {f['rule']}{where}: "
                      f"{f['message']}")
        if report["errors"]:
            exit_code = max(exit_code, 1)
    if args.out and report is not None:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2)
    return exit_code


if __name__ == "__main__":
    import sys

    sys.exit(main())
