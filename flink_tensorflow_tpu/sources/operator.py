"""SplitSourceOperator — the runtime host of a SplitSource.

The physical counterpart of ``env.from_source(split_source)``: one
instance per source subtask, driven by the mailbox event loop in
``core/runtime.py`` (``_Subtask.run_split_source``).  The operator owns
the read-side state machine — current split, its record iterator, the
per-split offset — and the loop owns all waiting; :meth:`poll_next`
never blocks, it answers "here is a record", "park until ``due``", or
"input exhausted".

Checkpoint identity: the in-flight split (offset included) snapshots
under this subtask's (task, index) key; reader 0 additionally carries
the coordinator's unassigned-pool snapshot (taken consistently at the
barrier — sources/coordinator.py).  ``offset`` mirrors the legacy
SourceOperator's emitted-record counter so count-based barrier
positions (``checkpoint.every_n_records``) keep working — note that
with dynamic assignment those positions are NOT deterministic across
runs, so multi-host cohorts should keep legacy sources for now.

Unlike the legacy source, this operator RESCALES: on a restore with a
different source parallelism, every old reader's in-flight split and
the old pool redistribute through the coordinator — new readers pull
from the merged pool and resume each split at its recorded offset.
"""

from __future__ import annotations

import time
import typing

from flink_tensorflow_tpu.core.operators import Operator
from flink_tensorflow_tpu.sources.api import NotReady, SourceSplit, SplitSource

if typing.TYPE_CHECKING:
    from flink_tensorflow_tpu.sources.coordinator import SplitCoordinator
    from flink_tensorflow_tpu.sources.mailbox import SourceMailbox

#: poll_next answers for the runtime loop.
RECORD = "record"
WAIT = "wait"
DONE = "done"


class SplitSourceOperator(Operator):
    #: Read by the executor (thread-body selection) and the chaining
    #: pass: this source's wait is mailbox-wakeable, so timer-driven
    #: operators MAY fuse into its chain.
    is_split_source = True
    wakeable = True

    def __init__(self, name: str, source: SplitSource):
        super().__init__(name)
        self.source = source
        self.reader = None
        self.coordinator: typing.Optional["SplitCoordinator"] = None
        self.mailbox: typing.Optional["SourceMailbox"] = None
        self.reader_index = 0
        #: Total records emitted by this subtask (count-based barriers).
        self.offset = 0
        self.current_split: typing.Optional[SourceSplit] = None
        self._iter: typing.Optional[typing.Iterator[typing.Any]] = None
        self._split_started_s: typing.Optional[float] = None
        self.splits_completed = 0
        self._restored: typing.Optional[dict] = None
        #: Span tracer + track (from ctx at open): split-lifecycle
        #: events — request/assign instants, one "split.read" span per
        #: consumed split.  None = untraced.
        self._tracer = None
        self._track: typing.Optional[str] = None
        self._split_requested = False
        #: Pool snapshot staged by on_barrier for the NEXT snapshot()
        #: call (reader 0 only) — snapshot() itself has no checkpoint-id
        #: channel down to _operator_snapshot.
        self._staged_pool: typing.Any = None
        self._staged_pool_set = False

    # -- wiring (executor, before open/restore) ---------------------------
    def attach(self, coordinator: "SplitCoordinator", index: int,
               mailbox: "SourceMailbox") -> None:
        self.coordinator = coordinator
        self.reader_index = index
        self.mailbox = mailbox
        coordinator.add_reader(index, mailbox)

    # -- lifecycle ---------------------------------------------------------
    def open(self) -> None:
        self.reader = self.source.create_reader(self.ctx)
        self.reader.open(self.ctx)
        self._tracer = getattr(self.ctx, "tracer", None)
        self._track = f"{self.ctx.task_name}.{self.ctx.subtask_index}"
        grp = self.ctx.metrics
        # Per-split observability: how work actually distributed (the
        # work-stealing evidence) and what each reader is chewing on now.
        grp.gauge("splits_completed", lambda: self.splits_completed)
        grp.gauge("current_split_id",
                  lambda: self.current_split.split_id if self.current_split else None)
        grp.gauge("current_split_age_s", self._split_age)
        if self.reader_index == 0:
            grp.gauge("splits_assigned",
                      lambda: self.coordinator.splits_dispensed
                      if self.coordinator else 0)

    def close(self) -> None:
        if self.reader is not None:
            self.reader.close()
            self.reader = None

    def _split_age(self) -> typing.Optional[float]:
        if self._split_started_s is None:
            return None
        return time.monotonic() - self._split_started_s

    # -- record plane (called only by the run_split_source loop) ----------
    def poll_next(self) -> typing.Tuple[str, typing.Any]:
        """Non-blocking step: (RECORD, value) | (WAIT, due-or-None) |
        (DONE, None).  The loop emits RECORD values immediately, so the
        split-offset bump here cannot race a barrier (single thread,
        barriers are served between polls)."""
        from flink_tensorflow_tpu.sources.coordinator import (
            ASSIGNED,
            EXHAUSTED,
        )

        tracer = self._tracer
        while True:
            if self._iter is None:
                if self.current_split is None:
                    if tracer is not None and not self._split_requested:
                        # First pull toward the coordinator for the NEXT
                        # split (request -> assign -> read lifecycle).
                        self._split_requested = True
                        tracer.instant(self._track, "split.request")
                    status, split = self.coordinator.poll_split(self.reader_index)
                    if status == EXHAUSTED:
                        return DONE, None
                    if status != ASSIGNED:
                        return WAIT, None
                    self.current_split = split
                    if tracer is not None:
                        self._split_requested = False
                        tracer.instant(self._track, "split.assign",
                                       args={"split": split.split_id})
                # (A restored in-flight split arrives with current_split
                # set and no iterator — same path as a fresh assignment.)
                self._iter = self.reader.read(self.current_split)
                self._split_started_s = time.monotonic()
            try:
                value = next(self._iter)
            except StopIteration:
                if tracer is not None and self._split_started_s is not None:
                    tracer.span(self._track, "split.read",
                                self._split_started_s, time.monotonic(),
                                args={"split": self.current_split.split_id})
                self._iter = None
                self.current_split = None
                self._split_started_s = None
                self.splits_completed += 1
                continue
            if isinstance(value, NotReady):
                return WAIT, value.due
            self.current_split.offset += 1
            return RECORD, value

    def record_emitted(self) -> None:
        self.offset += 1

    def pending_alignments(self) -> typing.List[int]:
        """Frozen alignments this reader still owes a barrier to, IF it
        is parked split-less (a reader with no split cannot advance its
        offset toward a count-based trigger position — the runtime cuts
        these barriers at the wait point to break the freeze deadlock).
        Mid-split readers return [] — their own trigger will come."""
        if self.coordinator is None or self.current_split is not None:
            return []
        return self.coordinator.pending_alignments(self.reader_index)

    def process_record(self, record):  # pragma: no cover - sources have no input
        raise RuntimeError("SplitSourceOperator has no input")

    # -- checkpoint protocol ----------------------------------------------
    def on_barrier(self, checkpoint_id: int) -> None:
        """Called by the loop as it cuts its stream at this barrier,
        BEFORE snapshot(): registers passage with the coordinator and
        stages the pool snapshot when this reader persists it."""
        snap = self.coordinator.on_barrier(checkpoint_id, self.reader_index)
        if self.reader_index == 0:
            self._staged_pool = snap
            self._staged_pool_set = True

    def _operator_snapshot(self):
        snap = {
            "offset": self.offset,
            "in_flight": (self.current_split.freeze()
                          if self.current_split is not None else None),
        }
        if self.reader_index == 0:
            if self._staged_pool_set:
                pool = self._staged_pool
                self._staged_pool = None
                self._staged_pool_set = False
            else:
                # Final/job-end snapshot (no barrier staged a pool).
                pool = (self.coordinator.live_pool_state()
                        if self.coordinator is not None else None)
            snap["pool"] = pool
        return snap

    def _operator_restore(self, state) -> None:
        self._restored = dict(state)

    def apply_restore(self) -> None:
        """Push restored state where it lives: called by the executor
        AFTER restore() delivered snapshots and BEFORE any reader thread
        runs (so the lazily-built enumerator always sees it)."""
        if self._restored is None:
            return
        state = self._restored
        self._restored = None
        self.offset = state.get("offset", 0)
        # The in-flight split resumes ON THIS READER at its recorded
        # offset (same-parallelism restore keeps locality); rescale
        # routes old in-flight splits through "extra_splits" instead.
        self.current_split = state.get("in_flight")
        pool = state.get("pool")
        if pool is not None:
            self.coordinator.deliver_restored_state(pool)
        extras = state.get("extra_splits")
        if extras:
            self.coordinator.add_splits_back(extras)

    def rescale(self, old, index, parallelism, max_parallelism):
        """Source parallelism changed across the restart: POOL everything
        — the old unassigned splits plus every old reader's in-flight
        split (offsets intact) — and let the new readers pull.  Reader 0
        carries the merged pool; everyone starts with nothing in flight."""
        snap = {"keyed": {}, "function": None,
                "operator": {"offset": 0, "in_flight": None}}
        if index != 0:
            return snap
        in_flight = []
        pool = None
        for s in old.values():
            if s is None:
                continue
            op_state = s.get("operator") or {}
            if op_state.get("in_flight") is not None:
                in_flight.append(op_state["in_flight"])
            if op_state.get("pool") is not None:
                pool = op_state["pool"]
        snap["operator"] = {
            "offset": 0,
            "in_flight": None,
            "pool": pool,
            "extra_splits": in_flight,
        }
        return snap

    def finish(self) -> None:
        if self.coordinator is not None:
            self.coordinator.reader_finished(self.reader_index)
