"""Chaos plane + self-healing cohort (ISSUE 11).

Every fault class the plan can schedule — kill, sever, stall,
checkpoint-store failure — must recover with output BYTE-IDENTICAL to a
fault-free run of the same job, verified through the 2PC sink's
``read_committed()`` (the repo's exactly-once oracle).  Plus the
machinery the faults force into existence: checkpoint deadline abort
(a stuck barrier no longer wedges the job), restart-epoch fencing
(zombie senders cannot corrupt a restored run), restart-budget backoff,
and cohort heartbeat death detection.
"""

import dataclasses
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core.environment import RestartStrategy
from flink_tensorflow_tpu.core.faults import (
    FaultPlan,
    FaultSpec,
    parse_fault_spec,
)
from flink_tensorflow_tpu.core.runtime import JobFailure
from flink_tensorflow_tpu.core.state import StateDescriptor
from flink_tensorflow_tpu.io.files import ExactlyOnceRecordFileSink, read_committed
from flink_tensorflow_tpu.tensors import TensorValue
from flink_tensorflow_tpu.tensors.serde import encode_record

SUM = StateDescriptor("sum", default_factory=lambda: 0)
NUM_KEYS = 4


class KeyedSum(fn.ProcessFunction):
    """Running per-key sum in keyed state: any duplicated or skipped
    record after recovery shows up as a wrong sum somewhere downstream,
    so byte-equality of the committed output IS the exactly-once proof."""

    def process_element(self, value, ctx, out):
        state = ctx.state(SUM)
        cur = state.value() + int(value)
        state.update(cur)
        out.collect(TensorValue(
            {"v": np.int64(cur)},
            {"key": int(ctx.current_key), "i": int(value)},
        ))


def committed_bytes(out_dir):
    """Canonical byte-level digest of a 2PC sink directory: the sorted
    serialized records (sorting removes subtask-interleaving order,
    nothing else)."""
    return sorted(bytes(encode_record(r)) for r in read_committed(out_dir))


def run_keyed_job(tmp_path, tag, *, n=120, every=20, faults=None,
                  restart=None, throttle=0.002, timeout_s=0.0,
                  parallelism=2):
    """source -> key_by -> KeyedSum (par 2) -> 2PC sink, count-based
    checkpoints; returns (env, out_dir)."""
    out = str(tmp_path / f"out-{tag}")
    env = StreamExecutionEnvironment(parallelism=parallelism)
    env.enable_checkpointing(str(tmp_path / f"chk-{tag}"),
                             every_n_records=every)
    if timeout_s:
        env.configure(checkpoint=dataclasses.replace(
            env.config.checkpoint, timeout_s=timeout_s))
    if faults is not None:
        env.configure(faults=faults)
    env.source_throttle_s = throttle
    (
        env.from_collection(list(range(n)), name="src")
        .key_by(lambda x: x % NUM_KEYS)
        .process(KeyedSum(), name="count", parallelism=parallelism)
        .add_sink(ExactlyOnceRecordFileSink(out), name="sink",
                  parallelism=1)
    )
    env.execute(f"faults-{tag}", timeout=120, restart_strategy=restart)
    return env, out


class TestFaultPlan:
    def test_spec_grammar(self):
        assert parse_fault_spec("kill:count.0@50") == FaultSpec(
            "kill", "count", 0, 50)
        assert parse_fault_spec("stall:count.1@80~0.5#1") == FaultSpec(
            "stall", "count", 1, 80, duration_s=0.5, epoch=1)
        assert parse_fault_spec("store_fail@2") == FaultSpec(
            "store_fail", at=2)
        assert parse_fault_spec("delay:sum.1@5~0.01x3") == FaultSpec(
            "delay", "sum", 1, 5, duration_s=0.01, count=3)
        plan = FaultPlan.parse("kill:a.0@1;sever:b.1@2")
        assert [s.kind for s in plan.specs] == ["kill", "sever"]

    def test_malformed_specs_raise(self):
        for bad in ("nuke:a.0@1", "kill@5", "kill:a.0", "kill:a.0@0"):
            with pytest.raises(ValueError):
                parse_fault_spec(bad)

    def test_env_var_overrides(self, monkeypatch):
        monkeypatch.setenv("FLINK_TPU_FAULTS", "kill:x.0@7")
        plan = FaultPlan.resolve(None)
        assert plan.specs[0] == FaultSpec("kill", "x", 0, 7)

    def test_epoch_filtering(self):
        from flink_tensorflow_tpu.core.faults import FaultInjector

        plan = FaultPlan.parse("kill:a.0@1#0;kill:a.0@1#1")
        assert FaultInjector(plan, epoch=0).active
        inj1 = FaultInjector(plan, epoch=1)
        assert inj1.active
        assert not FaultInjector(plan, epoch=2).active
        # epoch-1 injector fires exactly the epoch-1 spec.
        with pytest.raises(Exception):
            inj1.record_point("a.0", 1)
        assert inj1.fired == [("kill", "a.0", 1)]


class TestKillRecovery:
    def test_source_kill_byte_identical(self, tmp_path):
        """Kill the source subtask at record 50; the restart strategy
        restores from the last count-based checkpoint and the committed
        output is byte-identical to the fault-free run."""
        _, baseline = run_keyed_job(tmp_path, "baseline")
        env, out = run_keyed_job(
            tmp_path, "kill", faults="kill:src.0@50",
            restart=RestartStrategy(max_restarts=2, delay_s=0.01),
        )
        assert committed_bytes(out) == committed_bytes(baseline)
        rep = env.metric_registry.report()
        assert rep["recovery.restarts_total"] == 1
        assert rep["recovery.recovery_duration_s"]["count"] == 1.0
        assert rep["faults.kill"]["count"] == 1

    def test_keyed_worker_kill_byte_identical(self, tmp_path):
        """Kill a KEYED subtask mid-stream (its own chain, so the fault
        targets the worker loop, not a source)."""
        _, baseline = run_keyed_job(tmp_path, "baseline")
        _, out = run_keyed_job(
            tmp_path, "wkill", faults="kill:count.1@25",
            restart=RestartStrategy(max_restarts=2),
        )
        assert committed_bytes(out) == committed_bytes(baseline)

    def test_unrecovered_kill_fails_the_job(self, tmp_path):
        with pytest.raises(JobFailure):
            run_keyed_job(tmp_path, "nokill", faults="kill:src.0@10")


class TestStallAndCheckpointAbort:
    def test_stall_aborts_checkpoint_then_later_succeeds(self, tmp_path):
        """A stalled operator wedges barrier alignment past the
        checkpoint deadline: the coordinator declines the expired
        checkpoint (sources keep triggering), and once the stall clears
        a LATER checkpoint completes and lands on disk."""
        from flink_tensorflow_tpu.checkpoint.store import latest_checkpoint_id

        _, baseline = run_keyed_job(tmp_path, "baseline")
        out = str(tmp_path / "out-stall")
        env = StreamExecutionEnvironment(parallelism=2)
        env.enable_checkpointing(str(tmp_path / "chk-stall"),
                                 every_n_records=10)
        env.configure(
            checkpoint=dataclasses.replace(env.config.checkpoint,
                                           timeout_s=0.25),
            faults="stall:count.0@20~0.6",
        )
        # Pace the source PAST the stall window so checkpoints keep
        # triggering after the wedge clears — the ones cut during the
        # stall abort, the later ones must complete.
        env.source_throttle_s = 0.012
        (
            env.from_collection(list(range(120)), name="src")
            .key_by(lambda x: x % NUM_KEYS)
            .process(KeyedSum(), name="count", parallelism=2)
            .add_sink(ExactlyOnceRecordFileSink(out), name="sink",
                      parallelism=1)
        )
        handle = env.execute_async("faults-stall")
        handle.wait(120)
        coordinator = handle.executor.coordinator
        rep = env.metric_registry.report()
        assert rep["recovery.checkpoints_aborted"] >= 1
        assert coordinator.aborted_ids
        assert rep["faults.stall"]["count"] == 1
        # The stream survived the abort with nothing lost or duplicated.
        assert committed_bytes(out) == committed_bytes(baseline)
        # A checkpoint NEWER than every aborted id completed durably —
        # the abort declined ONE snapshot, it did not stop checkpointing.
        latest = latest_checkpoint_id(str(tmp_path / "chk-stall"))
        assert latest is not None
        assert latest > min(coordinator.aborted_ids)

    def test_zero_credit_edge_aborts_expired_checkpoint_then_succeeds(
            self, tmp_path):
        """Flow-control regression for the deadline-abort backstop: the
        wedge here is NOT a stalled operator but a credit-PARKED remote
        edge — the consumer stalls, stops granting, and the producer's
        RemoteSink parks at zero credit with checkpoint barriers queued
        behind it.  The coordinator's deadline sweeper must decline the
        expired checkpoints (a zero-credit edge can park data, never
        wedge the job), and once grants resume a LATER checkpoint
        completes durably with nothing lost."""
        from flink_tensorflow_tpu.checkpoint.store import latest_checkpoint_id
        from flink_tensorflow_tpu.io.remote import RemoteSink, RemoteSource

        out = str(tmp_path / "pipe-abort")
        # Tiny receive queue -> credit window of 2: the park is reached
        # within a handful of records once grants stop.
        source = RemoteSource(bind="127.0.0.1", queue_capacity=64)
        errors = []

        def consume():
            try:
                cenv = StreamExecutionEnvironment(parallelism=1)
                # Stall the CONSUMER pipeline (the sink is chained into
                # the source, so the source scope is the record point):
                # the stalled chain stops pulling the RemoteSource
                # generator, grants stop, and the producer-side sink
                # parks at zero credit.
                cenv.configure(faults="stall:rsrc.0@6~0.8")
                cenv.from_source(source, name="rsrc").add_sink(
                    ExactlyOnceRecordFileSink(out), name="csink")
                cenv.execute("consumer-abort", timeout=60)
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        t = threading.Thread(target=consume)
        t.start()
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / "chk-abort"),
                                 every_n_records=5)
        # chaining=False keeps src/tv/rsink separate subtasks: a barrier
        # cut while the sink is parked sits in a real queue BEHIND the
        # park (in a fused chain the source thread itself would park, so
        # no barrier could ever be pending during the wedge).
        env.configure(chaining=False, checkpoint=dataclasses.replace(
            env.config.checkpoint, timeout_s=0.25))
        # Pace the source PAST the 0.8s park so checkpoints keep being
        # cut after grants resume — the ones cut while the sink was
        # parked expire and abort, the later ones must complete.
        env.source_throttle_s = 0.012
        (
            env.from_collection(list(range(120)), name="src")
            .map(lambda v: TensorValue({"v": np.int64(v)}, {"i": int(v)}),
                 name="tv")
            .add_sink(RemoteSink("127.0.0.1", source.port, flush_bytes=0),
                      name="rsink")
        )
        handle = env.execute_async("producer-abort")
        handle.wait(120)
        t.join(60)
        assert not errors, errors
        rep = env.metric_registry.report()
        # The edge really did hit zero credit (this is what distinguishes
        # the regression from the plain operator-stall abort above) ...
        assert rep["rsink.0.edge.credit_starved_s"] > 0.2
        # ... the sweeper declined at least one expired checkpoint ...
        coordinator = handle.executor.coordinator
        assert rep["recovery.checkpoints_aborted"] >= 1
        assert coordinator.aborted_ids
        # ... a NEWER checkpoint completed once grants resumed ...
        latest = latest_checkpoint_id(str(tmp_path / "chk-abort"))
        assert latest is not None
        assert latest > min(coordinator.aborted_ids)
        # ... and the stream itself lost nothing through the park.
        got = sorted((int(r.meta["i"]), int(r["v"]))
                     for r in read_committed(out))
        assert got == [(i, i) for i in range(120)]


class TestStoreFailure:
    def test_store_write_failure_declines_checkpoint(self, tmp_path):
        """Checkpoint 2's store write fails: it must be declined (absent
        on disk, no 2PC commit), a later checkpoint must commit, and the
        committed output stays byte-identical."""
        from flink_tensorflow_tpu.checkpoint.store import checkpoint_ids

        _, baseline = run_keyed_job(tmp_path, "baseline")
        env, out = run_keyed_job(
            tmp_path, "store", faults="store_fail@2", every=15,
        )
        assert committed_bytes(out) == committed_bytes(baseline)
        ids = checkpoint_ids(str(tmp_path / "chk-store"))
        assert 2 not in ids
        assert any(i > 2 for i in ids)
        rep = env.metric_registry.report()
        assert rep["faults.store_fail"]["count"] == 1
        assert rep["recovery.checkpoints_aborted"] >= 1


class TestSeverRecovery:
    def _pipe(self, tmp_path, tag, faults=None):
        """Producer job (RemoteSink, per-record flush) -> consumer job
        (RemoteSource -> 2PC sink) in a thread; returns the consumer's
        committed dir."""
        from flink_tensorflow_tpu.io.remote import RemoteSink, RemoteSource

        out = str(tmp_path / f"pipe-{tag}")
        source = RemoteSource(bind="127.0.0.1")
        errors = []

        def consume():
            try:
                cenv = StreamExecutionEnvironment(parallelism=1)
                cenv.from_source(source, name="rsrc").add_sink(
                    ExactlyOnceRecordFileSink(out), name="csink")
                cenv.execute(f"consumer-{tag}", timeout=60)
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        t = threading.Thread(target=consume)
        t.start()
        env = StreamExecutionEnvironment(parallelism=1)
        if faults:
            env.configure(faults=faults)
        (
            env.from_collection(list(range(50)), name="src")
            .map(lambda v: TensorValue({"v": np.int64(v)}, {"i": int(v)}),
                 name="tv")
            .add_sink(RemoteSink("127.0.0.1", source.port, flush_bytes=0),
                      name="rsink")
        )
        env.execute(f"producer-{tag}", timeout=60)
        t.join(60)
        assert not errors, errors
        return env, out

    def test_severed_pipe_reconnects_loss_free(self, tmp_path):
        """Sever the RemoteSink edge at its 3rd frame: the sink's
        exponential-backoff reconnect resends the in-flight burst, the
        source holds the fan-in slot open, and the committed output is
        byte-identical to the fault-free pipe."""
        _, baseline = self._pipe(tmp_path, "baseline")
        env, out = self._pipe(tmp_path, "sever", faults="sever:rsink.0@3")
        assert committed_bytes(out) == committed_bytes(baseline)
        rep = env.metric_registry.report()
        assert rep["rsink.0.reconnects"] == 1
        assert rep["faults.sever"]["count"] == 1
        assert rep["recovery.edge_reconnects"]["count"] == 1

    def test_reconnect_resets_coalescing_counters_parity(self, tmp_path):
        """Regression (flow-control PR): a reconnect must RESET the
        per-edge coalescing bookkeeping, not double-book the resent
        burst — the flush-reason attribution identity
        ``wire_flush_total == size + timeout + close`` has to hold
        across the sever, with the replay visible ONLY on
        ``resent_bursts``.  The credit handshake also re-runs on the
        replacement socket (credits_available >= 0 means the loop came
        back up, not the -1 'credit-free' sentinel)."""
        _, baseline = self._pipe(tmp_path, "fc-baseline")
        env, out = self._pipe(tmp_path, "fc-sever", faults="sever:rsink.0@3")
        assert committed_bytes(out) == committed_bytes(baseline)
        rep = env.metric_registry.report()
        assert rep["rsink.0.reconnects"] == 1
        assert rep["rsink.0.resent_bursts"] >= 1
        by_reason = (rep["rsink.0.wire_flush_size"]
                     + rep["rsink.0.wire_flush_timeout"]
                     + rep["rsink.0.wire_flush_close"])
        assert rep["rsink.0.wire_flush_total"]["count"] == by_reason
        assert rep["rsink.0.edge.credits_available"] >= 0.0


class TestEpochFence:
    def test_zombie_frames_dropped(self):
        """A sender handshaking with an older restart epoch is fenced:
        its records AND its EndOfPartition never reach the gate, its
        disconnect is not an error, and the drops are counted."""
        from flink_tensorflow_tpu.core import elements as el
        from flink_tensorflow_tpu.core.channels import InputGate
        from flink_tensorflow_tpu.core.shuffle import (
            RemoteChannelWriter,
            ShuffleServer,
        )
        from flink_tensorflow_tpu.metrics.registry import MetricRegistry

        reg = MetricRegistry()
        errors = []
        server = ShuffleServer("127.0.0.1", 0, on_error=errors.append,
                               metrics=reg, epoch=2)
        gate = InputGate(1, capacity=64)
        server.register_gate("sum", 0, gate)
        server.start()
        try:
            zombie = RemoteChannelWriter("127.0.0.1", server.port, "sum",
                                         0, 0, epoch=1, flush_bytes=0)
            for i in range(5):
                zombie.write(el.StreamRecord(("zombie", i), None))
            zombie.write(el.EndOfPartition())
            zombie.close()
            live = RemoteChannelWriter("127.0.0.1", server.port, "sum",
                                       0, 0, epoch=2, flush_bytes=0)
            for i in range(3):
                live.write(el.StreamRecord(("live", i), None))
            live.write(el.EndOfPartition())
            live.close()
            got = []
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and len(got) < 4:
                item = gate.poll(timeout=0.2)
                if item is not None:
                    got.append(item[1])
        finally:
            time.sleep(0.2)
            server.close()
        values = [e.value for e in got if isinstance(e, el.StreamRecord)]
        assert values == [("live", 0), ("live", 1), ("live", 2)]
        assert reg.report()["recovery.stale_epoch_frames"] >= 6
        assert not errors

    def test_same_epoch_not_fenced(self):
        from flink_tensorflow_tpu.core import elements as el
        from flink_tensorflow_tpu.core.channels import InputGate
        from flink_tensorflow_tpu.core.shuffle import (
            RemoteChannelWriter,
            ShuffleServer,
        )

        server = ShuffleServer("127.0.0.1", 0, epoch=3)
        gate = InputGate(1, capacity=16)
        server.register_gate("t", 0, gate)
        server.start()
        try:
            w = RemoteChannelWriter("127.0.0.1", server.port, "t", 0, 0,
                                    epoch=3, flush_bytes=0)
            w.write(el.StreamRecord("x", None))
            w.write(el.EndOfPartition())
            w.close()
            item = gate.poll(timeout=5.0)
            assert item is not None and item[1].value == "x"
        finally:
            server.close()


class TestRestartBackoff:
    def test_exponential_schedule_with_cap(self):
        rs = RestartStrategy(delay_s=0.1, backoff_multiplier=2.0,
                             max_delay_s=0.5)
        assert [round(rs.delay_for(k), 3) for k in (1, 2, 3, 4, 5)] == [
            0.1, 0.2, 0.4, 0.5, 0.5]

    def test_fixed_delay_default_unchanged(self):
        rs = RestartStrategy(delay_s=0.25)
        assert [rs.delay_for(k) for k in (1, 2, 3)] == [0.25, 0.25, 0.25]

    def test_jitter_is_bounded_and_deterministic(self):
        rs = RestartStrategy(delay_s=1.0, backoff_multiplier=1.0,
                             jitter=0.2)
        d1 = rs.delay_for(1, seed=7)
        assert d1 == rs.delay_for(1, seed=7)  # deterministic
        assert d1 != rs.delay_for(2, seed=7)  # decorrelated per attempt
        for k in range(1, 6):
            assert 0.8 <= rs.delay_for(k, seed=7) <= 1.2


class TestHeartbeatDeathDetection:
    def test_silent_peer_fails_fast(self, tmp_path):
        """A 2-process cohort whose peer NEVER comes up: with heartbeats
        on, process 0 fails with CohortPeerLost right after the
        first-contact grace — instead of wedging until join() times out."""
        from flink_tensorflow_tpu.core.distributed import DistributedConfig

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            my_port = s.getsockname()[1]
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        env = StreamExecutionEnvironment(parallelism=1)
        env.set_distributed(DistributedConfig(
            0, 2, (f"127.0.0.1:{my_port}", f"127.0.0.1:{dead_port}"),
            connect_timeout_s=0.5, heartbeat_timeout_s=0.4,
            telemetry_interval_s=0.0,
        ))
        # Par-1 pipeline: every subtask lands on process 0, so no record
        # -plane connect ever touches the dead peer — the HEARTBEAT is
        # the only thing that can notice it (the hung-peer shape).  The
        # throttled source outlives the first-contact grace.
        env.source_throttle_s = 0.05
        (
            env.from_collection(list(range(60)), name="src")
            .map(lambda x: x, name="ident")
            .sink_to_list()
        )
        t0 = time.monotonic()
        with pytest.raises(JobFailure, match="cohort peer 1 silent"):
            env.execute("hb", timeout=30)
        assert time.monotonic() - t0 < 10.0


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.mark.slow
class TestCohortChaosSoak:
    def test_two_process_soak_kill_recovers_byte_identical(self, tmp_path):
        """Slow 2-process cohort chaos soak: a scheduled kill takes the
        cohort down mid-stream (the survivor fails fast on peer loss),
        the cohort restarts at epoch 1 from the latest COMMON checkpoint
        with the sanitizer on, and the committed output equals the
        fault-free expectation exactly."""
        from flink_tensorflow_tpu.parallel import latest_common_checkpoint

        sys.path.insert(0, os.path.dirname(__file__))
        from _distributed_worker import expected_emissions  # noqa: E402

        worker = os.path.join(os.path.dirname(__file__),
                              "_distributed_worker.py")
        n, every = 240, 40
        out = str(tmp_path / "out")
        chk = str(tmp_path / "chk")
        chks = [os.path.join(chk, f"proc-{i:05d}") for i in range(2)]

        def spawn(i, ports, extra_env=None, restore_id=-1):
            cmd = [sys.executable, worker, "--index", str(i),
                   "--ports", ",".join(map(str, ports)), "--out", out,
                   "--n", str(n), "--every", str(every),
                   "--restore-id", str(restore_id),
                   "--throttle", "0.005", "--chk", chk]
            env_vars = dict(os.environ)
            env_vars["PYTHONPATH"] = os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__)),
                 env_vars.get("PYTHONPATH", "")])
            env_vars.update(extra_env or {})
            return subprocess.Popen(cmd, env=env_vars,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT)

        # Round 1: process 0's keyed subtask is scheduled to die at its
        # 60th record (the FLINK_TPU_FAULTS env var reaches the worker
        # unchanged — no worker-side support needed; small-int key
        # groups route to subtask 0, which round-robin places on
        # process 0).  The peer must notice and fail fast too.
        ports = _free_ports(2)
        procs = [
            spawn(0, ports, {"FLINK_TPU_FAULTS": "kill:keyed_sum.0@60"}),
            spawn(1, ports),
        ]
        rcs = []
        for p in procs:
            try:
                pout, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                pout, _ = p.communicate()
                raise AssertionError(
                    f"worker hung:\n{pout.decode(errors='replace')}")
            rcs.append((p.returncode, pout.decode(errors="replace")))
        assert rcs[0][0] != 0, "faulted worker should have died"
        assert rcs[1][0] != 0, f"survivor ignored peer loss:\n{rcs[1][1]}"
        common = latest_common_checkpoint(chks)
        assert common is not None, "no common checkpoint before the kill"

        # Round 2: restart the cohort (fresh processes = restart epoch 1
        # for fencing purposes; the fault env var is gone) from the
        # latest common checkpoint, sanitizer on WITH the distributed
        # happens-before log — each worker dumps hb.proc<k>.json at join
        # and the stitcher must find the soak protocol-conformant.
        hb_base = str(tmp_path / "hb.json")
        ports2 = _free_ports(2)
        procs = [
            spawn(i, ports2, {"FLINK_TPU_SANITIZE": "1",
                              "FLINK_TPU_SANITIZE_LOG": hb_base},
                  restore_id=common)
            for i in range(2)
        ]
        for i, p in enumerate(procs):
            pout, _ = p.communicate(timeout=180)
            assert p.returncode == 0, (
                f"restored worker {i} failed:\n"
                f"{pout.decode(errors='replace')}")
        got = sorted(
            (int(r.meta["key"]), int(r.meta["i"]), int(r["v"]))
            for r in read_committed(out)
        )
        assert got == expected_emissions(n)
        # Distributed conformance: stitch the per-process hb logs and run
        # all five cross-process checks — zero violations alongside the
        # byte-identical output, and the record plane actually exercised
        # (frames + credits on the stitched timeline).
        from flink_tensorflow_tpu.core.sanitizer_rt import load_hb_log
        from flink_tensorflow_tpu.core.sanitizer_stitch import stitch

        docs = [load_hb_log(str(tmp_path / f"hb.proc{i}.json"))
                for i in range(2)]
        assert all(doc["reason"] == "shutdown" for doc in docs)
        report = stitch(docs)
        assert report["violations"] == [], report["violations"]
        assert report["local_violations"] == []
        kinds = {row[0] for doc in docs for row in doc["events"]}
        assert "frame.send" in kinds and "frame.recv" in kinds
        assert "credit.grant" in kinds
        assert "epoch.handshake" in kinds

    def test_stall_delay_soak_flow_control_bounds_sender_queue(self, tmp_path):
        """Flow-control chaos-soak arm: a 2-process cohort runs the keyed
        job under scheduled ``stall`` + ``delay`` faults with credits ON
        and a deliberately tiny channel capacity (credit window 2).  The
        stalled consumer stops granting, so the producer-side remote
        writers must PARK rather than buffer: every cross-process edge's
        run-long ``peak_send_queue_bytes`` high-water mark stays under
        credit window x frame quantum for the WHOLE run, and the
        committed output is still byte-for-byte the fault-free
        expectation (0 records lost through the parks)."""
        import json

        from flink_tensorflow_tpu.core.shuffle import (
            CREDIT_OVERFLOW_FRAMES,
            credit_window,
        )

        sys.path.insert(0, os.path.dirname(__file__))
        from _distributed_worker import expected_emissions  # noqa: E402

        worker = os.path.join(os.path.dirname(__file__),
                              "_distributed_worker.py")
        n, every, cap, flush_bytes = 240, 40, 64, 512
        out = str(tmp_path / "out")
        chk = str(tmp_path / "chk")
        metrics = str(tmp_path / "metrics.json")
        # Subtask 1 of the keyed stage (round-robin -> process 1) stalls
        # mid-stream; subtask 0 (process 0) gets a burst of per-record
        # delays.  Both workers receive the full plan — each injector
        # fires only where its subtask actually lives.
        faults = "stall:keyed_sum.1@40~0.5;delay:keyed_sum.0@30~0.004x25"
        ports = _free_ports(2)
        procs = []
        for i in range(2):
            cmd = [sys.executable, worker, "--index", str(i),
                   "--ports", ",".join(map(str, ports)), "--out", out,
                   "--n", str(n), "--every", str(every),
                   "--throttle", "0.005", "--chk", chk,
                   "--cap", str(cap),
                   "--wire-flush-bytes", str(flush_bytes),
                   "--metrics-out", metrics]
            env_vars = dict(os.environ)
            env_vars["PYTHONPATH"] = os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__)),
                 env_vars.get("PYTHONPATH", "")])
            env_vars["FLINK_TPU_FAULTS"] = faults
            procs.append(subprocess.Popen(cmd, env=env_vars,
                                          stdout=subprocess.PIPE,
                                          stderr=subprocess.STDOUT))
        for i, p in enumerate(procs):
            try:
                pout, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                p.kill()
                pout, _ = p.communicate()
                raise AssertionError(
                    f"worker {i} hung:\n{pout.decode(errors='replace')}")
            assert p.returncode == 0, (
                f"worker {i} failed:\n{pout.decode(errors='replace')}")
        # Exactly-once through the parks: byte-identical to the
        # fault-free expectation, 0 lost, 0 duplicated.
        got = sorted(
            (int(r.meta["key"]), int(r.meta["i"]), int(r["v"]))
            for r in read_committed(out)
        )
        assert got == expected_emissions(n)
        # The bounded-memory claim, asserted from each process's final
        # metric dump: peak_send_queue_bytes is a run-long high-water
        # mark, so reading it once at exit IS the whole-run assertion.
        # Bound = (window + barrier-overdraw allowance) frames of
        # (flush quantum + one straggler record / control frame).
        bound = ((credit_window(cap) + CREDIT_OVERFLOW_FRAMES)
                 * (flush_bytes + 4096))
        saw_remote_edge = False
        saw_grants = False
        for i in range(2):
            with open(f"{metrics}.proc{i}") as f:
                rep = json.load(f)
            for key, val in rep.items():
                if (key.startswith("shuffle.out.")
                        and key.endswith(".peak_send_queue_bytes")):
                    saw_remote_edge = True
                    assert val <= bound, (key, val, bound)
                if key.endswith(".credit_grants") and val > 0:
                    saw_grants = True
        assert saw_remote_edge, "no cross-process edge metrics dumped"
        assert saw_grants, "credit loop never engaged during the soak"
