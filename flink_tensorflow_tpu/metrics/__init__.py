"""Job observability plane: metric types, pluggable reporters, inspector.

- :mod:`.registry` — Counter/Meter/Gauge/Timer/Histogram + the per-job
  :class:`MetricRegistry` (scope-tree snapshots, seeded reservoirs).
- :mod:`.reporters` — :class:`MetricReporter` sinks (JSON-lines,
  Prometheus text exposition, console) driven by a daemon
  :class:`ReporterThread`; configured via :class:`MetricConfig`.
- :mod:`.inspector` — ``python -m flink_tensorflow_tpu.metrics
  <pipeline.py>`` / ``flink-tpu-inspect``: execute a pipeline under the
  metric plane and print per-operator rate, latency percentiles, queue
  depth, backpressure, and watermark lag (``--live --cohort``: rows
  aggregated over a whole DistributedExecutor cohort).
- :mod:`.cohort` — distributed metric aggregation: per-process state
  trees merge on the process-0 :class:`CohortCollector` (meters sum,
  reservoirs merge, gauges per policy) — the cohort-wide inspector view
  and the autoscaling supervisor's programmatic feed.
"""

from flink_tensorflow_tpu.metrics.cohort import (
    CohortCollector,
    merge_states,
    state_to_snapshot,
)
from flink_tensorflow_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Meter,
    MetricGroup,
    MetricRegistry,
    Timer,
)
from flink_tensorflow_tpu.metrics.reporters import (
    ConsoleReporter,
    JsonLinesReporter,
    LatestSnapshotReporter,
    MetricConfig,
    MetricReporter,
    PrometheusFileReporter,
    ReporterThread,
)

__all__ = [
    "CohortCollector",
    "ConsoleReporter",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonLinesReporter",
    "LatestSnapshotReporter",
    "Meter",
    "MetricConfig",
    "MetricGroup",
    "MetricRegistry",
    "MetricReporter",
    "PrometheusFileReporter",
    "ReporterThread",
    "Timer",
    "merge_states",
    "state_to_snapshot",
]
