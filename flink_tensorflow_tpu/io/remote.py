"""Remote record plane — cross-process/host stream channels over TCP.

The reference's record plane is Flink's Netty shuffle between
TaskManagers (SURVEY.md §2 "Distributed communication backend").  In the
TPU framework, *gradients* never touch this layer (they ride XLA
collectives over ICI/DCN inside the compiled step); the host-side record
plane only carries stream records between processes/hosts — job-to-job
pipes, ingestion from feeders, multi-host source fan-in.

``RemoteSink`` streams length-prefixed codec frames (tensors/serde.py)
to a peer; ``RemoteSource`` accepts connections and yields records.
Delivery is at-least-once only if the upstream replays on failure — TCP
sources are non-replayable, so exactly-once jobs should front them with
a durable log, exactly as Flink treats raw socket sources.

**Coalescing** (Flink's buffer timeout): the sink buffers records and
flushes one multi-record wire burst on a size threshold
(``flush_bytes``, default ``JobConfig.wire_flush_bytes``) or a timeout
(``flush_ms``, default ``JobConfig.wire_flush_ms``); ``close()``
force-flushes, so nothing is ever dropped.  A homogeneous flushed run
encodes **columnar** (``tensors/serde.encode_batch``: one header +
per-field contiguous buffers — the arrow-style fast path) instead of N
independent frames; heterogeneous runs fall back to per-record frames
in one ``sendall``.  ``flush_bytes=0`` restores the frame-per-record
wire.

**Single-reader event loop**: ``RemoteSource`` multiplexes its
``fan_in`` peers over one ``selectors`` loop inside the source
generator — no thread per connection, no intermediate queue;
backpressure is the generator's own pace (records are decoded only as
the pipeline consumes them, then the kernel TCP windows close).

Wire narrowing: ``RemoteSink(wire_dtype="bf16"|"f16"|"int8")`` ships
floating-point field buffers in the compact on-the-wire dtype; the
receiving decode restores the original dtype transparently, so
RemoteSource needs no matching flag.  Defaults to the job-wide
``JobConfig.wire_dtype`` when unset.  Bytes saved are counted on the
``wire_bytes_saved`` metric.  Narrowing composes with the columnar
path (one vectorized cast per field per frame).
"""

from __future__ import annotations

import collections
import os
import select
import selectors
import socket
import struct
import threading
import time
import typing

from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core.reactor import FlushScheduler, LengthPrefixedParser
from flink_tensorflow_tpu.core.shuffle import (
    CREDIT_OVERFLOW_FRAMES,
    _sendall_parts,
    connect_with_retry,
    credit_window,
)
from flink_tensorflow_tpu.tensors.serde import (
    batch_signature,
    decode_frame,
    encode_batch,
    encode_record,
)
from flink_tensorflow_tpu.tensors.value import TensorValue

_LEN = struct.Struct("<Q")

#: Credit flow-control handshake on the job-to-job pipe: a sink that
#: wants credits ships this 8-byte payload as an ordinary
#: length-prefixed frame right after connecting (and after every
#: reconnect).  A RemoteSource that understands it replies with credit
#: grants — raw little-endian u64 *increments* on the sink-bound half
#: of the same socket (the only bytes that ever flow that direction) —
#: starting with an initial window of ``credit_window(queue_capacity)``
#: frames.  Sinks that see no grant within the probe grace downgrade
#: permanently to the classic credit-free wire, so raw TCP readers and
#: pre-credit peers keep working unchanged.
_FC_MAGIC = b"\xffFLOWCTL"
_FC_PROBE_GRACE_S = 2.0
_GRANT = struct.Struct("<Q")

#: Cached origin pid for cross-process trace stamps (matches the
#: tracer's own _PID — same process).
_PID = os.getpid()


class RemoteSink(fn.SinkFunction):
    """Ships records (TensorValue) to a RemoteSource over TCP, coalesced
    into multi-record bursts with a columnar fast path."""

    #: Frames replayed after a restore are SENT AGAIN down the wire and
    #: the peer cannot tell them from fresh ones — the statecheck
    #: exactly-once dataflow pass ERRORs when at-least-once provenance
    #: terminates here.
    idempotent = False

    def __init__(self, host: str, port: int, *, connect_timeout_s: float = 30.0,
                 wire_dtype: typing.Optional[str] = None,
                 flush_bytes: typing.Optional[int] = None,
                 flush_ms: typing.Optional[float] = None,
                 columnar: bool = True,
                 reconnect_timeout_s: float = 5.0,
                 flow_control: typing.Optional[bool] = None):
        from flink_tensorflow_tpu.tensors.serde import normalize_wire_dtype

        self.host = host
        self.port = port
        self.connect_timeout_s = connect_timeout_s
        #: Self-healing send path: a burst whose send fails reconnects
        #: with exponential backoff within this budget and is resent
        #: whole (the peer RemoteSource holds the fan-in slot open for
        #: the replacement connection).  Frames already swallowed by the
        #: dead socket's kernel buffer are NOT resent — raw TCP pipes
        #: stay at-least-once (module docstring; the exactly-once
        #: boundary lint points at the durable-WAL pattern).  0 restores
        #: fail-fast sends.
        self.reconnect_timeout_s = reconnect_timeout_s
        #: Compact on-the-wire dtype for float fields (tensors/serde.py);
        #: None defers to JobConfig.wire_dtype at open().
        self.wire_dtype = normalize_wire_dtype(wire_dtype)
        #: Coalescing knobs; None defers to JobConfig.wire_flush_bytes /
        #: wire_flush_ms (env-overridable) at open().
        self.flush_bytes = flush_bytes
        self.flush_ms = flush_ms
        self.columnar = columnar
        #: Credit-based flow control (module `_FC_MAGIC` docs): None
        #: defers to JobConfig.flow_control at open(); False pins the
        #: classic credit-free wire.
        self.flow_control = flow_control
        self._wire: typing.Optional[str] = self.wire_dtype
        self._sock: typing.Optional[socket.socket] = None
        self._tracer = None
        self._san = None
        self._hb_edge = ""
        self._track: typing.Optional[str] = None
        self._saved_counter = None
        self._lock = threading.Lock()
        self._buf: typing.List[TensorValue] = []
        self._buf_bytes = 0
        self._buf_t0 = 0.0
        self._timer_armed = False
        self._flush_bytes = 0
        self._flush_ms = 0.0
        self._error: typing.Optional[BaseException] = None
        self._flush_counters: typing.Optional[dict] = None
        self._frame_records = self._frame_bytes = None
        self._flush_total = None
        self._fault_hook = None
        self._reconnects = None
        self._edge_reconnects = None
        # Credit state.  "off": classic wire.  "probe": hello sent,
        # waiting for the peer's first grant.  "on": every data burst
        # spends one credit; zero credit parks the producer.
        self._fc_state = "off"
        self._fc_credits = 0
        self._fc_rxbuf = b""
        self._fc_probe_waited = False
        self._credit_starved_s = 0.0
        self._resends = None
        self._resent_total = 0

    def clone(self):
        return RemoteSink(self.host, self.port,
                          connect_timeout_s=self.connect_timeout_s,
                          wire_dtype=self.wire_dtype,
                          flush_bytes=self.flush_bytes,
                          flush_ms=self.flush_ms,
                          columnar=self.columnar,
                          reconnect_timeout_s=self.reconnect_timeout_s,
                          flow_control=self.flow_control)

    def open(self, ctx) -> None:
        from flink_tensorflow_tpu.core.shuffle import (
            DEFAULT_FLUSH_BYTES,
            DEFAULT_FLUSH_MS,
            env_flush_bytes,
            env_flush_ms,
        )

        self._tracer = getattr(ctx, "tracer", None)
        self._track = f"{ctx.task_name}.{ctx.subtask_index}"
        # Distributed sanitizer: the job-to-job pipe logs its half of
        # each happens-before edge.  The edge name is directional and
        # sink-local on purpose — the pipe has no conn handshake, so the
        # stitcher must never pair these with the receiving job's log
        # (pairing without a shared conn id would be a false positive
        # factory); they enrich the per-process dump and local checks.
        self._san = getattr(ctx, "sanitizer", None)
        self._hb_edge = (f"{ctx.task_name}.{ctx.subtask_index}"
                         f"->{self.host}:{self.port}")
        self._wire = (self.wire_dtype
                      if self.wire_dtype is not None
                      else getattr(ctx, "wire_dtype", None))
        env_b, env_ms = env_flush_bytes(), env_flush_ms()
        self._flush_bytes = (
            env_b if env_b is not None
            else self.flush_bytes if self.flush_bytes is not None
            else getattr(ctx, "wire_flush_bytes", None) or DEFAULT_FLUSH_BYTES)
        self._flush_ms = (
            env_ms if env_ms is not None
            else self.flush_ms if self.flush_ms is not None
            else getattr(ctx, "wire_flush_ms", None) or DEFAULT_FLUSH_MS)
        if ctx.metrics is not None:
            if self._wire is not None:
                self._saved_counter = ctx.metrics.counter("wire_bytes_saved")
            # Flush-reason attribution + per-edge frame shape (satellite
            # of the coalescing plane; invoke/flush serialize on _lock).
            self._flush_counters = {
                reason: ctx.metrics.counter(f"wire_flush_{reason}")
                for reason in ("size", "timeout", "close")
            }
            self._frame_records = ctx.metrics.histogram("frame_records")
            self._frame_bytes = ctx.metrics.histogram("frame_bytes")
            self._flush_total = ctx.metrics.meter("wire_flush_total")
            self._reconnects = ctx.metrics.counter("reconnects")
            #: Resent bursts are booked HERE, never on the wire_flush_*
            #: reason counters — one logical flush ticks its reason
            #: exactly once no matter how many times the burst hits the
            #: wire, so attribution parity
            #: (wire_flush_total == size+timeout+close) holds across
            #: reconnects.
            self._resends = ctx.metrics.counter("resent_bursts")
            # Credit-plane observability (health rule `credit-starvation`
            # + doctor bottleneck ranking key off these).
            ctx.metrics.gauge("edge.credits_available",
                              lambda: float(self._fc_credits)
                              if self._fc_state == "on" else -1.0)
            ctx.metrics.gauge("edge.credit_starved_s",
                              lambda: self._credit_starved_s)
            registry = getattr(ctx.metrics, "_registry", None)
            if registry is not None:
                self._edge_reconnects = registry.group("recovery").meter(
                    "edge_reconnects")
        # Chaos plane: sever/blackhole/delay specs targeting this sink's
        # subtask fire inside _flush_locked (core/faults.py).
        injector = getattr(ctx, "fault_injector", None)
        if injector is not None:
            self._fault_hook = injector.edge_hook(
                ctx.task_name, ctx.subtask_index)

        # Bounded-backoff connect retry (the same loop the shuffle plane
        # uses for cohort startup): ANY OSError — refused, unreachable,
        # reset mid-handshake — retries until the deadline, because the
        # peer's listener may come up, or come BACK up, after this job
        # starts.
        self._sock = connect_with_retry(
            self.host, self.port, self.connect_timeout_s)
        fc_on = (self.flow_control if self.flow_control is not None
                 else getattr(ctx, "flow_control", True))
        if fc_on:
            self._fc_hello()

    def _fc_hello(self) -> None:
        """Start the credit handshake on the current socket: ship the
        FC hello frame and enter "probe" — the first grant (whenever it
        arrives) locks credits on.  Probe is non-terminal: a silent
        peer costs one probe-grace wait on the first burst, after which
        bursts flow credit-free while the sink keeps listening — so raw
        pre-credit readers never park this sink, yet a RemoteSource
        whose generator starts late (a consumer already overloaded at
        startup) still gets the credit loop the moment it grants."""
        self._fc_state = "probe"
        self._fc_probe_waited = False
        self._fc_credits = 0
        self._fc_rxbuf = b""
        try:
            self._sock.sendall(_LEN.pack(len(_FC_MAGIC)) + _FC_MAGIC)
        except OSError:
            pass  # the next burst's send notices and reconnects

    def _harvest_grants(self, timeout: float) -> bool:
        """Pull any credit grants off the sink-bound half of the socket
        (raw u64 increments).  Returns False when the peer is gone (EOF
        or socket error) — the caller stops parking and lets the send
        path run its reconnect loop."""
        sock = self._sock
        if sock is None:
            return False
        try:
            readable, _, _ = select.select([sock], [], [], timeout)
        except (OSError, ValueError):
            return False
        if not readable:
            return True
        try:
            chunk = sock.recv(4096)
        except (BlockingIOError, InterruptedError):
            return True
        except OSError:
            return False
        if not chunk:
            return False
        buf = self._fc_rxbuf + chunk
        while len(buf) >= _GRANT.size:
            self._fc_credits += _GRANT.unpack_from(buf)[0]
            buf = buf[_GRANT.size:]
            if self._fc_state == "probe":
                self._fc_state = "on"
        self._fc_rxbuf = buf
        return True

    def _fc_available(self) -> bool:
        """Non-destructive peek for the timeout-flush skip."""
        if self._fc_state == "off":
            return True
        self._harvest_grants(0.0)
        return self._fc_state != "on" or self._fc_credits > 0

    def _fc_acquire(self, fc: str) -> None:
        """Spend one credit for a burst about to hit the wire.

        ``fc`` mirrors the shuffle writer's modes: "data" parks at
        floor 0 until the RemoteSource grants; "align" (close-flush)
        may overdraw to -CREDIT_OVERFLOW_FRAMES so teardown never
        wedges on a stalled consumer; "bypass" (the EOS marker) spends
        nothing.  Parked time accrues on ``edge.credit_starved_s``.
        """
        if fc == "bypass" or self._fc_state == "off":
            return
        if self._fc_state == "probe":
            if not self._fc_probe_waited:
                deadline = time.monotonic() + _FC_PROBE_GRACE_S
                while (self._fc_state == "probe"
                       and time.monotonic() < deadline):
                    if not self._harvest_grants(0.05):
                        break
                self._fc_probe_waited = True
            else:
                self._harvest_grants(0.0)
            if self._fc_state != "on":
                return  # still probing: send credit-free, keep listening
        floor = -CREDIT_OVERFLOW_FRAMES if fc == "align" else 0
        san = self._san
        self._harvest_grants(0.0)
        if self._fc_credits > floor:
            self._fc_credits -= 1
            if san is not None:
                san.hb("credit.spend", self._hb_edge,
                       balance=self._fc_credits, floor=floor)
            return
        t0 = time.monotonic()
        if san is not None:
            san.hb("credit.park", self._hb_edge, floor=floor)
        while self._fc_credits <= floor:
            if not self._harvest_grants(0.05):
                break  # peer gone; the send path reconnects (or raises)
        waited = time.monotonic() - t0
        self._credit_starved_s += waited
        if san is not None:
            san.hb("credit.unpark", self._hb_edge, waited_s=waited)
        if self._tracer is not None and waited > 1e-3:
            self._tracer.span(self._track, "wire.credit_wait",
                              t0, time.monotonic(), args={"mode": fc})
        self._fc_credits -= 1
        if san is not None:
            san.hb("credit.spend", self._hb_edge,
                   balance=self._fc_credits, floor=floor)

    def invoke(self, value) -> None:
        if not isinstance(value, TensorValue):
            raise TypeError("RemoteSink carries TensorValue records")
        if self._saved_counter is not None:
            from flink_tensorflow_tpu.tensors.serde import wire_bytes_saved

            self._saved_counter.inc(wire_bytes_saved(value, self._wire))
        tracer = self._tracer
        if tracer is not None:
            # The record's trace id rides the frame (TensorValue metadata
            # encodes with the record), so the receiving RemoteSource
            # re-admits it under the SAME trace — one logical record, one
            # trace, across the job boundary.  The origin pid + send
            # stamp let a clock-synced receiver record the remote hop as
            # an offset-corrected queue span (Tracer.admit); an unsynced
            # receiver keeps only the id, as before.
            tctx = tracer.current()
            if tctx is not None:
                value = value.with_meta(
                    __trace__=(tctx.trace_id, _PID, time.monotonic()))
        with self._lock:
            if self._error is not None:
                exc, self._error = self._error, None
                raise exc
            if self._flush_bytes <= 0:
                self._buf.append(value)
                self._flush_locked("size")
                return
            self._buf.append(value)
            self._buf_bytes += sum(
                a.nbytes for a in value.fields.values()) + 64
            if len(self._buf) == 1:
                self._buf_t0 = time.monotonic()
                if self._flush_ms > 0 and not self._timer_armed:
                    # One pending deadline per sink, re-armed from the
                    # timer thread (mirrors RemoteChannelWriter): the hot
                    # invoke path never wakes the shared timer.
                    self._timer_armed = True
                    FlushScheduler.shared().schedule(
                        self._buf_t0 + self._flush_ms / 1e3,
                        self._timer_fire)
            if self._buf_bytes >= self._flush_bytes:
                self._flush_locked("size")
            elif self._flush_ms <= 0:
                self._flush_locked("timeout")

    def _timer_fire(self) -> None:
        # Non-blocking acquire: the invoke thread may hold _lock for
        # seconds while parked on credits, and this runs on the
        # process-wide FlushScheduler thread — one starved edge must
        # not stall every other edge's timers.
        if not self._lock.acquire(blocking=False):
            FlushScheduler.shared().schedule(
                time.monotonic() + max(self._flush_ms, 5.0) / 1e3,
                self._timer_fire)
            return
        try:
            if self._sock is None or not self._buf:
                self._timer_armed = False
                return
            due = self._buf_t0 + self._flush_ms / 1e3
            if time.monotonic() + 1e-4 < due:
                # Size-flushed and refilled since arming: sleep on
                # towards the current buffer's deadline.
                FlushScheduler.shared().schedule(due, self._timer_fire)
                return
            self._timer_armed = False
            try:
                self._flush_locked("timeout")
            except (OSError, ConnectionError) as exc:
                # Off-thread failure: the next invoke() re-raises it on
                # the sink's own subtask.
                self._error = exc
        finally:
            self._lock.release()

    def _flush_locked(self, reason: str) -> None:
        buf = self._buf
        if not buf:
            return
        if (reason == "timeout" and self._flush_ms > 0
                and self._fc_state == "on" and not self._fc_available()):
            # Zero credit on a deadline flush: keep coalescing instead
            # of parking the shared timer thread; the deadline re-arms
            # and fires again once the consumer grants.
            if not self._timer_armed:
                self._timer_armed = True
                FlushScheduler.shared().schedule(
                    time.monotonic() + self._flush_ms / 1e3,
                    self._timer_fire)
            return
        self._buf = []
        self._buf_bytes = 0
        t_first = self._buf_t0
        n = len(buf)
        t0 = time.monotonic()
        if n > 1 and self.columnar:
            sig = batch_signature(buf[0])
            homogeneous = sig is not None and all(
                batch_signature(v) == sig for v in buf[1:])
        else:
            homogeneous = False
        if homogeneous:
            payload = encode_batch(buf, self._wire)
            parts = [_LEN.pack(len(payload)), payload]
        else:
            parts = []
            for v in buf:
                payload = encode_record(v, self._wire)
                parts.append(_LEN.pack(len(payload)))
                parts.append(payload)
        burst_bytes = sum(len(p) for p in parts)
        t1 = time.monotonic()
        self._send_burst(parts, fc="align" if reason == "close" else "data")
        t2 = time.monotonic()
        if self._flush_counters is not None:
            self._flush_counters[reason].inc()
            self._frame_records.record(n)
            self._frame_bytes.record(burst_bytes)
            self._flush_total.mark()
        tracer = self._tracer
        if tracer is not None:
            # Coalescing delay attributed separately from encode + send,
            # so `flink-tpu-trace` prices the buffer timeout on its own.
            tracer.span(self._track, "wire.flush", t_first, t0,
                        args={"reason": reason, "records": n})
            tracer.span(self._track, "serde", t0, t1,
                        args={"bytes": burst_bytes, "records": n,
                              "columnar": homogeneous})
            tracer.span(self._track, "wire", t1, t2,
                        args={"bytes": burst_bytes})

    def _send_burst(self, parts, fc: str = "data") -> None:
        """One burst onto the wire (scatter-gather sendmsg, no
        concatenation copy), with the chaos hook, the credit gate, and
        the self-healing retry: a failed send reconnects with
        exponential backoff within ``reconnect_timeout_s`` and resends
        the whole burst — the peer RemoteSource keeps the fan-in slot
        open for the replacement connection (see its reconnect grace)."""
        try:
            if self._fault_hook is not None and self._fault_hook() == "drop":
                # Injected blackhole: the burst vanishes.  Checked
                # BEFORE the credit spend — the receiver never sees a
                # dropped burst, so a spent credit could never be
                # replenished (a slow leak of the window under chaos).
                return
            self._fc_acquire(fc)
            _sendall_parts(self._sock, parts)
            if self._san is not None:
                self._san.hb("frame.send", self._hb_edge, fc=fc,
                             nbytes=sum(len(p) for p in parts))
            return
        except (OSError, ConnectionError):
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            if self.reconnect_timeout_s <= 0:
                raise
        deadline = time.monotonic() + self.reconnect_timeout_s
        backoff = 0.05
        attempt = 0
        while True:
            attempt += 1
            time.sleep(min(backoff, max(0.0, deadline - time.monotonic())))
            backoff = min(backoff * 2.0, 1.0)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ConnectionError(
                    f"RemoteSink to {self.host}:{self.port}: send failed and "
                    f"reconnect did not succeed within "
                    f"{self.reconnect_timeout_s}s")
            try:
                self._sock = connect_with_retry(
                    self.host, self.port, max(0.05, remaining))
                self._reset_after_reconnect()
                self._fc_acquire(fc)
                _sendall_parts(self._sock, parts)
                if self._san is not None:
                    self._san.hb("frame.send", self._hb_edge, fc=fc,
                                 nbytes=sum(len(p) for p in parts),
                                 resend=True)
            except (OSError, ConnectionError, TimeoutError):
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                continue
            if self._reconnects is not None:
                self._reconnects.inc()
            if self._edge_reconnects is not None:
                self._edge_reconnects.mark()
            if self._resends is not None:
                self._resends.inc()
            self._resent_total += 1
            import logging

            logging.getLogger(__name__).warning(
                "RemoteSink to %s:%d re-established after %d attempt(s); "
                "in-flight burst resent", self.host, self.port, attempt)
            return

    def _reset_after_reconnect(self) -> None:
        """Fresh connection, fresh per-edge state.

        Credits: grants from the dead socket died with it and the
        replacement fan-in slot re-grants a full window, so the local
        count restarts from the new hello (stale grants can never be
        spent against the new connection).

        Coalescing attribution: the buffer-age stamp is reset so the
        resent burst's outage time is not billed to the NEXT buffer's
        `wire.flush` span, and the resend itself is booked on the
        `resent_bursts` counter only — the wire_flush_* reason counters
        tick once per logical flush, keeping
        wire_flush_total == size + timeout + close across reconnects.
        """
        if self._fc_state != "off":
            self._fc_hello()
        self._buf_t0 = time.monotonic()

    def close(self) -> None:
        if self._sock is not None:
            with self._lock:
                try:
                    self._flush_locked("close")
                except (OSError, ConnectionError):
                    pass  # peer already gone; nothing left to preserve
            try:
                # End-of-stream marker (a zero-length frame): the peer
                # RemoteSource counts this peer DONE only after seeing
                # it — a bare FIN is treated as an unclean drop eligible
                # for reconnect, so sink restarts and severed links are
                # distinguishable from completion.
                self._sock.sendall(_LEN.pack(0))
            except OSError:
                pass
            try:
                self._sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            self._sock.close()
            self._sock = None


class RemoteSource(fn.SourceFunction):
    """Accepts ``fan_in`` RemoteSink connections and yields their records.

    Bind with port=0 to pick a free port; read it from :attr:`port`
    after construction (the listener opens eagerly so peers can connect
    before the job starts).

    ``fan_in>=1`` peers multiplex over ONE ``selectors`` event loop
    running inside the source generator itself — no reader threads, no
    hand-off queue.  Records interleave in arrival order (no ordering
    across peers, exactly like Flink's network shuffle fan-in) and the
    source finishes when ALL peers have closed cleanly.  A truncated
    peer stream fails the source loudly.  Backpressure is inherent: the
    loop only reads more bytes once the pipeline consumed the decoded
    records, so a slow job closes the kernel TCP windows.
    """

    #: Plan-time marker for the `exactly-once-boundary` lint: a TCP
    #: stream cannot be rewound to a checkpoint offset, so jobs that
    #: replay after failure re-read NOTHING from this source — delivery
    #: through it is at-least-once unless fronted by a durable log.
    replayable = False

    def __init__(self, bind: str = "0.0.0.0", port: int = 0,
                 *, fan_in: int = 1, accept_timeout_s: float = 60.0,
                 queue_capacity: int = 1024,
                 reconnect_grace_s: float = 5.0):
        if fan_in < 1:
            raise ValueError(f"fan_in must be >= 1, got {fan_in}")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((bind, port))
        self._listener.listen(fan_in)
        self.port = self._listener.getsockname()[1]
        self.fan_in = fan_in
        self.accept_timeout_s = accept_timeout_s
        #: Self-healing fan-in: a peer that drops WITHOUT the
        #: end-of-stream marker (reset, sink-side sever, truncated
        #: frame) frees its slot and the source waits this long for the
        #: peer to reconnect (RemoteSink resends its in-flight burst on
        #: the replacement connection) before failing loudly.  0
        #: restores fail-fast.
        self.reconnect_grace_s = reconnect_grace_s
        #: Retained for API compatibility; the threadless loop needs no
        #: hand-off queue (its backlog is the per-connection parser).
        self.queue_capacity = queue_capacity
        self._tracer = None
        self._san = None
        self._hb_edge = ""
        self._track: typing.Optional[str] = None
        self._credit_grants = None
        self._wire_latency = None
        self._wire_latency_err = 0.0

    def clone(self):
        return self  # the listener is the identity; parallelism must be 1

    def open(self, ctx) -> None:
        self._tracer = getattr(ctx, "tracer", None)
        self._track = f"{ctx.task_name}.{ctx.subtask_index}"
        # Directional receive-side edge name; deliberately distinct from
        # any sender's edge so the cohort stitcher never pairs the
        # conn-less pipe (see RemoteSink.open).
        self._san = getattr(ctx, "sanitizer", None)
        self._hb_edge = f"remote:{self.port}->{self._track}"
        if ctx.metrics is not None:
            self._credit_grants = ctx.metrics.counter("credit_grants")
            # One-way wire latency per remote edge (send stamp rides the
            # __trace__ meta; mapped into this clock via the cohort
            # offsets) with the estimation error bound published beside
            # it — a reading is only as trustworthy as its bound.
            self._wire_latency = ctx.metrics.histogram("edge.wire_latency_s")
            ctx.metrics.gauge("edge.wire_latency_err_s",
                              lambda: self._wire_latency_err)
        if ctx.parallelism != 1:
            raise RuntimeError(
                "RemoteSource owns one listener — run it with "
                f"parallelism=1 (got {ctx.parallelism}); scale ingest by "
                "raising fan_in instead"
            )

    def _record_wire_latency(self, record, t_recv: float) -> None:
        """One-way send->recv latency for a decoded frame, read off the
        first record's ``__trace__`` stamp (peeked, not popped — the
        admitting source still re-admits the trace).  Recorded only once
        the cohort clock sync knows the origin's offset; the current
        error bound is published alongside so a reading smaller than its
        bound is visibly noise, not signal."""
        hist = self._wire_latency
        tracer = self._tracer
        if hist is None or tracer is None:
            return
        meta = getattr(record, "meta", None)
        stamp = meta.get("__trace__") if meta else None
        if type(stamp) is not tuple:
            return
        _trace_id, origin, t_send = stamp
        off = tracer.clock_offsets.get(origin)
        if off is None or not t_send:
            return
        hist.record(max(0.0, t_recv - (t_send + off)))
        self._wire_latency_err = tracer.clock_error.get(origin, 0.0)

    def run(self) -> typing.Iterator[typing.Any]:
        """Yields records; yields SOURCE_IDLE while waiting (accepting or
        between frames) so the source loop can serve checkpoint barriers
        — a source blocked in recv() would otherwise stall coordinator-
        triggered checkpoints for the whole job."""
        from flink_tensorflow_tpu.core.elements import SOURCE_IDLE

        sel = selectors.DefaultSelector()
        self._listener.setblocking(False)
        sel.register(self._listener, selectors.EVENT_READ, None)
        parsers: typing.Dict[socket.socket, LengthPrefixedParser] = {}
        #: Peers whose end-of-stream marker arrived: their EOF is clean
        #: completion; any other drop is reconnect-eligible.
        eos: typing.Set[socket.socket] = set()
        ready: typing.Deque[TensorValue] = collections.deque()
        started = closed = 0      # first-time accepts / completed peers
        lost = 0                  # unclean drops awaiting reconnect
        lost_deadline = 0.0
        deadline = time.monotonic() + self.accept_timeout_s
        tracer = self._tracer
        # Credit plane (module _FC_MAGIC docs): peers that sent the FC
        # hello, the data frames consumed from each since the last
        # grant, and grant bytes awaiting a writable socket.  Grants
        # are queued only AFTER the frame's records were yielded — the
        # pipeline demonstrably consumed them — so a stalled consumer
        # stops the grant stream and parks the sender within one
        # credit window.
        window = credit_window(self.queue_capacity)
        fc_conns: typing.Set[socket.socket] = set()
        unacked: typing.Dict[socket.socket, int] = {}
        grant_out: typing.Dict[socket.socket, bytearray] = {}
        grants_counter = self._credit_grants

        san = self._san

        def queue_grant(conn: socket.socket, n: int) -> None:
            grant_out.setdefault(conn, bytearray()).extend(_GRANT.pack(n))
            if grants_counter is not None:
                grants_counter.inc(n)
            if san is not None:
                san.hb("credit.grant", self._hb_edge, n=n)

        def flush_grants() -> None:
            for c in list(grant_out):
                buf = grant_out[c]
                if c not in parsers or not buf:
                    del grant_out[c]
                    continue
                try:
                    sent = c.send(bytes(buf))
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    # Peer gone; its reconnect re-grants a full window.
                    del grant_out[c]
                    continue
                del buf[:sent]
                if not buf:
                    del grant_out[c]

        def drop_unclean(conn: socket.socket, why: str):
            nonlocal lost, lost_deadline
            sel.unregister(conn)
            try:
                conn.close()
            except OSError:
                pass
            del parsers[conn]
            eos.discard(conn)
            fc_conns.discard(conn)
            unacked.pop(conn, None)
            grant_out.pop(conn, None)
            if self.reconnect_grace_s <= 0:
                raise ConnectionError(
                    f"remote peer dropped uncleanly ({why}) and "
                    "reconnect_grace_s=0")
            lost += 1
            lost_deadline = time.monotonic() + self.reconnect_grace_s
            import logging

            logging.getLogger(__name__).warning(
                "remote peer dropped uncleanly (%s); holding its fan-in "
                "slot %.1fs for a reconnect", why, self.reconnect_grace_s)

        try:
            while closed < self.fan_in:
                # Drain decoded records FIRST: reading more while the
                # pipeline lags would just buffer unboundedly.
                while ready:
                    yield ready.popleft()
                # Everything decoded so far has been consumed by the
                # pipeline — NOW replenish the senders' credits.
                if unacked:
                    for c, n in unacked.items():
                        if c in parsers:
                            queue_grant(c, n)
                    unacked.clear()
                if grant_out:
                    flush_grants()
                now = time.monotonic()
                if started < self.fan_in and now > deadline:
                    raise TimeoutError(
                        f"RemoteSource accepted {started}/{self.fan_in} "
                        f"peers within {self.accept_timeout_s}s"
                    )
                if lost > 0 and now > lost_deadline:
                    raise ConnectionError(
                        f"{lost} remote peer(s) dropped uncleanly and did "
                        f"not reconnect within {self.reconnect_grace_s}s "
                        "(records in the dead connection's kernel buffer "
                        "are lost — TCP sources are at-least-once)"
                    )
                events = sel.select(timeout=0.1)
                if not events:
                    yield SOURCE_IDLE
                    continue
                for key, _ in events:
                    if key.fileobj is self._listener:
                        if started >= self.fan_in and lost <= 0:
                            continue
                        try:
                            conn, _addr = self._listener.accept()
                        except (BlockingIOError, OSError):
                            continue
                        conn.setblocking(False)
                        parsers[conn] = LengthPrefixedParser()
                        sel.register(conn, selectors.EVENT_READ, None)
                        if lost > 0:
                            # A dropped peer came back: the sink resends
                            # its in-flight burst on this connection.
                            lost -= 1
                            import logging

                            logging.getLogger(__name__).info(
                                "remote peer reconnected; %d still lost",
                                lost)
                        else:
                            started += 1
                        continue
                    conn = typing.cast(socket.socket, key.fileobj)
                    parser = parsers[conn]
                    try:
                        chunk = conn.recv(1 << 20)
                    except (BlockingIOError, InterruptedError):
                        continue
                    except OSError as exc:
                        drop_unclean(conn, f"recv failed: {exc!r}")
                        continue
                    if not chunk:
                        if parser.buffered:
                            drop_unclean(conn, "closed mid-frame")
                            continue
                        if conn not in eos:
                            drop_unclean(conn, "closed without end-of-"
                                               "stream marker")
                            continue
                        sel.unregister(conn)
                        conn.close()
                        del parsers[conn]
                        eos.discard(conn)
                        fc_conns.discard(conn)
                        unacked.pop(conn, None)
                        grant_out.pop(conn, None)
                        continue
                    for payload, length in parser.feed(chunk):
                        if length == 0:
                            # End-of-stream marker: this peer is DONE —
                            # only now does its slot count completed.
                            eos.add(conn)
                            closed += 1
                            continue
                        if (length == len(_FC_MAGIC)
                                and payload == _FC_MAGIC):
                            # Credit handshake: grant the initial
                            # window (re-granted whole on reconnect —
                            # the dead socket's credits died with it).
                            fc_conns.add(conn)
                            queue_grant(conn, window)
                            continue
                        if conn in fc_conns:
                            # One credit per data frame, owed back once
                            # its records are yielded downstream.
                            unacked[conn] = unacked.get(conn, 0) + 1
                        if san is not None:
                            san.hb("frame.recv", self._hb_edge,
                                   nbytes=length)
                        if tracer is None:
                            ready.extend(decode_frame(payload))
                        else:
                            t0 = time.monotonic()
                            records = decode_frame(payload)
                            tracer.span(self._track, "serde", t0,
                                        time.monotonic(),
                                        args={"bytes": length,
                                              "records": len(records)})
                            if records:
                                self._record_wire_latency(records[0], t0)
                            ready.extend(records)
            while ready:
                yield ready.popleft()
        finally:
            for conn in parsers:
                try:
                    conn.close()
                except OSError:
                    pass
            sel.close()

    def close(self) -> None:
        self._listener.close()
