"""PacedSplitSource — open-loop arrival process on the split API.

The split-based successor of ``io.sources.PacedSource`` (the bench's
coordinated-omission-free arrival model): records are due on a schedule
regardless of pipeline progress, and each emitted record carries its
SCHEDULED time in ``meta[ts_key]`` so sinks measure latency against the
schedule, not the emit instant.

The decisive difference from PacedSource: pacing never sleeps inside
user code.  The reader yields :class:`~flink_tensorflow_tpu.sources.api.
NotReady` markers carrying the next due time and the runtime parks on
the subtask MAILBOX — wakeable by checkpoint barriers and by chained
operators' timer deadlines.  That is what makes this the open-loop
source that can share a thread with a count-or-timeout window: the old
source's in-generator sleeps were exactly why the chaining pass forbade
timer-driven members in source chains.

``cycles=None`` makes the source UNBOUNDED: the enumerator re-issues the
data's range splits cycle after cycle until the job is cancelled — the
bench's run-forever open-loop mode.
"""

from __future__ import annotations

import dataclasses
import time
import typing
import zlib

from flink_tensorflow_tpu.sources.api import (
    NotReady,
    SourceReader,
    SourceSplit,
    SplitEnumerator,
    SplitSource,
)
from flink_tensorflow_tpu.sources.replay import range_splits


@dataclasses.dataclass
class PacedSplit(SourceSplit):
    """Records ``[start, stop)`` of cycle ``cycle``, paced per schedule."""

    start: int = 0
    stop: int = 0
    cycle: int = 0


class _PacedEnumerator(SplitEnumerator):
    """Generates each cycle's range splits on demand (an unbounded
    source cannot materialize its split list)."""

    def __init__(self, source: "PacedSplitSource"):
        self._source = source
        self._template = range_splits(len(source.data), source.num_splits)
        self._cycle = 0
        self._index = 0
        self._backlog: typing.List[PacedSplit] = []

    def next_split(self, reader_index: int) -> typing.Optional[PacedSplit]:
        if self._backlog:
            return self._backlog.pop(0)
        cycles = self._source.cycles
        if not self._template or (cycles is not None and self._cycle >= cycles):
            return None
        t = self._template[self._index]
        split = PacedSplit(
            split_id=f"cycle{self._cycle}/{t.split_id}",
            start=t.start, stop=t.stop, cycle=self._cycle,
        )
        self._index += 1
        if self._index >= len(self._template):
            self._index = 0
            self._cycle += 1
        return split

    def add_splits_back(self, splits) -> None:
        self._backlog[:0] = list(splits)

    def snapshot_state(self):
        return {"cycle": self._cycle, "index": self._index,
                "backlog": [s.freeze() for s in self._backlog]}

    def restore_state(self, state) -> None:
        self._cycle = state["cycle"]
        self._index = state["index"]
        self._backlog = [s.freeze() for s in state["backlog"]]


class _PacedReader(SourceReader):
    def __init__(self, source: "PacedSplitSource"):
        self._source = source

    def _offsets(self, split: PacedSplit):
        import numpy as np

        src = self._source
        n = split.stop - split.start
        if src.jitter == "poisson":
            # Deterministic per split (replay resumes the same schedule
            # shape), independent across splits and cycles.
            seed = zlib.crc32(f"{src.seed}/{split.split_id}".encode())
            rng = np.random.RandomState(seed)
            gaps = rng.exponential(1.0 / src.rate_hz, size=n)
        else:
            gaps = np.full(n, 1.0 / src.rate_hz)
        return np.cumsum(gaps)

    def read(self, split: PacedSplit) -> typing.Iterator[typing.Any]:
        src = self._source
        offsets = self._offsets(split)
        # Restore-rebase (PacedSource.seek's contract): already-emitted
        # records must not re-run their inter-arrival waits — the first
        # remaining record is due one gap after (re)assignment.
        base = float(offsets[split.offset - 1]) if split.offset else 0.0
        t0 = time.monotonic()
        for j in range(split.offset, split.stop - split.start):
            due = t0 + src.start_delay_s + float(offsets[j]) - base
            while time.monotonic() < due:
                yield NotReady(due)
            value = src.data[split.start + j]
            if hasattr(value, "with_meta"):
                value = value.with_meta(**{src.ts_key: due})
            yield value


class PacedSplitSource(SplitSource):
    def __init__(self, data: typing.Sequence[typing.Any], rate_hz: float, *,
                 jitter: str = "poisson", seed: int = 0,
                 num_splits: int = 8, cycles: typing.Optional[int] = 1,
                 ts_key: str = "sched_ts", start_delay_s: float = 0.0,
                 schema=None):
        if rate_hz <= 0:
            raise ValueError("rate_hz must be > 0")
        if jitter not in ("poisson", "none"):
            raise ValueError(f"unknown jitter {jitter!r}")
        if num_splits <= 0:
            raise ValueError(f"num_splits must be positive, got {num_splits}")
        if cycles is not None and cycles <= 0:
            raise ValueError(f"cycles must be positive or None, got {cycles}")
        self.data = data
        #: Per-READER offered rate: aggregate = rate_hz x however many
        #: readers hold splits concurrently (splits pace independently).
        self.rate_hz = rate_hz
        self.jitter = jitter
        self.seed = seed
        self.num_splits = num_splits
        self.cycles = cycles
        self.ts_key = ts_key
        self.start_delay_s = start_delay_s
        self.schema = schema
        self.bounded = cycles is not None

    def create_enumerator(self) -> SplitEnumerator:
        return _PacedEnumerator(self)

    def create_reader(self, ctx) -> SourceReader:
        return _PacedReader(self)

    def plan_split_count(self) -> typing.Optional[int]:
        if self.cycles is None:
            return None
        per_cycle = max(1, min(self.num_splits, len(self.data))) if len(self.data) else 0
        return per_cycle * self.cycles
