"""Record codec + remote record plane: cross-process stream channels
(the Netty-shuffle counterpart, SURVEY.md §2 distributed backend)."""

import threading

import numpy as np

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.io.remote import RemoteSink, RemoteSource
from flink_tensorflow_tpu.tensors import TensorValue
from flink_tensorflow_tpu.tensors.serde import decode_record, encode_record


class TestSerde:
    def test_roundtrip(self):
        rec = TensorValue(
            {"image": np.arange(12, dtype=np.float32).reshape(3, 4),
             "label": np.int32(7)},
            {"id": 42, "tag": "x"},
        )
        out = decode_record(encode_record(rec))
        assert out == rec and out.meta == {"id": 42, "tag": "x"}

    def test_decode_is_zero_copy(self):
        rec = TensorValue({"x": np.arange(1000, dtype=np.float32)})
        data = encode_record(rec)
        out = decode_record(data)
        assert out["x"].base is not None  # view over the wire buffer

    def test_bad_magic(self):
        import pytest

        with pytest.raises(ValueError):
            decode_record(b"\x00" * 16)

    def test_zero_size_field(self):
        rec = TensorValue({"x": np.zeros((0, 3), np.float32),
                           "y": np.ones((2,), np.float32)})
        out = decode_record(encode_record(rec))
        assert out == rec and out["x"].shape == (0, 3)

    def test_numpy_meta_roundtrip(self):
        rec = TensorValue({"x": np.zeros(2, np.float32)},
                          {"id": np.int64(7), "pair": (1, 2)})
        out = decode_record(encode_record(rec))
        assert out.meta["id"] == 7 and out.meta["pair"] == (1, 2)


class TestRemoteChannel:
    def test_job_to_job_pipe(self):
        """Two jobs in separate 'processes' (threads here): upstream maps
        and ships records over TCP; downstream consumes and sinks."""
        source = RemoteSource(bind="127.0.0.1")

        def upstream():
            env = StreamExecutionEnvironment(parallelism=1)
            records = [
                TensorValue({"x": np.full(4, i, np.float32)}, {"i": i})
                for i in range(50)
            ]
            (
                env.from_collection(records)
                .map(lambda r: r.replace(x=r["x"] * 2))
                .add_sink(RemoteSink("127.0.0.1", source.port))
            )
            env.execute(timeout=60)

        t = threading.Thread(target=upstream)
        t.start()

        env2 = StreamExecutionEnvironment(parallelism=1)
        out = env2.from_source(source).sink_to_list()
        env2.execute(timeout=60)
        t.join()

        assert len(out) == 50
        got = {r.meta["i"]: float(r["x"][0]) for r in out}
        assert got == {i: 2.0 * i for i in range(50)}
