"""Worker process for the cohort trace-stitching tests.

One process of a 2-process cohort running ``source(par 1, process 0)
-> rebalance -> map(par 2, one subtask per process) -> sink(par 1,
process 0)`` with tracing on: the round-robin rebalance edge GUARANTEES
half the records cross the process boundary (keyed edges with few small
integer keys can land entirely in process 0's key-group range), and the
map.1 -> sink.0 edge crosses back — so the exported per-process trace
files hold genuinely cross-process record journeys for
``flink-tpu-trace --cohort`` stitching.
"""

import argparse

from flink_tensorflow_tpu.utils.platform import force_cpu

force_cpu(1)

from flink_tensorflow_tpu import (  # noqa: E402
    DistributedConfig,
    StreamExecutionEnvironment,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--index", type=int, required=True)
    p.add_argument("--ports", required=True)
    p.add_argument("--n", type=int, default=120)
    p.add_argument("--throttle", type=float, default=0.01)
    p.add_argument("--trace", required=True)
    p.add_argument("--telemetry-interval", type=float, default=0.2)
    args = p.parse_args()

    ports = [int(x) for x in args.ports.split(",")]
    peers = tuple(f"127.0.0.1:{pt}" for pt in ports)
    env = StreamExecutionEnvironment(parallelism=1)
    env.configure(source_throttle_s=args.throttle, trace=True,
                  trace_path=args.trace)
    env.set_distributed(DistributedConfig(
        args.index, len(ports), peers, connect_timeout_s=30.0,
        telemetry_interval_s=args.telemetry_interval))
    (
        env.from_collection(list(range(args.n)), parallelism=1)
        .map(lambda x: x + 1, name="work", parallelism=2)
        .sink_to_callable(lambda v: None, name="sink", parallelism=1)
    )
    env.execute("cohort-trace", timeout=180)


if __name__ == "__main__":
    main()
