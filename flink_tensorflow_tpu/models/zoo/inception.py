"""Inception-v3 — the north-star workload's model (BASELINE.json:2,7).

The reference's flagship example labels an image stream with a frozen
Inception-v3 GraphDef pulled into an embedded TF session (SURVEY.md §1 L6,
§3.1).  This is the native flax definition of the same architecture
(Szegedy et al. 2015, "Rethinking the Inception Architecture"): stem ->
3x InceptionA -> ReductionA -> 4x InceptionB -> ReductionB -> 2x InceptionC
-> global pool -> logits.  299x299x3 inputs, 1000 classes, NHWC, bfloat16
compute so every conv tiles onto the MXU.

All the asymmetric 1xN/Nx1 factorized convs are expressed directly; XLA
fuses the BN+relu chains into the conv epilogues, which is the fusion the
reference relies on cuDNN for.
"""

from __future__ import annotations

import functools
import typing

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from flink_tensorflow_tpu.models.base import ModelMethod
from flink_tensorflow_tpu.models.zoo.registry import ModelDef, register_model_def
from flink_tensorflow_tpu.tensors.schema import RecordSchema, spec


class ConvBN(nn.Module):
    """conv -> batchnorm -> relu, the Inception "BasicConv2d" unit."""

    features: int
    kernel: typing.Tuple[int, int]
    strides: typing.Tuple[int, int] = (1, 1)
    padding: typing.Any = "VALID"
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.compute_dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9997,
                         epsilon=1e-3, dtype=self.compute_dtype)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = functools.partial(ConvBN, compute_dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b5 = c(48, (1, 1))(x, train)
        b5 = c(64, (5, 5), padding="SAME")(b5, train)
        b3 = c(64, (1, 1))(x, train)
        b3 = c(96, (3, 3), padding="SAME")(b3, train)
        b3 = c(96, (3, 3), padding="SAME")(b3, train)
        bp = c(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = functools.partial(ConvBN, compute_dtype=self.dtype)
        b3 = c(384, (3, 3), strides=(2, 2))(x, train)
        bd = c(64, (1, 1))(x, train)
        bd = c(96, (3, 3), padding="SAME")(bd, train)
        bd = c(96, (3, 3), strides=(2, 2))(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionB(nn.Module):
    """The 17x17 blocks with factorized 7x7 (1x7 then 7x1) convs."""

    channels_7x7: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = functools.partial(ConvBN, compute_dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b7 = c(c7, (1, 1))(x, train)
        b7 = c(c7, (1, 7), padding="SAME")(b7, train)
        b7 = c(192, (7, 1), padding="SAME")(b7, train)
        bd = c(c7, (1, 1))(x, train)
        bd = c(c7, (7, 1), padding="SAME")(bd, train)
        bd = c(c7, (1, 7), padding="SAME")(bd, train)
        bd = c(c7, (7, 1), padding="SAME")(bd, train)
        bd = c(192, (1, 7), padding="SAME")(bd, train)
        bp = c(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class ReductionB(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = functools.partial(ConvBN, compute_dtype=self.dtype)
        b3 = c(192, (1, 1))(x, train)
        b3 = c(320, (3, 3), strides=(2, 2))(b3, train)
        b7 = c(192, (1, 1))(x, train)
        b7 = c(192, (1, 7), padding="SAME")(b7, train)
        b7 = c(192, (7, 1), padding="SAME")(b7, train)
        b7 = c(192, (3, 3), strides=(2, 2))(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionC(nn.Module):
    """The 8x8 blocks with split 1x3/3x1 branches."""

    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = functools.partial(ConvBN, compute_dtype=self.dtype)
        b1 = c(320, (1, 1))(x, train)
        b3 = c(384, (1, 1))(x, train)
        b3a = c(384, (1, 3), padding="SAME")(b3, train)
        b3b = c(384, (3, 1), padding="SAME")(b3, train)
        bd = c(448, (1, 1))(x, train)
        bd = c(384, (3, 3), padding="SAME")(bd, train)
        bda = c(384, (1, 3), padding="SAME")(bd, train)
        bdb = c(384, (3, 1), padding="SAME")(bd, train)
        bp = c(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b3a, b3b, bda, bdb, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    compute_dtype: jnp.dtype = jnp.bfloat16
    dropout_rate: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = functools.partial(ConvBN, compute_dtype=self.compute_dtype)
        x = x.astype(self.compute_dtype)
        # Stem: 299x299x3 -> 35x35x192
        x = c(32, (3, 3), strides=(2, 2))(x, train)
        x = c(32, (3, 3))(x, train)
        x = c(64, (3, 3), padding="SAME")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(80, (1, 1))(x, train)
        x = c(192, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 35x35
        x = InceptionA(32, self.compute_dtype)(x, train)
        x = InceptionA(64, self.compute_dtype)(x, train)
        x = InceptionA(64, self.compute_dtype)(x, train)
        x = ReductionA(self.compute_dtype)(x, train)
        # 17x17
        x = InceptionB(128, self.compute_dtype)(x, train)
        x = InceptionB(160, self.compute_dtype)(x, train)
        x = InceptionB(160, self.compute_dtype)(x, train)
        x = InceptionB(192, self.compute_dtype)(x, train)
        x = ReductionB(self.compute_dtype)(x, train)
        # 8x8
        x = InceptionC(self.compute_dtype)(x, train)
        x = InceptionC(self.compute_dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        if train and self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=False)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


@register_model_def("inception_v3")
def build(num_classes: int = 1000, image_size: int = 299,
          uint8_input: bool = False) -> ModelDef:
    """``uint8_input=True``: records carry raw uint8 pixels and the model
    normalizes on device (x/127.5 - 1, Inception's canonical transform) —
    4x less host->HBM traffic per batch, and the normalize fuses into the
    first conv.  The reference does the same thing for the same reason:
    its Inception example builds the normalization INTO the TF graph
    (SURVEY.md §2 "Examples": "image normalization graph built
    programmatically")."""
    module = InceptionV3(num_classes=num_classes)
    in_dtype = np.uint8 if uint8_input else np.float32
    schema = RecordSchema({"image": spec((image_size, image_size, 3), in_dtype)})

    def _prep(x):
        if uint8_input:
            from flink_tensorflow_tpu.ops.preprocessing import inception_normalize

            return inception_normalize(x)
        return x

    def serve(variables, inputs):
        logits = module.apply(variables, _prep(inputs["image"]), train=False)
        prob = jax.nn.softmax(logits, axis=-1)
        return {
            "logits": logits,
            "label": jnp.argmax(logits, axis=-1).astype(jnp.int32),
            "score": jnp.max(prob, axis=-1),
        }

    def init_fn(rng):
        return module.init(rng, jnp.zeros((1, image_size, image_size, 3)), train=False)

    def loss_fn(variables, batch, rng):
        import optax

        from flink_tensorflow_tpu.models.zoo._common import weighted_metrics

        logits, new_state = module.apply(
            variables, _prep(batch["image"]), train=True, mutable=["batch_stats"],
            rngs={"dropout": rng},
        )
        labels = batch["label"]
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        hits = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        loss, acc = weighted_metrics(per_ex, hits, batch.get("valid"))
        return loss, (new_state, {"loss": loss, "accuracy": acc})

    methods = {
        "serve": ModelMethod(
            name="serve",
            input_schema=schema,
            output_names=("logits", "label", "score"),
            fn=serve,
            compute_dtype=jnp.bfloat16,
        )
    }
    return ModelDef(
        architecture="inception_v3",
        config={"num_classes": num_classes, "image_size": image_size,
                "uint8_input": uint8_input},
        module=module,
        input_schema=schema,
        methods=methods,
        init_fn=init_fn,
        loss_fn=loss_fn,
    )
