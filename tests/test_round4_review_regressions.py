"""Pins for defects uncovered by the round-4 in-session reviews
(VERDICT r3 #8: pin anything the round's work uncovers).

1. ``_steady_rps`` trailing exclusion: the end-of-input flush burst
   (last pipeline-depth windows completing together) must leave the
   measured span, and a small run (records < 2*batch) must clamp the
   exclusion instead of indexing past the arrivals list.
2. ``_delta_timing``: the shared probe-timing helper widens the K
   spread once when tunnel RTT variance inverts the delta, and reports
   degenerate (never a negative rate) when even the widened spread
   inverts.
3. Stage stamps tile: the per-record stage boundaries stamped by the
   runner must telescope exactly to t0..t_done — the decomposition's
   "nothing unexplained" invariant.
4. The per-sample decomposition must not double-count assemble time
   (lane_wait INCLUDES it; h2d_dispatch is the launch interval proper).
"""

import numpy as np

import bench


class TestSteadyRps:
    def test_trailing_exclusion_shrinks_span(self):
        arrivals = [i * 0.01 for i in range(100)]
        rps, span = bench._steady_rps(arrivals, 100, 10, 1,
                                      trailing_exclude=30)
        assert abs(span - (arrivals[69] - arrivals[0])) < 1e-9
        assert abs(rps - 60 / span) < 1e-6

    def test_small_run_clamps_instead_of_crashing(self):
        """records_n < 2*batch: the caller's max(0, ...) clamp pattern
        must yield a working zero exclusion."""
        arrivals = [i * 0.01 for i in range(100)]
        records_n, batch, depth = 100, 64, 6
        trailing = max(0, min(depth * batch, records_n - 2 * batch))
        assert trailing == 0
        rps, span = bench._steady_rps(arrivals, records_n, batch, 1,
                                      trailing_exclude=trailing)
        assert rps > 0 and span > 0

    def test_too_few_records_raises(self):
        import pytest

        with pytest.raises(ValueError, match="more windows"):
            bench._steady_rps([0.0, 0.1], 2, 1, 1, trailing_exclude=1)


class TestDeltaTiming:
    def test_clean_delta(self):
        import time as _time

        base = [0.0]

        def fake_monotonic():
            return base[0]

        def run(k):
            base[0] += {2: 0.1, 12: 0.6}[k]

        _time.monotonic, saved = fake_monotonic, _time.monotonic
        try:
            per, degenerate, k2 = bench._delta_timing(run, 2, 12)
            assert not degenerate
            assert abs(per - 0.05) < 1e-9
            assert k2 == 12
        finally:
            _time.monotonic = saved

    def test_inverted_delta_widens_then_degenerates(self):
        import time as _time

        base = [0.0]

        def fake_monotonic():
            return base[0]

        # k=2 takes LONGER than any larger k (inverted medians — the
        # tunnel-RTT-variance pathology): widened once, then degenerate.
        def run(k):
            base[0] += 0.5 if k == 2 else 0.1

        _time.monotonic, saved = fake_monotonic, _time.monotonic
        try:
            per, degenerate, k2 = bench._delta_timing(run, 2, 12)
            assert degenerate
            assert k2 == 48  # widened exactly once
        finally:
            _time.monotonic = saved

    def test_widening_can_recover(self):
        import time as _time

        base = [0.0]

        def fake_monotonic():
            return base[0]

        # Inverted at k=12 but recovers at the widened k=48.
        def run(k):
            base[0] += {2: 0.3, 12: 0.25, 48: 2.3}[k]

        _time.monotonic, saved = fake_monotonic, _time.monotonic
        try:
            per, degenerate, k2 = bench._delta_timing(run, 2, 12)
            assert not degenerate and k2 == 48
            assert abs(per - (2.3 - 0.3) / 46) < 1e-9
        finally:
            _time.monotonic = saved


class TestCapToPeak:
    @staticmethod
    def _rewrite(o, rate):
        o["rate"] = round(rate, 1) if rate is not None else None

    def test_valid_probe_untouched(self):
        out = {"achieved_tflops": 80.0, "mfu_pct": 40.6, "rate": 7000.0}
        got = bench._cap_to_peak(dict(out), False, 197.0, 11e9, self._rewrite)
        assert got == out

    def test_above_peak_capped_and_flagged(self):
        out = {"achieved_tflops": 500.0, "mfu_pct": 253.0, "rate": 45000.0}
        got = bench._cap_to_peak(out, False, 197.0, 11e9, self._rewrite)
        assert got["probe_invalid_capped_to_peak"] is True
        assert got["achieved_tflops"] == 197.0 and got["mfu_pct"] == 100.0
        assert abs(got["rate"] - round(197e12 / 11e9, 1)) < 0.2

    def test_degenerate_without_peak_withholds(self):
        out = {"achieved_tflops": 0.0, "mfu_pct": None, "rate": 1.0}
        got = bench._cap_to_peak(out, True, None, 11e9, self._rewrite)
        assert got["probe_invalid_capped_to_peak"] is True
        assert got["rate"] is None and got["achieved_tflops"] is None


class TestStageTiling:
    def test_stage_boundaries_telescope(self):
        """The runner's stamps must tile t0..t_done with no overlap and
        no gap — and lane_wait must CONTAIN assemble (the review found a
        double-count where h2d_dispatch re-added assemble_s)."""
        import jax

        from flink_tensorflow_tpu.functions.runner import CompiledMethodRunner
        from flink_tensorflow_tpu.models import get_model_def
        from flink_tensorflow_tpu.tensors import (
            BucketLadder,
            BucketPolicy,
            TensorValue,
        )

        mdef = get_model_def("lenet", num_classes=10)
        model = mdef.to_model(jax.jit(mdef.init_fn)(jax.random.key(0)))
        r = CompiledMethodRunner(
            model, policy=BucketPolicy(batch=BucketLadder.up_to(4)),
            dispatch_lanes=2)
        r.stamp_stages = True
        r.open(None)
        try:
            r.warmup([1, 2, 4])
            rng = np.random.RandomState(0)
            out = r.run_batch([
                TensorValue({"image": rng.rand(28, 28, 1).astype(np.float32)})
                for _ in range(3)
            ])
            st = out[0].meta["__stages__"]
            # Boundaries are monotone and the intervals tile exactly.
            assert st["t0"] <= st["t_lane_start"] <= st["t_dispatched"]
            assert st["t_dispatched"] <= st["t_fetch_start"] <= st["t_done"]
            total = st["t_done"] - st["t0"]
            tiled = (
                (st["t_lane_start"] - st["t0"])
                + (st["t_dispatched"] - st["t_lane_start"])
                + (st["t_fetch_start"] - st["t_dispatched"])
                + (st["t_done"] - st["t_fetch_start"])
            )
            assert abs(tiled - total) < 1e-9
            # assemble happens INSIDE the lane interval, not after it.
            assert st["assemble_s"] <= st["t_lane_start"] - st["t0"] + 1e-9 \
                or st["assemble_s"] <= st["lane_wait_s"] + 1e-9
            assert st["lane_wait_s"] == st["t_lane_start"] - st["t0"]
        finally:
            r.close()
