"""Pipeline sanitizer tests (PR 5).

Two halves under test:

- ``core/sanitizer_rt``: the debug-mode concurrency sanitizer — the
  three SEEDED-BUG fixtures (lock-order inversion, lost wakeup,
  barrier-alignment violation) must each be *caught*, the waits-for
  deadlock detector must break a real cycle instead of hanging, the
  protocol state machines must accept the healthy runtime, and a full
  sanitized job must report zero violations.
- ``analysis/sanitizer`` + the ``replay-purity`` /
  ``legacy-source-timer-chain`` lint rules: the bytecode purity matrix
  (wall clock, unseeded RNG, global mutation, mutable closure, I/O;
  ERROR on keyed paths, WARN elsewhere) and the PR 4 migration lint.

Plus the two bugs the wiring surfaced: the SourceMailbox shutdown race
(notify is one-shot; close is the sticky, idempotent signal) and the
split-assignment FREEZE DEADLOCK (a split-less reader parked on the
freeze can never reach its count-based trigger position).
"""

import random
import sys
import tempfile
import threading
import time

import pytest

sys.path.insert(0, ".")

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.analysis import Severity, analyze
from flink_tensorflow_tpu.core import elements as el
from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core.channels import ChannelWriter, InputGate
from flink_tensorflow_tpu.core.sanitizer_rt import (
    ConcurrencySanitizer,
    SanitizerError,
    env_enabled,
)
from flink_tensorflow_tpu.sources import ReplaySplitSource
from flink_tensorflow_tpu.sources.coordinator import (
    ASSIGNED,
    WAIT,
    SplitCoordinator,
)
from flink_tensorflow_tpu.sources.mailbox import SourceMailbox


def _kinds(san):
    return [v.kind for v in san.violations]


# ---------------------------------------------------------------------------
# Seeded bug 1/3: lock-order inversion.
# ---------------------------------------------------------------------------


class TestLockOrder:
    def test_seeded_inversion_is_caught(self):
        san = ConcurrencySanitizer("t")
        a, b = san.lock("A"), san.lock("B")

        def ab():
            with a:
                with b:
                    pass

        def ba():  # the seeded bug: opposite order on another thread
            with b:
                with a:
                    pass

        for body in (ab, ba):  # sequential: no actual deadlock, only order
            t = threading.Thread(target=body)
            t.start()
            t.join(5.0)
        assert "lock-order-inversion" in _kinds(san)
        with pytest.raises(SanitizerError):
            san.check()

    def test_consistent_order_is_clean(self):
        san = ConcurrencySanitizer("t")
        a, b = san.lock("A"), san.lock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert san.violations == []

    def test_inversion_reported_once_per_pair(self):
        san = ConcurrencySanitizer("t")
        a, b = san.lock("A"), san.lock("B")
        with a:
            with b:
                pass
        for _ in range(5):
            with b:
                with a:
                    pass
        assert _kinds(san).count("lock-order-inversion") == 1


# ---------------------------------------------------------------------------
# Waits-for deadlock cycle: detected AND escaped, not hung.
# ---------------------------------------------------------------------------


class TestDeadlockCycle:
    def test_real_cycle_raises_instead_of_hanging(self):
        san = ConcurrencySanitizer("t")
        a, b = san.lock("A"), san.lock("B")
        holds_a = threading.Event()
        release_a = threading.Event()

        def t1():
            with a:
                holds_a.set()
                release_a.wait(10.0)
                with b:  # blocks: main holds B
                    pass

        th = threading.Thread(target=t1, daemon=True)
        th.start()
        assert holds_a.wait(5.0)
        b.acquire()
        release_a.set()
        # Wait until t1 is registered as blocked on B.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with san._mu:
                if any(w[0] == "lock" and w[1] == "B"
                       for w in san._waiting.values()):
                    break
            time.sleep(0.01)
        # Closing the cycle (acquire A while holding B, A's owner blocked
        # on B) must raise, not deadlock.
        with pytest.raises(SanitizerError) as err:
            a.acquire()
        assert "waits-for cycle" in str(err.value)
        assert "deadlock-cycle" in _kinds(san)
        b.release()
        th.join(5.0)
        assert not th.is_alive()


# ---------------------------------------------------------------------------
# Seeded bug 2/3: lost wakeup -> stall watchdog + stack/ownership dump.
# ---------------------------------------------------------------------------


class TestLostWakeupWatchdog:
    def test_seeded_lost_wakeup_is_caught_with_dump(self):
        san = ConcurrencySanitizer("t", stall_timeout_s=0.3)
        cond = san.condition("mbox.cond")
        parked = threading.Event()

        def buggy_wait():
            # The seeded bug: a bare check-then-park wait that does NOT
            # consume pending signals — the notify below lands before
            # the park and is lost, so the thread stalls forever.
            with cond:
                parked.set()
                cond.wait()  # untimed: nothing will ever wake it

        with cond:
            cond.notify()  # the wakeup that gets lost
        th = threading.Thread(target=buggy_wait, daemon=True,
                              name="lost-wakeup-victim")
        th.start()
        assert parked.wait(5.0)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and "stall" not in _kinds(san):
            time.sleep(0.05)
        assert "stall" in _kinds(san)
        stall = next(v for v in san.violations if v.kind == "stall")
        assert "mbox.cond" in stall.message
        # The dump carries every thread's stack + the ownership map.
        assert stall.dump and "state dump" in stall.dump
        assert "buggy_wait" in stall.dump
        san.shutdown()
        with cond:
            cond.notify_all()
        th.join(5.0)
        assert not th.is_alive()

    def test_timed_waits_never_stall_flag(self):
        san = ConcurrencySanitizer("t", stall_timeout_s=0.1)
        cond = san.condition("c")
        with cond:
            cond.wait(0.4)  # timed: wakes itself, not a stall
        time.sleep(0.3)
        san.shutdown()
        assert san.violations == []


# ---------------------------------------------------------------------------
# Seeded bug 3/3: barrier-alignment violation.
# ---------------------------------------------------------------------------


class _AlignmentBlindGate(InputGate):
    """Seeded bug: ignores channel blocking — an element from a channel
    blocked for alignment is delivered instead of stashed, overtaking
    the checkpoint cut."""

    def poll(self, timeout=None):
        with self._not_empty:
            if not self._queue:
                return None
            idx, element = self._queue.popleft()
        if self._san is not None:
            self._san.gate_delivered(self._san_name, idx)
        return idx, element


class TestBarrierAlignmentMachine:
    def test_seeded_blocked_channel_delivery_is_caught(self):
        san = ConcurrencySanitizer("t")
        gate = _AlignmentBlindGate(2, sanitizer=san, name="g")
        gate.block_channel(0)  # barrier from channel 0 seen: aligned
        ChannelWriter(gate, 0).write(el.StreamRecord(1, None))
        item = gate.poll(timeout=0.5)  # the bug delivers it anyway
        assert item is not None
        assert "barrier-blocked-channel" in _kinds(san)

    def test_healthy_gate_stashes_and_stays_clean(self):
        san = ConcurrencySanitizer("t")
        gate = InputGate(2, sanitizer=san, name="g")
        gate.block_channel(0)
        ChannelWriter(gate, 0).write(el.StreamRecord(1, None))
        ChannelWriter(gate, 1).write(el.StreamRecord(2, None))
        idx, e = gate.poll(timeout=1.0)
        assert (idx, e.value) == (1, 2)  # only the unblocked channel
        gate.unblock_all()
        idx, e = gate.poll(timeout=1.0)
        assert (idx, e.value) == (0, 1)  # stashed element replays after
        assert san.violations == []

    def test_snapshot_order_machine(self):
        san = ConcurrencySanitizer("t")
        # Healthy: head-to-tail, no gaps — for two interleaved ids.
        for pos in range(3):
            san.chain_snapshot("op.0", 1, pos, 3)
            san.chain_snapshot("op.0", 2, pos, 3)
        assert san.violations == []
        # Seeded: the chain snapshots position 2 before position 1.
        san.chain_snapshot("op.0", 3, 0, 3)
        san.chain_snapshot("op.0", 3, 2, 3)
        assert "snapshot-order" in _kinds(san)


# ---------------------------------------------------------------------------
# Assignment-freeze invariant (split coordinator).
# ---------------------------------------------------------------------------


class _FreezeBlindCoordinator(SplitCoordinator):
    """Seeded bug: dispenses splits without honoring the alignment
    freeze — the enumerator-pool snapshot loses consistency."""

    def poll_split(self, reader_index):
        with self._lock:
            return self._dispense_locked(reader_index)


class TestAssignmentFreeze:
    def test_seeded_frozen_dispense_is_caught(self):
        san = ConcurrencySanitizer("t")
        src = ReplaySplitSource(list(range(20)), num_splits=4)
        coord = _FreezeBlindCoordinator(src, 2, sanitizer=san, name="replay")
        coord.on_barrier(1, 0)  # freeze: reader 1 has not passed yet
        status, split = coord.poll_split(1)
        assert status == ASSIGNED and split is not None  # the bug
        assert "assignment-freeze" in _kinds(san)

    def test_healthy_coordinator_waits_and_stays_clean(self):
        san = ConcurrencySanitizer("t")
        src = ReplaySplitSource(list(range(20)), num_splits=4)
        coord = SplitCoordinator(src, 2, sanitizer=san, name="replay")
        coord.on_barrier(1, 0)
        assert coord.poll_split(1) == (WAIT, None)
        coord.on_barrier(1, 1)  # alignment completes, freeze lifts
        status, _ = coord.poll_split(1)
        assert status == ASSIGNED
        assert san.violations == []

    def test_pending_alignments_lists_unpassed_readers_only(self):
        src = ReplaySplitSource(list(range(20)), num_splits=4)
        coord = SplitCoordinator(src, 3)
        coord.on_barrier(7, 0)
        assert coord.pending_alignments(0) == []
        assert coord.pending_alignments(1) == [7]
        coord.on_barrier(7, 1)
        assert coord.pending_alignments(1) == []
        assert coord.pending_alignments(2) == [7]


# ---------------------------------------------------------------------------
# SourceMailbox shutdown: sticky close, idempotent notify/close.
# ---------------------------------------------------------------------------


class TestMailboxShutdown:
    def test_notify_then_wait_consumes_signal(self):
        m = SourceMailbox()
        m.notify()
        assert m.wait(0.0) is True
        assert m.wait(0.01) is False

    def test_close_is_sticky_and_idempotent(self):
        m = SourceMailbox()
        m.close()
        m.close()  # idempotent
        assert m.closed
        for _ in range(3):  # every future wait returns immediately
            assert m.wait(None) is True
        m.notify()  # no-op after close, must not raise or re-arm
        assert m.wait(None) is True

    def test_close_releases_concurrent_untimed_waiter(self):
        m = SourceMailbox()
        released = threading.Event()

        def waiter():
            if m.wait(None):
                released.set()

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        time.sleep(0.05)  # let it park
        m.close()
        assert released.wait(2.0), "close() must wake a parked waiter"
        th.join(2.0)

    def test_close_notify_race_cannot_strand_waiter(self):
        # The shutdown race close() exists for: signal, then a consumer
        # that drains the signal BEFORE parking again must still observe
        # shutdown on its next wait — stickiness, not a counted token.
        m = SourceMailbox()
        m.notify()
        assert m.wait(0.0) is True  # drains the one-shot signal
        m.close()
        assert m.wait(None) is True  # would hang forever with notify()

    def test_sanitized_mailbox_roundtrip(self):
        san = ConcurrencySanitizer("t")
        m = SourceMailbox(sanitizer=san, name="src.0.mailbox")
        m.notify()
        assert m.wait(0.0) is True
        m.close()
        assert m.wait(None) is True
        assert san.violations == []


# ---------------------------------------------------------------------------
# Freeze-deadlock regression: split source + count-based checkpoints +
# parallelism > 1 (found by the sanitizer wiring; pre-PR5 this hangs).
# ---------------------------------------------------------------------------


class TestFreezeDeadlockRegression:
    @pytest.mark.parametrize("sanitize", [False, True])
    def test_parallel_split_source_with_count_checkpoints_completes(
            self, sanitize, tmp_path):
        env = StreamExecutionEnvironment(parallelism=2)
        env.configure(sanitize=sanitize)
        env.enable_checkpointing(str(tmp_path), every_n_records=16)
        src = ReplaySplitSource(list(range(200)), num_splits=8)
        out = (env.from_source(src, name="replay", parallelism=2)
               .map(lambda v: v, name="ident", parallelism=2)
               .sink_to_list())
        env.execute("freeze-deadlock-regression", timeout=120)
        assert sorted(out) == list(range(200))
        if sanitize:
            snap = env.metric_registry.report()
            assert snap.get("sanitizer.violations") == 0


# ---------------------------------------------------------------------------
# Whole-job sanitize mode: clean pipelines report zero violations.
# ---------------------------------------------------------------------------


class TestSanitizedJob:
    def test_chained_rebalance_checkpoint_job_is_clean(self):
        with tempfile.TemporaryDirectory() as d:
            env = StreamExecutionEnvironment(parallelism=2)
            env.configure(sanitize=True)
            env.enable_checkpointing(d, every_n_records=8)
            out = (env.from_collection(list(range(64)), parallelism=1)
                   .map(lambda v: v + 1, name="inc", parallelism=1)
                   .rebalance()
                   .map(lambda v: v * 2, name="dbl", parallelism=2)
                   .sink_to_list())
            env.execute("sanitized-job", timeout=120)
            assert sorted(out) == sorted((v + 1) * 2 for v in range(64))
            snap = env.metric_registry.report()
            assert snap.get("sanitizer.violations") == 0
            assert snap.get("sanitizer.tracked_ops", 0) > 0

    def test_env_var_enables_sanitizer(self, monkeypatch):
        monkeypatch.setenv("FLINK_TPU_SANITIZE", "1")
        assert env_enabled()
        from flink_tensorflow_tpu.core.runtime import LocalExecutor

        env = StreamExecutionEnvironment(parallelism=1)
        env.from_collection([1, 2, 3]).sink_to_list()
        ex = LocalExecutor(env.graph)
        assert ex.sanitizer is not None

    def test_off_by_default_no_instrumentation(self, monkeypatch):
        monkeypatch.delenv("FLINK_TPU_SANITIZE", raising=False)
        from flink_tensorflow_tpu.core.runtime import LocalExecutor

        env = StreamExecutionEnvironment(parallelism=1)
        env.from_collection([1, 2, 3]).map(lambda v: v).sink_to_list()
        ex = LocalExecutor(env.graph)
        assert ex.sanitizer is None
        for gate in ex._gates:
            assert gate._san is None
            assert isinstance(gate._lock, type(threading.Lock()))


# ---------------------------------------------------------------------------
# Replay-purity lint matrix.
# ---------------------------------------------------------------------------


class _ImpureKeyedFn(fn.ProcessFunction):
    def process_element(self, value, ctx, out):
        out.collect((value, time.time(), random.random()))


class _IOKeyedFn(fn.ProcessFunction):
    def process_element(self, value, ctx, out):
        with open("/tmp/never-written", "a") as f:  # noqa: F841
            pass
        out.collect(value)


_SCAN_GLOBAL = 0


class _GlobalMutFn(fn.MapFunction):
    def map(self, value):
        global _SCAN_GLOBAL
        _SCAN_GLOBAL += 1
        return value


def _purity_diags(env):
    return [d for d in analyze(env.graph, config=env.config)
            if d.rule == "replay-purity"]


class TestReplayPurityLint:
    def test_keyed_impurity_is_error(self):
        env = StreamExecutionEnvironment()
        (env.from_collection([1, 2, 3])
            .key_by(lambda v: v)
            .process(_ImpureKeyedFn(), name="keyed_impure"))
        diags = _purity_diags(env)
        errors = [d for d in diags if d.severity == Severity.ERROR]
        assert errors, diags
        assert all(d.node == "keyed_impure" for d in errors)
        symbols = " | ".join(d.message for d in errors)
        assert "time.time" in symbols and "random.random" in symbols

    def test_keyed_io_is_error(self):
        env = StreamExecutionEnvironment()
        (env.from_collection([1, 2, 3])
            .key_by(lambda v: v)
            .process(_IOKeyedFn(), name="keyed_io"))
        errors = [d for d in _purity_diags(env)
                  if d.severity == Severity.ERROR]
        assert errors and "open" in errors[0].message

    def test_nonkeyed_impurity_is_warn_not_error(self):
        env = StreamExecutionEnvironment()
        env.from_collection([1, 2, 3]).map(
            lambda v: (v, time.time()), name="wallclock_map")
        diags = _purity_diags(env)
        assert diags and all(d.severity == Severity.WARN for d in diags)
        assert diags[0].node == "wallclock_map"

    def test_global_mutation_flagged(self):
        env = StreamExecutionEnvironment()
        env.from_collection([1, 2, 3]).map(_GlobalMutFn(), name="gmut")
        diags = _purity_diags(env)
        assert any("global _SCAN_GLOBAL" in d.message for d in diags)

    def test_mutable_closure_capture_flagged(self):
        env = StreamExecutionEnvironment()
        acc = []
        env.from_collection([1, 2, 3]).map(
            lambda v: acc.append(v) or v, name="closure_map")
        diags = _purity_diags(env)
        assert any("closure 'acc'" in d.message for d in diags)
        assert all(d.severity == Severity.WARN for d in diags)

    def test_pure_pipeline_is_clean(self):
        env = StreamExecutionEnvironment()
        (env.from_collection([1, 2, 3])
            .map(lambda v: v * 2, name="pure")
            .filter(lambda v: v > 2, name="flt"))
        assert _purity_diags(env) == []

    def test_seeded_rng_in_user_code_is_clean(self):
        import numpy as np

        env = StreamExecutionEnvironment()

        def seeded(v):
            rng = np.random.RandomState(0)
            return v + float(rng.rand())

        env.from_collection([1, 2, 3]).map(seeded, name="seeded")
        assert _purity_diags(env) == []


# ---------------------------------------------------------------------------
# Satellite lint: legacy-source chain cut before a timer-driven member.
# ---------------------------------------------------------------------------


class _SumWindow(fn.WindowFunction):
    def process_window(self, key, window, elements, out):
        out.collect(sum(elements))


class TestLegacySourceTimerChainLint:
    def _diags(self, env):
        return [d for d in analyze(env.graph, config=env.config)
                if d.rule == "legacy-source-timer-chain"]

    def test_legacy_source_before_timer_op_warns(self):
        env = StreamExecutionEnvironment(parallelism=1)
        (env.from_collection(list(range(32)), parallelism=1)
            .map(lambda x: x, name="pre", parallelism=1)
            .count_window(4, timeout_s=1.0)
            .apply(_SumWindow(), name="timed", parallelism=1))
        diags = self._diags(env)
        assert len(diags) == 1
        assert diags[0].severity == Severity.WARN
        assert "SplitSource" in diags[0].message
        assert diags[0].edge == "pre -> timed"

    def test_split_source_head_stays_quiet(self):
        env = StreamExecutionEnvironment(parallelism=1)
        src = ReplaySplitSource(list(range(32)), num_splits=4)
        (env.from_source(src, name="split", parallelism=1)
            .count_window(4, timeout_s=1.0)
            .apply(_SumWindow(), name="timed", parallelism=1))
        assert self._diags(env) == []

    def test_pure_count_window_stays_quiet(self):
        env = StreamExecutionEnvironment(parallelism=1)
        (env.from_collection(list(range(32)), parallelism=1)
            .count_window(4)
            .apply(_SumWindow(), name="counted", parallelism=1))
        assert self._diags(env) == []
