"""Zoo registry — names architectures so loaders can reconstruct them.

A saved bundle stores ``{"architecture": "lenet", "config": {...}}``; the
loader looks the name up here and rebuilds the flax module, then attaches
restored params (models/loaders.py).  This is the TPU-native stand-in for
the reference's GraphDef self-description: our "graph" is code, so bundles
carry a pointer to it instead of protobuf ops.
"""

from __future__ import annotations

import dataclasses
import typing

from flink_tensorflow_tpu.models.base import Model, ModelMethod
from flink_tensorflow_tpu.tensors.schema import RecordSchema


@dataclasses.dataclass(frozen=True)
class ModelDef:
    """An instantiable architecture: flax module + typed methods + loss."""

    architecture: str
    config: typing.Dict[str, typing.Any]
    module: typing.Any  # flax nn.Module
    input_schema: RecordSchema
    methods: typing.Mapping[str, ModelMethod]
    #: rng -> variables pytree (flax ``{"params": ..., "batch_stats": ...}``)
    init_fn: typing.Callable[[typing.Any], typing.Any]
    #: ``loss_fn(variables, batch, rng) -> (loss, (new_model_state, metrics))``
    #: for trainable defs; None for inference-only use.
    loss_fn: typing.Optional[typing.Callable] = None

    def init_params(self, rng) -> typing.Any:
        return self.init_fn(rng)

    def to_model(self, params, name: typing.Optional[str] = None) -> Model:
        return Model(
            name or self.architecture,
            params,
            self.methods,
            metadata={"architecture": self.architecture, "config": dict(self.config)},
        )


_BUILDERS: typing.Dict[str, typing.Callable[..., ModelDef]] = {}


def register_model_def(name: str):
    def deco(builder):
        _BUILDERS[name] = builder
        return builder

    return deco


_ZOO_MODULES = ("lenet", "inception", "resnet", "bilstm", "widedeep",
                "chartransformer")


def get_model_def(architecture: str, **config) -> ModelDef:
    # Import zoo modules lazily so registry import stays cheap.
    import importlib

    if architecture not in _BUILDERS:
        for mod in _ZOO_MODULES:
            importlib.import_module(f"flink_tensorflow_tpu.models.zoo.{mod}")
    try:
        builder = _BUILDERS[architecture]
    except KeyError:
        raise KeyError(
            f"unknown architecture {architecture!r}; registered: {sorted(_BUILDERS)}"
        ) from None
    return builder(**config)
