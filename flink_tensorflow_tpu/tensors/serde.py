"""Binary record codec — the TypeInformation/serializer counterpart.

The reference registers tensors with Flink's serializer stack so records
survive network shuffles and checkpoints (SURVEY.md §2 "Tensor
TypeInformation/serializer").  In-process hops here pass records by
reference (no serialization at all — threads share the arena/heap); this
codec exists for the boundaries where bytes are unavoidable: the remote
record plane between hosts (io/remote.py) and compact persisted streams.

Wire format (little-endian):
  u32 magic 'FTTR' | u32 header_len | u32 meta_len | header (json)
  | meta (pickle) | field buffers
header = {"fields": [[name, shape, dtype], ...]}
Meta is pickled (it is "arbitrary picklable metadata" per TensorValue's
contract — numpy scalars, tuples, non-str keys all round-trip; the
record plane is an intra-cluster trust boundary, same stance as Flink's
Kryo).  Buffers follow in header order, tightly packed — decode is
zero-copy (``np.frombuffer`` views over the received bytes).

**Wire narrowing** (opt-in): ``encode_record(..., wire_dtype=...)``
ships floating-point field buffers in a compact on-the-wire dtype —
``"bf16"``/``"f16"`` halve the bytes of every f32 field, ``"int8"``
quarters them with a per-field absmax scale — and ``decode_record``
restores the original dtype, so the narrowing is invisible to everything
downstream of the frame.  Narrowed field entries extend the header row
to ``[name, shape, dtype, wire, scale]`` (``scale`` is None except for
int8); un-narrowed fields keep the 3-element row, so ``"f32"``/None
produces byte-identical frames to the pre-narrowing codec.  Integer,
bool, and already-narrow fields pass through unchanged.  Accuracy
caveat: bf16 keeps f32's range at ~3 decimal digits of mantissa, f16
keeps ~3.3 digits but saturates beyond ±65504, int8 is a uniform
absmax quantization (worst-case error = absmax/254 per field) — use it
only for activations/scores that tolerate it, never for ids.
"""

from __future__ import annotations

import json
import pickle
import struct
import typing

import numpy as np

from flink_tensorflow_tpu.tensors.value import TensorValue

MAGIC = 0x52545446  # 'FTTR'
_HEADER = struct.Struct("<III")

#: Accepted ``wire_dtype`` names.  ``"f32"`` and None both mean "ship
#: buffers verbatim" (the identity codec).
WIRE_DTYPES = ("f32", "bf16", "f16", "int8")


def _wire_np_dtype(wire: str) -> np.dtype:
    """The numpy dtype a narrowed buffer is laid out as on the wire."""
    if wire == "bf16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if wire == "f16":
        return np.dtype(np.float16)
    if wire == "int8":
        return np.dtype(np.int8)
    raise ValueError(f"unknown wire dtype {wire!r} (expected one of {WIRE_DTYPES})")


def normalize_wire_dtype(wire: typing.Optional[str]) -> typing.Optional[str]:
    """Validate + canonicalize a wire-dtype name; ``"f32"`` -> None."""
    if wire is None or wire == "f32":
        return None
    if wire not in WIRE_DTYPES:
        raise ValueError(
            f"unknown wire dtype {wire!r} (expected one of {WIRE_DTYPES})")
    return wire


def _narrowable(dtype: np.dtype) -> bool:
    """Only full-width floats narrow; ints/bools/f16 ship verbatim."""
    return dtype.kind == "f" and dtype.itemsize >= 4


def wire_bytes_saved(record: TensorValue, wire: typing.Optional[str]) -> int:
    """Field-buffer bytes a narrowed frame saves vs. the identity codec
    (header/meta overhead excluded — it is identical modulo the few
    bytes of wire tags)."""
    wire = normalize_wire_dtype(wire)
    if wire is None:
        return 0
    itemsize = _wire_np_dtype(wire).itemsize
    saved = 0
    for arr in record.fields.values():
        a = np.asarray(arr)
        if _narrowable(a.dtype):
            saved += a.size * (a.dtype.itemsize - itemsize)
    return saved


def _narrow(a: np.ndarray, wire: str):
    """``(buffer_bytes, scale)`` of one field narrowed to ``wire``."""
    if wire == "int8":
        absmax = float(np.max(np.abs(a))) if a.size else 0.0
        scale = absmax / 127.0 if absmax > 0.0 else 1.0
        q = np.clip(np.rint(a.astype(np.float64) / scale), -127, 127)
        return q.astype(np.int8).tobytes(), scale
    return a.astype(_wire_np_dtype(wire)).tobytes(), None


def encode_record(record: TensorValue,
                  wire_dtype: typing.Optional[str] = None) -> bytes:
    wire = normalize_wire_dtype(wire_dtype)
    fields = []
    buffers = []
    for name, arr in record.fields.items():
        a = np.asarray(arr)
        if a.dtype.hasobject:
            # tobytes() on an object array emits raw PyObject POINTERS —
            # the frame decodes (or crashes) on the peer with garbage.
            # Fail at the sender, where the offending field is visible.
            raise TypeError(
                f"field {name!r} has object dtype {a.dtype} — record fields "
                "must be numeric/bytes tensors (put Python objects in meta)"
            )
        # NB: ascontiguousarray would promote 0-d to 1-d; keep the true
        # shape and let tobytes() handle contiguity.
        if wire is not None and _narrowable(a.dtype):
            buf, scale = _narrow(a, wire)
            fields.append([name, list(a.shape), a.dtype.str, wire, scale])
            buffers.append(buf)
        else:
            fields.append([name, list(a.shape), a.dtype.str])
            buffers.append(a.tobytes())
    header = json.dumps({"fields": fields}).encode()
    meta = pickle.dumps(dict(record.meta), protocol=pickle.HIGHEST_PROTOCOL)
    return b"".join(
        [_HEADER.pack(MAGIC, len(header), len(meta)), header, meta, *buffers]
    )


def decode_record(data: typing.Union[bytes, memoryview]) -> TensorValue:
    view = memoryview(data)
    magic, header_len, meta_len = _HEADER.unpack_from(view, 0)
    if magic != MAGIC:
        raise ValueError(f"bad record magic {magic:#x}")
    off = _HEADER.size
    header = json.loads(bytes(view[off:off + header_len]))
    off += header_len
    meta = pickle.loads(view[off:off + meta_len])
    off += meta_len
    out = {}
    for entry in header["fields"]:
        name, shape, dtype_str = entry[0], entry[1], entry[2]
        dtype = np.dtype(dtype_str)
        count = int(np.prod(shape)) if shape else 1  # prod(()) is 1 anyway
        if len(entry) > 3:
            # Narrowed field: the buffer is laid out in the wire dtype;
            # restore the declared dtype here so the narrowing never
            # leaks past the codec (the restore allocates — zero-copy is
            # a property of the identity path only).
            wire, scale = entry[3], entry[4]
            wdt = _wire_np_dtype(wire)
            raw = np.frombuffer(view, dtype=wdt, count=count, offset=off)
            if wire == "int8":
                arr = (raw.astype(dtype) * dtype.type(scale)).reshape(shape)
            else:
                arr = raw.astype(dtype).reshape(shape)
            # Freshly allocated by astype — freeze in place so the
            # TensorValue constructor aliases instead of re-copying.
            arr.setflags(write=False)
            off += count * wdt.itemsize
        else:
            arr = np.frombuffer(view, dtype=dtype, count=count,
                                offset=off).reshape(shape)
            off += count * dtype.itemsize
        out[name] = arr
    return TensorValue(out, meta)
