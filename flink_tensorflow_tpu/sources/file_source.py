"""FileSplitSource — split-based record-file source over io/files.py.

The split-based successor of ``RecordFileSource``: instead of a frozen
stride (subtask i decodes records ``i, i+N, ...`` of the concatenation),
each FILE — or, with ``records_per_split``, each record RANGE within a
file — is one :class:`FileSplit` that any reader can pull.  Skewed file
sizes stop mattering: the reader stuck on the big file keeps reading it
while its peers drain the small ones (the bench's work-stealing
demonstration, ``bench.py --workload filesplit``).

Replay skips cheaply: frames are length-prefixed, so seeking to
``start + offset`` walks headers without decoding payloads (the same
trick RecordFileSource uses for strides).
"""

from __future__ import annotations

import dataclasses
import typing

from flink_tensorflow_tpu.io.files import iter_record_frames
from flink_tensorflow_tpu.sources.api import (
    ListSplitEnumerator,
    SourceReader,
    SourceSplit,
    SplitEnumerator,
    SplitSource,
)
from flink_tensorflow_tpu.tensors.serde import decode_record


@dataclasses.dataclass
class FileSplit(SourceSplit):
    """A record range of one frame file: ``[start, stop)`` record
    indices within the file (``stop=None`` = through end of file)."""

    path: str = ""
    start: int = 0
    stop: typing.Optional[int] = None


class _FileSplitReader(SourceReader):
    def read(self, split: FileSplit) -> typing.Iterator[typing.Any]:
        first = split.start + split.offset
        for i, payload in enumerate(iter_record_frames(split.path)):
            if split.stop is not None and i >= split.stop:
                return
            if i >= first:
                yield decode_record(payload)


class FileSplitSource(SplitSource):
    """Bounded split source over one or more frame files.

    ``records_per_split=None`` (default): one split per file.  With a
    value, each file is chunked into ranges of at most that many records
    (the chunking scan walks frame headers only — no payload decode) so
    a single huge file still parallelizes.
    """

    #: THE write-ahead-log ingest path the exactly-once boundary lint
    #: prescribes: durable frame files, split offsets in snapshots.
    wal_fronted = True

    def __init__(self, paths: typing.Union[str, typing.Sequence[str]], *,
                 records_per_split: typing.Optional[int] = None,
                 schema=None):
        if records_per_split is not None and records_per_split <= 0:
            raise ValueError(
                f"records_per_split must be positive, got {records_per_split}")
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        self.records_per_split = records_per_split
        self.schema = schema

    def create_enumerator(self) -> SplitEnumerator:
        splits: typing.List[FileSplit] = []
        if self.records_per_split is None:
            for path in self.paths:
                splits.append(FileSplit(split_id=path, path=path))
        else:
            per = self.records_per_split
            for path in self.paths:
                count = sum(1 for _ in iter_record_frames(path))
                for start in range(0, count, per):
                    stop = min(start + per, count)
                    splits.append(FileSplit(
                        split_id=f"{path}[{start}:{stop}]",
                        path=path, start=start, stop=stop,
                    ))
        return ListSplitEnumerator(splits)

    def create_reader(self, ctx) -> SourceReader:
        return _FileSplitReader()

    def plan_split_count(self) -> typing.Optional[int]:
        # Chunked counts need a file scan — not a plan-time cost; the
        # per-file mode is exact for free.
        return len(self.paths) if self.records_per_split is None else None
