"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's MiniCluster strategy (SURVEY.md §4): Flink projects
test "multi-node" in one JVM; we test multi-chip sharding on virtual CPU
devices.  Env vars must be set before jax initializes its backends, hence
at conftest import time.
"""

# Force CPU even when the environment preselects a TPU platform (e.g.
# JAX_PLATFORMS=axon tunneling to one real chip): tests need the virtual
# 8-device mesh, and must not monopolize/depend on bench hardware.  The
# env var alone is not enough — the axon PJRT plugin re-registers itself
# as default — so pin the platform via jax.config too.
from flink_tensorflow_tpu.utils.platform import force_cpu

# force_cpu REPLACES any preset device-count flag (a stray
# XLA_FLAGS=--xla_force_host_platform_device_count=4 in the environment
# would otherwise silently shrink the suite's required 8-device mesh and
# fail tests confusingly) and pins jax.config past the axon plugin.
force_cpu(8)

import pytest  # noqa: E402


@pytest.fixture
def env():
    from flink_tensorflow_tpu import StreamExecutionEnvironment

    return StreamExecutionEnvironment(parallelism=2)
