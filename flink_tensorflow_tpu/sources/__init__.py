"""Split-based sources (FLIP-27-style): SplitEnumerator + SourceReader
+ pull-based dynamic split assignment + a wakeable mailbox source loop.

See sources/api.py for the protocol contract and README "Split-based
sources" for the migration story from ``SourceFunction``.
"""

from flink_tensorflow_tpu.sources.api import (
    ListSplitEnumerator,
    NotReady,
    SourceReader,
    SourceSplit,
    SplitEnumerator,
    SplitSource,
)
from flink_tensorflow_tpu.sources.coordinator import SplitCoordinator
from flink_tensorflow_tpu.sources.file_source import FileSplit, FileSplitSource
from flink_tensorflow_tpu.sources.mailbox import SourceMailbox
from flink_tensorflow_tpu.sources.operator import SplitSourceOperator
from flink_tensorflow_tpu.sources.paced import PacedSplit, PacedSplitSource
from flink_tensorflow_tpu.sources.replay import (
    RangeSplit,
    ReplaySplitSource,
    range_splits,
)

__all__ = [
    "FileSplit",
    "FileSplitSource",
    "ListSplitEnumerator",
    "NotReady",
    "PacedSplit",
    "PacedSplitSource",
    "RangeSplit",
    "ReplaySplitSource",
    "SourceMailbox",
    "SourceReader",
    "SourceSplit",
    "SplitCoordinator",
    "SplitEnumerator",
    "SplitSource",
    "SplitSourceOperator",
    "range_splits",
]
