"""Pallas kernel tests (interpreter mode on CPU — same code path that
compiles on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from flink_tensorflow_tpu.ops import flash_attention
from flink_tensorflow_tpu.parallel import full_attention


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        rng = np.random.RandomState(0)
        b, t, h, d = 2, 64, 2, 16
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal)
        got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal, block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_odd_block_sizes_shrink(self):
        rng = np.random.RandomState(1)
        b, t, h, d = 1, 24, 1, 8  # 24 not divisible by 128 -> gcd blocks
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_bfloat16_inputs(self):
        rng = np.random.RandomState(2)
        b, t, h, d = 1, 32, 2, 16
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16) for _ in range(3))
        want = full_attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32), atol=3e-2)

    def test_lse_residual_recombines_split_kv(self):
        """The returned log-sum-exp must be exactly the residual needed to
        fold two half-K/V flash calls into full attention — the contract
        the seq-axis ring relies on."""
        from flink_tensorflow_tpu.parallel.ring_attention import _combine_blocks

        rng = np.random.RandomState(3)
        b, t, h, d = 2, 32, 2, 8
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

        o1, lse1 = flash_attention(jnp.asarray(q), jnp.asarray(k[:, :16]),
                                   jnp.asarray(v[:, :16]), return_lse=True)
        o2, lse2 = flash_attention(jnp.asarray(q), jnp.asarray(k[:, 16:]),
                                   jnp.asarray(v[:, 16:]), return_lse=True)
        assert lse1.shape == (b, h, t)
        got, _ = _combine_blocks(o1, lse1, o2, lse2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_tpu_matches_interpret(self):
        """Compiled-TPU vs interpret-mode equivalence (VERDICT r1 #7).
        Skips unless a real TPU is attached (the conftest pins tests to
        the virtual CPU mesh; the driver's bench path exercises this)."""
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("needs a real TPU; interpret-only backend here")
        rng = np.random.RandomState(5)
        b, t, h, d = 2, 256, 4, 64
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16) for _ in range(3))
        for causal in (False, True):
            o_t, lse_t = flash_attention(q, k, v, causal=causal,
                                         interpret=False, return_lse=True)
            o_i, lse_i = flash_attention(q, k, v, causal=causal,
                                         interpret=True, return_lse=True)
            np.testing.assert_allclose(np.asarray(o_t, np.float32),
                                       np.asarray(o_i, np.float32), atol=3e-3)
            np.testing.assert_allclose(np.asarray(lse_t), np.asarray(lse_i), atol=1e-4)

    def test_lse_fully_masked_rows_are_neg_inf(self):
        """Causal first row attends only to itself; a fully-masked block
        (k entirely after q in a later ring step) must yield lse=-inf —
        exercised here via the ring's skip branch shape contract."""
        rng = np.random.RandomState(4)
        b, t, h, d = 1, 16, 1, 8
        q, k, v = (rng.randn(b, t, h, d).astype(np.float32) for _ in range(3))
        _, lse = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                 causal=True, return_lse=True)
        assert np.all(np.isfinite(np.asarray(lse)))


class TestTileableBlocks:
    def test_block_selection_is_mosaic_legal(self):
        """Mosaic requires a block's sublane dim divisible by 8 OR equal
        to the whole array dim; the old gcd picked sizes like 4 for
        t=100, which crashed only on the real chip (interpret mode can't
        catch it)."""
        from flink_tensorflow_tpu.ops.flash_attention import _tileable_block

        for t in [8, 12, 64, 100, 128, 136, 200, 264, 1000, 1001, 4096]:
            b = _tileable_block(t, 128)
            assert t % b == 0, (t, b)
            assert b % 8 == 0 or b == t, (t, b)
            assert b <= 128 or b == t, (t, b)

    def test_non_divisible_lengths_match_reference(self):
        """Shapes that used to crash Mosaic (t=100, 264, mixed) run the
        same kernel path in interpret mode and match full attention."""
        import jax.numpy as jnp

        from flink_tensorflow_tpu.ops.flash_attention import flash_attention
        from flink_tensorflow_tpu.parallel import full_attention

        rng = np.random.RandomState(3)
        for t, tk in [(100, 100), (264, 136), (12, 200)]:
            q = rng.randn(1, t, 2, 16).astype(np.float32)
            k = rng.randn(1, tk, 2, 16).astype(np.float32)
            v = rng.randn(1, tk, 2, 16).astype(np.float32)
            got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
            want = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-5)
