from flink_tensorflow_tpu.core.environment import (
    JobHandle,
    JobResult,
    StreamExecutionEnvironment,
)
from flink_tensorflow_tpu.core.functions import (
    Collector,
    FilterFunction,
    FlatMapFunction,
    MapFunction,
    ProcessFunction,
    RichFunction,
    SinkFunction,
    SourceFunction,
    WindowFunction,
)
from flink_tensorflow_tpu.core.state import StateDescriptor
from flink_tensorflow_tpu.core.stream import DataStream, KeyedStream, WindowedStream

__all__ = [
    "StreamExecutionEnvironment",
    "JobHandle",
    "JobResult",
    "DataStream",
    "KeyedStream",
    "WindowedStream",
    "MapFunction",
    "FlatMapFunction",
    "FilterFunction",
    "ProcessFunction",
    "WindowFunction",
    "SourceFunction",
    "SinkFunction",
    "RichFunction",
    "Collector",
    "StateDescriptor",
]
