"""TensorRing — schema-typed zero-copy record ring over the native arena.

One producer thread writes records field-by-field into a reserved slot;
one consumer thread claims N contiguous slots and gets the batch as
``[N, ...]`` numpy views ONTO the arena — no stacking copy.  Feed those
views straight to ``jax.device_put`` and the host-side cost of batch
assembly drops to the producer's single record write (the
"zero-copy Row<->DeviceArray marshalling" of BASELINE.json's north star).

Arena layout is **SoA**: each field owns a contiguous
``[capacity, *field_shape]`` region, so a claimed batch view is a plain
C-CONTIGUOUS slice ``region[start:start+n]`` — ``device_put`` consumes
it without any host-side repack.  (The r2 layout packed fields AoS per
slot; the claimed views strided by the padded slot size, so the
"zero-copy" label silently paid a repack inside ``device_put`` —
VERDICT r2 weak #6.)

The consumer must finish with the views (i.e. after ``device_put``
returns) before calling :meth:`release`, which recycles the slots.

Falls back to a lock-based Python ring (same API, same contiguity
guarantees) when the native library isn't built.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import struct
import tempfile
import typing
import threading

import numpy as np

from flink_tensorflow_tpu.tensors.schema import RecordSchema

_LIB = None
_LIB_TRIED = False


def _lib_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "native", "lib", "libftt_native.so")


def _load_lib():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    lib.ring_create.restype = ctypes.c_void_p
    lib.ring_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.ring_destroy.argtypes = [ctypes.c_void_p]
    lib.ring_arena.restype = ctypes.c_void_p
    lib.ring_arena.argtypes = [ctypes.c_void_p]
    lib.ring_slot_size.restype = ctypes.c_uint64
    lib.ring_slot_size.argtypes = [ctypes.c_void_p]
    lib.ring_capacity.restype = ctypes.c_uint64
    lib.ring_capacity.argtypes = [ctypes.c_void_p]
    lib.ring_push_reserve.restype = ctypes.c_int64
    lib.ring_push_reserve.argtypes = [ctypes.c_void_p]
    lib.ring_push_commit.argtypes = [ctypes.c_void_p]
    lib.ring_poppable.restype = ctypes.c_uint64
    lib.ring_poppable.argtypes = [ctypes.c_void_p]
    lib.ring_pop_release.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    _LIB = lib
    return lib


def native_available() -> bool:
    return _load_lib() is not None


def _soa_layout(schema: RecordSchema, length_bucket: int, capacity: int):
    """SoA arena layout: per field, (region_offset, shape, dtype,
    row_nbytes).  Each field's region is ``capacity`` tightly-packed
    rows (tight packing is what makes a claimed ``[n, ...]`` slice
    C-contiguous); region STARTS are 64-byte aligned.  Returns (layout,
    total_arena_bytes)."""
    layout = {}
    offset = 0
    shapes = schema.resolve_dynamic(length_bucket)
    for name in schema.names:
        spec = schema[name]
        shape = shapes[name]
        dtype = np.dtype(spec.dtype)
        row = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        layout[name] = (offset, shape, dtype, row)
        offset += (capacity * row + 63) & ~63
    return layout, offset


class _PyRing:
    """Fallback: same SPSC semantics with a mutex (correct, not lock-free)."""

    def __init__(self, slot_size: int, n_slots: int):
        pow2 = 1
        while pow2 < n_slots:
            pow2 *= 2
        self.slot_size = slot_size
        self.n_slots = pow2
        self.mask = pow2 - 1
        self.arena = np.zeros(slot_size * pow2, np.uint8)
        self.head = 0
        self.tail = 0
        self._lock = threading.Lock()

    def push_reserve(self) -> int:
        with self._lock:
            if self.tail - self.head >= self.n_slots:
                return -1
            return self.tail & self.mask

    def push_commit(self) -> None:
        with self._lock:
            self.tail += 1

    def poppable(self) -> int:
        with self._lock:
            return self.tail - self.head

    def pop_release(self, count: int) -> None:
        with self._lock:
            self.head += count

    def arena_view(self) -> np.ndarray:
        return self.arena

    def destroy(self) -> None:
        pass


class _NativeRing:
    def __init__(self, slot_size: int, n_slots: int):
        self._lib = _load_lib()
        self._ptr = self._lib.ring_create(slot_size, n_slots)
        if not self._ptr:
            raise MemoryError("ring_create failed")
        self.slot_size = self._lib.ring_slot_size(self._ptr)
        self.n_slots = self._lib.ring_capacity(self._ptr)
        nbytes = self.slot_size * self.n_slots
        base = self._lib.ring_arena(self._ptr)
        self._arena = np.ctypeslib.as_array(
            (ctypes.c_uint8 * nbytes).from_address(base)
        )

    def push_reserve(self) -> int:
        return self._lib.ring_push_reserve(self._ptr)

    def push_commit(self) -> None:
        self._lib.ring_push_commit(self._ptr)

    def poppable(self) -> int:
        return self._lib.ring_poppable(self._ptr)

    def pop_release(self, count: int) -> None:
        self._lib.ring_pop_release(self._ptr, count)

    def arena_view(self) -> np.ndarray:
        return self._arena

    def destroy(self) -> None:
        if self._ptr:
            self._lib.ring_destroy(self._ptr)
            self._ptr = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.destroy()
        except Exception:
            pass


def shm_dir() -> str:
    """Where shared ring files live: tmpfs (``/dev/shm``) when the
    platform has it — a page-cache-backed temp dir otherwise (still
    mmap-shareable, just not guaranteed RAM-only)."""
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


class ShmByteRing:
    """Cross-process SPSC byte-frame ring — TensorRing's sibling for the
    same-host record plane.

    Where :class:`TensorRing` is schema-typed and intra-process (its
    arena is private memory), this ring carries OPAQUE variable-length
    frames over a shared ``mmap`` so two processes on one host exchange
    record-plane frames without touching the kernel TCP stack: the
    producer writes ``[u32 len][payload]`` frames at ``tail``, the
    consumer drains at ``head``, and both cursors live in the mapping
    itself (one writer each — the SPSC contract the TensorRing layouts
    already rely on; cursors sit on separate cache lines).  Publication
    order is payload-then-cursor, so a reader never observes a frame
    before its bytes.

    The file lives in :func:`shm_dir` (tmpfs on Linux).  The CREATING
    side owns the name; the attaching side maps it read-write.  Either
    side may :meth:`close`; ``unlink=True`` removes the file (guarded —
    first unlinker wins, crashes leave at most one small file behind).
    """

    _CURSOR = struct.Struct("<Q")
    _FRAME = struct.Struct("<I")
    _HEAD_OFF, _TAIL_OFF, _CAP_OFF, _DATA_OFF = 0, 64, 128, 192
    #: Consumer-parked doorbell flag (shares the read-mostly capacity
    #: cache line; written by the consumer, cleared by the producer).
    _PARK_OFF = 136
    #: Cumulative credit grants (record-plane flow control): the
    #: CONSUMER is the only writer — it adds the initial window at
    #: attach and one credit per frame its gate drained; the producer
    #: compares against its own spent-frames count before each write.
    #: Cumulative u64 counters keep the cell SPSC-safe exactly like the
    #: head/tail cursors (no read-modify-write races across processes).
    _CREDIT_OFF = 144

    def __init__(self, path: str, mm: mmap.mmap, capacity: int, *,
                 created: bool):
        self.path = path
        self._mm = mm
        self.capacity = capacity
        self._created = created
        self._view = memoryview(mm)
        self._closed = False

    # -- construction ----------------------------------------------------
    @classmethod
    def create(cls, path: str, capacity: int = 1 << 20) -> "ShmByteRing":
        pow2 = 1
        while pow2 < capacity:
            pow2 *= 2
        size = cls._DATA_OFF + pow2
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        ring = cls(path, mm, pow2, created=True)
        ring._store(cls._HEAD_OFF, 0)
        ring._store(cls._TAIL_OFF, 0)
        ring._store(cls._CAP_OFF, pow2)
        ring._store(cls._PARK_OFF, 0)
        ring._store(cls._CREDIT_OFF, 0)
        return ring

    @classmethod
    def attach(cls, path: str) -> "ShmByteRing":
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        (capacity,) = cls._CURSOR.unpack_from(mm, cls._CAP_OFF)
        if cls._DATA_OFF + capacity != size:
            raise ValueError(f"shm ring {path!r} header/size mismatch")
        return cls(path, mm, capacity, created=False)

    # -- cursors ---------------------------------------------------------
    def _load(self, off: int) -> int:
        return self._CURSOR.unpack_from(self._mm, off)[0]

    def _store(self, off: int, value: int) -> None:
        self._CURSOR.pack_into(self._mm, off, value)

    # -- producer --------------------------------------------------------
    def free_bytes(self) -> int:
        return self.capacity - (self._load(self._TAIL_OFF)
                                - self._load(self._HEAD_OFF))

    def try_write(self, payload: typing.Union[bytes, bytearray, memoryview]
                  ) -> bool:
        """Write one frame; False when the ring lacks space (the caller
        backs off — ring-full IS the backpressure signal)."""
        need = self._FRAME.size + len(payload)
        if need > self.capacity:
            raise ValueError(
                f"frame of {len(payload)} bytes exceeds the shm ring "
                f"capacity {self.capacity} — raise the ring size or "
                "lower wire_flush_bytes"
            )
        tail = self._load(self._TAIL_OFF)
        if need > self.capacity - (tail - self._load(self._HEAD_OFF)):
            return False
        self._put_bytes(tail, self._FRAME.pack(len(payload)))
        self._put_bytes(tail + self._FRAME.size, payload)
        # Publish AFTER the payload is in the mapping.
        self._store(self._TAIL_OFF, tail + need)
        return True

    def try_write_parts(self, parts: typing.Sequence[typing.Any],
                        total: int) -> bool:
        """Scatter-gather :meth:`try_write`: writes ``parts`` (whose
        lengths sum to ``total``) as ONE frame without concatenating
        them first — the zero-copy send path for multi-part wire frames."""
        need = self._FRAME.size + total
        if need > self.capacity:
            raise ValueError(
                f"frame of {total} bytes exceeds the shm ring "
                f"capacity {self.capacity} — raise the ring size or "
                "lower wire_flush_bytes"
            )
        tail = self._load(self._TAIL_OFF)
        if need > self.capacity - (tail - self._load(self._HEAD_OFF)):
            return False
        self._put_bytes(tail, self._FRAME.pack(total))
        pos = tail + self._FRAME.size
        for p in parts:
            self._put_bytes(pos, p)
            pos += len(p) if not isinstance(p, memoryview) else p.nbytes
        self._store(self._TAIL_OFF, tail + need)
        return True

    def _put_bytes(self, pos: int, data) -> None:
        cap = self.capacity
        off = pos & (cap - 1)
        data = memoryview(data).cast("B") if not isinstance(data, bytes) else data
        n = len(data)
        first = min(n, cap - off)
        base = self._DATA_OFF
        self._view[base + off:base + off + first] = data[:first]
        if first < n:  # wrap
            self._view[base:base + n - first] = data[first:]

    # -- doorbell --------------------------------------------------------
    # The consumer parks before sleeping; the producer sends its (socket)
    # notify ONLY when it observes the parked flag, clearing it first so
    # back-to-back frames ring the doorbell once.  mmap stores carry no
    # memory fence, so a publish racing a park can — very rarely — leave
    # the consumer asleep with data in the ring; the consumer side MUST
    # therefore keep a bounded re-poll while parked (the reactor's ring
    # poller).  Suppression is a throughput optimisation, never the sole
    # wakeup path.

    def consumer_parked(self) -> bool:
        return self._load(self._PARK_OFF) != 0

    def set_consumer_parked(self, parked: bool) -> None:
        self._store(self._PARK_OFF, 1 if parked else 0)

    # -- flow control ----------------------------------------------------
    def credits_granted(self) -> int:
        """Cumulative credits the consumer has granted over the ring's
        lifetime (producer side compares with its own spent total)."""
        return self._load(self._CREDIT_OFF)

    def add_credits(self, n: int) -> None:
        """Grant ``n`` more frame credits (CONSUMER only — single
        writer, like the head cursor)."""
        self._store(self._CREDIT_OFF, self._load(self._CREDIT_OFF) + n)

    # -- consumer --------------------------------------------------------
    def readable(self) -> bool:
        return self._load(self._TAIL_OFF) != self._load(self._HEAD_OFF)

    def read(self) -> typing.Optional[bytearray]:
        """Pop one frame as a WRITABLE standalone buffer; None if empty."""
        head = self._load(self._HEAD_OFF)
        if self._load(self._TAIL_OFF) == head:
            return None
        (length,) = self._FRAME.unpack(
            bytes(self._get_bytes(head, self._FRAME.size)))
        payload = self._get_bytes(head + self._FRAME.size, length)
        self._store(self._HEAD_OFF, head + self._FRAME.size + length)
        return payload

    def _get_bytes(self, pos: int, n: int) -> bytearray:
        cap = self.capacity
        off = pos & (cap - 1)
        out = bytearray(n)
        first = min(n, cap - off)
        base = self._DATA_OFF
        out[:first] = self._view[base + off:base + off + first]
        if first < n:  # wrap
            out[first:] = self._view[base:base + n - first]
        return out

    # -- lifecycle -------------------------------------------------------
    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._view.release()
            self._mm.close()
        except (BufferError, ValueError, OSError):
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class TensorRing:
    """Schema-typed SPSC record ring with zero-copy batch views."""

    def __init__(
        self,
        schema: RecordSchema,
        capacity: int = 256,
        *,
        length_bucket: int = 128,
        native: typing.Optional[bool] = None,
    ):
        self.schema = schema
        if native is None:
            native = native_available()
        elif native and not native_available():
            raise RuntimeError("native ring requested but libftt_native.so not built "
                               "(run: make -C native)")
        self.is_native = bool(native)
        # The low-level rings round capacity up to a power of two;
        # mirror that BEFORE computing the SoA regions (their extents
        # depend on the final capacity).
        pow2 = 1
        while pow2 < capacity:
            pow2 *= 2
        self.layout, total_bytes = _soa_layout(schema, length_bucket, pow2)
        # The native ring allocates slot_size * n_slots bytes and only
        # manages counters — the SoA interpretation of the blob is ours.
        slot_size = (total_bytes + pow2 - 1) // pow2
        slot_size = (slot_size + 63) & ~63
        ring_cls = _NativeRing if self.is_native else _PyRing
        self._ring = ring_cls(slot_size, pow2)
        self.capacity = self._ring.n_slots
        assert self.capacity == pow2, (self.capacity, pow2)
        #: Pipelining cursor: slots claimed but not yet released.  The
        #: low-level rings claim from ``head`` (which only moves on
        #: release), so overlapping claims — several dispatched batches
        #: in flight at once — are sequenced here.  Claims and releases
        #: must both happen on the single consumer thread (SPSC).
        self._claim_ahead = 0
        self._claim_idx = 0

    # -- producer ----------------------------------------------------------
    def try_push(self, record: typing.Mapping[str, np.ndarray]) -> bool:
        """Write one record into the ring; False if full (caller backs off).

        Raises ValueError (BEFORE reserving a slot) when a dynamic field
        exceeds its resolved bucket — a mid-push broadcast crash would
        leave a reserved-but-uncommitted slot and kill the producer."""
        for name, (offset, shape, dtype, row) in self.layout.items():
            src_shape = np.asarray(record[name]).shape
            if src_shape != tuple(shape) and any(
                s > d for s, d in zip(src_shape, shape)
            ):
                raise ValueError(
                    f"field {name!r} shape {src_shape} exceeds the ring's "
                    f"slot shape {tuple(shape)} (length_bucket too small)"
                )
        slot = self._ring.push_reserve()
        if slot < 0:
            return False
        arena = self._ring.arena_view()
        for name, (offset, shape, dtype, row) in self.layout.items():
            dst = np.frombuffer(
                arena.data, dtype=dtype, count=int(np.prod(shape)) if shape else 1,
                offset=offset + slot * row,
            ).reshape(shape)
            src = np.asarray(record[name])
            if src.shape != tuple(shape):  # dynamic field: write prefix, zero-pad
                dst.fill(0)
                dst[tuple(slice(0, s) for s in src.shape)] = src
            else:
                dst[...] = src
        self._ring.push_commit()
        return True

    # -- consumer ----------------------------------------------------------
    def poppable(self) -> int:
        return self._ring.poppable()

    def claim_batch(self, max_n: int) -> typing.Tuple[typing.Dict[str, np.ndarray], int]:
        """Claim up to ``max_n`` contiguous records; returns ({field ->
        C-CONTIGUOUS [n, ...] zero-copy view}, n).  Call :meth:`release`
        when done.

        Claims may overlap (claim B while A's views are still in use);
        releases apply oldest-claim-first."""
        ready = self._ring.poppable() - self._claim_ahead
        if ready <= 0:
            return {}, 0
        start = self._claim_idx
        n = min(max_n, ready, self.capacity - start)
        self._claim_ahead += n
        self._claim_idx = (start + n) % self.capacity
        arena = self._ring.arena_view()
        views = {}
        for name, (offset, shape, dtype, row) in self.layout.items():
            elems = int(np.prod(shape)) if shape else 1
            # SoA region: rows are tightly packed, so the claimed slice
            # is a plain contiguous view — device_put reads it directly.
            flat = np.frombuffer(
                arena.data, dtype=dtype, count=n * elems,
                offset=offset + start * row,
            )
            views[name] = flat.reshape((n, *shape)) if shape else flat
        return views, n

    def release(self, count: int) -> None:
        self._ring.pop_release(count)
        self._claim_ahead -= count

    def close(self) -> None:
        self._ring.destroy()
