"""Plan-time static analysis over the DataflowGraph.

The TypeInformation role the reference got from Flink's job-graph
translation, rebuilt for the TPU-native plan: schema/shape/dtype
propagation through every operator, a lint-rule registry (cycles,
dangling roots, keyed partitioning, mesh divisibility, dynamic dims at
jit boundaries, recompilation churn), and three front doors —

- ``analyze(graph, config=...) -> list[Diagnostic]``
- ``env.execute(..., validate=True)`` (raises PlanValidationError on ERROR)
- ``python -m flink_tensorflow_tpu.analysis examples/<pipeline>.py``

All of it runs before a single record is emitted or a chip is touched.
"""

from flink_tensorflow_tpu.analysis.analyzer import analyze, has_errors
from flink_tensorflow_tpu.analysis.chaining import (
    ChainPlan,
    chainable_edge,
    compute_chains,
    sharding_axes_of,
    sharding_fusion_conflict,
)
from flink_tensorflow_tpu.analysis.capture import (
    PlanCaptured,
    capture_pipeline_file,
    capture_plan,
    capturing_execution,
)
from flink_tensorflow_tpu.analysis.diagnostics import (
    Diagnostic,
    PlanValidationError,
    Severity,
    edge_name,
    format_diagnostics,
    worst_severity,
)
from flink_tensorflow_tpu.analysis.rules import RULES, AnalysisContext, LintRule, rule
from flink_tensorflow_tpu.analysis.sanitizer import (
    PurityFinding,
    scan_callable,
    scan_code,
    scan_operator,
)
from flink_tensorflow_tpu.analysis.schema_prop import SchemaFlow, propagate
from flink_tensorflow_tpu.analysis.shardcheck import (
    OpAudit,
    PlanAudit,
    SpecLayout,
    audit_of,
    audit_plan,
    report_for_env,
)
from flink_tensorflow_tpu.analysis.statecheck import (
    OpStateAudit,
    PlanStateAudit,
    audit_of as statecheck_audit_of,
    audit_plan as statecheck_audit_plan,
    report_for_env as statecheck_report_for_env,
)

__all__ = [
    "RULES",
    "AnalysisContext",
    "ChainPlan",
    "Diagnostic",
    "LintRule",
    "OpAudit",
    "OpStateAudit",
    "PlanAudit",
    "PlanCaptured",
    "PlanStateAudit",
    "PlanValidationError",
    "PurityFinding",
    "SchemaFlow",
    "Severity",
    "SpecLayout",
    "analyze",
    "audit_of",
    "audit_plan",
    "capture_pipeline_file",
    "capture_plan",
    "capturing_execution",
    "chainable_edge",
    "compute_chains",
    "edge_name",
    "format_diagnostics",
    "has_errors",
    "propagate",
    "report_for_env",
    "rule",
    "scan_callable",
    "scan_code",
    "scan_operator",
    "sharding_axes_of",
    "sharding_fusion_conflict",
    "statecheck_audit_of",
    "statecheck_audit_plan",
    "statecheck_report_for_env",
    "worst_severity",
]
