"""ModelFunction-in-stream integration tests — the reference's MiniCluster
end-to-end shape (SURVEY.md §4): a bounded stream through a model operator
with a tiny model, asserting exact outputs."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.functions import (
    GraphWindowFunction,
    ModelMapFunction,
    ModelWindowFunction,
)
from flink_tensorflow_tpu.models import freeze_method, get_model_def, save_bundle
from flink_tensorflow_tpu.tensors import BucketPolicy, TensorValue


@pytest.fixture(scope="module")
def lenet_model():
    mdef = get_model_def("lenet")
    params = jax.jit(mdef.init_fn)(jax.random.key(0))
    return mdef.to_model(params)


@pytest.fixture(scope="module")
def images():
    rng = np.random.RandomState(7)
    return [
        TensorValue({"image": rng.rand(28, 28, 1).astype(np.float32)}, {"i": i})
        for i in range(10)
    ]


@pytest.fixture(scope="module")
def expected_labels(lenet_model, images):
    serve = jax.jit(lenet_model.method("serve").fn)
    batch = jnp.stack([jnp.asarray(r["image"]) for r in images])
    out = serve(lenet_model.params, {"image": batch})
    return [int(x) for x in np.asarray(out["label"])]


class TestModelWindowFunction:
    def test_windowed_microbatch_inference(self, lenet_model, images, expected_labels):
        env = StreamExecutionEnvironment(parallelism=1)
        results = (
            env.from_collection(images)
            .count_window(4)
            .apply(ModelWindowFunction(lenet_model))
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert len(results) == 10
        got = {r.meta["i"]: int(r["label"]) for r in results}
        assert got == {i: l for i, l in enumerate(expected_labels)}

    def test_parallel_subtasks_share_host_model(self, lenet_model, images, expected_labels):
        env = StreamExecutionEnvironment(parallelism=2)
        results = (
            env.from_collection(images)
            .rebalance()
            .count_window(4, timeout_s=0.2)
            .apply(ModelWindowFunction(lenet_model), parallelism=2)
            .sink_to_list()
        )
        env.execute(timeout=120)
        got = {r.meta["i"]: int(r["label"]) for r in results}
        assert got == {i: l for i, l in enumerate(expected_labels)}

    def test_pipelined_dispatch_completeness(self, lenet_model, images, expected_labels):
        """pipeline_depth=3: in-flight batches must all flush at end of
        input — every record exactly once, labels correct."""
        env = StreamExecutionEnvironment(parallelism=1)
        results = (
            env.from_collection(images)
            .count_window(2)
            .apply(ModelWindowFunction(lenet_model, pipeline_depth=3))
            .sink_to_list()
        )
        env.execute(timeout=120)
        got = {r.meta["i"]: int(r["label"]) for r in results}
        assert got == {i: l for i, l in enumerate(expected_labels)}

    def test_oversized_window_chunks(self, lenet_model, images, expected_labels):
        env = StreamExecutionEnvironment(parallelism=1)
        results = (
            env.from_collection(images)
            .count_window(10)
            .apply(ModelWindowFunction(lenet_model, policy=BucketPolicy(fixed_batch=4)))
            .sink_to_list()
        )
        env.execute(timeout=120)
        got = {r.meta["i"]: int(r["label"]) for r in results}
        assert got == {i: l for i, l in enumerate(expected_labels)}

    def test_bundle_path_source(self, lenet_model, images, expected_labels, tmp_path):
        mdef = get_model_def("lenet")
        path = str(tmp_path / "bundle")
        save_bundle(mdef, lenet_model.params, path)
        env = StreamExecutionEnvironment(parallelism=1)
        results = (
            env.from_collection(images[:4])
            .count_window(4)
            .apply(ModelWindowFunction(path))
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert [int(r["label"]) for r in results] == expected_labels[:4]


class TestModelMapFunction:
    def test_per_record_inference(self, lenet_model, images, expected_labels):
        env = StreamExecutionEnvironment(parallelism=1)
        results = (
            env.from_collection(images[:3])
            .map(ModelMapFunction(lenet_model))
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert [int(r["label"]) for r in results] == expected_labels[:3]


class TestGraphFunction:
    def test_frozen_window_inference(self, lenet_model, images, expected_labels):
        frozen = freeze_method(lenet_model, "serve", batch=4)
        env = StreamExecutionEnvironment(parallelism=1)
        results = (
            env.from_collection(images)
            .count_window(4)
            .apply(GraphWindowFunction(
                frozen, batch=4,
                input_schema=lenet_model.method("serve").input_schema,
            ))
            .sink_to_list()
        )
        env.execute(timeout=120)
        got = {r.meta["i"]: int(r["label"]) for r in results}
        assert got == {i: l for i, l in enumerate(expected_labels)}


class TestMetrics:
    def test_inference_metrics_populated(self, lenet_model, images):
        env = StreamExecutionEnvironment(parallelism=1)
        (
            env.from_collection(images)
            .count_window(5)
            .apply(ModelWindowFunction(lenet_model), name="infer")
            .sink_to_list()
        )
        result = env.execute(timeout=120)
        assert result.metrics["infer.0.records"]["count"] == 10
        assert result.metrics["infer.0.batches"] == 2
        assert result.metrics["infer.0.record_latency_s"]["p50"] > 0
