"""AdaptiveLatencyTrigger — the latency-TARGETING batching policy
(SURVEY.md §7 hard part 3; VERDICT r2 next-round #2).

Unit tests pin the policy math on a fake clock; the integration test
runs a paced sub-saturation stream and asserts partial windows flush at
the arrival cadence instead of parking at the hard budget (the static
CountOrTimeoutTrigger's failure mode: p50 ~ timeout)."""

import time

import numpy as np
import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core import windows as W
from flink_tensorflow_tpu.core.operators import WindowOperator
from flink_tensorflow_tpu.io import PacedSource


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def clock(monkeypatch):
    c = FakeClock()
    monkeypatch.setattr(W.time, "monotonic", c)
    return c


def _arrive(trigger, buf, clock, t):
    clock.t = t
    buf.add(object(), None)
    return trigger.on_element(buf)


class TestAdaptiveLatencyTriggerPolicy:
    def test_fills_like_count_trigger_at_high_rate(self, clock):
        """Arrivals fast enough to fill within budget: no early fire, the
        count fires the full window."""
        trig = W.AdaptiveLatencyTrigger(4, 1.0)
        buf = W.WindowBuffer(window=W.CountWindow(0))
        assert not _arrive(trig, buf, clock, 100.00)
        assert not _arrive(trig, buf, clock, 100.01)
        # Projection: 2 remaining * 0.01s << budget -> hold for the count.
        assert trig.deadline(buf) == pytest.approx(100.0 + 1.0)
        assert not _arrive(trig, buf, clock, 100.02)
        assert _arrive(trig, buf, clock, 100.03)  # full at 4

    def test_flushes_one_gap_after_last_arrival_at_low_rate(self, clock):
        """Arrivals too slow to fill: deadline collapses to one expected
        gap past the last arrival, NOT the hard budget."""
        trig = W.AdaptiveLatencyTrigger(16, 1.0)
        buf = W.WindowBuffer(window=W.CountWindow(0))
        _arrive(trig, buf, clock, 100.0)
        # No estimate yet: conservative hard deadline.
        assert trig.deadline(buf) == pytest.approx(101.0)
        _arrive(trig, buf, clock, 100.3)
        # gap ewma = 0.3; 14 remaining -> fill at ~104.5 > 101 budget:
        # flush at last_arrival + gap = 100.6.
        assert trig.deadline(buf) == pytest.approx(100.6)

    def test_deadline_never_exceeds_hard_budget(self, clock):
        trig = W.AdaptiveLatencyTrigger(16, 0.2)
        buf = W.WindowBuffer(window=W.CountWindow(0))
        _arrive(trig, buf, clock, 100.0)
        _arrive(trig, buf, clock, 100.19)
        assert trig.deadline(buf) <= 100.0 + 0.2

    def test_arrival_refreshes_grace_but_not_past_budget(self, clock):
        """An arrival into a window whose one-gap grace lapsed REFRESHES
        the grace (Nagle-style micro-burst coalescing) — the lapsed
        deadline is fire_due's job, not on_element's.  The hard budget
        is not refreshable: an arrival past it fires immediately."""
        trig = W.AdaptiveLatencyTrigger(16, 1.0)
        buf = W.WindowBuffer(window=W.CountWindow(0))
        _arrive(trig, buf, clock, 100.0)
        _arrive(trig, buf, clock, 100.1)   # ewma 0.1 -> grace 100.2
        assert not _arrive(trig, buf, clock, 100.5)  # grace refreshed
        assert trig.deadline(buf) > 100.5
        assert _arrive(trig, buf, clock, 101.05)  # past first+budget: fire

    def test_ewma_persists_across_windows(self, clock):
        """The rate estimate carries into the next window: its FIRST
        element already projects (no conservative full-budget wait)."""
        trig = W.AdaptiveLatencyTrigger(16, 1.0)
        buf = W.WindowBuffer(window=W.CountWindow(0))
        _arrive(trig, buf, clock, 100.0)
        _arrive(trig, buf, clock, 100.4)
        buf2 = W.WindowBuffer(window=W.CountWindow(1))
        _arrive(trig, buf2, clock, 100.8)
        # gap ewma ~0.4 -> 15 remaining won't fill in 1s: one-gap flush.
        assert trig.deadline(buf2) < 100.8 + 0.5

    def test_empty_buffer_has_no_deadline(self, clock):
        trig = W.AdaptiveLatencyTrigger(4, 1.0)
        assert trig.deadline(W.WindowBuffer(window=W.CountWindow(0))) is None

    def test_validation(self):
        with pytest.raises(ValueError):
            W.AdaptiveLatencyTrigger(0, 1.0)
        with pytest.raises(ValueError):
            W.AdaptiveLatencyTrigger(4, 0.0)
        with pytest.raises(ValueError):
            W.AdaptiveLatencyTrigger(4, 1.0, ewma_alpha=0.0)


class _CollectWindows(fn.WindowFunction):
    def __init__(self, sizes, latencies, ts_key="sched_ts"):
        self.sizes = sizes
        self.latencies = latencies
        self.ts_key = ts_key

    def clone(self):
        return self  # shared collector across subtasks (parallelism 1)

    def process_window(self, key, window, elements, out):
        now = time.monotonic()
        self.sizes.append(len(elements))
        for e in elements:
            sched = e.meta.get(self.ts_key)
            if sched is not None:
                self.latencies.append(now - sched)
            out.collect(e)


class TestWindowOperatorIntegration:
    def test_trigger_cloned_per_operator(self):
        trig = W.AdaptiveLatencyTrigger(4, 1.0)
        op = WindowOperator("w", _CollectWindows([], []), trig)
        assert op.trigger is not trig
        assert isinstance(op.trigger, W.AdaptiveLatencyTrigger)
        # Stateless triggers stay shared (no behavior change).
        ct = W.CountTrigger(4)
        assert WindowOperator("w2", _CollectWindows([], []), ct).trigger is ct

    def test_stateless_triggers_share_instance(self):
        t = W.CountOrTimeoutTrigger(4, 1.0)
        assert t.clone() is t

    def test_paced_substream_flushes_at_arrival_cadence(self):
        """20 records at ~25 rec/s into count_window(16,
        latency_budget_s=2.0): the window provably can't fill 16 slots
        within... it CAN (16/25 = 0.64s < 2) — so use a slower rate.
        10 records at 10 rec/s, window 64, budget 1.5s: fill needs 6.4s
        -> early flush ~one gap (0.1s) after each lull.  With the static
        timeout this stream's p50 would sit at the 1.5s budget."""
        from flink_tensorflow_tpu.tensors import TensorValue

        records = [TensorValue({"x": np.float32(i)}, {"i": i}) for i in range(10)]
        sizes, latencies = [], []
        env = StreamExecutionEnvironment(parallelism=1)
        (
            env.from_source(
                PacedSource(records, 10.0, jitter="none"), name="paced",
                parallelism=1)
            .count_window(64, latency_budget_s=1.5)
            .apply(_CollectWindows(sizes, latencies), name="adaptive")
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert sum(sizes) == 10
        # Early flush: no window waited for the full 64, and the policy
        # must have split the stream into several small windows.
        assert len(sizes) >= 3
        # Latency below the 1.5s budget: p50 ~ one 0.1s gap + slack (the
        # static timeout would park every record at ~1.5s; the loose 1.0
        # bound absorbs CI scheduling noise while still separating the
        # two behaviors).
        lat = np.percentile(np.asarray(latencies), 50)
        assert lat < 1.0, f"p50 {lat:.3f}s should beat the 1.5s budget"

    def test_adaptive_trigger_with_ring_ingestion(self):
        """The adaptive trigger is non-retaining, so zero-copy ring
        ingestion stays eligible; partial (early-fired) windows must
        claim/pad arena slots correctly."""
        import jax

        from flink_tensorflow_tpu.functions import ModelWindowFunction
        from flink_tensorflow_tpu.models import get_model_def
        from flink_tensorflow_tpu.tensors import BucketPolicy, TensorValue

        mdef = get_model_def("lenet")
        model = mdef.to_model(jax.jit(mdef.init_fn)(jax.random.key(0)))
        rng = np.random.RandomState(5)
        records = [
            TensorValue({"image": rng.rand(28, 28, 1).astype(np.float32)},
                        {"i": i})
            for i in range(11)
        ]
        env = StreamExecutionEnvironment(parallelism=1)
        f = ModelWindowFunction(model, policy=BucketPolicy(fixed_batch=4),
                                warmup_batches=(4,))
        results = (
            env.from_source(
                PacedSource(records, 40.0, jitter="none"), name="paced",
                parallelism=1)
            .count_window(4, latency_budget_s=0.15)
            .apply(f, name="ringwin")
            .sink_to_list()
        )
        env.execute(timeout=180)
        serve = jax.jit(model.method("serve").fn)
        import jax.numpy as jnp

        ref = serve(model.params,
                    {"image": jnp.stack([jnp.asarray(r["image"]) for r in records])})
        got = {r.meta["i"]: int(r["label"]) for r in results}
        assert got == {i: int(x) for i, x in enumerate(np.asarray(ref["label"]))}

    def test_full_rate_stream_keeps_full_windows(self):
        """from_collection (infinite rate): every steady window is full —
        the adaptive policy must not shrink batches when the rate
        supports filling."""
        from flink_tensorflow_tpu.tensors import TensorValue

        records = [TensorValue({"x": np.float32(i)}, {"i": i}) for i in range(64)]
        sizes, latencies = [], []
        env = StreamExecutionEnvironment(parallelism=1)
        (
            env.from_collection(records, parallelism=1)
            .count_window(16, latency_budget_s=5.0)
            .apply(_CollectWindows(sizes, latencies), name="adaptive")
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert sizes == [16, 16, 16, 16]
