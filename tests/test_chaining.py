"""Operator chaining: plan-time fusion rules, chained execution
semantics (barriers, watermarks, checkpoint/restore, failover), and the
event-driven record plane that replaces the timed idle polls.

The acceptance contract (ISSUE 3): a forward pipeline of N chainable
operators runs as ONE subtask thread per chain with zero inter-operator
queue traffic (verified via the per-edge gauges), while every logical
operator keeps its own metric scope and checkpoint identity.
"""

import threading
import time

import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.analysis.chaining import (
    compute_chains,
    sharding_axes_of,
    sharding_fusion_conflict,
)
from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core.channels import InputGate


def _chain_names(graph, **kw):
    return compute_chains(graph, **kw).names()


class _GangMap(fn.MapFunction):
    is_gang = True

    def map(self, value):
        return value


class _ShardedMap(fn.MapFunction):
    def __init__(self, axes):
        self.sharding_axes = axes

    def map(self, value):
        return value


class _CountingRichMap(fn.MapFunction):
    """Stateful chained operator for the exactly-once tests: counts every
    record through it, snapshot/restore carries the count."""

    def __init__(self, box=None):
        self.count = 0
        #: shared across clones so the test can read the final count.
        self.box = box if box is not None else [0]

    def clone(self):
        return _CountingRichMap(self.box)

    def map(self, value):
        self.count += 1
        self.box[0] = self.count
        return value

    def snapshot_state(self):
        return {"count": self.count}

    def restore_state(self, state):
        self.count = state["count"]
        self.box[0] = self.count


class TestChainPlan:
    def test_linear_forward_pipeline_fuses_completely(self, env):
        s = env.from_collection(range(8), parallelism=2)
        s.map(lambda x: x, name="a", parallelism=2) \
            .filter(lambda x: True, name="b", parallelism=2) \
            .sink_to_list(name="c", parallelism=2)
        assert _chain_names(env.graph) == [["collection", "a", "b", "c"]]

    def test_keyed_broadcast_rebalance_edges_never_fuse(self, env):
        s = env.from_collection(range(8), parallelism=2)
        keyed = s.key_by(lambda x: x).process(
            _KeyedNoop(), name="keyed", parallelism=2)
        keyed.broadcast().map(lambda x: x, name="bcast", parallelism=2) \
            .rebalance().map(lambda x: x, name="rebal", parallelism=2)
        names = _chain_names(env.graph)
        # Every operator is its own chain: hash, broadcast and rebalance
        # edges all re-route records between subtasks.
        assert names == [["collection"], ["keyed"], ["bcast"], ["rebal"]]

    def test_parallelism_change_and_fanout_break_chains(self, env):
        s = env.from_collection(range(8), parallelism=1)
        m = s.map(lambda x: x, name="wide", parallelism=2)  # 1 -> 2
        m.map(lambda x: x, name="t1", parallelism=2)
        m.map(lambda x: x, name="t2", parallelism=2)  # fan-out from wide
        names = _chain_names(env.graph)
        assert ["collection"] in names and ["wide"] in names
        assert ["t1"] in names and ["t2"] in names

    def test_two_input_operators_head_their_own_chain(self, env):
        a = env.from_collection(range(4), parallelism=1)
        b = env.from_collection(range(4), parallelism=1)
        joined = a.union(b)
        joined.map(lambda x: x, name="after", parallelism=1)
        plan = compute_chains(env.graph)
        union_chain = plan.chain_of(
            next(t for t in env.graph.transformations if t.name == "union"))
        # The union merge has two input edges -> never fused INTO; its
        # forward downstream still chains onto it.
        assert [t.name for t in union_chain] == ["union", "after"]

    def test_escape_hatches_respected(self, env):
        s = env.from_collection(range(8), parallelism=1)
        s.map(lambda x: x, name="a", parallelism=1) \
            .map(lambda x: x, name="b", parallelism=1).start_new_chain() \
            .map(lambda x: x, name="c", parallelism=1).disable_chaining() \
            .map(lambda x: x, name="d", parallelism=1)
        names = _chain_names(env.graph)
        assert names == [["collection", "a"], ["b"], ["c"], ["d"]]
        reasons = compute_chains(env.graph).unchained_reasons
        assert any("starts a new chain" in r for r in reasons.values())
        assert any("chaining disabled" in r for r in reasons.values())

    def test_gang_operators_never_fuse(self, env):
        s = env.from_collection(range(8), parallelism=1)
        s.map(lambda x: x, name="pre", parallelism=1) \
            .map(_GangMap(), name="gang", parallelism=1) \
            .map(lambda x: x, name="post", parallelism=1)
        names = _chain_names(env.graph)
        assert ["gang"] in names
        assert ["post"] in names

    def test_mismatched_sharding_never_fuses_matching_does(self, env):
        s = env.from_collection(range(8), parallelism=1)
        s.map(_ShardedMap(("data",)), name="d1", parallelism=1) \
            .map(_ShardedMap(("model",)), name="m1", parallelism=1) \
            .map(_ShardedMap(("model",)), name="m2", parallelism=1)
        plan = compute_chains(env.graph)
        names = plan.names()
        # data|model mismatch splits; model|model fuses.
        assert ["m1", "m2"] in names
        assert all("m1" not in c for c in names if "d1" in c)
        assert any("mismatched sharding" in r
                   for r in plan.unchained_reasons.values())

    def test_sharding_helpers_shared_vocabulary(self):
        gang = _GangMap()
        assert sharding_axes_of(gang) == ("data",)
        assert sharding_axes_of(_ShardedMap(("model",))) == ("model",)
        assert sharding_axes_of(None) is None

        class Op:
            def __init__(self, f):
                self.function = f

        assert sharding_fusion_conflict(Op(gang), Op(None)) is not None
        assert sharding_fusion_conflict(Op(None), Op(None)) is None

    def test_timer_operator_never_chains_into_source_loop(self, env):
        s = env.from_collection(range(32), parallelism=1)
        # count-or-timeout window declares wall-clock deadlines; a pure
        # count window is arrival-driven and may ride the source thread.
        s.map(lambda x: x, name="pre", parallelism=1) \
            .count_window(4, timeout_s=1.0) \
            .apply(_SumWindow(), name="timed", parallelism=1)
        plan = compute_chains(env.graph)
        assert ["collection", "pre"] in plan.names()
        assert any("timer-driven" in r for r in plan.unchained_reasons.values())

        env2 = StreamExecutionEnvironment(parallelism=1)
        env2.from_collection(range(32), parallelism=1) \
            .count_window(4).apply(_SumWindow(), name="counted", parallelism=1)
        assert ["collection", "counted"] in _chain_names(env2.graph)

    def test_disabled_chaining_mode_degenerates(self, env):
        s = env.from_collection(range(4), parallelism=1)
        s.map(lambda x: x, name="a", parallelism=1)
        plan = compute_chains(env.graph, enabled=False)
        assert plan.names() == [["collection"], ["a"]]
        assert plan.chained_edge_count == 0


class _KeyedNoop(fn.ProcessFunction):
    def process_element(self, value, ctx, out):
        out.collect(value)


class _SumWindow(fn.WindowFunction):
    def process_window(self, key, window, elements, out):
        out.collect(sum(elements))


class _SumFirstWindow(fn.WindowFunction):
    """Sums the integer component of (i, ts) event tuples per window."""

    def process_window(self, key, window, elements, out):
        out.collect(sum(e[0] for e in elements))


class TestChainedExecution:
    def test_one_thread_per_chain_zero_queue_traffic(self):
        env = StreamExecutionEnvironment(parallelism=1)
        out = (
            env.from_collection(list(range(50)), parallelism=1)
            .map(lambda x: x * 2, name="dbl")
            .filter(lambda x: x % 4 == 0, name="quad")
            .sink_to_list()
        )
        ex = env._make_executor()
        assert len(ex.subtasks) == 1          # one THREAD for the chain
        assert ex.total_subtasks == 4         # four LOGICAL operators
        assert ex._gates == []                # no queue anywhere
        ex.run(timeout=60)
        assert sorted(out) == [x * 2 for x in range(50) if (x * 2) % 4 == 0]
        # Per-edge gauges are the no-traffic witness: none exist because
        # no edge has a queue.
        report = ex.metrics.report()
        assert not [k for k in report if "_queue_puts" in k]

    def test_unchained_comparison_has_queue_traffic(self):
        env = StreamExecutionEnvironment(parallelism=1)
        env.configure(chaining=False)
        out = (
            env.from_collection(list(range(50)), parallelism=1)
            .map(lambda x: x * 2, name="dbl")
            .sink_to_list()
        )
        ex = env._make_executor()
        assert len(ex.subtasks) == 3
        ex.run(timeout=60)
        assert len(out) == 50
        report = ex.metrics.report()
        puts = {k: v for k, v in report.items() if k.endswith("_queue_puts")}
        assert puts, "per-edge gauges must exist for real channels"
        # 50 records + 1 end-of-partition down each of the two edges.
        assert report["dbl.0.edge0_collection_queue_puts"] >= 50
        assert report["collect.0.edge0_dbl_queue_puts"] >= 50

    def test_chaining_on_off_parity(self):
        def run(chaining):
            env = StreamExecutionEnvironment(parallelism=1)
            env.configure(chaining=chaining)
            out = (
                env.from_collection(list(range(40)), parallelism=1)
                .map(lambda x: x + 1, name="inc")
                .flat_map(lambda x: [x, -x], name="fan")
                .sink_to_list()
            )
            env.execute(timeout=60)
            return sorted(out)

        assert run(True) == run(False)

    def test_per_logical_operator_metrics_preserved(self):
        env = StreamExecutionEnvironment(parallelism=1)
        (
            env.from_collection(list(range(30)), parallelism=1)
            .map(lambda x: x, name="ident")
            .filter(lambda x: x % 3 == 0, name="third")
            .sink_to_list(name="sink")
        )
        env.execute(timeout=60)
        rep = env.metric_registry.report()
        assert rep["collection.0.records_out"]["count"] == 30
        assert rep["ident.0.records_in"]["count"] == 30
        assert rep["ident.0.records_out"]["count"] == 30
        assert rep["third.0.records_in"]["count"] == 30
        assert rep["third.0.records_out"]["count"] == 10
        assert rep["sink.0.records_in"]["count"] == 10
        # Per-operator latency timers ticked for every fused member.
        for scope in ("ident.0", "third.0", "sink.0"):
            assert rep[f"{scope}.process_latency_s"]["count"] > 0
        # Chain-shape gauges: 4 members, 3 fused edges, on every scope.
        assert rep["ident.0.chain_length"] == 4
        assert rep["sink.0.chained_edges"] == 3

    def test_watermarks_traverse_chain_in_order(self):
        """Event-time windows fused into the source chain still fire on
        watermark passage with every preceding record processed first."""
        env = StreamExecutionEnvironment(parallelism=1)
        events = [(i, float(i)) for i in range(20)]
        out = (
            env.from_collection(events, parallelism=1)
            .assign_timestamps(lambda e: e[1], watermark_every=2)
            .time_window_all(5.0)
            .apply(_SumFirstWindow(), name="win", parallelism=1)
            .sink_to_list()
        )
        ex = env._make_executor()
        assert len(ex.subtasks) == 1  # fully fused incl. the window
        ex.run(timeout=60)
        # Tumbling [0,5) [5,10) [10,15) [15,20): sums of i per window.
        assert sorted(out) == [sum(range(0, 5)), sum(range(5, 10)),
                               sum(range(10, 15)), sum(range(15, 20))]

    def test_barrier_snapshots_every_chained_operator_in_order(self, tmp_path):
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / "c"))
        env.source_throttle_s = 0.005
        box = [0]
        (
            env.from_collection(list(range(200)), parallelism=1)
            .map(_CountingRichMap(box), name="counted")
            .sink_to_list()
        )
        handle = env.execute_async()
        time.sleep(0.25)
        snapshots = handle.trigger_checkpoint(timeout=30)
        # One snapshot per LOGICAL operator, all cut at the same barrier.
        assert set(snapshots) >= {"collection", "counted", "collect"}
        offset = snapshots["collection"][0]["operator"]["offset"]
        counted = snapshots["counted"][0]["function"]["count"]
        assert 0 < offset < 200, "checkpoint should be mid-stream"
        # The chain is synchronous: everything the source emitted before
        # the barrier was fully processed by the chained map — the two
        # counts agree EXACTLY, no in-flight records.
        assert counted == offset
        handle.cancel()
        handle.wait(timeout=30)

    def test_chained_restore_is_exactly_once(self, tmp_path):
        ckpt = str(tmp_path / "c")
        env1 = StreamExecutionEnvironment(parallelism=1)
        env1.enable_checkpointing(ckpt)
        env1.source_throttle_s = 0.005
        (
            env1.from_collection(list(range(200)), parallelism=1)
            .map(_CountingRichMap(), name="counted")
            .sink_to_list()
        )
        handle = env1.execute_async()
        time.sleep(0.25)
        snaps = handle.trigger_checkpoint(timeout=30)
        assert 0 < snaps["collection"][0]["operator"]["offset"] < 200
        handle.cancel()
        handle.wait(timeout=30)

        env2 = StreamExecutionEnvironment(parallelism=1)
        box = [0]
        out = (
            env2.from_collection(list(range(200)), parallelism=1)
            .map(_CountingRichMap(box), name="counted")
            .sink_to_list()
        )
        env2.execute(restore_from=ckpt, timeout=60)
        # Replay resumes at the restored offset; the map's restored count
        # continues seamlessly: every record counted exactly once.
        assert box[0] == 200
        assert len(out) + snaps["collection"][0]["operator"]["offset"] == 200

    def test_failover_restart_of_chained_job(self, tmp_path):
        from flink_tensorflow_tpu.core.environment import RestartStrategy

        crashed = [False]

        class FailingMap(fn.MapFunction):
            def __init__(self, count=0):
                self.count = count

            def clone(self):
                return FailingMap(self.count)

            def map(self, value):
                self.count += 1
                if not crashed[0] and self.count >= 60:
                    crashed[0] = True
                    raise RuntimeError("injected chain failure")
                return value

            def snapshot_state(self):
                return {"count": self.count}

            def restore_state(self, state):
                self.count = state["count"]

        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / "c"), interval_s=0.05)
        env.source_throttle_s = 0.002
        out = (
            env.from_collection(list(range(150)), parallelism=1)
            .map(FailingMap(), name="fragile")
            .sink_to_list()
        )
        result = env.execute(
            timeout=120, restart_strategy=RestartStrategy(max_restarts=2))
        assert crashed[0]
        assert result.restarts >= 1
        # At-least-once sink emission, exactly-once state replay: every
        # value present, duplicates only from records between the last
        # checkpoint and the crash.
        assert set(out) == set(range(150))


class TestEventDrivenRecordPlane:
    def test_no_timed_poll_constants_remain(self):
        """The 50 ms quanta of BENCH_r05's fixed floor components are
        gone from both layers — waits are condition-variable driven."""
        from flink_tensorflow_tpu.core import channels, runtime

        assert not hasattr(channels, "_POLL_INTERVAL_S")
        assert not hasattr(runtime, "_IDLE_POLL_S")

    def test_blocked_poll_wakes_on_put_immediately(self):
        """A reader parked with NO timeout is woken by the first put —
        the latency of an idle hop is a notify, not a poll quantum."""
        gate = InputGate(1, capacity=8)
        got = []

        def consume():
            got.append(gate.poll(timeout=None))

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.2)  # reader is parked, provably idle
        t0 = time.monotonic()
        gate.put(0, "x")
        t.join(timeout=5.0)
        wake_s = time.monotonic() - t0
        assert got == [(0, "x")]
        assert wake_s < 0.045, (
            f"wakeup took {wake_s * 1e3:.1f}ms — an event-driven gate "
            "must beat the old 50ms poll quantum by an order of magnitude")

    def test_blocked_put_wakes_on_drain(self):
        gate = InputGate(1, capacity=1)
        gate.put(0, "a")
        blocked_s = []

        def writer():
            blocked_s.append(gate.put(0, "b"))

        t = threading.Thread(target=writer)
        t.start()
        time.sleep(0.15)
        assert gate.poll(timeout=1.0) == (0, "a")
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert blocked_s and blocked_s[0] >= 0.1  # backpressure attributed
        assert gate.blocked_put_s >= 0.1

    def test_close_releases_blocked_reader_and_writer(self):
        full = InputGate(1, capacity=1)
        full.put(0, "a")
        w = threading.Thread(target=lambda: full.put(0, "b"))
        w.start()
        time.sleep(0.05)
        full.close()
        w.join(timeout=5.0)
        assert not w.is_alive()

        empty = InputGate(1)
        got = []
        r = threading.Thread(target=lambda: got.append(empty.poll(timeout=None)))
        r.start()
        time.sleep(0.05)
        empty.close()
        r.join(timeout=5.0)
        assert not r.is_alive()
        assert got == [None]


@pytest.mark.slow
class TestLatencyFloorGuard:
    """CI latency-floor regression guard (slow tier): the chained
    forward pipeline must show ZERO inter-operator queue puts, and the
    idle path must be event-driven (no timed 50 ms poll)."""

    def test_two_op_forward_pipeline_floor(self):
        env = StreamExecutionEnvironment(parallelism=1)
        out = (
            env.from_collection(list(range(64)), parallelism=1)
            .map(lambda x: x + 1, name="stage1")
            .map(lambda x: x * 2, name="stage2")
            .sink_to_list()
        )
        ex = env._make_executor()
        assert len(ex.subtasks) == 1
        ex.run(timeout=60)
        assert sorted(out) == [(x + 1) * 2 for x in range(64)]
        report = ex.metrics.report()
        # Zero inter-operator queue traffic, asserted via the per-edge
        # gauges: none exist (no gate was even built), and the gate list
        # is empty.
        edge_puts = {k: v for k, v in report.items()
                     if k.endswith("_queue_puts")}
        assert edge_puts == {}
        assert ex._gates == []
        assert report["stage2.0.chained_edges"] == 3

        # No timed poll in the idle path: a worker chain parked on an
        # empty gate reacts to a put within single-digit milliseconds.
        gate = InputGate(1)
        woke = []

        def park():
            woke.append(gate.poll(timeout=None))

        t = threading.Thread(target=park)
        t.start()
        time.sleep(0.3)
        t0 = time.monotonic()
        gate.put(0, "ping")
        t.join(timeout=5.0)
        assert woke and (time.monotonic() - t0) < 0.045
