"""CompiledMethodRunner — the Session.run replacement.

The reference's ``ModelFunction`` binds a model method to per-record (or
per-window) ``Session.run`` calls across the JNI boundary (SURVEY.md §3.1
hot loop).  The TPU-native engine room:

- ``open()``: place params in HBM once (reference: Session owns variables
  on device).  Optionally pre-warm executables for expected buckets so the
  stream never stalls on a first-fire XLA compile.
- per batch: coerce -> assemble (pad to bucket) -> ONE host->HBM transfer
  -> ONE jitted call -> fetch -> unbatch.  ``jax.jit`` caches one
  executable per bucket shape (the compile cache of SURVEY.md §7 step 3);
  input buffers are donated so XLA reuses their HBM pages for outputs.
- dispatch is async: the jitted call returns futures, and ``run_batch``
  only blocks when fetching results — back-to-back windows overlap host
  batching with device compute.
- ``dispatch_lanes > 1`` runs assemble+transfer+launch on a small thread
  pool.  On tunnel/network-attached devices the host->device wire
  transfer is paid synchronously inside the dispatch call, so one lane
  caps throughput at single-stream wire bandwidth; concurrent lanes
  overlap the transfers of consecutive micro-batches (measured ~2x
  aggregate bandwidth on the axon tunnel).  Results are collected in
  dispatch order regardless of lane completion order.
- result fetches run on a dedicated **fetch thread** (r5): the d2h
  round trip happens in the background the moment a batch's lane work
  resolves, so the subtask thread only ever drains already-fetched
  results.  The r4 decomposition showed the poll-then-fetch path
  serializing one full transport round trip per window AFTER readiness
  (fetch p50 110.9ms ≈ the 93.3ms fixed call RTT) — and on the axon
  tunnel ``is_ready`` can ack before completion, so a readiness-gated
  fetch may block arbitrarily anyway.  The fetch thread also removes
  the need for readiness polling entirely: a blocking fetch IS the
  completion signal.
- **double-buffered transfers** (r6): even at ``dispatch_lanes=1`` the
  assemble+h2d+launch runs on a small lane pool (2 workers) instead of
  the subtask thread, so the h2d of batch N+1 overlaps the device
  compute of batch N AND the subtask thread stays free to accept
  arrivals — ``lane_wait``/``ready_wait`` stalls shrink to the pool
  queue.  ``double_buffer=False`` restores the inline single-lane path.
- **device-resident dataflow** (r6): with ``emit_device_batches`` set
  (wired by the executor when the next chained operator accepts device
  batches), the fetch thread does NOT fetch — it waits for compute via
  ``block_until_ready`` and hands out ONE
  :class:`~flink_tensorflow_tpu.tensors.transfer.DeviceBatch` whose
  arrays stay in HBM; the d2h is elided until the first host-only
  consumer materializes (trace: ``d2h.elided`` instant here, the
  deferred ``d2h`` span at the boundary).  Symmetrically,
  ``dispatch_device`` consumes an upstream DeviceBatch with NO h2d
  (``h2d.elided``), so a model->model chain pays the wire exactly once
  per direction end to end.  ``wire_dtype`` ("bf16"/"f16") narrows the
  h2d bytes of batches that DO cross, with the declared dtype restored
  inside the jitted call (the upcast fuses into the executable).
"""

from __future__ import annotations

import collections
import concurrent.futures
import functools
import threading
import time
import typing

from flink_tensorflow_tpu.models.base import Model
from flink_tensorflow_tpu.tensors.batching import Batch, BucketPolicy, assemble
from flink_tensorflow_tpu.tensors.coercion import coerce
from flink_tensorflow_tpu.tensors.transfer import DeviceTransfer
from flink_tensorflow_tpu.tensors.value import TensorValue
from flink_tensorflow_tpu.utils.profiling import annotate_batch

if typing.TYPE_CHECKING:
    from flink_tensorflow_tpu.core.runtime_context import RuntimeContext


@functools.lru_cache(maxsize=64)
def _build_decode_calls(prefill_fn, decode_fn, capacity: int):
    """Jitted (prefill_into, step_full, step_exact) per (model methods,
    capacity) — cached at MODULE level so every DecodeStepRunner built
    over the same model (a restarted job, the bench's comparison arms,
    parallel subtasks) reuses the same callables and therefore jax's
    compiled executables: the 1-3s decode/prefill compiles are paid
    once per process, not once per operator open()."""
    import jax

    def prefill_into(params, tokens, lengths, slots, kc, vc):
        import jax.numpy as jnp

        out = prefill_fn(params, {"tokens": tokens, "lengths": lengths})
        t = tokens.shape[1]
        pad = capacity - t
        k_new, v_new = out["k_cache"], out["v_cache"]
        if pad:
            widths = ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
            k_new = jnp.pad(k_new, widths)
            v_new = jnp.pad(v_new, widths)
        # Bucket-padding rows carry slot == S: out of range, dropped.
        kc = kc.at[slots].set(k_new, mode="drop")
        vc = vc.at[slots].set(v_new, mode="drop")
        return out["next_token"], kc, vc

    def step_full(params, tokens, lengths, mask, kc, vc):
        import jax.numpy as jnp

        out = decode_fn(params, {
            "token": tokens, "lengths": lengths,
            "k_cache": kc, "v_cache": vc,
        })
        keep = mask[:, None, None, None, None]
        return (out["next_token"],
                jnp.where(keep, out["k_cache"], kc),
                jnp.where(keep, out["v_cache"], vc))

    def step_exact(params, tokens, lengths, slots, kc, vc):
        out = decode_fn(params, {
            "token": tokens, "lengths": lengths,
            "k_cache": kc[slots], "v_cache": vc[slots],
        })
        return (out["next_token"],
                kc.at[slots].set(out["k_cache"]),
                vc.at[slots].set(out["v_cache"]))

    return (jax.jit(prefill_into, donate_argnums=(4, 5)),
            jax.jit(step_full, donate_argnums=(4, 5)),
            jax.jit(step_exact, donate_argnums=(4, 5)))


class DecodeStepRunner:
    """Autoregressive decode dispatch — CompiledMethodRunner's sibling
    for the serving plane (flink_tensorflow_tpu/serving/).

    Where CompiledMethodRunner pays one h2d + one compute + one d2h per
    micro-batch, generation threads a KV cache through EVERY step, so
    the residency rules invert:

    - the cache POOL (``[S, L, C, H, Dh]`` K/V arrays, one row per
      active-session slot) lives in HBM for the runner's whole life and
      is DONATED into each jitted step — XLA updates it in place, and
      the only h2d per decode step is the ``[S]`` int32 token/length
      vectors (bytes counted in ``step_h2d_bytes``; the serving tests'
      one-h2d-per-admitted-token guard reads exactly this);
    - greedy argmax runs INSIDE the jitted methods, so the only d2h per
      step is ``[S]`` int32 next-tokens;
    - per-session cache blocks cross the pool boundary only at
      admission (``insert_block`` — h2d iff the block is host-resident)
      and extraction (``extract_block`` — d2h iff the caller asks for
      host form; barriers do, device-resident preemption doesn't).

    Shape discipline: with ``padding_buckets`` the decode step always
    runs the FULL pool shape ``[S]`` (inactive rows masked — one
    executable, ever) and prefill shapes quantize to the admit x
    prompt-length bucket grid; without it, every distinct active count
    and prompt length compiles fresh — the churn the
    ``serving-recompile-churn`` lint flags.

    The model contributes two typed methods (models/zoo/chartransformer
    is the reference instance):

    - ``prefill``:     ``{tokens [B, T], lengths [B]}`` ->
      ``{next_token [B], k_cache [B, L, T, H, Dh], v_cache ...}``
    - ``decode_step``: ``{token [B], lengths [B], k_cache, v_cache}`` ->
      same outputs with the caches grown by one position.
    """

    def __init__(
        self,
        model: Model,
        *,
        pool_slots: int,
        capacity: int,
        padding_buckets: bool = True,
        prompt_buckets: typing.Optional[typing.Sequence[int]] = None,
        device=None,
    ):
        self.model = model
        self.pool_slots = pool_slots
        self.capacity = capacity
        self.padding_buckets = padding_buckets
        self.prompt_buckets = tuple(prompt_buckets or ())
        self.device = device
        self._prefill = model.method("prefill")
        self._decode = model.method("decode_step")
        self._params_on_device = None
        self._kc = None       # [S, L, C, H, Dh] jax arrays (lazy, first prefill)
        self._vc = None
        self._prefill_fn = None
        self._step_full_fn = None
        self._step_exact_fn = None
        self._metrics = None
        self._tracer = None
        self._roofline = None
        self._trace_track: typing.Optional[str] = None
        #: Plain counters (mirrored to the metric plane when open(ctx)
        #: wired one): the serving tests' residency guards read these.
        self.step_h2d_bytes = 0
        self.block_h2d_events = 0     # host block -> pool (admission/restore)
        self.block_d2h_events = 0     # pool -> host block (barrier/preempt)
        self.device_block_moves = 0   # pool <-> DeviceKVBlock (no host touch)

    # -- lifecycle ---------------------------------------------------------
    def open(self, ctx: typing.Optional["RuntimeContext"] = None) -> None:
        import jax

        if ctx is not None:
            if self.device is None and ctx.device is not None:
                self.device = ctx.device
            self._metrics = ctx.metrics
            self._tracer = getattr(ctx, "tracer", None)
            if self._tracer is not None:
                self._trace_track = f"{ctx.task_name}.{ctx.subtask_index}"
            plane = getattr(ctx, "roofline", None)
            if plane is not None:
                # Per-operator roofline probe: joins each measured
                # prefill/decode step against the plan's CostTable and
                # publishes roofline.* gauges on this subtask's scope.
                self._roofline = plane.probe(ctx.task_name,
                                             metrics=ctx.metrics)
        self._params_on_device = jax.device_put(self.model.params, self.device)
        self._build_calls()

    def _build_calls(self) -> None:
        (self._prefill_fn, self._step_full_fn,
         self._step_exact_fn) = _build_decode_calls(
            self._prefill.fn, self._decode.fn, self.capacity)

    def close(self) -> None:
        self._params_on_device = None
        self._kc = self._vc = None
        self._prefill_fn = self._step_full_fn = self._step_exact_fn = None

    def warmup(self, admit_buckets: typing.Sequence[int],
               prompt_buckets: typing.Sequence[int]) -> None:
        """Pre-compile every (admit x prompt-length) prefill bucket plus
        the decode step, so the first live session never pays an XLA
        compile inside its measured latency.  Warmup rows scatter to the
        out-of-range slot (dropped) and the warm decode runs fully
        masked — the pool stays clean.  Counters, metrics, and stage
        spans are suppressed (compile time must not masquerade as
        steady-state transfer cost), mirroring CompiledMethodRunner.
        Only meaningful under padding buckets — exact-shape mode churns
        by design and has nothing finite to warm."""
        import numpy as np

        if not self.padding_buckets:
            return
        metrics, self._metrics = self._metrics, None
        tracer, self._tracer = self._tracer, None
        saved = (self.step_h2d_bytes, self.block_h2d_events,
                 self.block_d2h_events, self.device_block_moves)
        t_warm = time.monotonic()
        if self._roofline is not None:
            # Warmup compiles still log compile events (trigger =
            # "warmup"), but none of the throughput accounting.
            self._roofline.begin_warmup()
        try:
            for b in admit_buckets:
                for t in prompt_buckets:
                    t = min(t, self.capacity)
                    self.prefill([np.ones((t,), np.int32)], [t],
                                 [self.pool_slots], batch_bucket=b)
            self.decode_step([0] * self.pool_slots, [0] * self.pool_slots, [])
        finally:
            if self._roofline is not None:
                self._roofline.end_warmup()
            self._metrics = metrics
            self._tracer = tracer
            (self.step_h2d_bytes, self.block_h2d_events,
             self.block_d2h_events, self.device_block_moves) = saved
            if tracer is not None:
                tracer.span(self._trace_track, "jit_warmup_compile",
                            t_warm, time.monotonic(),
                            args={"admit_buckets": list(admit_buckets),
                                  "prompt_buckets": list(prompt_buckets)})

    @property
    def pool_built(self) -> bool:
        return self._kc is not None

    def _ensure_pool(self, k_like) -> None:
        """Allocate the pool on first use, shaped after one session's
        cache ``[L, C, H, Dh]`` (shape knowledge lives in the model)."""
        import jax
        import jax.numpy as jnp

        if self._kc is not None:
            return
        # k_like: [B, L, T, H, Dh] for any T <= capacity — the pool is
        # always allocated at FULL capacity (one decode shape, ever).
        _, layers, _, heads, hd = k_like.shape
        shape = (self.pool_slots, layers, self.capacity, heads, hd)
        # Two DISTINCT buffers: the jitted step donates both pools, and
        # aliased zeros would be one buffer donated twice.
        self._kc = jax.device_put(jnp.zeros(shape, k_like.dtype), self.device)
        self._vc = jax.device_put(jnp.zeros(shape, k_like.dtype), self.device)

    # -- dispatch ----------------------------------------------------------
    def _bucket_len(self, n: int) -> int:
        if not self.padding_buckets:
            return max(1, n)
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return self.capacity

    def prefill(self, prompts: typing.Sequence, lengths: typing.Sequence[int],
                slots: typing.Sequence[int],
                *, batch_bucket: typing.Optional[int] = None):
        """Prefill newly admitted sessions into their pool slots.

        ``prompts``: per-session int32 token arrays; ``slots``: their
        pool rows.  Returns the per-session first generated token (host
        int32, in order).  Shapes quantize to (batch_bucket x
        prompt-length bucket) under ``padding_buckets``."""
        import jax
        import numpy as np

        n = len(prompts)
        b = batch_bucket or n
        t = self._bucket_len(max(int(x) for x in lengths))
        tokens = np.zeros((b, t), np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
        lens = np.zeros((b,), np.int32)
        lens[:n] = np.asarray(lengths, np.int32)
        slot_arr = np.full((b,), self.pool_slots, np.int32)  # pad rows drop
        slot_arr[:n] = np.asarray(slots, np.int32)
        t0 = time.monotonic()
        if self._kc is None:
            # Bootstrap: run the raw prefill once to learn the cache
            # shape, then scatter through the jitted path like any
            # other call (one extra compile, first admission only).
            out = jax.jit(self._prefill.fn)(
                self._params_on_device,
                {"tokens": jax.device_put(tokens, self.device),
                 "lengths": jax.device_put(lens, self.device)})
            self._ensure_pool(out["k_cache"])
        next_tok, self._kc, self._vc = self._prefill_fn(
            self._params_on_device,
            jax.device_put(tokens, self.device),
            jax.device_put(lens, self.device),
            jax.device_put(slot_arr, self.device),
            self._kc, self._vc,
        )
        host = np.asarray(jax.device_get(next_tok))[:n]
        t1 = time.monotonic()
        self.step_h2d_bytes += tokens.nbytes + lens.nbytes + slot_arr.nbytes
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "decode.prefill", t0, t1,
                              args={"batch": n, "bucket": [b, t]})
        if self._metrics is not None:
            self._metrics.histogram("prefill_s").record(t1 - t0)
            self._metrics.counter("prefill_batches").inc()
        if self._roofline is not None:
            self._roofline.observe(
                "prefill", t1 - t0, signature=f"prefill:{b}x{t}",
                h2d_bytes=tokens.nbytes + lens.nbytes + slot_arr.nbytes,
                d2h_bytes=b * 4)
        return host

    def decode_step(self, tokens_by_slot, lengths_by_slot, active_slots):
        """One decode step over the pool.

        ``tokens_by_slot``/``lengths_by_slot``: ``[S]`` int32 host
        arrays (inactive rows 0); ``active_slots``: the slots whose
        results matter.  Returns ``[S]`` next tokens (host int32).
        """
        import jax
        import numpy as np

        if self._kc is None:
            raise RuntimeError("decode_step before any prefill")
        t0 = time.monotonic()
        h2d_before = self.step_h2d_bytes
        if self.padding_buckets:
            mask = np.zeros((self.pool_slots,), bool)
            mask[list(active_slots)] = True
            args = (jax.device_put(np.asarray(tokens_by_slot, np.int32), self.device),
                    jax.device_put(np.asarray(lengths_by_slot, np.int32), self.device),
                    jax.device_put(mask, self.device))
            self.step_h2d_bytes += (len(tokens_by_slot) * 4
                                    + len(lengths_by_slot) * 4
                                    + mask.nbytes)
            next_tok, self._kc, self._vc = self._step_full_fn(
                self._params_on_device, *args, self._kc, self._vc)
            out = np.asarray(jax.device_get(next_tok))
        else:
            slots = np.asarray(sorted(active_slots), np.int32)
            toks = np.asarray([tokens_by_slot[s] for s in slots], np.int32)
            lens = np.asarray([lengths_by_slot[s] for s in slots], np.int32)
            self.step_h2d_bytes += toks.nbytes + lens.nbytes + slots.nbytes
            next_tok, self._kc, self._vc = self._step_exact_fn(
                self._params_on_device,
                jax.device_put(toks, self.device),
                jax.device_put(lens, self.device),
                jax.device_put(slots, self.device),
                self._kc, self._vc)
            got = np.asarray(jax.device_get(next_tok))
            out = np.zeros((self.pool_slots,), np.int32)
            out[slots] = got
        t1 = time.monotonic()
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "decode.step", t0, t1,
                              args={"active": len(active_slots)})
        if self._metrics is not None:
            self._metrics.histogram("decode_step_s").record(t1 - t0)
            self._metrics.counter("decode_steps").inc()
        if self._roofline is not None:
            # Padded mode always presents the one [S] signature; exact
            # mode churns by design — each active-set size is its own
            # (unpriced, unpredicted) signature.
            sig = (f"decode:{self.pool_slots}" if self.padding_buckets
                   else f"decode:{len(active_slots)}")
            self._roofline.observe(
                "decode_step", t1 - t0, signature=sig,
                h2d_bytes=self.step_h2d_bytes - h2d_before,
                d2h_bytes=int(out.nbytes))
        return out

    # -- block movement (keyed-state residency boundary) -------------------
    def extract_block(self, slot: int, length: int, *, host: bool):
        """One session's cache out of the pool.

        ``host=True`` forces the d2h (barrier snapshots — the cache
        must pickle); ``host=False`` returns live device slices (a
        device-resident preemption: the block parks in keyed state
        without touching the wire).  Returns ``(k, v)``."""
        import jax

        k, v = self._kc[slot], self._vc[slot]
        if not host:
            self.device_block_moves += 1
            if self._tracer is not None:
                self._tracer.instant(self._trace_track, "cache.resident",
                                     args={"slot": slot, "length": length})
            return k, v
        t0 = time.monotonic()
        k, v = jax.device_get((k, v))
        t1 = time.monotonic()
        self.block_d2h_events += 1
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "cache.d2h", t0, t1,
                              args={"slot": slot, "length": length,
                                    "bytes": int(k.nbytes + v.nbytes)})
        if self._roofline is not None:
            # Tier-move transfer: priced against the plan's cache_move
            # entries WITHOUT minting a compile event — block moves are
            # data motion, not executables (the PR-17 "non-runner h2d
            # attribution" deferral).
            self._roofline.observe_transfer(
                "cache_move", t1 - t0, signature="cache:block",
                d2h_bytes=int(k.nbytes + v.nbytes))
        return k, v

    def insert_block(self, slot: int, k, v) -> None:
        """One session's cache back into the pool.  Host arrays pay the
        h2d here (admission after restore / host-mode preemption);
        device arrays scatter device-side — zero host traffic."""
        import numpy as np

        if self._kc is None:
            import jax.numpy as jnp

            self._ensure_pool(jnp.asarray(k)[None])
        is_host = isinstance(k, np.ndarray)
        t0 = time.monotonic()
        self._kc = self._kc.at[slot].set(k)
        self._vc = self._vc.at[slot].set(v)
        t1 = time.monotonic()
        if is_host:
            self.block_h2d_events += 1
            if self._tracer is not None:
                self._tracer.span(self._trace_track, "cache.h2d", t0, t1,
                                  args={"slot": slot,
                                        "bytes": int(k.nbytes + v.nbytes)})
            if self._roofline is not None:
                self._roofline.observe_transfer(
                    "cache_move", t1 - t0, signature="cache:block",
                    h2d_bytes=int(k.nbytes + v.nbytes))
        else:
            self.device_block_moves += 1
            if self._tracer is not None:
                self._tracer.instant(self._trace_track, "cache.resident",
                                     args={"slot": slot})


@functools.lru_cache(maxsize=64)
def _build_paged_calls(prefill_fn, decode_fn, capacity: int,
                       page_tokens: int, num_pages: int):
    """Jitted (paged_prefill_into, paged_step, copy_page) per (model
    methods, capacity, page geometry) — module-level cache for the same
    reason as :func:`_build_decode_calls`: restarted jobs, comparison
    bench arms, and parallel subtasks all reuse the compiled
    executables.

    The paged step is gather -> dense kernel -> scatter
    (ops/paged_attention.py): the decode/prefill MATH is byte-for-byte
    the model's existing methods over a materialized dense view, which
    is what makes paged output bit-identical to the dense pool on the
    same schedule.  Sentinel table entries (``num_pages``) clamp on
    gather (garbage masked by lengths) and drop on scatter, so inactive
    rows, bucket-padding rows, and prefix-SHARED pages (sentinel in the
    prefill scatter table — the first writer's bytes stay authoritative)
    all ride the one padded signature with no mask argument."""
    import jax

    from flink_tensorflow_tpu.ops.paged_attention import (
        gather_pages,
        scatter_pages,
    )

    def prefill_into(params, tokens, lengths, tables, kp, vp):
        import jax.numpy as jnp

        out = prefill_fn(params, {"tokens": tokens, "lengths": lengths})
        t = tokens.shape[1]
        pad = capacity - t
        k_new, v_new = out["k_cache"], out["v_cache"]
        if pad:
            widths = ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
            k_new = jnp.pad(k_new, widths)
            v_new = jnp.pad(v_new, widths)
        kp = scatter_pages(kp, tables, k_new, page_tokens)
        vp = scatter_pages(vp, tables, v_new, page_tokens)
        return out["next_token"], kp, vp

    def step(params, tokens, lengths, tables, kp, vp):
        kc = gather_pages(kp, tables)
        vc = gather_pages(vp, tables)
        out = decode_fn(params, {
            "token": tokens, "lengths": lengths,
            "k_cache": kc, "v_cache": vc,
        })
        kp = scatter_pages(kp, tables, out["k_cache"], page_tokens)
        vp = scatter_pages(vp, tables, out["v_cache"], page_tokens)
        return out["next_token"], kp, vp

    def copy_page(src, dst, kp, vp):
        # The copy-on-write split: duplicate one page device-side
        # before a write into shared bytes.  Scalar int32 src/dst trace
        # once — one executable for every split.
        return kp.at[dst].set(kp[src]), vp.at[dst].set(vp[src])

    return (jax.jit(prefill_into, donate_argnums=(4, 5)),
            jax.jit(step, donate_argnums=(4, 5)),
            jax.jit(copy_page, donate_argnums=(2, 3)))


class PagedDecodeStepRunner(DecodeStepRunner):
    """Paged variant of :class:`DecodeStepRunner`: the HBM pool is
    ``num_pages`` fixed-size pages ``[P, L, page_tokens, H, Dh]`` and
    every active slot carries a block table instead of owning a
    contiguous ``[L, C, H, Dh]`` row.

    What changes at the dispatch boundary: the per-step int32 h2d grows
    the ``[S, C/page_tokens]`` block tables alongside the token/length
    vectors (the tables ARE host state — they re-serialize every step,
    which is what keeps them out of the donation cycle), the pool is
    still donated through the jitted step, and admission needs FREE
    PAGES, not a slot-shaped hole.  The host-side policy objects
    (:class:`~flink_tensorflow_tpu.serving.paged.PagedKVPool` free
    list/refcounts, the radix prefix index) live on this runner; the
    serving operator drives them through the block-movement methods
    below (park/attach for hot preemption, insert/extract for the
    warm/cold tiers, ``ensure_writable`` for the copy-on-write check
    before each step's write position).

    Paged mode requires ``padding_buckets`` — the whole point is ONE
    decode signature over the padded pool; exact-shape churn would
    recompile per active-set size with the table width riding along."""

    def __init__(
        self,
        model: Model,
        *,
        pool_slots: int,
        capacity: int,
        page_tokens: int = 16,
        num_pages: typing.Optional[int] = None,
        prefix_sharing: bool = True,
        padding_buckets: bool = True,
        prompt_buckets: typing.Optional[typing.Sequence[int]] = None,
        device=None,
    ):
        from flink_tensorflow_tpu.ops.paged_attention import (
            pages_per_session,
        )
        from flink_tensorflow_tpu.serving.paged import (
            PagedKVPool,
            RadixPrefixIndex,
        )

        if not padding_buckets:
            raise ValueError(
                "paged KV requires padding_buckets — the paged step has "
                "exactly one [S, C/page_tokens] signature by design")
        super().__init__(model, pool_slots=pool_slots, capacity=capacity,
                         padding_buckets=padding_buckets,
                         prompt_buckets=prompt_buckets, device=device)
        self.page_tokens = page_tokens
        self.table_width = pages_per_session(capacity, page_tokens)
        self.num_pages = (num_pages if num_pages is not None
                          else pool_slots * self.table_width)
        if self.num_pages < self.table_width:
            raise ValueError(
                f"hbm_pages {self.num_pages} cannot seat even one "
                f"full-capacity session ({self.table_width} pages) — "
                "grow the pool or shrink capacity")
        self.pool = PagedKVPool(self.num_pages, page_tokens)
        self.index = RadixPrefixIndex(self.pool) if prefix_sharing else None
        #: Active slot -> block table (logical page i at position i).
        self._tables: typing.Dict[int, typing.List[int]] = {}
        self._paged_prefill_fn = None
        self._paged_step_fn = None
        self._copy_page_fn = None

    def _build_calls(self) -> None:
        (self._paged_prefill_fn, self._paged_step_fn,
         self._copy_page_fn) = _build_paged_calls(
            self._prefill.fn, self._decode.fn, self.capacity,
            self.page_tokens, self.num_pages)

    def close(self) -> None:
        super().close()
        self._paged_prefill_fn = self._paged_step_fn = None
        self._copy_page_fn = None
        self._tables.clear()

    # -- pool geometry -----------------------------------------------------
    def _ensure_pool(self, k_like) -> None:
        import jax
        import jax.numpy as jnp

        if self._kc is not None:
            return
        _, layers, _, heads, hd = k_like.shape
        shape = (self.num_pages, layers, self.page_tokens, heads, hd)
        # Two DISTINCT buffers, same donation reasoning as the dense pool.
        self._kc = jax.device_put(jnp.zeros(shape, k_like.dtype), self.device)
        self._vc = jax.device_put(jnp.zeros(shape, k_like.dtype), self.device)

    def page_nbytes(self) -> typing.Optional[int]:
        """K+V bytes of ONE page (None before the pool is built)."""
        if self._kc is None:
            return None
        per = 1
        for d in self._kc.shape[1:]:
            per *= d
        return 2 * per * self._kc.dtype.itemsize

    def _alloc(self, n: int) -> typing.Optional[typing.List[int]]:
        """Allocate ``n`` pages, evicting index-only pages LRU under
        pressure; None when the pool is genuinely out (the caller's
        tier machinery demotes parked sessions and retries)."""
        if n <= 0:
            return []
        got = self.pool.alloc(n)
        if got is None and self.index is not None:
            self.index.evict_until(n)
            got = self.pool.alloc(n)
        return got

    def free_pages_evictable(self) -> int:
        """Free pages plus what index eviction could free — the
        admission gate's optimistic bound."""
        free = self.pool.free_pages
        if self.index is not None:
            free += sum(1 for _, _, node in self.index._leaves()
                        if self.pool.refs[node.page] == 1)
        return free

    # -- dispatch ----------------------------------------------------------
    def prefill(self, prompts: typing.Sequence, lengths: typing.Sequence[int],
                slots: typing.Sequence[int],
                *, batch_bucket: typing.Optional[int] = None):
        """Paged prefill: per session, adopt prefix pages from the
        radix index (refcount bump, zero compute), allocate the rest,
        and scatter the freshly computed K/V ONLY into owned pages (the
        scatter table carries the sentinel where pages are shared —
        the first writer's bytes stay authoritative, which is the
        byte-identity argument for prefix sharing)."""
        import jax
        import numpy as np

        n = len(prompts)
        b = batch_bucket or n
        t = self._bucket_len(max(int(x) for x in lengths))
        tokens = np.zeros((b, t), np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
        lens = np.zeros((b,), np.int32)
        lens[:n] = np.asarray(lengths, np.int32)
        # Scatter tables: sentinel everywhere a page is NOT owned by the
        # prefilling session (pad rows, beyond-allocation, adopted).
        scatter = np.full((b, self.table_width), self.num_pages, np.int32)
        adopted_pages = 0
        for i, (p, ln, slot) in enumerate(zip(prompts, lengths, slots)):
            slot = int(slot)
            if slot >= self.pool_slots:
                continue  # warmup pad row: all-sentinel, pure compile
            adopted: typing.List[int] = []
            if self.index is not None:
                full, partial = self.index.match(p)
                adopted = full + ([partial] if partial is not None else [])
            own_n = self.pool.pages_for(int(ln)) - len(adopted)
            own = self._alloc(own_n)
            if own is None:
                self.pool.release(adopted)
                raise RuntimeError(
                    f"paged KV pool exhausted at prefill: need {own_n} "
                    f"pages, {self.pool.free_pages} free — the admission "
                    "gate should have held this session back")
            table = adopted + own
            self._tables[slot] = table
            adopted_pages += len(adopted)
            for j in range(len(adopted), len(table)):
                scatter[i, j] = table[j]
        t0 = time.monotonic()
        if self._kc is None:
            # Bootstrap: one raw prefill to learn the cache shape (same
            # one-extra-compile cost as the dense runner's first call).
            out = jax.jit(self._prefill.fn)(
                self._params_on_device,
                {"tokens": jax.device_put(tokens, self.device),
                 "lengths": jax.device_put(lens, self.device)})
            self._ensure_pool(out["k_cache"])
        next_tok, self._kc, self._vc = self._paged_prefill_fn(
            self._params_on_device,
            jax.device_put(tokens, self.device),
            jax.device_put(lens, self.device),
            jax.device_put(scatter, self.device),
            self._kc, self._vc,
        )
        host = np.asarray(jax.device_get(next_tok))[:n]
        t1 = time.monotonic()
        h2d = tokens.nbytes + lens.nbytes + scatter.nbytes
        self.step_h2d_bytes += h2d
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "decode.prefill", t0, t1,
                              args={"batch": n, "bucket": [b, t],
                                    "pages_shared": adopted_pages})
        if self._metrics is not None:
            self._metrics.histogram("prefill_s").record(t1 - t0)
            self._metrics.counter("prefill_batches").inc()
        if self._roofline is not None:
            self._roofline.observe(
                "prefill", t1 - t0, signature=f"prefill:{b}x{t}",
                h2d_bytes=h2d, d2h_bytes=b * 4)
        return host

    def decode_step(self, tokens_by_slot, lengths_by_slot, active_slots):
        """One paged decode step: the block tables ride the per-step
        int32 h2d alongside the token/length vectors; rows without a
        table (inactive, warmup) go all-sentinel and no-op through the
        gather/scatter."""
        import jax
        import numpy as np

        if self._kc is None:
            raise RuntimeError("decode_step before any prefill")
        t0 = time.monotonic()
        s = self.pool_slots
        tables = np.full((s, self.table_width), self.num_pages, np.int32)
        for slot, table in self._tables.items():
            tables[slot, :len(table)] = table
        toks = np.asarray(tokens_by_slot, np.int32)
        lens = np.asarray(lengths_by_slot, np.int32)
        h2d = toks.nbytes + lens.nbytes + tables.nbytes
        self.step_h2d_bytes += h2d
        next_tok, self._kc, self._vc = self._paged_step_fn(
            self._params_on_device,
            jax.device_put(toks, self.device),
            jax.device_put(lens, self.device),
            jax.device_put(tables, self.device),
            self._kc, self._vc)
        out = np.asarray(jax.device_get(next_tok))
        t1 = time.monotonic()
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "decode.step", t0, t1,
                              args={"active": len(active_slots)})
        if self._metrics is not None:
            self._metrics.histogram("decode_step_s").record(t1 - t0)
            self._metrics.counter("decode_steps").inc()
        if self._roofline is not None:
            self._roofline.observe(
                "decode_step", t1 - t0, signature=f"decode:{s}",
                h2d_bytes=h2d, d2h_bytes=int(out.nbytes))
        return out

    # -- copy-on-write / growth -------------------------------------------
    def ensure_writable(self, slot: int, length: int) -> bool:
        """Guarantee the page holding write position ``length`` exists
        and is exclusively owned before the step runs.  Allocates the
        next page at a page boundary; splits a shared page
        (copy-on-write) when the write would land in bytes the prefix
        index or another session still references.  False = the pool is
        out of pages even after index eviction — the operator's tier
        machinery must free pressure and retry."""
        table = self._tables[slot]
        li = length // self.page_tokens
        while len(table) <= li:
            got = self._alloc(1)
            if got is None:
                return False
            table.extend(got)
        pid = table[li]
        if self.pool.is_shared(pid):
            got = self._alloc(1)
            if got is None:
                return False
            self._copy_page(pid, got[0])
            self.pool.decref(pid)
            self.pool.cow_splits += 1
            table[li] = got[0]
        return True

    def _copy_page(self, src: int, dst: int) -> None:
        import numpy as np

        self._kc, self._vc = self._copy_page_fn(
            np.int32(src), np.int32(dst), self._kc, self._vc)
        if self._tracer is not None:
            self._tracer.instant(self._trace_track, "cache.cow",
                                 args={"src": src, "dst": dst})

    # -- block movement (tier-ladder boundary) -----------------------------
    def park(self, slot: int, length: int):
        """Hot preemption: the session's pages STAY in HBM behind a
        :class:`~flink_tensorflow_tpu.serving.paged.PagedKVHandle`;
        only the block table leaves the step batch.  Zero traffic —
        the paged analogue of the dense device-resident preemption."""
        from flink_tensorflow_tpu.serving.paged import PagedKVHandle

        table = self._tables.pop(slot)
        self.device_block_moves += 1
        if self._tracer is not None:
            self._tracer.instant(self._trace_track, "cache.resident",
                                 args={"slot": slot, "length": length,
                                       "pages": len(table)})
        return PagedKVHandle(table, length)

    def attach(self, slot: int, handle) -> None:
        """Re-admission of a hot-parked session: re-attach the table."""
        self._tables[slot] = list(handle.pages)
        self.device_block_moves += 1
        if self._tracer is not None:
            self._tracer.instant(self._trace_track, "cache.resident",
                                 args={"slot": slot, "pages":
                                       len(handle.pages)})

    def _gather_host(self, pages: typing.Sequence[int], length: int):
        """Pages -> dense host ``[L, C, H, Dh]`` K/V (zero-fill beyond
        the allocated pages; positions past ``length`` are masked by
        every consumer).  Returns ``(k, v, wire_bytes)`` — only the
        gathered pages cross the wire; the capacity pad is minted
        host-side and must not count as transfer traffic."""
        import jax
        import numpy as np

        from flink_tensorflow_tpu.ops.paged_attention import pages_to_dense

        ids = np.asarray(pages, np.int32)
        k_pages, v_pages = jax.device_get(
            (self._kc[ids], self._vc[ids]))
        wire_bytes = int(k_pages.nbytes + v_pages.nbytes)
        k = pages_to_dense(np.asarray(k_pages)[None])[0]
        v = pages_to_dense(np.asarray(v_pages)[None])[0]
        layers, got, heads, hd = k.shape
        if got < self.capacity:
            pad = np.zeros((layers, self.capacity - got, heads, hd), k.dtype)
            k = np.concatenate([k, pad], axis=1)
            v = np.concatenate([v, pad], axis=1)
        return k, v, wire_bytes

    def snapshot_block(self, slot: int, length: int):
        """Barrier copy of an ACTIVE session: dense host K/V, pages
        untouched (the pool stays authoritative — same contract as the
        dense ``extract_block(host=True)`` at a barrier)."""
        t0 = time.monotonic()
        k, v, wire = self._gather_host(self._tables[slot], length)
        t1 = time.monotonic()
        self.block_d2h_events += 1
        n = len(self._tables[slot])
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "cache.d2h", t0, t1,
                              args={"slot": slot, "length": length,
                                    "pages": n, "bytes": wire})
        if self._roofline is not None:
            self._roofline.observe_transfer(
                "cache_move", t1 - t0, signature=f"cache:pages:{n}",
                d2h_bytes=wire)
        return k, v

    def extract_host(self, slot: int, length: int):
        """Demotion of an ACTIVE session (pressure preemption to the
        warm tier): dense host K/V out, pages released."""
        table = self._tables.pop(slot)
        t0 = time.monotonic()
        k, v, wire = self._gather_host(table, length)
        t1 = time.monotonic()
        self.pool.release(table)
        self.block_d2h_events += 1
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "cache.d2h", t0, t1,
                              args={"slot": slot, "length": length,
                                    "pages": len(table), "bytes": wire})
        if self._roofline is not None:
            self._roofline.observe_transfer(
                "cache_move", t1 - t0,
                signature=f"cache:pages:{len(table)}",
                d2h_bytes=wire)
        return k, v

    def demote_handle(self, handle):
        """Hot -> warm: a PARKED session's pages gather d2h into a host
        :class:`~flink_tensorflow_tpu.serving.kv_cache.KVBlock` and
        free."""
        from flink_tensorflow_tpu.serving.kv_cache import KVBlock

        t0 = time.monotonic()
        k, v, wire = self._gather_host(handle.pages, handle.length)
        t1 = time.monotonic()
        self.pool.release(handle.pages)
        self.block_d2h_events += 1
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "cache.d2h", t0, t1,
                              args={"length": handle.length,
                                    "pages": len(handle.pages),
                                    "bytes": wire})
        if self._roofline is not None:
            self._roofline.observe_transfer(
                "cache_move", t1 - t0,
                signature=f"cache:pages:{len(handle.pages)}",
                d2h_bytes=wire)
        return KVBlock(k, v, handle.length)

    def insert_block(self, slot: int, k, v,
                     length: typing.Optional[int] = None) -> None:
        """Warm/cold revival: a host block's exact bytes back into
        freshly allocated pages (the admission gate reserved them).
        ``length`` bounds the pages allocated — a full-capacity scatter
        would waste pages on masked positions."""
        import jax
        import numpy as np

        from flink_tensorflow_tpu.ops.paged_attention import dense_to_pages

        if length is None:
            length = k.shape[1]
        if self._kc is None:
            import jax.numpy as jnp

            self._ensure_pool(jnp.asarray(k)[None])
        n = self.pool.pages_for(int(length))
        got = self._alloc(n)
        if got is None:
            raise RuntimeError(
                f"paged KV pool exhausted at re-admission: need {n} "
                f"pages, {self.pool.free_pages} free — the admission "
                "gate should have held this session back")
        self._tables[slot] = got
        ids = np.asarray(got, np.int32)
        k_pages = dense_to_pages(np.asarray(k)[None], self.page_tokens)[0][:n]
        v_pages = dense_to_pages(np.asarray(v)[None], self.page_tokens)[0][:n]
        t0 = time.monotonic()
        self._kc = self._kc.at[ids].set(jax.device_put(k_pages, self.device))
        self._vc = self._vc.at[ids].set(jax.device_put(v_pages, self.device))
        t1 = time.monotonic()
        self.block_h2d_events += 1
        if self._tracer is not None:
            self._tracer.span(self._trace_track, "cache.h2d", t0, t1,
                              args={"slot": slot, "pages": n,
                                    "bytes": int(k_pages.nbytes
                                                 + v_pages.nbytes)})
        if self._roofline is not None:
            self._roofline.observe_transfer(
                "cache_move", t1 - t0, signature=f"cache:pages:{n}",
                h2d_bytes=int(k_pages.nbytes + v_pages.nbytes))

    def release_finished(self, slot: int, cached_tokens,
                         length: int) -> None:
        """A finished session leaves the pool: its FULL pages publish to
        the prefix index (keyed by the token sequence that produced
        them — future sessions sharing the prefix adopt instead of
        recompute), everything else frees."""
        table = self._tables.pop(slot)
        if self.index is not None:
            self.index.publish(cached_tokens, table)
        self.pool.release(table)

    # -- legacy interface guards ------------------------------------------
    def extract_block(self, slot: int, length: int, *, host: bool):
        """The dense runner's extraction split maps onto the paged
        world as snapshot (host copy, pages keep) — the only dense call
        site that reaches a paged runner is the barrier hook."""
        if not host:
            raise RuntimeError(
                "paged preemption parks pages (park()/attach()); "
                "device-resident extract_block is a dense-pool concept")
        return self.snapshot_block(slot, length)


class _FetchError:
    """Completed-queue marker for a batch whose lane work or fetch
    failed; the exception re-raises on the collecting thread."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class CompiledMethodRunner:
    """Executes one model method on one device, bucketed and compiled."""

    def __init__(
        self,
        model: Model,
        method_name: str = "serve",
        *,
        policy: typing.Optional[BucketPolicy] = None,
        device=None,
        donate_inputs: bool = False,
        output_names: typing.Optional[typing.Sequence[str]] = None,
        dispatch_lanes: int = 1,
        wire_dtype: typing.Optional[str] = None,
        double_buffer: bool = True,
    ):
        if dispatch_lanes < 1:
            raise ValueError("dispatch_lanes must be >= 1")
        self.model = model
        self.method = model.method(method_name)
        self.policy = policy or BucketPolicy()
        self.device = device
        self.donate_inputs = donate_inputs
        self.dispatch_lanes = dispatch_lanes
        #: Compact h2d wire dtype ("bf16"/"f16"); the declared input
        #: dtype is restored INSIDE the jitted call (fused upcast).
        from flink_tensorflow_tpu.tensors.serde import normalize_wire_dtype

        self.wire_dtype = normalize_wire_dtype(wire_dtype)
        #: Run assemble+h2d+launch on a small lane pool even at
        #: dispatch_lanes=1, so the h2d of batch N+1 overlaps the
        #: compute of batch N (and the subtask thread never blocks in
        #: the transfer).  False restores the inline single-lane path.
        self.double_buffer = double_buffer
        #: Device-resident emission: results stay in HBM as ONE
        #: DeviceBatch per micro-batch; the d2h is elided until a
        #: host-only consumer materializes.  Set post-open by the model
        #: function when the executor marked the downstream chained
        #: operator device-capable (or forced via device_resident=True).
        self.emit_device_batches = False
        self._pool: typing.Optional[concurrent.futures.ThreadPoolExecutor] = None
        #: Subset of method outputs to return; selection happens INSIDE the
        #: jitted fn so XLA dead-code-eliminates unused heads and the
        #: device->host fetch only moves what the job consumes (fetch bytes
        #: are a first-order cost on tunneled/PCIe-attached devices).
        self.output_names = tuple(output_names) if output_names is not None else None
        self._params_on_device = None
        self._jit_fn = None
        self._transfer: typing.Optional[DeviceTransfer] = None
        self._metrics = None
        #: In-flight dispatched batches: (batch, output futures, t0).
        #: Appended by the dispatching thread, consumed (FIFO) by the
        #: fetch thread; guarded by ``_lock``.
        self._pending: collections.deque = collections.deque()
        #: Dispatch timestamps of in-flight batches (same order as
        #: ``_pending``) — lets callers age the oldest batch without
        #: touching lane futures.
        self._pending_t0: collections.deque = collections.deque()
        #: Batches the fetch thread has fully fetched+unbatched, waiting
        #: for the subtask thread to collect: ``(results, on_done)`` or
        #: a :class:`_FetchError`.  ``on_done`` (ring-slot release) runs
        #: at COLLECTION, on the subtask thread — the TensorRing is
        #: SPSC and claims happen there, so releases must too.
        self._completed: collections.deque = collections.deque()
        self._lock = threading.Lock()
        #: Signals the fetch thread that ``_pending`` gained work.
        self._work_cv = threading.Condition(self._lock)
        #: Signals collectors that ``_completed`` gained results.
        self._done_cv = threading.Condition(self._lock)
        self._fetcher: typing.Optional[threading.Thread] = None
        self._fetch_stop = False
        #: Optional zero-arg callback fired (from the fetch thread) each
        #: time a batch's results land in ``_completed`` — wired to the
        #: subtask gate's ``wake()`` so emission doesn't wait out the
        #: poll interval.
        self.on_results_ready: typing.Optional[typing.Callable[[], None]] = None
        self._batch_seq = 0
        #: Stamp per-record stage timestamps into result metadata
        #: (``meta["__stages__"]``) — the open-loop bench's per-sample
        #: latency decomposition (VERDICT r3 #1).  Off by default: the
        #: stamps cost a dict per record on the hot path.
        self.stamp_stages = False
        #: EWMA of dispatch-call -> results-fetched seconds per batch.
        #: Fed to latency-budget triggers (AdaptiveLatencyTrigger
        #: reserves this much of the budget for service).
        self.service_ewma_s: typing.Optional[float] = None
        #: Span tracer + track (from ctx at open): per-batch stage spans
        #: lane_wait / h2d / compute / d2h — the decomposition the
        #: latency-attribution profiler folds into its table.  None =
        #: untraced (production no-op path).
        self._tracer = None
        self._trace_track: typing.Optional[str] = None
        #: Roofline probe (metrics/roofline.py) when the executor wired
        #: a plane through ctx.roofline: each fetched batch's compute
        #: time joins against the plan's static cost entries.
        self._roofline = None

    # -- lifecycle ---------------------------------------------------------
    def open(self, ctx: typing.Optional["RuntimeContext"] = None) -> None:
        import jax

        device = self.device
        if device is None and ctx is not None and ctx.device is not None:
            device = ctx.device
        self.device = device
        self._transfer = DeviceTransfer(device, self.wire_dtype)
        # Params to HBM once — the Session-owns-variables analogue.
        self._params_on_device = jax.device_put(self.model.params, device)

        method = self.method
        select = self.output_names
        schema = method.input_schema
        # Device-side dtype restore: fields a narrowed wire (or an
        # upstream device batch) delivers in a different dtype are cast
        # back to the schema's declared dtype as the FIRST op of the
        # jitted call — XLA fuses the upcast, and an already-correct
        # dtype is a no-op.  Dynamic-length fields keep their pad dtype.
        restore = {n: schema[n].dtype for n in schema.names}

        from flink_tensorflow_tpu.tensors.transfer import is_scale_key, scale_key

        def widen(inputs):
            # Restores the declared dtype as the FIRST (fused) op of the
            # jitted call; int8-narrowed fields also multiply their
            # absmax scale back in (the companion __scale__ inputs ride
            # the same device_put pytree and never reach the model).
            out = {}
            for k, v in inputs.items():
                if is_scale_key(k):
                    continue
                if k in restore and v.dtype != restore[k]:
                    v = v.astype(restore[k])
                    scale = inputs.get(scale_key(k))
                    if scale is not None:
                        v = v * scale
                out[k] = v
            return out

        def prune(outputs):
            if select is None:
                return outputs
            missing = set(select) - set(outputs)
            if missing:
                raise KeyError(f"method {method.name!r} has no outputs {missing}")
            return {k: outputs[k] for k in select}

        if method.needs_lengths:
            def call(params, inputs, lengths):
                return prune(method.fn(params, widen(inputs), lengths))
        else:
            def call(params, inputs):
                return prune(method.fn(params, widen(inputs)))
        # Inference outputs (logits/labels) never alias input image/token
        # buffers, so donation buys nothing here and XLA warns per bucket;
        # opt in only for methods whose outputs can reuse input pages.
        donate = (1,) if self.donate_inputs else ()
        # Pin execution to the subtask's device; params already live there.
        self._jit_fn = jax.jit(call, donate_argnums=donate)
        lanes = self.dispatch_lanes
        if lanes == 1 and self.double_buffer:
            # Double-buffered transfers: two lane workers keep the h2d
            # of batch N+1 in flight while batch N computes, and the
            # subtask thread never pays the transfer inline.
            lanes = 2
        if lanes > 1 and self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=lanes,
                thread_name_prefix=f"{self.model.name}-dispatch",
            )
        if self._fetcher is None:
            self._fetch_stop = False
            self._fetcher = threading.Thread(
                target=self._fetch_loop,
                name=f"{self.model.name}-fetch",
                daemon=True,
            )
            self._fetcher.start()
        if ctx is not None:
            self._metrics = ctx.metrics
            self._tracer = getattr(ctx, "tracer", None)
            if self._tracer is not None:
                # Track name computed only on the traced path — bare
                # test contexts carry metrics but no task identity.
                self._trace_track = f"{ctx.task_name}.{ctx.subtask_index}"
            plane = getattr(ctx, "roofline", None)
            if plane is not None:
                self._roofline = plane.probe(ctx.task_name,
                                             metrics=ctx.metrics)

    def warmup(self, batch_sizes: typing.Iterable[int], length_bucket: int = 128) -> None:
        """Pre-compile executables for the given batch buckets (open-time,
        so the first live window doesn't pay the 20-40s XLA compile)."""
        import numpy as np

        batch_sizes = tuple(batch_sizes)
        schema = self.method.input_schema
        shapes = schema.resolve_dynamic(length_bucket)
        # Warmup batches pay the XLA compile inside the dispatch interval;
        # keep them out of the steady-state metrics (dispatch_s would
        # otherwise report compile time as wire-transfer time) AND out of
        # the service-time EWMA (a compile-contaminated estimate would
        # make the latency-budget trigger reserve seconds it never needs).
        metrics, self._metrics = self._metrics, None
        tracer, self._tracer = self._tracer, None
        t_warm = time.monotonic()
        if self._roofline is not None:
            # Compile events still log (trigger = "warmup"); throughput
            # accounting is suppressed like the metrics above.
            self._roofline.begin_warmup()
        try:
            for b in batch_sizes:
                fields = {n: np.zeros(shapes[n], schema[n].dtype) for n in schema.names}
                self.run_batch([TensorValue(fields)] * b)
        finally:
            if self._roofline is not None:
                self._roofline.end_warmup()
            self._metrics = metrics
            self._tracer = tracer
            self.service_ewma_s = None
            if tracer is not None:
                # One span for the whole warmup (per-stage spans are
                # suppressed above for the same reason as the metrics:
                # compile time must not masquerade as steady-state
                # h2d/compute cost).
                tracer.span(self._trace_track, "jit_warmup_compile",
                            t_warm, time.monotonic(),
                            args={"batches": list(batch_sizes)})

    def close(self) -> None:
        # Drain dispatched work through the fetch thread before dropping
        # it: fetch completion is a stronger barrier than
        # block_until_ready (the executable can no longer be reading
        # input buffers that alias the ring arena — CPU-backend
        # device_put is zero-copy and the caller frees the arena right
        # after close()), and the deferred ring releases must run here,
        # on the consumer thread.  Errors are irrelevant during teardown.
        deadline = time.monotonic() + 60.0
        while True:
            entries: typing.List[typing.Any] = []
            with self._lock:
                while self._completed:
                    entries.append(self._completed.popleft())
                if not entries:
                    fetching = (self._pending
                                and self._fetcher is not None
                                and self._fetcher.is_alive())
                    if fetching and time.monotonic() < deadline:
                        self._done_cv.wait(timeout=0.5)
                        continue
            for e in entries:
                try:
                    self._consume(e)
                except Exception:  # noqa: BLE001 - cancellation teardown
                    pass
            if not entries:
                break
        with self._lock:
            self._fetch_stop = True
            self._pending.clear()
            self._pending_t0.clear()
            self._completed.clear()
            self._work_cv.notify_all()
        if self._fetcher is not None:
            self._fetcher.join(timeout=10.0)
            self._fetcher = None
        if self._pool is not None:
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._pool = None
        self._params_on_device = None
        self._jit_fn = None

    # -- execution ---------------------------------------------------------
    def dispatch(self, records: typing.Sequence[typing.Any]) -> None:
        """Assemble + transfer + launch one micro-batch WITHOUT blocking.

        jax dispatch is async: the jitted call returns future-backed
        arrays immediately, so the device crunches this batch while the
        host assembles the next one.  With ``dispatch_lanes > 1`` the
        whole assemble+transfer+launch runs on a lane thread, overlapping
        the wire transfers of consecutive batches.  Results are collected
        in dispatch order by :meth:`collect_ready` / :meth:`flush`.
        """
        if self._jit_fn is None:
            raise RuntimeError("runner not opened")
        t0 = time.monotonic()
        self._batch_seq += 1
        seq = self._batch_seq
        if self._pool is not None:
            item = self._pool.submit(self._dispatch_work, list(records), t0, seq)
        else:
            item = self._dispatch_work(records, t0, seq)
        self._enqueue(item, t0)

    def dispatch_batch(self, batch: Batch, *, assemble_s: float = 0.0,
                       on_done: typing.Optional[typing.Callable[[], None]] = None) -> None:
        """Transfer + launch a pre-assembled :class:`Batch` (zero-copy ring
        path: ``batch.arrays`` are views onto the ring arena).

        ``on_done`` fires when the batch's results are COLLECTED on the
        subtask thread — by then the fetch completed, so the arena slots
        are provably no longer read by the executable (completion order
        == dispatch order, so ring releases stay FIFO, and claims and
        releases stay on the single SPSC consumer thread).  Releasing
        earlier would let the producer overwrite slots that a
        CPU-backend ``device_put`` aliases zero-copy.
        """
        if self._jit_fn is None:
            raise RuntimeError("runner not opened")
        t0 = time.monotonic()
        self._batch_seq += 1
        seq = self._batch_seq
        if self._pool is not None:
            item = self._pool.submit(
                self._launch_batch, batch, t0, seq, assemble_s, on_done)
        else:
            item = self._launch_batch(batch, t0, seq, assemble_s, on_done)
        self._enqueue(item, t0)

    def _enqueue(self, item, t0: float) -> None:
        with self._lock:
            self._pending.append(item)
            self._pending_t0.append(t0)
            self._work_cv.notify()

    def _dispatch_work(self, records: typing.Sequence[typing.Any], t0: float, seq: int):
        """Assemble + transfer + launch; returns (batch, output futures, timings)."""
        tvs = [
            r if isinstance(r, TensorValue) else coerce(r, self.method.input_schema)
            for r in records
        ]
        t_a = time.monotonic()
        batch = assemble(tvs, self.method.input_schema, self.policy)
        return self._launch_batch(batch, t0, seq, time.monotonic() - t_a, None)

    def _launch_batch(self, batch: Batch, t0: float, seq: int,
                      assemble_s: float, on_done):
        """Transfer + launch; returns (batch, output futures, timings, on_done)."""
        import jax

        with annotate_batch(f"{self.model.name}.{self.method.name}", seq):
            t_b = time.monotonic()
            inputs, h2d_bytes, wire_saved = self._transfer.ship(batch)
            if self.method.needs_lengths:
                lengths = self._transfer.lengths_to_device(batch)
                outputs = self._jit_fn(self._params_on_device, inputs, lengths)
            else:
                outputs = self._jit_fn(self._params_on_device, inputs)
            # Start the d2h result copy the moment compute finishes,
            # overlapping it with the queueing/fetch of earlier batches —
            # the r4 decomposition showed the copy serialized as a full
            # transport round trip AFTER readiness.  Best-effort: a
            # backend without the hook just pays the copy inside fetch.
            for leaf in jax.tree.leaves(outputs):
                if hasattr(leaf, "copy_to_host_async"):
                    try:
                        leaf.copy_to_host_async()
                    except Exception:  # noqa: BLE001 - optional fast path
                        break
            t_c = time.monotonic()
        timings = {
            "t0": t0,
            "assemble_s": assemble_s,
            # On tunnel-attached devices the h2d wire transfer blocks inside
            # the jitted-call dispatch, so this interval IS the transfer cost.
            "dispatch_s": t_c - t_b,
            # Bytes that actually crossed (narrowed when wire_dtype set).
            "h2d_bytes": h2d_bytes,
            "wire_saved": wire_saved,
            # Stage boundaries for the per-sample latency decomposition:
            # t0 -> t_lane_start is lane-pool queueing, t_lane_start ->
            # t_dispatched is assemble + h2d transfer + launch.
            "t_lane_start": t_b,
            "t_dispatched": t_c,
        }
        return batch, outputs, timings, on_done

    # -- device-resident input (HBM-resident chained handoff) -------------
    def can_accept_device(self, dbatch) -> bool:
        """Whether an upstream :class:`DeviceBatch` can feed this runner's
        jitted call directly: every schema field present among the batch
        arrays with matching trailing (static) shape.  Dtype mismatches
        are fine — the jitted call casts to the declared dtype as its
        first fused op.  Methods taking per-record lengths stay on the
        host path (the lengths side input is host bookkeeping)."""
        if self.method.needs_lengths:
            return False
        schema = self.method.input_schema
        for name in schema.names:
            arr = dbatch.arrays.get(name)
            if arr is None:
                return False
            spec_shape = schema[name].shape
            got = tuple(arr.shape[1:])
            if len(got) != len(spec_shape):
                return False
            for d, g in zip(spec_shape, got):
                if d is not None and d != g:
                    return False
        return True

    def dispatch_device(self, dbatch) -> bool:
        """Launch an upstream DeviceBatch WITHOUT a host round trip: the
        h2d transfer is elided (arrays are already HBM-resident) and the
        jitted call consumes them directly.  Returns False when the batch
        is not schema-compatible — the caller falls back to
        ``materialize()`` + the host dispatch path.

        The consumer takes ownership of the batch's arrays (with
        ``donate_inputs=True`` XLA may reuse their pages); do not
        materialize a DeviceBatch after handing it here.
        """
        if self._jit_fn is None:
            raise RuntimeError("runner not opened")
        if not self.can_accept_device(dbatch):
            return False
        t0 = time.monotonic()
        self._batch_seq += 1
        seq = self._batch_seq
        if self._pool is not None:
            item = self._pool.submit(self._launch_device, dbatch, t0, seq)
        else:
            item = self._launch_device(dbatch, t0, seq)
        self._enqueue(item, t0)
        return True

    def _launch_device(self, dbatch, t0: float, seq: int):
        import jax

        from flink_tensorflow_tpu.tensors.batching import Batch

        schema = self.method.input_schema
        with annotate_batch(f"{self.model.name}.{self.method.name}", seq):
            t_b = time.monotonic()
            inputs = {n: dbatch.arrays[n] for n in schema.names}
            outputs = self._jit_fn(self._params_on_device, inputs)
            for leaf in jax.tree.leaves(outputs):
                if hasattr(leaf, "copy_to_host_async"):
                    try:
                        leaf.copy_to_host_async()
                    except Exception:  # noqa: BLE001 - optional fast path
                        break
            t_c = time.monotonic()
        # Bookkeeping shell: unbatch only needs valid/metas, and the
        # h2d row is honest — zero bytes crossed for this batch.
        shell = Batch(arrays={}, valid=dbatch.valid, lengths={},
                      metas=dbatch.metas)
        timings = {
            "t0": t0,
            "assemble_s": 0.0,
            "dispatch_s": t_c - t_b,
            "h2d_bytes": 0,
            "wire_saved": 0,
            "h2d_elided": True,
            "t_lane_start": t_b,
            "t_dispatched": t_c,
        }
        return shell, outputs, timings, None

    # -- background fetch ---------------------------------------------------
    def _fetch_loop(self) -> None:
        """Fetch-thread body: resolve the oldest in-flight batch, fetch
        its results (the blocking d2h round trip), run the bookkeeping,
        and hand ``(results, on_done)`` to the completed queue.  FIFO by
        construction — one thread, oldest first — so result order and
        ring-release order both match dispatch order."""
        while True:
            with self._lock:
                while not self._pending and not self._fetch_stop:
                    self._work_cv.wait()
                if not self._pending:
                    return  # stop requested and queue drained
                item = self._pending[0]
            try:
                entry = self._process_item(item)
            except BaseException as exc:  # noqa: BLE001 - re-raised on collect
                entry = _FetchError(exc)
            with self._lock:
                # Teardown may have cleared the queues mid-fetch; the
                # guards keep this thread alive to observe the stop flag
                # (an unguarded popleft would die on the empty deque).
                if self._pending:
                    self._pending.popleft()
                if self._pending_t0:
                    self._pending_t0.popleft()
                if not self._fetch_stop:
                    self._completed.append(entry)
                self._done_cv.notify_all()
            cb = self.on_results_ready
            if cb is not None:
                try:
                    cb()
                except Exception:  # noqa: BLE001 - wakeup is best-effort
                    pass

    def _process_item(self, item):
        if isinstance(item, concurrent.futures.Future):
            item = item.result()  # re-raises lane-thread failures here
        # Stamped AFTER the lane future resolves: the fetch thread can
        # reach this batch while its lane is still transferring, and that
        # wait belongs to ready_wait (t_dispatched -> t_fetch_start),
        # keeping the stage boundaries monotone and exactly tiling
        # t0..t_done.
        t_fetch_start = time.monotonic()
        batch, outputs, timings, on_done = item
        if self.emit_device_batches:
            return self._complete_device(
                batch, outputs, timings, on_done, t_fetch_start)
        host = DeviceTransfer.fetch(outputs)  # blocks on this batch only
        t_done = time.monotonic()
        results = batch.unbatch(host)
        dt = t_done - timings["t0"]
        # Per-batch service time (dispatch call -> results on host): the
        # latency-budget trigger reserves this out of its budget.
        self.service_ewma_s = (
            dt if self.service_ewma_s is None
            else 0.75 * self.service_ewma_s + 0.25 * dt
        )
        tracer = self._tracer
        if tracer is not None:
            # Per-batch stage spans on this operator's track — the
            # boundaries tile t0..t_done exactly (same cuts as the
            # __stages__ stamps below): lane-pool queueing, assemble +
            # host->device wire + jit launch, launch -> fetch reached
            # (device compute, overlapped with earlier fetches), and the
            # batch's own d2h round trip.  A batch fed by an upstream
            # DeviceBatch records NO h2d span — the elision shows as an
            # ``h2d.elided`` instant (the CI guard greps for exactly
            # this shape: zero h2d spans between fused model ops).
            track = self._trace_track
            n = len(results)
            tracer.span(track, "lane_wait", timings["t0"],
                        timings["t_lane_start"], args={"batch": n})
            if timings.get("h2d_elided"):
                tracer.instant(track, "h2d.elided",
                               ts=timings["t_lane_start"], args={"batch": n})
            else:
                tracer.span(track, "h2d", timings["t_lane_start"],
                            timings["t_dispatched"],
                            args={"bytes": timings["h2d_bytes"], "batch": n,
                                  "assemble_s": round(timings["assemble_s"], 6)})
            tracer.span(track, "compute", timings["t_dispatched"],
                        t_fetch_start, args={"batch": n})
            tracer.span(track, "d2h", t_fetch_start, t_done,
                        args={"batch": n})
        if self.stamp_stages:
            stages = {
                "t0": timings["t0"],
                # lane_wait INCLUDES coerce+assemble on the dispatch()
                # path (both run on the lane thread before launch);
                # assemble_s is its sub-component, t_lane_start the
                # boundary — so the stage intervals t0 -> t_lane_start ->
                # t_dispatched -> t_fetch_start -> t_done tile the batch
                # lifetime exactly (no overlap, no gap).
                "lane_wait_s": timings["t_lane_start"] - timings["t0"],
                "assemble_s": timings["assemble_s"],
                "dispatch_s": timings["dispatch_s"],
                "t_lane_start": timings["t_lane_start"],
                "t_dispatched": timings["t_dispatched"],
                "t_fetch_start": t_fetch_start,
                "t_done": t_done,
                "batch_n": len(results),
            }
            for r in results:
                # Each result's meta dict is its own copy (unbatch
                # rebuilds TensorValues) AND each gets its own copy of
                # the stages dict — a consumer mutating one record's
                # stamps must not mutate its batch-siblings' (VERDICT r4
                # weak #5: the shared dict made the isolation claim a
                # half-truth).
                r.meta["__stages__"] = dict(stages)
        if self._metrics is not None:
            self._metrics.meter("records").mark(len(results))
            self._metrics.histogram("batch_latency_s").record(dt)
            self._metrics.histogram("record_latency_s").record(dt / max(1, len(results)))
            self._metrics.histogram("assemble_s").record(timings["assemble_s"])
            self._metrics.histogram("dispatch_s").record(timings["dispatch_s"])
            self._metrics.counter("h2d_bytes").inc(timings["h2d_bytes"])
            if timings.get("wire_saved"):
                self._metrics.counter("wire_bytes_saved").inc(
                    timings["wire_saved"])
            self._metrics.counter("batches").inc()
            self._metrics.counter("padded_records").inc(batch.padded_size - batch.num_records)
        if self._roofline is not None:
            # Busy time = the compute span (launch -> fetch reached);
            # the padded batch size is the jit signature the cost table
            # keyed its entries on.
            self._roofline.observe(
                self.method.name, t_fetch_start - timings["t_dispatched"],
                signature=f"b{batch.padded_size}",
                h2d_bytes=timings["h2d_bytes"])
        return results, on_done

    def _complete_device(self, batch, outputs, timings, on_done,
                         t_fetch_start: float):
        """Device-resident completion: wait for COMPUTE (not transfer) —
        ``block_until_ready`` is the pipeline-depth barrier the fetch
        used to provide — then hand out one HBM-resident DeviceBatch.
        The d2h is elided here; it lands (once) wherever the first
        host-only consumer materializes."""
        import jax

        from flink_tensorflow_tpu.tensors.transfer import DeviceBatch

        jax.block_until_ready(outputs)
        t_done = time.monotonic()
        n = batch.num_records
        dt = t_done - timings["t0"]
        self.service_ewma_s = (
            dt if self.service_ewma_s is None
            else 0.75 * self.service_ewma_s + 0.25 * dt
        )
        tracer = self._tracer
        if tracer is not None:
            track = self._trace_track
            tracer.span(track, "lane_wait", timings["t0"],
                        timings["t_lane_start"], args={"batch": n})
            if timings.get("h2d_elided"):
                tracer.instant(track, "h2d.elided",
                               ts=timings["t_lane_start"], args={"batch": n})
            else:
                tracer.span(track, "h2d", timings["t_lane_start"],
                            timings["t_dispatched"],
                            args={"bytes": timings["h2d_bytes"], "batch": n,
                                  "assemble_s": round(timings["assemble_s"], 6)})
            # Compute runs to t_done (block_until_ready IS the barrier);
            # the d2h.elided instant is what the attribution table and
            # the CI guard read as "no fetch happened here".
            tracer.span(track, "compute", timings["t_dispatched"],
                        t_done, args={"batch": n})
            tracer.instant(track, "d2h.elided", ts=t_done, args={"batch": n})
        if self._metrics is not None:
            self._metrics.meter("records").mark(n)
            self._metrics.histogram("batch_latency_s").record(dt)
            self._metrics.histogram("record_latency_s").record(dt / max(1, n))
            self._metrics.histogram("assemble_s").record(timings["assemble_s"])
            self._metrics.histogram("dispatch_s").record(timings["dispatch_s"])
            self._metrics.counter("h2d_bytes").inc(timings["h2d_bytes"])
            if timings.get("wire_saved"):
                self._metrics.counter("wire_bytes_saved").inc(
                    timings["wire_saved"])
            self._metrics.counter("fetch_elided_batches").inc()
            self._metrics.counter("batches").inc()
            self._metrics.counter("padded_records").inc(
                batch.padded_size - batch.num_records)
        if self._roofline is not None:
            # block_until_ready IS the compute barrier on this path.
            self._roofline.observe(
                self.method.name, t_done - timings["t_dispatched"],
                signature=f"b{batch.padded_size}",
                h2d_bytes=timings["h2d_bytes"])
        dbatch = DeviceBatch(outputs, batch.valid, batch.metas,
                             tracer=tracer, track=self._trace_track)
        return [dbatch], on_done

    def _consume(self, entry) -> typing.List[TensorValue]:
        """Collect one completed entry on the calling (subtask) thread:
        re-raise fetch-thread failures, run the deferred ring release."""
        if isinstance(entry, _FetchError):
            raise entry.exc
        results, on_done = entry
        if on_done is not None:
            on_done()
        return results

    def has_completed(self) -> bool:
        """True when fetched results are waiting to be collected."""
        return bool(self._completed)

    def collect_ready(self, max_in_flight: int = 1) -> typing.List[TensorValue]:
        """Drain completed batches until <= ``max_in_flight`` remain in
        flight (dispatched but not yet fetched), blocking as needed."""
        max_in_flight = max(0, max_in_flight)
        out: typing.List[TensorValue] = []
        while True:
            entries: typing.List[typing.Any] = []
            with self._lock:
                while self._completed:
                    entries.append(self._completed.popleft())
                done = len(self._pending) <= max_in_flight
                if not entries and not done:
                    self._done_cv.wait(timeout=0.2)
                    if (self._fetcher is None or not self._fetcher.is_alive()) \
                            and self._pending and not self._completed:
                        raise RuntimeError(
                            "fetch thread died with batches in flight")
                    continue
            for e in entries:
                out.extend(self._consume(e))
            if done:
                return out

    def collect_available(self) -> typing.List[TensorValue]:
        """Drain every batch the fetch thread has already completed —
        never blocks on in-flight compute or transfer.  This is the
        open-loop latency lever: the subtask thread emits results the
        moment they land instead of parking in a full ``flush`` for the
        whole device round trip (which turns the operator into a
        blocking M/D/1 server and queues every later window behind the
        wire — BENCH_r03's unexplained 536ms p50)."""
        out: typing.List[TensorValue] = []
        while True:
            with self._lock:
                if not self._completed:
                    return out
                entry = self._completed.popleft()
            out.extend(self._consume(entry))

    def collect_progress(self, max_in_flight: int) -> typing.List[TensorValue]:
        """Opportunistic collection on the hot path: everything already
        READY (non-blocking), then block only as far as the pipeline-
        depth bound requires.  Keeps emission latency at one arrival
        interval instead of one pipeline drain without sacrificing the
        depth backpressure."""
        out = self.collect_available()
        out.extend(self.collect_ready(max_in_flight))
        return out

    def oldest_pending_age_s(self, now: typing.Optional[float] = None) -> typing.Optional[float]:
        """Seconds since the oldest in-flight batch was dispatched, or
        None when nothing is pending (stall-detection hook)."""
        with self._lock:
            if not self._pending_t0:
                return None
            t0 = self._pending_t0[0]
        return (now if now is not None else time.monotonic()) - t0

    def flush(self) -> typing.List[TensorValue]:
        """Block for every in-flight batch (end of input / pre-snapshot)."""
        return self.collect_ready(0)

    def run_batch(self, records: typing.Sequence[typing.Any]) -> typing.List[TensorValue]:
        """Synchronous micro-batch: dispatch + wait (single-record map and
        tests; the windowed path pipelines via dispatch/collect_ready)."""
        self.dispatch(records)
        return self.flush()
