"""Typed job configuration — one validated dataclass per job.

The reference threads job settings through Flink's untyped
``Configuration``/``ParameterTool``/``GlobalJobParameters`` (SURVEY.md §5
"Config / flag system"); SURVEY prescribes the rebuild use "a single typed
config dataclass per job; no global flags".  ``JobConfig`` is that
dataclass: every framework knob (checkpointing, channels, source pacing,
device/mesh selection) lives here, is validated before the executor is
built, and is frozen so a running job's configuration cannot drift.

User-level parameters (the reference's ``GlobalJobParameters`` role —
model paths, thresholds, anything a user function reads at runtime) go in
``user_params``; the old untyped ``env.job_config`` dict is a deprecated
alias for it.
"""

from __future__ import annotations

import dataclasses
import typing

from flink_tensorflow_tpu.metrics.health import HealthConfig
from flink_tensorflow_tpu.metrics.reporters import MetricConfig


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Where and how often aligned snapshots persist."""

    #: Directory for persisted snapshots; None disables persistence.
    dir: typing.Optional[str] = None
    #: Periodic trigger interval; None means manual triggers only.
    interval_s: typing.Optional[float] = None
    #: Count-based triggers: each source injects barrier k after its
    #: k*N-th record — barrier positions become a deterministic function
    #: of the stream, the consistency contract multi-host cohorts need
    #: (every process cuts snapshots at identical stream positions).
    #: Mutually exclusive with interval_s; disables manual triggers.
    every_n_records: typing.Optional[int] = None
    #: Budget for one aligned checkpoint to drain.
    timeout_s: float = 60.0
    #: Keep only the newest N completed checkpoints on disk (Flink's
    #: retained-checkpoints policy); None keeps everything.  Pruning
    #: happens after a NEWER checkpoint is durable (and, on a
    #: DistributedExecutor cohort, after its GLOBAL 2PC commit fired,
    #: so every peer holds the retained ids too).  CAUTION for
    #: hand-rolled CohortSupervisor cohorts (independent per-worker
    #: executors, per-worker dirs, no global gate): each worker prunes
    #: alone, so size retain_last comfortably above the worst-case
    #: cross-worker checkpoint skew (>= 3 recommended) or the
    #: latest-COMMON-checkpoint restore point can be pruned away on the
    #: fastest worker.
    retain_last: typing.Optional[int] = None

    def validate(self) -> None:
        if self.interval_s is not None:
            if self.dir is None:
                raise ValueError("checkpoint.interval_s requires checkpoint.dir")
            if self.interval_s <= 0:
                raise ValueError(f"checkpoint.interval_s must be > 0, got {self.interval_s}")
        if self.every_n_records is not None:
            if self.dir is None:
                raise ValueError("checkpoint.every_n_records requires checkpoint.dir")
            if self.interval_s is not None:
                raise ValueError(
                    "checkpoint.every_n_records and interval_s are mutually "
                    "exclusive (count-based barriers must stay deterministic)"
                )
            if self.every_n_records < 1:
                raise ValueError(
                    f"checkpoint.every_n_records must be >= 1, got {self.every_n_records}"
                )
        if self.timeout_s <= 0:
            raise ValueError(f"checkpoint.timeout_s must be > 0, got {self.timeout_s}")
        if self.retain_last is not None:
            if self.dir is None:
                raise ValueError("checkpoint.retain_last requires checkpoint.dir")
            if self.retain_last < 1:
                raise ValueError(
                    f"checkpoint.retain_last must be >= 1, got {self.retain_last}"
                )


@dataclasses.dataclass(frozen=True)
class JobConfig:
    """All framework-level knobs for one job, validated at ``execute()``.

    Fields mirror what the environment's fluent setters configure; the
    setters are retained as conveniences that rebuild this config via
    ``dataclasses.replace``.
    """

    #: Default operator parallelism (Flink's env-level parallelism).
    parallelism: int = 1
    #: Key-group count (Flink's maxParallelism): the upper bound on keyed
    #: parallelism, fixed for the job's lifetime so keyed state can be
    #: redistributed when a restart changes parallelism.
    max_parallelism: int = 128
    #: Bounded capacity of inter-subtask channels (records).
    channel_capacity: int = 1024
    #: Operator chaining (analysis/chaining.py): fuse forward
    #: same-parallelism neighbors into one subtask thread so records
    #: pass by direct method call instead of a queue hop.  Off is the
    #: ``chaining=off`` comparison mode (one thread + channel per
    #: operator, the pre-chaining layout); per-operator opt-outs are
    #: ``stream.start_new_chain()`` / ``stream.disable_chaining()``.
    chaining: bool = True
    #: Debug-mode concurrency sanitizer (core.sanitizer_rt): instrument
    #: the runtime's locks/condvars (channels, source mailboxes, split
    #: and checkpoint coordinators), record a happens-before graph with
    #: lock-order-inversion + waits-for-deadlock detection, and assert
    #: the barrier protocol invariants (no record past a blocked channel
    #: during alignment, snapshot order == chain stream order, split
    #: assignment frozen during the enumerator-pool snapshot).  Off (the
    #: default) is a zero-cost no-op path — plain threading primitives.
    #: The FLINK_TPU_SANITIZE=1 env var force-enables it without config
    #: changes; FLINK_TPU_SANITIZE_STALL_S adds the stall watchdog.
    sanitize: bool = False
    #: Where the sanitizer's cross-process happens-before event log is
    #: dumped (the ``flink-tpu-sanitize --cohort`` input); a cohort
    #: process suffixes ``.proc<k>`` before the extension.  None keeps
    #: the ring in memory only.  FLINK_TPU_SANITIZE_LOG overrides.
    sanitize_log_path: typing.Optional[str] = None
    #: End-to-end span tracing (flink_tensorflow_tpu.tracing): thread a
    #: per-record/per-batch trace context from source admission through
    #: chains, channels, h2d/compute/d2h, checkpoint alignment, split
    #: lifecycle, and remote edges; spans land in per-thread ring
    #: buffers and export as Chrome Trace Event JSON (Perfetto).  Off
    #: (the default) is a zero-cost no-op path — one is-None test per
    #: hook site, zero per-record allocation.  FLINK_TPU_TRACE=1
    #: force-enables without config changes.
    trace: bool = False
    #: Where the Chrome trace JSON is written when the job finishes (or
    #: fails); None keeps spans in memory only (reachable through the
    #: executor's tracer — the flink-tpu-trace CLI path).  The
    #: FLINK_TPU_TRACE_PATH env var overrides.
    trace_path: typing.Optional[str] = None
    #: Head-based sampling: admit every round(1/rate)-th record per
    #: source subtask into the trace (deterministic given the metrics
    #: seed — see tracing.Tracer).  1.0 traces everything.
    trace_sample_rate: float = 1.0
    #: Flight recorder (tracing/flight.py): an always-on bounded ring of
    #: recent control-rate events (job/subtask lifecycle, barrier
    #: injections, snapshots, per-report metric deltas) — independent of
    #: ``trace`` — dumped to ``flight_path`` on crash, sanitizer
    #: violation, SIGTERM/SIGINT, or ``JobHandle.cancel`` and replayable
    #: via ``flink-tpu-trace --from-flight-dump``.  False is the
    #: zero-alloc off path (FLINK_TPU_FLIGHT overrides either way).
    flight_recorder: bool = True
    #: Where flight dumps land; None records in memory only (no disk
    #: write even on crash).  FLINK_TPU_FLIGHT_PATH overrides.
    flight_path: typing.Optional[str] = None
    #: Device-resident dataflow (tensors/transfer.DeviceBatch): chains
    #: of device-capable operators (model -> model, model -> elementwise
    #: device map) hand HBM-resident batches between fused members — the
    #: d2h fetch is elided until the first host-only consumer (sink,
    #: keyed shuffle, remote edge) forces it exactly once, so a chained
    #: hop pays the wire once per direction end to end instead of twice
    #: per hop.  Off (the default) keeps every result on the host path.
    #: FLINK_TPU_DEVICE_RESIDENT=1 force-enables; per-operator override
    #: via ModelMapFunction(device_resident=True/False).
    device_resident: bool = False
    #: Compact on-the-wire dtype for float tensors: "bf16"/"f16" halve
    #: the bytes of every f32 field on BOTH the h2d hop (model runners
    #: narrow host-side; the declared dtype is restored inside the
    #: jitted call) and remote TCP frames (tensors/serde.py restores at
    #: decode); "int8" (absmax-quantized) applies to TCP frames only.
    #: None/"f32" ships full width.  FLINK_TPU_WIRE_DTYPE overrides.
    #: Accuracy caveats documented in tensors/serde.py.
    wire_dtype: typing.Optional[str] = None
    #: Frame coalescing on the remote record plane (core/shuffle.py,
    #: io/remote.py — Flink's network-buffer model): records buffer
    #: until this many estimated payload bytes, then flush as ONE
    #: multi-record frame.  0 disables coalescing (frame per record,
    #: the pre-coalescing wire).  FLINK_TPU_WIRE_FLUSH_BYTES overrides.
    wire_flush_bytes: int = 64 * 1024
    #: Flink-style buffer timeout: a partially filled buffer flushes
    #: this many milliseconds after its FIRST record, bounding the
    #: latency coalescing may add.  Barriers, watermarks and
    #: end-of-partition always force an immediate flush (alignment and
    #: exactly-once semantics never wait on the timeout).  0 flushes
    #: every record (Flink's bufferTimeout=0).  FLINK_TPU_WIRE_FLUSH_MS
    #: overrides.  Latency-sensitive open-loop jobs should keep this
    #: small — see the `remote-edge-buffer-timeout` lint.
    wire_flush_ms: float = 5.0
    #: Same-host shuffle edges ride a shared-memory ring
    #: (native/ring.ShmByteRing over tmpfs) instead of loopback TCP —
    #: the kernel network stack is skipped entirely; the TCP connection
    #: remains as handshake/wakeup/liveness channel.  Cross-host edges
    #: are unaffected.  FLINK_TPU_SHM=0/1 overrides.
    shm_channels: bool = True
    #: Credit-based flow control on the cross-process record plane
    #: (Flink's network-stack model): receivers grant per-edge credits
    #: (buffer quanta derived from ``channel_capacity``) in the shuffle
    #: handshake and replenish them as the downstream gate drains;
    #: senders spend one credit per flushed data frame and park when
    #: credit hits zero — a stalled consumer throttles the producer
    #: chain within one credit window instead of ballooning reactor
    #: send queues and kernel TCP buffers.  Barriers, watermarks,
    #: end-of-partition and 2PC/control announcements BYPASS credit so
    #: a zero-credit edge can never wedge checkpoint alignment (the
    #: checkpoint deadline-abort sweeper remains the backstop).
    #: FLINK_TPU_FLOW_CONTROL=0/1 overrides.  Disabling this on a
    #: checkpointed multi-process plan behind an open-loop source
    #: trips the `flow-control` lint.
    flow_control: bool = True
    #: Deterministic fault-injection plan (core.faults.FaultPlan, a spec
    #: string, or a sequence of FaultSpec/spec strings): scheduled
    #: kill/stall/sever/blackhole/delay/store_fail faults pinned to
    #: (restart epoch, stream position) — the chaos plane that exercises
    #: the restart/reconnect/abort machinery.  None (the default) keeps
    #: the production zero-cost path; FLINK_TPU_FAULTS overrides.
    faults: typing.Optional[typing.Any] = None
    #: Sleep between source emissions — test/backpressure pacing.
    source_throttle_s: float = 0.0
    checkpoint: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)
    #: Assigns a jax device per (task_name, subtask_index) — operator DP.
    device_provider: typing.Optional[typing.Callable[[str, int], typing.Any]] = None
    #: Shared jax.sharding.Mesh for gang operators (DP/TP training), or a
    #: jax.sharding.AbstractMesh (parallel.mesh.abstract_mesh) declaring a
    #: target layout for PLAN-TIME analysis only: a CPU-only dev box can
    #: declare a v5e-8 mesh and run analysis/shardcheck.py against it,
    #: but a job whose operators need devices cannot open on one.
    mesh: typing.Optional[typing.Any] = None
    #: Per-device HBM ceiling (bytes) for the static memory budget
    #: (analysis/shardcheck.py): params + optimizer state + KV pool +
    #: peak activation liveness, summed per device under the declared
    #: mesh, must fit or the plan fails validation with ERROR
    #: provenance.  None disables the budget gate.  The admission gate
    #: of the paged-KV-economy arc: v5e = 16 GiB/chip, v5p = 95 GiB.
    hbm_budget_bytes: typing.Optional[int] = None
    #: User-level parameters readable from RuntimeContext (the reference's
    #: GlobalJobParameters role).  Not interpreted by the framework.
    user_params: typing.Mapping[str, typing.Any] = dataclasses.field(default_factory=dict)
    #: Cohort membership for the cross-process record plane (subtasks
    #: placed over processes, keyed/rebalance edges spanning them through
    #: the shuffle).  None = single-process execution.  See
    #: core.distributed.DistributedConfig.
    distributed: typing.Optional[typing.Any] = None
    #: Observability plane: reporter interval + sinks + registry seed
    #: (metrics.reporters.MetricConfig).  The default publishes nothing
    #: while the job runs — no reporter thread, metrics only in the
    #: JobResult.
    metrics: MetricConfig = dataclasses.field(default_factory=MetricConfig)
    #: Health evaluation plane (metrics.health.HealthConfig): SLO rules
    #: evaluated over the (merged cohort) metric snapshot each telemetry
    #: interval on process 0, published back as ``health.*`` gauges,
    #: flight events, and trace instants.  With
    #: ``health.autoscale`` (core.autoscale.AutoscaleConfig) a sustained
    #: BREACH of a scaling rule additionally drives the
    #: checkpoint->stop->respawn-at-new-parallelism->rescale-restore
    #: loop.  None (the default) starts no evaluator thread.
    health: typing.Optional[HealthConfig] = None
    #: Roofline attribution plane (metrics.roofline.RooflineConfig):
    #: declares the DeviceSpec peak and drift tolerances; the captured
    #: plan's CostTable (analysis.costmodel) is priced automatically at
    #: execute() when ``roofline.cost_table`` is None.  Runners join
    #: measured step times against it and publish per-operator
    #: ``roofline.*`` gauges + compile events.  None (the default) costs
    #: nothing at runtime.
    roofline: typing.Optional[typing.Any] = None

    def validate(self) -> "JobConfig":
        if self.parallelism < 1:
            raise ValueError(f"parallelism must be >= 1, got {self.parallelism}")
        if self.max_parallelism < 1:
            raise ValueError(
                f"max_parallelism must be >= 1, got {self.max_parallelism}"
            )
        if self.channel_capacity < 1:
            raise ValueError(
                f"channel_capacity must be >= 1, got {self.channel_capacity}"
            )
        if self.source_throttle_s < 0:
            raise ValueError(
                f"source_throttle_s must be >= 0, got {self.source_throttle_s}"
            )
        if self.wire_flush_bytes < 0:
            raise ValueError(
                f"wire_flush_bytes must be >= 0, got {self.wire_flush_bytes}"
            )
        if self.wire_flush_ms < 0:
            raise ValueError(
                f"wire_flush_ms must be >= 0, got {self.wire_flush_ms}"
            )
        if self.wire_dtype is not None:
            from flink_tensorflow_tpu.tensors.serde import WIRE_DTYPES

            if self.wire_dtype not in WIRE_DTYPES:
                raise ValueError(
                    f"wire_dtype must be one of {WIRE_DTYPES} or None, "
                    f"got {self.wire_dtype!r}"
                )
        if self.faults is not None:
            from flink_tensorflow_tpu.core.faults import FaultPlan

            FaultPlan.resolve(self.faults)  # raises on malformed specs
        if not (0.0 < self.trace_sample_rate <= 1.0):
            raise ValueError(
                f"trace_sample_rate must be in (0, 1], got {self.trace_sample_rate}"
            )
        if self.device_provider is not None and not callable(self.device_provider):
            raise ValueError("device_provider must be callable (task, idx) -> device")
        if self.mesh is not None:
            # NOTE: hasattr(AbstractMesh, "devices") RAISES (jax makes the
            # unimplemented property loud), so probe shape/axis_names —
            # present on both Mesh and AbstractMesh — instead.
            if not (hasattr(self.mesh, "shape")
                    and hasattr(self.mesh, "axis_names")):
                raise ValueError(
                    "mesh must be a jax.sharding.Mesh (or AbstractMesh for "
                    f"plan-time analysis), got {type(self.mesh).__name__}"
                )
        if self.hbm_budget_bytes is not None and self.hbm_budget_bytes < 1:
            raise ValueError(
                f"hbm_budget_bytes must be >= 1, got {self.hbm_budget_bytes}"
            )
        if self.distributed is not None:
            self.distributed.validate()
            if self.checkpoint.interval_s is not None:
                raise ValueError(
                    "distributed jobs checkpoint with count-based triggers "
                    "(checkpoint.every_n_records), not interval_s — barrier "
                    "positions must be deterministic across the cohort"
                )
        self.metrics.validate()
        self.checkpoint.validate()
        if self.health is not None:
            self.health.validate()
        if self.roofline is not None:
            self.roofline.validate()
        return self
