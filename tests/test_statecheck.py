"""flink-tpu-statecheck (PR 20): exact-resume, RNG-stream &
rescale-safety static analyzer — the differential seeded-defect matrix.

Every defect family is proven BOTH ways: (a) the runtime actually
breaks byte-identical resume/replay in a small crash-and-restore job
(the clean run and the restored run disagree), and (b) statecheck
flags the same plan statically, with operator-level provenance, before
anything runs.  Healthy twins prove the opposite: declared state is
byte-identical across a crash AND audits clean.

Defect families:
- closure-captured TrainState (hidden state): replay double-applies it.
- global-seed / process-global RNG: replay re-samples a different
  continuation, keyed state rebuilt by replay diverges.
- snapshot-omitted optimizer momentum: restore resets the moment, the
  resumed trajectory diverges from the uninterrupted one.
- non-replayable source -> non-idempotent sink: restore loses records
  outright (the stream cannot rewind), output differs from clean.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, ".")

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.analysis import Severity, analyze
from flink_tensorflow_tpu.core import functions as fn
from flink_tensorflow_tpu.core.environment import RestartStrategy
from flink_tensorflow_tpu.core.state import StateDescriptor


def by_rule(diags, rule):
    return [d for d in diags if d.rule == rule]


def errors(diags):
    return [d for d in diags if d.severity == Severity.ERROR]


def run_with_restart(env, *, max_restarts=2):
    return env.execute(timeout=120,
                       restart_strategy=RestartStrategy(max_restarts=max_restarts))


# ---------------------------------------------------------------------------
# defect 1 — closure-captured TrainState (hidden state)
# ---------------------------------------------------------------------------


def _make_closure_step(train_state, crash_at, crashed_box):
    """The seeded defect: a map fn whose closure captures a
    TrainState-shaped dict and mutates it per record — state the
    checkpoint barriers never see."""

    def step(value):
        if (crash_at is not None and not crashed_box[0]
                and train_state["opt_state"]["count"] >= crash_at):
            crashed_box[0] = True
            raise RuntimeError("injected failure")
        train_state["opt_state"]["count"] += 1
        train_state["variables"]["w"] += float(value)
        return value

    return step


class TestClosureTrainStateDefect:
    N = 80

    def _build(self, tmp_path, tag, crash):
        train_state = {"variables": {"w": 0.0}, "opt_state": {"count": 0}}
        crashed = [False] if crash else [True]
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / f"chk-{tag}"),
                                 every_n_records=20)
        (env.from_collection(list(range(self.N)))
            .map(_make_closure_step(train_state, 50 if crash else None,
                                    crashed), name="closure_step")
            .sink_to_list())
        return env, train_state

    def test_runtime_replay_double_applies_closure_state(self, tmp_path):
        env, clean_state = self._build(tmp_path, "clean", crash=False)
        env.execute(timeout=120)
        assert clean_state["opt_state"]["count"] == self.N

        env, crashed_state = self._build(tmp_path, "crash", crash=True)
        result = run_with_restart(env)
        assert result.restarts == 1
        # The checkpoint rewound every DECLARED state, but the closure
        # dict survived the restore untouched: replayed records applied
        # their updates a second time.  Exact resume is broken.
        assert crashed_state["opt_state"]["count"] > self.N

    def test_static_hidden_state_error_with_provenance(self, tmp_path):
        env, _ = self._build(tmp_path, "static", crash=False)
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-hidden-state")
        errs = errors(diags)
        assert errs, "closure-captured TrainState must be an ERROR"
        assert errs[0].node == "closure_step"
        assert "train_state" in errs[0].message
        assert "TrainState" in errs[0].message


# ---------------------------------------------------------------------------
# defect 2 — global-seed / process-global RNG streams
# ---------------------------------------------------------------------------


class NoisySum(fn.ProcessFunction):
    """The seeded defect: keyed running sum salted from the PROCESS-
    GLOBAL numpy RNG — replayed records draw different values."""

    def __init__(self, crash_at=None, crashed_box=None):
        self.crash_at = crash_at
        self.crashed = crashed_box if crashed_box is not None else [True]
        self._seen = 0

    def clone(self):
        return type(self)(self.crash_at, self.crashed)

    def process_element(self, value, ctx, out):
        self._seen += 1
        if (self.crash_at and not self.crashed[0]
                and self._seen >= self.crash_at):
            self.crashed[0] = True
            raise RuntimeError("injected failure")
        total = ctx.state(StateDescriptor("total", lambda: 0.0))
        total.update((total.value() or 0.0) + value + np.random.rand())
        out.collect((ctx.current_key, total.value()))

    def snapshot_state(self):
        return {"seen": self._seen}

    def restore_state(self, state):
        self._seen = state["seen"]


class FoldSum(fn.ProcessFunction):
    """The healthy twin: per-key randomness derives via fold_in from
    keyed state (a per-key counter), so replay re-samples the IDENTICAL
    continuation."""

    def __init__(self, crash_at=None, crashed_box=None):
        self.crash_at = crash_at
        self.crashed = crashed_box if crashed_box is not None else [True]
        self._seen = 0

    def clone(self):
        return type(self)(self.crash_at, self.crashed)

    def open(self, ctx):
        self._base = jax.random.PRNGKey(7)

    def process_element(self, value, ctx, out):
        self._seen += 1
        if (self.crash_at and not self.crashed[0]
                and self._seen >= self.crash_at):
            self.crashed[0] = True
            raise RuntimeError("injected failure")
        count = ctx.state(StateDescriptor("count", lambda: 0))
        total = ctx.state(StateDescriptor("total", lambda: 0.0))
        i = (count.value() or 0) + 1
        count.update(i)
        key = jax.random.fold_in(
            jax.random.fold_in(self._base, ctx.current_key), i)
        total.update((total.value() or 0.0) + value
                     + float(jax.random.uniform(key)))
        out.collect((ctx.current_key, i, total.value()))

    def snapshot_state(self):
        return {"seen": self._seen}

    def restore_state(self, state):
        self._seen = state["seen"]


def _final_by_key(out):
    final = {}
    for row in out:
        final[row[0]] = row[-1]
    return final


class TestRngStreamDefect:
    N = 80

    def _run(self, tmp_path, tag, function_cls, crash):
        np.random.seed(1234)  # identical global stream for both runs
        crashed = [False] if crash else [True]
        f = function_cls(crash_at=50 if crash else None, crashed_box=crashed)
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / f"chk-{tag}"),
                                 every_n_records=20)
        out = (env.from_collection(list(range(self.N)))
                  .key_by(lambda x: x % 4)
                  .process(f, name="noisy_sum")
                  .sink_to_list())
        result = (run_with_restart(env) if crash
                  else env.execute(timeout=120))
        return _final_by_key(out), getattr(result, "restarts", 0)

    def test_runtime_global_rng_diverges_after_restore(self, tmp_path):
        clean, _ = self._run(tmp_path, "clean", NoisySum, crash=False)
        crashed, restarts = self._run(tmp_path, "crash", NoisySum, crash=True)
        assert restarts == 1
        # Replayed records drew from a FURTHER-ADVANCED global stream:
        # keyed state rebuilt by replay is a different continuation.
        assert any(abs(clean[k] - crashed[k]) > 1e-9 for k in clean)

    def test_runtime_fold_in_resumes_identically(self, tmp_path):
        clean, _ = self._run(tmp_path, "fclean", FoldSum, crash=False)
        crashed, restarts = self._run(tmp_path, "fcrash", FoldSum, crash=True)
        assert restarts == 1
        # fold_in from keyed state: byte-identical resume.
        assert clean == crashed

    def _plan(self, function):
        env = StreamExecutionEnvironment(parallelism=1)
        (env.from_collection(list(range(8)))
            .key_by(lambda x: x % 4)
            .process(function, name="noisy_sum")
            .sink_to_list())
        return env

    def test_static_global_rng_is_error_on_keyed_path(self):
        env = self._plan(NoisySum())
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-rng-stream")
        errs = errors(diags)
        assert errs and errs[0].node == "noisy_sum"
        assert "np.random.rand" in errs[0].message
        assert "fold_in" in errs[0].message

    def test_static_fold_in_twin_is_clean(self):
        env = self._plan(FoldSum())
        assert by_rule(analyze(env.graph, config=env.config),
                       "statecheck-rng-stream") == []

    def test_static_constant_reseed_in_record_path_flagged(self):
        class Reseed(fn.MapFunction):
            def map(self, value):
                k = jax.random.PRNGKey(0)
                return float(jax.random.uniform(k)) + value

        env = StreamExecutionEnvironment(parallelism=1)
        env.from_collection([1.0]).map(Reseed(), name="reseed").sink_to_list()
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-rng-stream")
        assert len(diags) == 1
        assert diags[0].severity == Severity.WARN  # unkeyed: advisory
        assert "jax.random.PRNGKey" in diags[0].message

    def test_static_seed_in_open_is_sanctioned(self):
        class SeedInOpen(fn.MapFunction):
            def open(self, ctx):
                self._key = jax.random.PRNGKey(3)

            def map(self, value):
                return value

        env = StreamExecutionEnvironment(parallelism=1)
        env.from_collection([1.0]).map(SeedInOpen()).sink_to_list()
        assert by_rule(analyze(env.graph, config=env.config),
                       "statecheck-rng-stream") == []


# ---------------------------------------------------------------------------
# defect 3 — snapshot-omitted optimizer momentum + train-state audits
# ---------------------------------------------------------------------------


class MiniMomentumTrain(fn.ProcessFunction):
    """The seeded defect: hand-rolled SGD-with-momentum whose snapshot
    covers the weights but NOT the momentum buffer — a restore resets
    the moment to zero and the resumed trajectory diverges."""

    def __init__(self, crash_at=None, crashed_box=None):
        self.crash_at = crash_at
        self.crashed = crashed_box if crashed_box is not None else [True]
        self._w = jnp.zeros((4,))
        self._m = jnp.zeros((4,))  # the hidden half of the train state
        self._seen = 0

    def clone(self):
        return type(self)(self.crash_at, self.crashed)

    def process_element(self, value, ctx, out):
        self._seen += 1
        if (self.crash_at and not self.crashed[0]
                and self._seen >= self.crash_at):
            self.crashed[0] = True
            raise RuntimeError("injected failure")
        grad = jnp.full((4,), float(value % 7) - 3.0)
        self._m = 0.9 * self._m + grad
        self._w = self._w - 0.1 * self._m
        out.collect(float(self._w[0]))

    def snapshot_state(self):
        return {"w": np.asarray(self._w), "seen": self._seen}

    def restore_state(self, state):
        self._w = jnp.asarray(state["w"])
        self._seen = state["seen"]


class TestTrainStateDefect:
    N = 80

    def _run(self, tmp_path, tag, crash):
        crashed = [False] if crash else [True]
        f = MiniMomentumTrain(crash_at=50 if crash else None,
                              crashed_box=crashed)
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / f"chk-{tag}"),
                                 every_n_records=20)
        out = (env.from_collection(list(range(self.N)))
                  .key_by(lambda x: 0)
                  .process(f, name="mini_train")
                  .sink_to_list())
        result = (run_with_restart(env) if crash
                  else env.execute(timeout=120))
        finals = [v for v in out if v is not None]
        return finals[-1], getattr(result, "restarts", 0)

    def test_runtime_momentum_reset_diverges(self, tmp_path):
        clean_w, _ = self._run(tmp_path, "clean", crash=False)
        crash_w, restarts = self._run(tmp_path, "crash", crash=True)
        assert restarts == 1
        # The restore brought back _w but zeroed _m: the resumed run
        # follows a DIFFERENT trajectory than the uninterrupted one.
        assert abs(clean_w - crash_w) > 1e-9

    def test_static_snapshot_omitted_momentum_is_error(self):
        env = StreamExecutionEnvironment(parallelism=1)
        (env.from_collection(list(range(8)))
            .key_by(lambda x: 0)
            .process(MiniMomentumTrain(), name="mini_train")
            .sink_to_list())
        errs = errors(by_rule(analyze(env.graph, config=env.config),
                              "statecheck-hidden-state"))
        assert errs and errs[0].node == "mini_train"
        assert "self._m" in errs[0].message
        assert "snapshot-omitted" in errs[0].message
        # The DECLARED half must not be flagged.
        assert not any("self._w" in d.message for d in errs)


def _toy_model_def(shape=(16, 8)):
    from flink_tensorflow_tpu.models import ModelDef
    from flink_tensorflow_tpu.tensors import RecordSchema, spec

    schema = RecordSchema({"x": spec((shape[0],)),
                           "label": spec((), np.int32)})
    return ModelDef(
        architecture="toy", config={}, module=None, input_schema=schema,
        methods={},
        init_fn=lambda rng: {"params": {"wo": jnp.zeros(shape),
                                        "wi": jnp.zeros(shape[::-1])}},
        loss_fn=lambda params, batch: jnp.float32(0.0),
    ), schema


def _train_plan(optimizer, *, model_shape=(16, 8), spec_layout=None,
                mesh_axes=None):
    import optax  # noqa: F401 - the optimizer param is optax-built

    from flink_tensorflow_tpu.functions import OnlineTrainFunction
    from flink_tensorflow_tpu.tensors import TensorValue

    mdef, schema = _toy_model_def(model_shape)
    f = OnlineTrainFunction(mdef, optimizer, train_schema=schema)
    if spec_layout is not None:
        f.spec_layout = spec_layout
    env = StreamExecutionEnvironment(parallelism=1)
    if mesh_axes is not None:
        from flink_tensorflow_tpu.parallel import abstract_mesh

        env.set_mesh(abstract_mesh(mesh_axes))
    recs = [TensorValue({"x": np.zeros(model_shape[0], np.float32),
                         "label": np.int32(0)}, meta={"k": 0})]
    (env.from_collection(recs, schema=schema)
        .key_by(lambda r: r.meta["k"])
        .process(f, name="train")
        .sink_to_list())
    return env


class TestTrainStateAudit:
    def test_dtype_drift_between_params_and_moments_warns(self):
        import optax

        env = _train_plan(optax.adam(1e-2, mu_dtype=jnp.bfloat16))
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-train-state")
        drift = [d for d in diags if "dtype drift" in d.message]
        assert drift and drift[0].severity == Severity.WARN
        assert "bfloat16" in drift[0].message

    def test_aligned_dtypes_stay_clean(self):
        import optax

        env = _train_plan(optax.adam(1e-2))
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-train-state")
        assert [d for d in diags if "dtype drift" in d.message] == []

    def test_moment_sharded_away_from_param_is_error(self):
        """Closes the PR-16 optimizer-state deferral: a moment leaf
        whose NAME loses the out-proj hint places (fsdp, tp) while its
        param places (tp, fsdp) — caught abstractly, no mesh attached."""
        import optax

        from flink_tensorflow_tpu.analysis import SpecLayout

        def renamed_init(params):
            return {"slots": {"moment_a": jnp.zeros((16, 8)),
                              "moment_b": jnp.zeros((8, 16))}}

        opt = optax.GradientTransformation(
            renamed_init, lambda g, s, p=None: (g, s))
        env = _train_plan(
            opt, spec_layout=SpecLayout(fsdp_axis="fsdp", tp_axis="tp"),
            mesh_axes={"fsdp": 2, "tp": 2})
        errs = errors(by_rule(analyze(env.graph, config=env.config),
                              "statecheck-train-state"))
        assert errs and errs[0].node == "train"
        assert "slots/moment_a" in errs[0].message
        assert "params/wo" in errs[0].message

    def test_undonated_large_train_state_warns(self):
        import optax

        env = _train_plan(optax.adam(1e-2), model_shape=(1024, 512))
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-train-state")
        donate = [d for d in diags if "not donated" in d.message]
        assert donate and donate[0].severity == Severity.WARN
        assert "MiB" in donate[0].message

    def test_small_train_state_donation_is_quiet(self):
        import optax

        env = _train_plan(optax.adam(1e-2), model_shape=(8, 4))
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-train-state")
        assert [d for d in diags if "not donated" in d.message] == []


class TestRescaleSafety:
    def _plan(self, *, scope="subtask", checkpoint=True, autoscale=False):
        import dataclasses

        import optax

        from flink_tensorflow_tpu.functions import OnlineTrainFunction
        from flink_tensorflow_tpu.tensors import TensorValue

        mdef, schema = _toy_model_def()
        env = StreamExecutionEnvironment(parallelism=1)
        if checkpoint:
            env.enable_checkpointing("/tmp/statecheck-rescale-lint",
                                     interval_s=10)
        if autoscale:
            from flink_tensorflow_tpu.core.autoscale import AutoscaleConfig
            from flink_tensorflow_tpu.core.config import HealthConfig

            env.config = dataclasses.replace(
                env.config, health=HealthConfig(autoscale=AutoscaleConfig()))
        recs = [TensorValue({"x": np.zeros(16, np.float32),
                             "label": np.int32(0)}, meta={"k": 0})]
        (env.from_collection(recs, schema=schema)
            .key_by(lambda r: r.meta["k"])
            .process(OnlineTrainFunction(mdef, optax.sgd(0.1),
                                         train_schema=schema, scope=scope),
                     name="train")
            .sink_to_list())
        return env

    def test_subtask_scope_under_checkpoint_warns(self):
        env = self._plan()
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-rescale")
        assert diags and diags[0].severity == Severity.WARN
        assert "StateNotRescalable" in diags[0].message

    def test_subtask_scope_under_autoscale_is_error(self):
        env = self._plan(autoscale=True)
        errs = errors(by_rule(analyze(env.graph, config=env.config),
                              "statecheck-rescale"))
        assert errs and "health.autoscale" in errs[0].message

    def test_key_scope_redistributes_info_only(self):
        env = self._plan(scope="key")
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-rescale")
        assert diags and all(d.severity == Severity.INFO for d in diags)
        assert "key group" in diags[0].message

    def _gang_plan(self, global_batch):
        import optax

        from flink_tensorflow_tpu.functions import DPTrainWindowFunction
        from flink_tensorflow_tpu.tensors import TensorValue

        mdef, schema = _toy_model_def()
        env = StreamExecutionEnvironment(parallelism=1)
        recs = [TensorValue({"x": np.zeros(16, np.float32),
                             "label": np.int32(0)}, meta={"k": 0})]
        (env.from_collection(recs, schema=schema)
            .key_by(lambda r: 0)
            .count_window(global_batch)
            .apply(DPTrainWindowFunction(mdef, optax.sgd(0.1),
                                         train_schema=schema,
                                         global_batch=global_batch),
                   name="gang")
            .sink_to_list())
        return env

    def test_gang_ladder_indivisible_batch_warns(self):
        env = self._gang_plan(24)  # 24 % 16 != 0: p'=16 rung breaks
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-rescale")
        bad = [d for d in diags if "reshard ladder" in d.message
               and d.severity == Severity.WARN]
        assert bad and "p′=16" in bad[0].message

    def test_gang_ladder_divisible_batch_is_info(self):
        env = self._gang_plan(32)
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-rescale")
        assert diags and all(d.severity == Severity.INFO for d in diags)
        assert "divides cleanly" in diags[0].message


# ---------------------------------------------------------------------------
# defect 4 — non-replayable source -> non-idempotent sink
# ---------------------------------------------------------------------------


class DestructiveSource(fn.SourceFunction):
    """The seeded defect: consumes a SHARED queue destructively (a live
    feed) — after a restore there is nothing left to rewind into."""

    replayable = False

    def __init__(self, queue):
        self.queue = queue

    def clone(self):
        return type(self)(self.queue)

    def run(self):
        while self.queue:
            yield self.queue.pop(0)


class EffectSink(fn.SinkFunction):
    """Non-idempotent side-effect sink: every invoke APPENDS."""

    idempotent = False

    def __init__(self, box):
        self.box = box

    def clone(self):
        return type(self)(self.box)

    def invoke(self, value):
        self.box.append(value)


class CrashMap(fn.MapFunction):
    def __init__(self, crash_at, crashed_box):
        self.crash_at = crash_at
        self.crashed = crashed_box
        self._seen = 0

    def clone(self):
        return type(self)(self.crash_at, self.crashed)

    def map(self, value):
        self._seen += 1
        if not self.crashed[0] and self._seen >= self.crash_at:
            self.crashed[0] = True
            raise RuntimeError("injected failure")
        return value

    def snapshot_state(self):
        return {"seen": self._seen}

    def restore_state(self, state):
        self._seen = state["seen"]


class TestExactlyOncePath:
    N = 60

    def test_runtime_restore_loses_records(self, tmp_path):
        box = []
        crashed = [False]
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / "chk"), every_n_records=20)
        (env.from_source(DestructiveSource(list(range(self.N))), name="live")
            .map(CrashMap(30, crashed), name="relay")
            .add_sink(EffectSink(box), name="effects"))
        result = run_with_restart(env)
        assert result.restarts == 1
        # The restored source offset points into a stream that no
        # longer exists: records the first attempt consumed past the
        # checkpoint are gone for good.
        assert set(box) != set(range(self.N))
        assert len(set(box)) < self.N

    def _plan(self, sink):
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing("/tmp/statecheck-eob-lint", interval_s=10)
        (env.from_source(DestructiveSource([1, 2, 3]), name="live")
            .map(lambda x: x + 1, name="relay")
            .add_sink(sink, name="effects"))
        return env

    def test_static_path_to_nonidempotent_sink_is_error(self):
        env = self._plan(EffectSink([]))
        diags = by_rule(analyze(env.graph, config=env.config),
                        "exactly-once-boundary")
        warns = [d for d in diags if d.severity == Severity.WARN]
        errs = errors(diags)
        # Back-compat boundary WARN at the source, plus the promoted
        # full-path ERROR at the sink.
        assert warns and warns[0].node == "live"
        assert "FileSplitSource" in warns[0].message
        assert errs and errs[0].node == "effects"
        assert "live -> relay -> effects" in errs[0].message
        assert "idempotent=False" in errs[0].message

    def test_static_transactional_sink_absorbs_to_info(self, tmp_path):
        from flink_tensorflow_tpu.io.files import ExactlyOnceRecordFileSink

        env = self._plan(ExactlyOnceRecordFileSink(str(tmp_path / "out")))
        diags = by_rule(analyze(env.graph, config=env.config),
                        "exactly-once-boundary")
        assert errors(diags) == []
        infos = [d for d in diags if d.severity == Severity.INFO]
        assert infos and "absorbed" in infos[0].message

    def test_static_wal_fronted_source_is_clean(self, tmp_path):
        from flink_tensorflow_tpu.io.files import write_record_file
        from flink_tensorflow_tpu.sources import FileSplitSource
        from flink_tensorflow_tpu.tensors import TensorValue

        path = str(tmp_path / "wal.rec")
        write_record_file(path, [TensorValue({"x": np.float32(1.0)})])
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / "chk"), interval_s=10)
        (env.from_source(FileSplitSource(path), name="wal")
            .add_sink(EffectSink([]), name="effects"))
        assert by_rule(analyze(env.graph, config=env.config),
                       "exactly-once-boundary") == []


# ---------------------------------------------------------------------------
# paged-KV key-group partition (closes the PR-19 deferral)
# ---------------------------------------------------------------------------


def _serving_plan(serving_config, max_parallelism):
    import dataclasses

    from flink_tensorflow_tpu import serving
    from flink_tensorflow_tpu.models import get_model_def

    mdef = get_model_def("char_transformer", vocab_size=32, embed_dim=16,
                         num_heads=2, num_layers=1, capacity=32)
    model = mdef.to_model(mdef.init_params(jax.random.PRNGKey(0)))
    requests = [serving.GenerateRequest(session_id="s0", prompt=[1, 2],
                                        max_new_tokens=2)]
    env = StreamExecutionEnvironment(parallelism=1)
    env.config = dataclasses.replace(env.config,
                                     max_parallelism=max_parallelism)
    (serving.continuous_batching(
        env.from_collection(requests).key_by(lambda r: r.session_id),
        model, config=serving_config, name="serve")
        .sink_to_list())
    return env


class TestPageKeygroupPartition:
    def test_indivisible_page_pool_warns_with_pool_provenance(self):
        from flink_tensorflow_tpu.serving import ServingConfig

        env = _serving_plan(
            ServingConfig(max_active_seqs=2, token_budget=64, capacity=32,
                          paged_kv=True, page_tokens=16, hbm_pages=12),
            max_parallelism=8)
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-page-keygroup")
        assert diags and diags[0].severity == Severity.WARN
        msg = diags[0].message
        assert "PagedKVPool" in msg and "12 pages" in msg
        assert "page_tokens=16" in msg and "8 key groups" in msg

    def test_divisible_page_pool_is_info(self):
        from flink_tensorflow_tpu.serving import ServingConfig

        env = _serving_plan(
            ServingConfig(max_active_seqs=2, token_budget=64, capacity=32,
                          paged_kv=True, page_tokens=16, hbm_pages=16),
            max_parallelism=8)
        diags = by_rule(analyze(env.graph, config=env.config),
                        "statecheck-page-keygroup")
        assert diags and diags[0].severity == Severity.INFO
        assert "pages, not sessions" in diags[0].message

    def test_dense_pool_stays_silent(self):
        from flink_tensorflow_tpu.serving import ServingConfig

        env = _serving_plan(
            ServingConfig(max_active_seqs=2, token_budget=64, capacity=32),
            max_parallelism=8)
        assert by_rule(analyze(env.graph, config=env.config),
                       "statecheck-page-keygroup") == []


# ---------------------------------------------------------------------------
# healthy plan: declared state is byte-identical across a crash AND
# audits clean
# ---------------------------------------------------------------------------


class KeyedCounter(fn.ProcessFunction):
    """Declared-state-only running count (the FailOnce shape)."""

    def __init__(self, crash_at=None, crashed_box=None):
        self.crash_at = crash_at
        self.crashed = crashed_box if crashed_box is not None else [True]
        self._seen = 0

    def clone(self):
        return type(self)(self.crash_at, self.crashed)

    def process_element(self, value, ctx, out):
        self._seen += 1
        if (self.crash_at and not self.crashed[0]
                and self._seen >= self.crash_at):
            self.crashed[0] = True
            raise RuntimeError("injected failure")
        count = ctx.state(StateDescriptor("count", lambda: 0))
        count.update((count.value() or 0) + 1)
        out.collect((ctx.current_key, count.value()))

    def snapshot_state(self):
        return {"seen": self._seen}

    def restore_state(self, state):
        self._seen = state["seen"]


class TestHealthyPlan:
    N = 80

    def test_declared_state_is_byte_identical_across_crash(self, tmp_path):
        def run(tag, crash):
            crashed = [False] if crash else [True]
            env = StreamExecutionEnvironment(parallelism=1)
            env.enable_checkpointing(str(tmp_path / f"chk-{tag}"),
                                     every_n_records=20)
            out = (env.from_collection(list(range(self.N)))
                      .key_by(lambda x: x % 4)
                      .process(KeyedCounter(50 if crash else None, crashed),
                               name="count")
                      .sink_to_list())
            result = (run_with_restart(env) if crash
                      else env.execute(timeout=120))
            return _final_by_key(out), getattr(result, "restarts", 0)

        clean, _ = run("clean", False)
        crashed, restarts = run("crash", True)
        assert restarts == 1
        assert clean == crashed == {k: self.N // 4 for k in range(4)}

    def test_healthy_plan_audits_zero_statecheck_errors(self, tmp_path):
        env = StreamExecutionEnvironment(parallelism=1)
        env.enable_checkpointing(str(tmp_path / "chk"), interval_s=10)
        (env.from_collection(list(range(8)))
            .key_by(lambda x: x % 4)
            .process(KeyedCounter(), name="count")
            .sink_to_list())
        diags = [d for d in analyze(env.graph, config=env.config)
                 if d.rule.startswith("statecheck")
                 or d.rule == "exactly-once-boundary"]
        assert errors(diags) == []


# ---------------------------------------------------------------------------
# interprocedural depth (satellite: lifts the PR-16 one-level limit)
# ---------------------------------------------------------------------------


def _leaf_helper():
    return time.time()


def _mid_helper():
    return _leaf_helper() + 1.0


def _deep_helper():  # depth 3 below outer: past the default cap
    return _leaf_helper()


def _mid2_helper():
    return _deep_helper()


def _cycle_a(n):
    return _cycle_b(n - 1) if n else 0


def _cycle_b(n):
    return _cycle_a(n) + time.time()


class TestInterproceduralDepth:
    def test_two_level_provenance_chain(self):
        from flink_tensorflow_tpu.analysis import scan_code

        def outer(x):
            return x + _mid_helper()

        findings = scan_code(outer.__code__, outer.__globals__, where="outer")
        clocks = [f for f in findings if f.kind == "wall-clock"]
        assert clocks, "helper-of-helper impurity must surface"
        assert clocks[0].where == "outer -> _mid_helper -> _leaf_helper"

    def test_depth_cap_is_configurable(self):
        from flink_tensorflow_tpu.analysis import scan_code

        def outer(x):
            return x + _mid2_helper()

        # _leaf_helper sits 3 calls deep: invisible at the default 2...
        default = scan_code(outer.__code__, outer.__globals__, where="outer")
        assert [f for f in default if f.kind == "wall-clock"] == []
        # ...visible at 3.
        deep = scan_code(outer.__code__, outer.__globals__, where="outer",
                         max_depth=3)
        clocks = [f for f in deep if f.kind == "wall-clock"]
        assert clocks
        assert clocks[0].where == (
            "outer -> _mid2_helper -> _deep_helper -> _leaf_helper")

    def test_cycle_guard_terminates_and_still_finds(self):
        from flink_tensorflow_tpu.analysis import scan_code

        def outer(x):
            return _cycle_a(x)

        findings = scan_code(outer.__code__, outer.__globals__, where="outer",
                             max_depth=10)
        assert any(f.kind == "wall-clock" for f in findings)

    def test_scan_cache_rehosts_provenance(self):
        from flink_tensorflow_tpu.analysis import scan_code
        from flink_tensorflow_tpu.analysis.sanitizer import _SCAN_CACHE

        def first(x):
            return _mid_helper() + x

        def second(x):
            return _mid_helper() * x

        a = scan_code(first.__code__, first.__globals__, where="first")
        assert id(_mid_helper.__code__) in _SCAN_CACHE
        b = scan_code(second.__code__, second.__globals__, where="second")
        wa = [f.where for f in a if f.kind == "wall-clock"]
        wb = [f.where for f in b if f.kind == "wall-clock"]
        assert wa == ["first -> _mid_helper -> _leaf_helper"]
        assert wb == ["second -> _mid_helper -> _leaf_helper"]


# ---------------------------------------------------------------------------
# report shape, CLI exit codes, doctor fold
# ---------------------------------------------------------------------------


CLEAN_PIPELINE = """
import sys
sys.path.insert(0, {repo!r})
from flink_tensorflow_tpu import StreamExecutionEnvironment


def main(argv=None):
    env = StreamExecutionEnvironment(parallelism=1)
    env.from_collection([1, 2, 3]).map(lambda x: x + 1).sink_to_list()
    env.execute("clean", timeout=60)
"""

DEFECT_PIPELINE = """
import sys
sys.path.insert(0, {repo!r})
from flink_tensorflow_tpu import StreamExecutionEnvironment

TRAIN_STATE = {{"variables": {{"w": 0.0}}, "opt_state": {{"count": 0}}}}


def main(argv=None):
    env = StreamExecutionEnvironment(parallelism=1)

    def step(v):
        TRAIN_STATE["opt_state"]["count"] += 1
        return v

    env.from_collection([1, 2, 3]).map(step, name="leaky").sink_to_list()
    env.execute("defect", timeout=60)
"""


def _write_pipeline(tmp_path, name, template):
    import pathlib

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    p = tmp_path / name
    p.write_text(template.format(repo=repo))
    return str(p)


class TestReportAndCli:
    def _defect_report(self, tmp_path):
        from flink_tensorflow_tpu.analysis import (
            capture_pipeline_file,
            statecheck_report_for_env,
        )

        path = _write_pipeline(tmp_path, "defect_pipeline.py",
                               DEFECT_PIPELINE)
        env = capture_pipeline_file(path)
        return statecheck_report_for_env(env, pipeline=path)

    def test_report_shape(self, tmp_path):
        report = self._defect_report(tmp_path)
        assert set(report) >= {"operators", "findings", "pipeline", "errors"}
        assert report["errors"] >= 1
        hidden = [f for f in report["findings"]
                  if f["rule"] == "statecheck-hidden-state"]
        assert hidden and hidden[0]["severity"] == "ERROR"
        assert hidden[0]["node"] == "leaky"
        leaky = [o for o in report["operators"] if o["node"] == "leaky"]
        assert leaky and leaky[0]["hidden_state"]

    def test_cli_exit_codes(self, tmp_path, capsys):
        from flink_tensorflow_tpu.analysis.statecheck import main

        clean = _write_pipeline(tmp_path, "clean_pipeline.py",
                                CLEAN_PIPELINE)
        defect = _write_pipeline(tmp_path, "defect_pipeline.py",
                                 DEFECT_PIPELINE)
        assert main([clean]) == 0
        assert main([defect]) == 1
        assert main([str(tmp_path / "missing.py")]) == 2
        capsys.readouterr()

    def test_cli_json_out(self, tmp_path, capsys):
        from flink_tensorflow_tpu.analysis.statecheck import main

        defect = _write_pipeline(tmp_path, "defect_pipeline.py",
                                 DEFECT_PIPELINE)
        out = tmp_path / "report.json"
        assert main([defect, "--json", "--out", str(out)]) == 1
        printed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        saved = json.loads(out.read_text())
        assert printed["errors"] == saved["errors"] >= 1

    def test_doctor_folds_statecheck_report(self, tmp_path, capsys):
        from flink_tensorflow_tpu.tracing import doctor

        report = self._defect_report(tmp_path)
        path = tmp_path / "statecheck.json"
        path.write_text(json.dumps(report))
        rc = doctor.main(["--statecheck", str(path), "--report-only"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "statecheck ERROR" in out
        assert "statecheck-hidden-state" in out

    def test_doctor_diagnose_keys_statecheck(self, tmp_path):
        from flink_tensorflow_tpu.tracing.doctor import diagnose

        report = self._defect_report(tmp_path)
        diag = diagnose(statecheck_report=report)
        assert diag["statecheck"]
        assert any("statecheck-hidden-state" in line
                   for line in diag["findings"])

    def test_bare_graph_without_config_skips_dataflow(self):
        env = StreamExecutionEnvironment(parallelism=1)
        env.from_source(DestructiveSource([1]), name="live").sink_to_list()
        assert by_rule(analyze(env.graph), "exactly-once-boundary") == []


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
