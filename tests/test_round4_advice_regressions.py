"""Pins for the round-3 advisor findings (ADVICE.md r3).

1. (medium) Cohort restore was impossible when num_processes exceeded the
   job's max operator parallelism: idle processes own no subtasks and
   never write proc-* shards, yet completeness required process indices
   {0..P-1}.  Shards now record the PARTICIPANT set (processes owning
   >= 1 subtask) and completeness is validated against it.
2. (low) A degenerate compute probe put float('nan') into the bench
   JSON (non-RFC-8259).  bench.py now emits None and dumps with
   allow_nan=False behind a recursive NaN/inf sanitizer.
3. (low) MapOperator flushes the async micro-batch before every
   watermark; with watermark_every=1 that silently degrades to
   batch-of-1 — now documented on ModelMapFunction (behavioral pin
   below: the flush itself must still happen, it is load-bearing for
   event-time safety).
4. (low) The global commit gate could stall teardown: no cancellation
   check before/between peer announcements, and a control writer's
   connect-retry loop ignored close().  Both paths now abort promptly.
"""

import json
import math
import os
import signal
import socket
import threading
import time
import types

import pytest

from flink_tensorflow_tpu.checkpoint.store import (
    read_cohort_checkpoint,
    select_cohort_checkpoint,
    write_checkpoint,
)


def _write_shard(base, proc, cid, *, num_processes, participants, tasks):
    import os

    job = {0: {"max_parallelism": 128, "num_processes": num_processes,
               "process_index": proc, "task_parallelism": {}}}
    if participants is not None:
        job[0]["participants"] = list(participants)
    snaps = {"__job__": job}
    for task, idx in tasks:
        snaps.setdefault(task, {})[idx] = {"x": idx}
    write_checkpoint(os.path.join(base, f"proc-{proc:05d}"), cid, snaps)


class TestOverprovisionedCohortRestore:
    """ADVICE r3 medium: num_processes=3 but max parallelism 2 — only
    processes 0 and 1 own subtasks and write shards; the checkpoint must
    still be restorable."""

    def test_participant_shards_form_complete_set(self, tmp_path):
        base = str(tmp_path)
        for p in range(2):  # process 2 is idle: writes nothing
            _write_shard(base, p, 1, num_processes=3, participants=[0, 1],
                         tasks=[("op", p)])
        cid, shards = select_cohort_checkpoint(base)
        assert cid == 1 and len(shards) == 2
        cid, snaps = read_cohort_checkpoint(base)
        assert sorted(snaps["op"]) == [0, 1]

    def test_lost_participant_shard_still_loud(self, tmp_path):
        """The participant set must not weaken the loss check: with
        participants {0,1} and only proc-0's shard present, restore
        refuses rather than silently dropping proc-1's state."""
        base = str(tmp_path)
        _write_shard(base, 0, 1, num_processes=3, participants=[0, 1],
                     tasks=[("op", 0)])
        with pytest.raises(ValueError, match="INCOMPLETE"):
            select_cohort_checkpoint(base, 1)
        with pytest.raises(FileNotFoundError):
            select_cohort_checkpoint(base)

    def test_r3_shards_without_participant_set_still_work(self, tmp_path):
        """Shards written before the participant set existed imply
        participants = {0..P-1} (the r3 rule), both ways."""
        base = str(tmp_path)
        for p in range(2):
            _write_shard(base, p, 1, num_processes=2, participants=None,
                         tasks=[("op", p)])
        cid, shards = select_cohort_checkpoint(base)
        assert cid == 1 and len(shards) == 2
        _write_shard(base, 0, 2, num_processes=2, participants=None,
                     tasks=[("op", 0)])
        with pytest.raises(ValueError, match="INCOMPLETE"):
            select_cohort_checkpoint(base, 2)

    def test_executor_records_participants(self, tmp_path):
        """The distributed executor's shard metadata carries the
        participant set it computes for the commit gate — the two must
        never diverge (restore validates what commit awaited)."""
        from flink_tensorflow_tpu import (
            DistributedConfig,
            StreamExecutionEnvironment,
        )

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        env = StreamExecutionEnvironment(parallelism=1)
        env.set_distributed(
            DistributedConfig(0, 1, (f"127.0.0.1:{port}",)))
        env.enable_checkpointing(str(tmp_path / "chk"), every_n_records=4)
        env.from_collection(list(range(8)), parallelism=1).sink_to_list()
        env.execute("participants-meta", timeout=60)
        cid, shards = select_cohort_checkpoint(str(tmp_path / "chk"))
        meta_path = f"{shards[0]}/chk-{cid:06d}/METADATA.json"
        with open(meta_path) as f:
            job = json.load(f)["job"]
        assert job["participants"] == [0]
        assert job["num_processes"] == 1


class TestOverprovisionedCohortEndToEnd:
    """The full ADVICE r3 medium scenario with real processes: a
    2-process cohort whose job has max parallelism 1, so process 1 is
    idle and writes no shard.  Kill the working process mid-stream, then
    restore the SAME over-provisioned cohort — pre-fix, restore raised
    'no complete cohort shard set' forever."""

    def test_kill_and_restore_with_idle_process(self, tmp_path):
        from flink_tensorflow_tpu.parallel import latest_common_checkpoint
        from test_distributed_plane import (
            _free_ports,
            _read_sorted,
            _spawn,
            _wait,
            expected_emissions,
        )

        out, chk = str(tmp_path / "out"), str(tmp_path / "chk")
        ports = _free_ports(2)

        def spawn(index, restore_id=-1):
            return _spawn(index, ports, out, chk=chk, n=240, every=40,
                          par=1, throttle=0.005, restore_id=restore_id)

        procs = [spawn(i) for i in range(2)]
        # Only proc-00000 writes shards (participants == {0}).
        shard0 = [os.path.join(chk, "proc-00000")]
        deadline = time.monotonic() + 60.0
        common = None
        while time.monotonic() < deadline:
            common = latest_common_checkpoint(shard0)
            if common is not None or procs[0].poll() is not None:
                break
            time.sleep(0.02)
        assert common is not None, "no checkpoint before worker 0 exited"
        procs[0].send_signal(signal.SIGKILL)
        for p in procs:
            _wait(p)

        common = latest_common_checkpoint(shard0)
        procs = [spawn(i, restore_id=common) for i in range(2)]
        for p in procs:
            rc, log = _wait(p)
            assert rc == 0, f"restored worker failed:\n{log}"
        assert _read_sorted(out) == expected_emissions(240)


class TestBenchJsonStrict:
    def test_json_safe_maps_nan_inf_to_none(self):
        import bench

        dirty = {"a": float("nan"), "b": [1.0, float("inf")],
                 "c": {"d": -float("inf"), "e": 2}, "f": "nan"}
        clean = bench._json_safe(dirty)
        assert clean == {"a": None, "b": [1.0, None],
                         "c": {"d": None, "e": 2}, "f": "nan"}
        # The pinned invariant: the emitted line parses under strict mode.
        line = json.dumps(clean, allow_nan=False)
        assert json.loads(line) == clean

    def test_degenerate_compute_probe_emits_null_not_nan(self):
        """The original finding's exact site: compute_rps=None must
        produce device_compute_s: null."""
        compute_rps = None
        batch_compute_s = 64 / compute_rps if compute_rps else None
        assert batch_compute_s is None
        out = {"device_compute_s": (
            round(batch_compute_s, 5) if batch_compute_s is not None else None)}
        assert "NaN" not in json.dumps(out, allow_nan=False)
        assert not any(
            isinstance(v, float) and not math.isfinite(v) for v in out.values())


class TestWatermarkFlushStillLoadBearing:
    def test_async_map_flushes_before_watermark(self):
        """The documented degradation (ADVICE r3 low #3) must not be
        'fixed' by dropping the flush: in-flight async results may never
        arrive behind the watermark that covers them."""
        from flink_tensorflow_tpu.core import elements as el
        from flink_tensorflow_tpu.core import functions as fn
        from flink_tensorflow_tpu.core.operators import MapOperator, Output
        from flink_tensorflow_tpu.core.state import KeyedStateStore

        class Buffering(fn.AsyncMapFunction):
            def __init__(self):
                self.buf = []

            def map_async(self, value, collector):
                self.buf.append(value)

            def flush(self, collector):
                for v in self.buf:
                    collector.collect(v * 10)
                self.buf.clear()

        op = MapOperator("m", Buffering())
        emitted, wms = [], []
        op.setup(None, Output([(None, [])]), KeyedStateStore())
        op.output.emit = lambda v, ts=None: emitted.append(v)
        op.output.broadcast_element = lambda e: wms.append(e.timestamp)
        op.open()
        op.process_record(el.StreamRecord(1, 0.5))
        op.process_record(el.StreamRecord(2, 0.6))
        assert emitted == []  # buffered, pipelined
        op.process_watermark(el.Watermark(1.0))
        # Results surfaced BEFORE the watermark was forwarded.
        assert emitted == [10, 20]
        assert wms == [1.0]


class TestCommitGateTeardown:
    def test_writer_connect_aborts_on_close(self):
        """A writer spinning in its connect-retry loop (peer dead) must
        abort within ~1 poll interval of close(), not wait out the full
        connect timeout."""
        from flink_tensorflow_tpu.core import elements as el
        from flink_tensorflow_tpu.core.shuffle import RemoteChannelWriter

        # A port with no listener: connect refuses instantly, so the
        # writer sits in its retry/sleep loop.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        w = RemoteChannelWriter("127.0.0.1", dead_port, "op", 0, 0,
                                connect_timeout_s=30.0)
        done = threading.Event()

        def attempt():
            try:
                w.write(el.StreamRecord(1))
            except (OSError, TimeoutError):
                pass
            done.set()

        t = threading.Thread(target=attempt, daemon=True)
        t.start()
        time.sleep(0.3)  # let it enter the retry loop
        start = time.monotonic()
        w.close()
        assert done.wait(5.0), "close() did not abort the connect loop"
        assert time.monotonic() - start < 5.0

    def test_gate_checks_cancellation_before_announcing(self):
        """A cancelled executor's gate returns False without touching the
        network (pre-fix it could first block a full connect timeout in
        a lazily-created control writer)."""
        from flink_tensorflow_tpu.core.distributed import (
            DistributedConfig,
            DistributedExecutor,
        )

        stub = types.SimpleNamespace(
            dist=DistributedConfig(
                0, 2, ("127.0.0.1:1", "127.0.0.1:2")).validate(),
            _participants=frozenset({0, 1}),
            _control_writers={},
            _durable_acks={},
            _durable_cv=threading.Condition(),
            cancelled=threading.Event(),
            checkpoint_timeout_s=60.0,
        )
        stub.cancelled.set()
        start = time.monotonic()
        ok = DistributedExecutor._global_commit_gate(stub, 1)
        assert ok is False
        assert time.monotonic() - start < 1.0
        # No control writer was created for the unreachable peer.
        assert stub._control_writers == {}
