"""Metrics — counters, meters, gauges, timers, latency histograms per
operator subtask.

The reference exposes Flink metric groups (counters/meters per operator,
SURVEY.md §5 "Metrics").  Here records/sec/chip and p50/p99 per-record
latency are first-class because they ARE the north-star metric
(BASELINE.json:2).  Histograms keep a bounded reservoir so the hot path
stays O(1) with no allocation beyond a float append.

Hot-path contract: push-side operations (``Counter.inc``, ``Meter.mark``,
``Histogram.record``, ``Timer.update``) are O(1) per record.  Everything
pull-based — :class:`Gauge` callbacks, rates, percentiles — is evaluated
only when a reporter (metrics.reporters) or the inspector CLI reads a
:meth:`MetricRegistry.snapshot`, so instrumentation that is never read
costs nothing beyond the increments.
"""

from __future__ import annotations

import threading
import time
import typing
import zlib

import numpy as np


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Meter:
    """Rate meter: events/sec over the job's lifetime and a sliding window.

    Thread-safe: one meter may be marked from several threads (an
    operator's background fetch thread and its subtask thread) while a
    reporter reads it.  ``window_rate()`` is PURE — it never consumes the
    window, so a reporter and user code can both read it; the owner of
    the window cadence calls :meth:`reset_window` explicitly.
    """

    __slots__ = ("count", "_start", "_win_count", "_win_start", "_lock")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.count = 0
        self._start = time.monotonic()
        self._win_count = 0
        self._win_start = self._start

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n
            self._win_count += n

    def rate(self) -> float:
        elapsed = time.monotonic() - self._start
        return self.count / elapsed if elapsed > 0 else 0.0

    def window_rate(self) -> float:
        """Events/sec since the last :meth:`reset_window` — read-only."""
        with self._lock:
            count, start = self._win_count, self._win_start
        elapsed = time.monotonic() - start
        return count / elapsed if elapsed > 0 else 0.0

    def reset_window(self) -> None:
        """Start a fresh rate window (the reporter thread owns the
        cadence; user code reading ``window_rate()`` must not steal it)."""
        with self._lock:
            self._win_count = 0
            self._win_start = time.monotonic()


class Histogram:
    """Bounded-reservoir histogram for latency percentiles.

    The reservoir uses a PER-INSTANCE ``np.random.Generator`` (seeded
    deterministically from the registry's configured seed + the metric's
    scope/name): sampling through the global ``np.random`` state would
    both break the repo's determinism guarantees (user jobs seed the
    global state) and race when other threads draw from it.
    """

    __slots__ = ("_samples", "_capacity", "count", "_rng")

    def __init__(self, capacity: int = 65536,
                 seed: typing.Optional[int] = None):
        self._samples: typing.List[float] = []
        self._capacity = capacity
        self.count = 0
        self._rng = np.random.default_rng(seed)

    def record(self, value: float) -> None:
        self.count += 1
        if len(self._samples) < self._capacity:
            self._samples.append(value)
        else:
            # Reservoir sampling keeps percentiles unbiased under overflow.
            j = int(self._rng.integers(0, self.count))
            if j < self._capacity:
                self._samples[j] = value

    def percentile(self, q: float) -> float:
        if not self._samples:
            return float("nan")
        return float(np.percentile(np.asarray(self._samples), q))

    def summary(self) -> typing.Dict[str, float]:
        return {
            "count": float(self.count),
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
            "mean": float(np.mean(self._samples)) if self._samples else float("nan"),
        }


class Gauge:
    """Pull-based metric: a zero-arg callback evaluated at REPORT time.

    The hot path never touches a gauge — instrumented code exposes live
    state (queue depth, accumulated blocked time, HBM bytes) and the
    reporter thread reads it at its own cadence.  A raising callback
    yields None (a dying metric must never fail a report)."""

    __slots__ = ("_fn",)

    def __init__(self, fn: typing.Callable[[], typing.Any]):
        self._fn = fn

    def set_callback(self, fn: typing.Callable[[], typing.Any]) -> None:
        self._fn = fn

    def value(self) -> typing.Any:
        try:
            return self._fn()
        except Exception:  # noqa: BLE001 - reporting must not kill the job
            return None


class Timer:
    """Duration tracker: a histogram of seconds + total time + count.

    Use as a context manager (``with timer.time(): ...``) or feed
    measured intervals via :meth:`update` when the caller already has
    the two clock reads (the runtime loop does — no extra ``monotonic()``
    calls on the hot path)."""

    __slots__ = ("histogram", "count", "total_s")

    def __init__(self, seed: typing.Optional[int] = None):
        self.histogram = Histogram(seed=seed)
        self.count = 0
        self.total_s = 0.0

    def update(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.histogram.record(seconds)

    class _Span:
        __slots__ = ("_timer", "_t0")

        def __init__(self, timer: "Timer"):
            self._timer = timer
            self._t0 = 0.0

        def __enter__(self) -> "Timer._Span":
            self._t0 = time.monotonic()
            return self

        def __exit__(self, *exc) -> None:
            self._timer.update(time.monotonic() - self._t0)

    def time(self) -> "Timer._Span":
        return Timer._Span(self)

    def summary(self) -> typing.Dict[str, float]:
        out = self.histogram.summary()
        out["total_s"] = self.total_s
        return out


def _strided(samples: typing.Sequence[float], cap: int) -> typing.List[float]:
    """Deterministic down-sample: every k-th reservoir entry, bounded by
    ``cap`` — no RNG, so two exports of the same state are identical
    (the cohort merge's determinism contract)."""
    n = len(samples)
    if n <= cap:
        return [float(s) for s in samples]
    stride = (n + cap - 1) // cap
    return [float(samples[i]) for i in range(0, n, stride)]


class MetricGroup:
    """Namespaced metric container for one operator subtask."""

    def __init__(self, scope: str, registry: "MetricRegistry"):
        self.scope = scope
        self._registry = registry

    def counter(self, name: str) -> Counter:
        return self._registry._get(self.scope, name, Counter)

    def meter(self, name: str) -> Meter:
        return self._registry._get(self.scope, name, Meter)

    def histogram(self, name: str) -> Histogram:
        seed = self._registry.metric_seed(self.scope, name)
        return self._registry._get(
            self.scope, name, lambda: Histogram(seed=seed))

    def timer(self, name: str) -> Timer:
        seed = self._registry.metric_seed(self.scope, name)
        return self._registry._get(self.scope, name, lambda: Timer(seed=seed))

    def gauge(self, name: str,
              fn: typing.Optional[typing.Callable[[], typing.Any]] = None) -> Gauge:
        """Register (or re-point) a pull-based gauge.  With ``fn`` the
        callback is installed — re-registration replaces it (a restarted
        operator re-binds its gauges to fresh state); without ``fn`` the
        existing gauge is returned for reading."""
        gauge = self._registry._get(
            self.scope, name, lambda: Gauge(fn if fn is not None else lambda: None))
        if fn is not None:
            gauge.set_callback(fn)
        return gauge


class MetricRegistry:
    """All metrics of one job, keyed by (scope, name).

    ``seed`` makes every histogram reservoir deterministic: each metric
    derives its own generator seed from (seed, scope, name), so two runs
    of the same seeded job sample identically regardless of thread
    interleaving elsewhere.  ``seed=None`` keeps instance-local
    OS-entropy generators (still race-free, just not reproducible).
    """

    def __init__(self, seed: typing.Optional[int] = None) -> None:
        self.seed = seed
        self._metrics: typing.Dict[typing.Tuple[str, str], typing.Any] = {}
        self._lock = threading.Lock()

    def metric_seed(self, scope: str, name: str) -> typing.Optional[int]:
        """Stable per-metric seed derived from the registry seed."""
        if self.seed is None:
            return None
        return zlib.crc32(f"{self.seed}/{scope}/{name}".encode())

    def _get(self, scope: str, name: str, factory: typing.Callable[[], typing.Any]):
        key = (scope, name)
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                metric = factory()
                self._metrics[key] = metric
            return metric

    def group(self, scope: str) -> MetricGroup:
        return MetricGroup(scope, self)

    def all_metrics(self) -> typing.Dict[typing.Tuple[str, str], typing.Any]:
        with self._lock:
            return dict(self._metrics)

    @staticmethod
    def _read(metric: typing.Any) -> typing.Any:
        if isinstance(metric, Counter):
            return metric.value
        if isinstance(metric, Meter):
            return {"count": metric.count, "rate": metric.rate(),
                    "window_rate": metric.window_rate()}
        if isinstance(metric, Timer):
            return metric.summary()
        if isinstance(metric, Histogram):
            return metric.summary()
        if isinstance(metric, Gauge):
            return metric.value()
        return metric

    def report(self) -> typing.Dict[str, typing.Any]:
        """Flat ``{scope.name: value}`` view (the legacy JobResult shape)."""
        out: typing.Dict[str, typing.Any] = {}
        for (scope, name), metric in self.all_metrics().items():
            out[f"{scope}.{name}"] = self._read(metric)
        return out

    def snapshot(self) -> typing.Dict[str, typing.Dict[str, typing.Any]]:
        """Scope-tree view ``{scope: {metric: value}}`` — what reporters
        and the inspector CLI consume.  Gauges are evaluated here (pull),
        meters are read without consuming their window."""
        tree: typing.Dict[str, typing.Dict[str, typing.Any]] = {}
        for (scope, name), metric in self.all_metrics().items():
            tree.setdefault(scope, {})[name] = self._read(metric)
        return tree

    def export_state(self, max_samples: int = 512) -> typing.Dict[str, typing.Dict[str, tuple]]:
        """Transferable per-metric STATE tree ``{scope: {name: (kind,
        payload)}}`` — what a cohort process pushes to the process-0
        collector (metrics/cohort.py).  Unlike :meth:`snapshot` this
        keeps histogram/timer RESERVOIR SAMPLES (strided down to
        ``max_samples`` so a push frame stays small) so the collector
        can merge distributions instead of averaging percentiles, and
        evaluates gauges to plain values so the receiving side applies
        an aggregation policy per name."""
        tree: typing.Dict[str, typing.Dict[str, tuple]] = {}
        for (scope, name), metric in self.all_metrics().items():
            if isinstance(metric, Counter):
                entry = ("counter", metric.value)
            elif isinstance(metric, Meter):
                entry = ("meter", {"count": metric.count,
                                   "rate": metric.rate(),
                                   "window_rate": metric.window_rate()})
            elif isinstance(metric, Timer):
                entry = ("timer", {
                    "count": metric.count, "total_s": metric.total_s,
                    "samples": _strided(metric.histogram._samples, max_samples),
                })
            elif isinstance(metric, Histogram):
                entry = ("histogram", {
                    "count": metric.count,
                    "samples": _strided(metric._samples, max_samples),
                })
            elif isinstance(metric, Gauge):
                entry = ("gauge", metric.value())
            else:
                entry = ("value", metric)
            tree.setdefault(scope, {})[name] = entry
        return tree

    def reset_windows(self) -> None:
        """Start a fresh window on every meter — the reporter thread calls
        this once per report so window rates mean "since last report"."""
        for metric in self.all_metrics().values():
            if isinstance(metric, Meter):
                metric.reset_window()
