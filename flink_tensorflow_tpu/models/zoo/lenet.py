"""LeNet-5 for the MNIST windowed micro-batch workload (BASELINE.json:8).

The reference runs a frozen MNIST LeNet graph inside a windowed
ProcessFunction ("count-window micro-batch").  This is the native flax
definition; weights can be imported from a TF checkpoint via
models.import_tf (gated on TF availability) or trained from scratch in
minutes.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from flink_tensorflow_tpu.models.base import ModelMethod
from flink_tensorflow_tpu.models.zoo.registry import ModelDef, register_model_def
from flink_tensorflow_tpu.tensors.schema import RecordSchema, spec


class LeNet(nn.Module):
    """Classic LeNet-5, NHWC.  Tiny, but still routed through the MXU:
    convs are lowered to matmuls by XLA, and the micro-batch dim keeps
    them fat enough to tile."""

    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.compute_dtype)(x))
        # Logits in float32: cheap, and keeps softmax numerics stable.
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


@register_model_def("lenet")
def build(num_classes: int = 10, image_size: int = 28, channels: int = 1) -> ModelDef:
    module = LeNet(num_classes=num_classes)
    schema = RecordSchema({"image": spec((image_size, image_size, channels), np.float32)})

    def serve(variables, inputs):
        logits = module.apply(variables, inputs["image"])
        return {
            "logits": logits,
            "label": jnp.argmax(logits, axis=-1).astype(jnp.int32),
            "prob": jax.nn.softmax(logits, axis=-1),
        }

    def init_fn(rng):
        return module.init(rng, jnp.zeros((1, image_size, image_size, channels)))

    def loss_fn(variables, batch, rng):
        import optax

        from flink_tensorflow_tpu.models.zoo._common import weighted_metrics

        logits = module.apply(variables, batch["image"])
        labels = batch["label"]
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        hits = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        loss, acc = weighted_metrics(per_ex, hits, batch.get("valid"))
        return loss, ({}, {"loss": loss, "accuracy": acc})

    methods = {
        "serve": ModelMethod(
            name="serve",
            input_schema=schema,
            output_names=("logits", "label", "prob"),
            fn=serve,
            compute_dtype=jnp.bfloat16,
        )
    }
    return ModelDef(
        architecture="lenet",
        config={"num_classes": num_classes, "image_size": image_size, "channels": channels},
        module=module,
        input_schema=schema,
        methods=methods,
        init_fn=init_fn,
        loss_fn=loss_fn,
    )


