"""Channel-layer property/stress tests (SURVEY.md §5 "Race detection":
property tests for the channel layer; VERDICT r1 §2 marked them missing).

The contracts under test:
- per-channel FIFO order survives concurrent multi-producer load,
- bounded capacity gives backpressure (writers block, nothing is lost),
- barrier stash/replay preserves per-channel order and loses nothing
  under randomized block/unblock cycles,
- close() unblocks stuck writers promptly.

Every interleaving runs twice: once on the plain production gate, and
once under ``FLINK_TPU_SANITIZE=1`` with a sanitizer-instrumented gate
(PR 5) — the same properties must hold AND the happens-before recorder
must report zero violations (no lock-order inversion, no delivery past
a blocked channel) across the full randomized schedule.

Slow mode adds a third arm: ``FLINK_TPU_SANITIZE_SHAKE=<seed>``
schedule fuzzing — the instrumented wrappers inject seeded randomized
delays at acquire/wait/notify so interleavings the OS scheduler rarely
produces get exercised under the same invariants (the PR-5 "shake"
deferral).
"""

import random
import threading
import time

import pytest

from flink_tensorflow_tpu.core import elements as el
from flink_tensorflow_tpu.core import sanitizer_rt
from flink_tensorflow_tpu.core.channels import ChannelWriter, InputGate
from flink_tensorflow_tpu.core.sanitizer_rt import ConcurrencySanitizer


def _rec(v):
    return el.StreamRecord(v, None)


def _plain_gate(n_channels, capacity=1024):
    return InputGate(n_channels, capacity=capacity)


class _SanitizedGateFactory:
    """Builds gates sharing one sanitizer so the whole test's lock
    traffic lands in a single happens-before record."""

    def __init__(self):
        self.san = ConcurrencySanitizer("channels-stress")

    def __call__(self, n_channels, capacity=1024):
        return InputGate(n_channels, capacity=capacity, sanitizer=self.san,
                         name=f"stress-gate[{n_channels}]")

    def assert_clean(self):
        assert self.san.violations == [], [
            v.format() for v in self.san.violations]


@pytest.fixture(params=[
    "plain",
    "sanitized",
    pytest.param("shake", marks=pytest.mark.slow),
])
def gate_factory(request, monkeypatch):
    if request.param == "plain":
        yield _plain_gate
        return
    monkeypatch.setenv("FLINK_TPU_SANITIZE", "1")
    if request.param == "shake":
        monkeypatch.setenv("FLINK_TPU_SANITIZE_SHAKE", "20260804")
        assert sanitizer_rt.env_shake_seed() == 20260804
    assert sanitizer_rt.env_enabled()
    factory = _SanitizedGateFactory()
    if request.param == "shake":
        assert factory.san.shake_seed == 20260804
    yield factory
    factory.assert_clean()


class TestMultiProducerFifo:
    def test_per_channel_order_under_concurrency(self, gate_factory):
        n_channels, per_channel = 8, 2000
        gate = gate_factory(n_channels, capacity=64)  # small: forces contention

        def producer(idx):
            w = ChannelWriter(gate, idx)
            for i in range(per_channel):
                w.write(_rec((idx, i)))

        threads = [threading.Thread(target=producer, args=(c,)) for c in range(n_channels)]
        for t in threads:
            t.start()
        seen = {c: [] for c in range(n_channels)}
        total = n_channels * per_channel
        got = 0
        while got < total:
            item = gate.poll(timeout=5.0)
            assert item is not None, f"stalled after {got}/{total}"
            idx, element = item
            seen[idx].append(element.value[1])
            got += 1
        for t in threads:
            t.join(timeout=5.0)
        for c in range(n_channels):
            # FIFO per channel: exactly 0..per_channel-1 in order.
            assert seen[c] == list(range(per_channel))

    def test_backpressure_blocks_writer_without_loss(self, gate_factory):
        gate = gate_factory(1, capacity=4)
        w = ChannelWriter(gate, 0)
        n = 200
        done = threading.Event()

        def producer():
            for i in range(n):
                w.write(_rec(i))
            done.set()

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.1)
        # Capacity 4: the producer cannot have finished.
        assert not done.is_set()
        out = []
        while len(out) < n:
            item = gate.poll(timeout=5.0)
            assert item is not None
            out.append(item[1].value)
        t.join(timeout=5.0)
        assert out == list(range(n))


class TestBarrierStashReplay:
    def test_randomized_block_unblock_preserves_order(self, gate_factory):
        """Property: under arbitrary block/unblock cycles, the reader
        still observes every channel's elements exactly once, in
        per-channel FIFO order, and never sees a blocked channel's
        element while it is blocked."""
        rng = random.Random(42)
        n_channels, per_channel = 4, 500
        gate = gate_factory(n_channels, capacity=32)

        def producer(idx):
            w = ChannelWriter(gate, idx)
            for i in range(per_channel):
                w.write(_rec((idx, i)))

        threads = [threading.Thread(target=producer, args=(c,)) for c in range(n_channels)]
        for t in threads:
            t.start()

        seen = {c: [] for c in range(n_channels)}
        blocked = set()
        total = n_channels * per_channel
        got = 0
        while got < total:
            # Randomly toggle alignment state, like barrier arrival does.
            if rng.random() < 0.05 and len(blocked) < n_channels - 1:
                c = rng.randrange(n_channels)
                gate.block_channel(c)
                blocked.add(c)
            if blocked and rng.random() < 0.03:
                gate.unblock_all()
                blocked.clear()
            # Short probe: a None here is the all-blocked case, not a
            # stall — a long timeout would dead-wait on stashed data.
            item = gate.poll(timeout=0.25)
            if item is None:
                # Every live channel blocked with data stashed: release.
                gate.unblock_all()
                blocked.clear()
                continue
            idx, element = item
            assert idx not in blocked, "delivered from a blocked channel"
            seen[idx].append(element.value[1])
            got += 1
        gate.unblock_all()
        assert gate.poll(timeout=0.2) is None  # nothing left behind
        for t in threads:
            t.join(timeout=5.0)
        for c in range(n_channels):
            assert seen[c] == list(range(per_channel)), f"channel {c} disordered"

    def test_stash_respects_reblock_between_cycles(self, gate_factory):
        gate = gate_factory(2, capacity=16)
        w0, w1 = ChannelWriter(gate, 0), ChannelWriter(gate, 1)
        gate.block_channel(0)
        w0.write(_rec("a0"))
        w1.write(_rec("b0"))
        idx, e = gate.poll(timeout=1.0)
        assert (idx, e.value) == (1, "b0")
        # Replay then immediately re-block: the replayed element must be
        # re-stashed, not delivered.
        gate.unblock_all()
        gate.block_channel(0)
        assert gate.poll(timeout=0.2) is None
        gate.unblock_all()
        idx, e = gate.poll(timeout=1.0)
        assert (idx, e.value) == (0, "a0")


class TestClose:
    def test_close_releases_blocked_writers(self, gate_factory):
        gate = gate_factory(1, capacity=1)
        w = ChannelWriter(gate, 0)
        w.write(_rec(0))  # fills capacity
        finished = threading.Event()

        def producer():
            w.write(_rec(1))  # blocks on full queue
            finished.set()

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.1)
        assert not finished.is_set()
        gate.close()
        t.join(timeout=2.0)
        assert finished.is_set(), "close() must unblock writers"
