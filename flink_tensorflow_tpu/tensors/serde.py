"""Binary record codec — the TypeInformation/serializer counterpart.

The reference registers tensors with Flink's serializer stack so records
survive network shuffles and checkpoints (SURVEY.md §2 "Tensor
TypeInformation/serializer").  In-process hops here pass records by
reference (no serialization at all — threads share the arena/heap); this
codec exists for the boundaries where bytes are unavoidable: the remote
record plane between hosts (io/remote.py) and compact persisted streams.

Wire format (little-endian):
  u32 magic 'FTTR' | u32 header_len | u32 meta_len | header (json)
  | meta (pickle) | field buffers
header = {"fields": [[name, shape, dtype], ...]}
Meta is pickled (it is "arbitrary picklable metadata" per TensorValue's
contract — numpy scalars, tuples, non-str keys all round-trip; the
record plane is an intra-cluster trust boundary, same stance as Flink's
Kryo).  Buffers follow in header order, tightly packed — decode is
zero-copy (``np.frombuffer`` views over the received bytes).

**Wire narrowing** (opt-in): ``encode_record(..., wire_dtype=...)``
ships floating-point field buffers in a compact on-the-wire dtype —
``"bf16"``/``"f16"`` halve the bytes of every f32 field, ``"int8"``
quarters them with a per-field absmax scale — and ``decode_record``
restores the original dtype, so the narrowing is invisible to everything
downstream of the frame.  Narrowed field entries extend the header row
to ``[name, shape, dtype, wire, scale]`` (``scale`` is None except for
int8); un-narrowed fields keep the 3-element row, so ``"f32"``/None
produces byte-identical frames to the pre-narrowing codec.  Integer,
bool, and already-narrow fields pass through unchanged.  Accuracy
caveat: bf16 keeps f32's range at ~3 decimal digits of mantissa, f16
keeps ~3.3 digits but saturates beyond ±65504, int8 is a uniform
absmax quantization (worst-case error = absmax/254 per field) — use it
only for activations/scores that tolerate it, never for ids.
"""

from __future__ import annotations

import json
import pickle
import struct
import typing

import numpy as np

from flink_tensorflow_tpu.tensors.value import TensorValue

MAGIC = 0x52545446  # 'FTTR'
#: Columnar batch frame: one header + per-field contiguous buffers for a
#: HOMOGENEOUS run of records (same field names/dtypes/shapes) — the
#: arrow-style fast path of the coalescing record plane.  N records cost
#: ONE json header + ONE metas pickle + len(fields) buffers instead of N
#: of each.
MAGIC_BATCH = 0x42545446  # 'FTTB'
_HEADER = struct.Struct("<III")

#: Accepted ``wire_dtype`` names.  ``"f32"`` and None both mean "ship
#: buffers verbatim" (the identity codec).
WIRE_DTYPES = ("f32", "bf16", "f16", "int8")


def _wire_np_dtype(wire: str) -> np.dtype:
    """The numpy dtype a narrowed buffer is laid out as on the wire."""
    if wire == "bf16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if wire == "f16":
        return np.dtype(np.float16)
    if wire == "int8":
        return np.dtype(np.int8)
    raise ValueError(f"unknown wire dtype {wire!r} (expected one of {WIRE_DTYPES})")


def normalize_wire_dtype(wire: typing.Optional[str]) -> typing.Optional[str]:
    """Validate + canonicalize a wire-dtype name; ``"f32"`` -> None."""
    if wire is None or wire == "f32":
        return None
    if wire not in WIRE_DTYPES:
        raise ValueError(
            f"unknown wire dtype {wire!r} (expected one of {WIRE_DTYPES})")
    return wire


def _narrowable(dtype: np.dtype) -> bool:
    """Only full-width floats narrow; ints/bools/f16 ship verbatim."""
    return dtype.kind == "f" and dtype.itemsize >= 4


def wire_bytes_saved(record: TensorValue, wire: typing.Optional[str]) -> int:
    """Field-buffer bytes a narrowed frame saves vs. the identity codec
    (header/meta overhead excluded — it is identical modulo the few
    bytes of wire tags)."""
    wire = normalize_wire_dtype(wire)
    if wire is None:
        return 0
    itemsize = _wire_np_dtype(wire).itemsize
    saved = 0
    for arr in record.fields.values():
        a = np.asarray(arr)
        if _narrowable(a.dtype):
            saved += a.size * (a.dtype.itemsize - itemsize)
    return saved


def _narrow(a: np.ndarray, wire: str):
    """``(buffer_bytes, scale)`` of one field narrowed to ``wire``."""
    if wire == "int8":
        absmax = float(np.max(np.abs(a))) if a.size else 0.0
        scale = absmax / 127.0 if absmax > 0.0 else 1.0
        q = np.clip(np.rint(a.astype(np.float64) / scale), -127, 127)
        return q.astype(np.int8).tobytes(), scale
    return a.astype(_wire_np_dtype(wire)).tobytes(), None


def encode_record(record: TensorValue,
                  wire_dtype: typing.Optional[str] = None) -> bytes:
    wire = normalize_wire_dtype(wire_dtype)
    fields = []
    buffers = []
    for name, arr in record.fields.items():
        a = np.asarray(arr)
        if a.dtype.hasobject:
            # tobytes() on an object array emits raw PyObject POINTERS —
            # the frame decodes (or crashes) on the peer with garbage.
            # Fail at the sender, where the offending field is visible.
            raise TypeError(
                f"field {name!r} has object dtype {a.dtype} — record fields "
                "must be numeric/bytes tensors (put Python objects in meta)"
            )
        # NB: ascontiguousarray would promote 0-d to 1-d; keep the true
        # shape and let tobytes() handle contiguity.
        if wire is not None and _narrowable(a.dtype):
            buf, scale = _narrow(a, wire)
            fields.append([name, list(a.shape), a.dtype.str, wire, scale])
            buffers.append(buf)
        else:
            fields.append([name, list(a.shape), a.dtype.str])
            buffers.append(a.tobytes())
    header = json.dumps({"fields": fields}).encode()
    meta = pickle.dumps(dict(record.meta), protocol=pickle.HIGHEST_PROTOCOL)
    return b"".join(
        [_HEADER.pack(MAGIC, len(header), len(meta)), header, meta, *buffers]
    )


def decode_record(data: typing.Union[bytes, memoryview]) -> TensorValue:
    view = memoryview(data)
    magic, header_len, meta_len = _HEADER.unpack_from(view, 0)
    if magic != MAGIC:
        raise ValueError(f"bad record magic {magic:#x}")
    off = _HEADER.size
    header = json.loads(bytes(view[off:off + header_len]))
    off += header_len
    meta = pickle.loads(view[off:off + meta_len])
    off += meta_len
    out = {}
    for entry in header["fields"]:
        name, shape, dtype_str = entry[0], entry[1], entry[2]
        dtype = np.dtype(dtype_str)
        count = int(np.prod(shape)) if shape else 1  # prod(()) is 1 anyway
        if len(entry) > 3:
            # Narrowed field: the buffer is laid out in the wire dtype;
            # restore the declared dtype here so the narrowing never
            # leaks past the codec (the restore allocates — zero-copy is
            # a property of the identity path only).
            wire, scale = entry[3], entry[4]
            wdt = _wire_np_dtype(wire)
            raw = np.frombuffer(view, dtype=wdt, count=count, offset=off)
            if wire == "int8":
                arr = (raw.astype(dtype) * dtype.type(scale)).reshape(shape)
            else:
                arr = raw.astype(dtype).reshape(shape)
            # Freshly allocated by astype — freeze in place so the
            # TensorValue constructor aliases instead of re-copying.
            arr.setflags(write=False)
            off += count * wdt.itemsize
        else:
            arr = np.frombuffer(view, dtype=dtype, count=count,
                                offset=off).reshape(shape)
            # A writable frame buffer (reactor receive path uses
            # bytearray) would yield WRITABLE views here, and the
            # TensorValue constructor copies writable arrays — freeze
            # the view so it aliases (zero-copy on both buffer kinds).
            if arr.flags.writeable:
                arr.setflags(write=False)
            off += count * dtype.itemsize
        out[name] = arr
    return TensorValue(out, meta)


# ---------------------------------------------------------------------------
# Columnar batch codec — the coalesced-frame fast path.
# ---------------------------------------------------------------------------

def batch_signature(value: typing.Any) -> typing.Optional[typing.Tuple]:
    """Hashable homogeneity key of one record, or None when the record
    cannot ride a columnar batch (not a TensorValue / object dtype).
    Two records with equal signatures stack into one columnar frame."""
    if not isinstance(value, TensorValue):
        return None
    sig = []
    for name, arr in value.fields.items():
        if arr.dtype.hasobject:
            return None
        sig.append((name, arr.dtype.str, arr.shape))
    return tuple(sig)


def encode_batch(records: typing.Sequence[TensorValue],
                 wire_dtype: typing.Optional[str] = None) -> bytearray:
    """Encode a HOMOGENEOUS run of records arrow-style: one json header,
    one pickled meta list, and per-field contiguous ``[N, ...]`` buffers
    (the caller asserts homogeneity via :func:`batch_signature`).

    Composes with wire narrowing: bf16/f16 narrow the stacked buffer in
    one vectorized cast; int8 keeps the PER-RECORD absmax scales (a
    scale list in the header row), so the worst-case quantization error
    bound of the per-record codec — absmax/254 per record per field —
    is unchanged by coalescing.
    """
    wire = normalize_wire_dtype(wire_dtype)
    n = len(records)
    first = records[0]
    fields = []
    #: Per-field fill plan: either pre-narrowed bytes, or (rows, dtype,
    #: nbytes) to concatenate straight into the frame — the identity
    #: path writes every row exactly ONCE (into the wire buffer), where
    #: the old np.stack->tobytes->join chain copied each byte 3x.
    plans: typing.List[typing.Tuple] = []
    for name in first.fields:
        a0 = np.asarray(first.fields[name])
        if a0.dtype.hasobject:
            raise TypeError(
                f"field {name!r} has object dtype {a0.dtype} — record fields "
                "must be numeric/bytes tensors (put Python objects in meta)"
            )
        row_shape = list(a0.shape)
        if wire is not None and _narrowable(a0.dtype):
            # Narrowed fields allocate (the cast is the work); int8 also
            # needs the scales BEFORE the header serializes.
            stacked = np.stack([np.asarray(r.fields[name]) for r in records])
            if wire == "int8":
                flat = stacked.reshape(n, -1).astype(np.float64)
                absmax = np.max(np.abs(flat), axis=1) if flat.shape[1] else \
                    np.zeros(n)
                scales = np.where(absmax > 0.0, absmax / 127.0, 1.0)
                q = np.clip(np.rint(flat / scales[:, None]), -127, 127)
                plans.append(("bytes", q.astype(np.int8).tobytes()))
                fields.append([name, row_shape, a0.dtype.str, wire,
                               [float(s) for s in scales]])
            else:
                plans.append(
                    ("bytes", stacked.astype(_wire_np_dtype(wire)).tobytes()))
                fields.append([name, row_shape, a0.dtype.str, wire, None])
        else:
            rows = [np.ravel(np.asarray(r.fields[name])) for r in records]
            plans.append(("rows", rows, a0.dtype,
                          sum(r.nbytes for r in rows)))
            fields.append([name, row_shape, a0.dtype.str])
    header = json.dumps({"n": n, "fields": fields}).encode()
    metas = pickle.dumps([dict(r.meta) for r in records],
                         protocol=pickle.HIGHEST_PROTOCOL)
    total = _HEADER.size + len(header) + len(metas) + sum(
        len(p[1]) if p[0] == "bytes" else p[3] for p in plans)
    out = bytearray(total)
    _HEADER.pack_into(out, 0, MAGIC_BATCH, len(header), len(metas))
    off = _HEADER.size
    out[off:off + len(header)] = header
    off += len(header)
    out[off:off + len(metas)] = metas
    off += len(metas)
    for plan in plans:
        if plan[0] == "bytes":
            buf = plan[1]
            out[off:off + len(buf)] = buf
            off += len(buf)
        else:
            _, rows, dtype, nbytes = plan
            dest = np.frombuffer(out, dtype=dtype,
                                 count=nbytes // dtype.itemsize, offset=off)
            np.concatenate(rows, out=dest)
            off += nbytes
    return out


def decode_batch(data: typing.Union[bytes, bytearray, memoryview]
                 ) -> typing.List[TensorValue]:
    """Decode one columnar frame into per-record TensorValues whose
    fields are zero-copy ROW VIEWS into the frame's contiguous buffers
    (identity path; narrowed fields allocate once for the restore)."""
    view = memoryview(data)
    magic, header_len, meta_len = _HEADER.unpack_from(view, 0)
    if magic != MAGIC_BATCH:
        raise ValueError(f"bad batch magic {magic:#x}")
    off = _HEADER.size
    header = json.loads(bytes(view[off:off + header_len]))
    off += header_len
    metas = pickle.loads(view[off:off + meta_len])
    off += meta_len
    n = header["n"]
    columns: typing.Dict[str, np.ndarray] = {}
    for entry in header["fields"]:
        name, shape, dtype_str = entry[0], entry[1], entry[2]
        dtype = np.dtype(dtype_str)
        row_elems = int(np.prod(shape)) if shape else 1
        count = n * row_elems
        if len(entry) > 3:
            wire, scales = entry[3], entry[4]
            wdt = _wire_np_dtype(wire)
            raw = np.frombuffer(view, dtype=wdt, count=count, offset=off)
            if wire == "int8":
                s = np.asarray(scales, dtype=dtype)
                arr = (raw.astype(dtype).reshape((n, row_elems))
                       * s[:, None]).reshape((n, *shape))
            else:
                arr = raw.astype(dtype).reshape((n, *shape))
            off += count * wdt.itemsize
        else:
            arr = np.frombuffer(view, dtype=dtype, count=count,
                                offset=off).reshape((n, *shape))
            off += count * dtype.itemsize
        # Frozen so row views alias into TensorValue without a copy
        # (decode allocates only for narrowed restores).
        if arr.flags.writeable:
            arr.setflags(write=False)
        columns[name] = arr
    out = []
    for i in range(n):
        fields = {}
        for name, col in columns.items():
            row = col[i]
            if not isinstance(row, np.ndarray):  # scalar field: 0-d view
                row = col[i:i + 1].reshape(())
            fields[name] = row
        out.append(TensorValue(fields, metas[i]))
    return out


def decode_frame(data: typing.Union[bytes, bytearray, memoryview]
                 ) -> typing.List[TensorValue]:
    """Decode either frame kind (single record or columnar batch) into a
    record list — the receive path's one dispatch point."""
    view = memoryview(data)
    (magic,) = struct.unpack_from("<I", view, 0)
    if magic == MAGIC_BATCH:
        return decode_batch(view)
    return [decode_record(view)]
