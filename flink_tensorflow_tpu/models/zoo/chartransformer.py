"""Char-level causal transformer — the LLM serving plane's CPU-tier model.

The serving subsystem (flink_tensorflow_tpu/serving/) needs a real
autoregressive decoder whose KV cache threads through a jitted
single-step call: this module is that decoder at char scale, small
enough that prefill + per-token decode run in milliseconds on the
tier-1 CPU mesh yet shaped exactly like the production case (multi-head
causal attention over a capacity-padded cache, RMSNorm + MLP blocks,
greedy head).  Two typed methods expose the two serving phases:

- ``prefill``: ``{tokens [B, C], lengths [B]}`` -> the first generated
  token per row plus the populated ``[B, L, C, H, Dh]`` K/V caches.
  Attention is the pallas flash kernel (ops/flash_attention.py, causal
  grid) — the prefill pass IS the long-context hot path.
- ``decode_step``: ``{token [B], lengths [B], k_cache, v_cache}`` ->
  the next token plus updated caches.  The new position's K/V scatter
  into the caches at ``lengths`` and attention is the O(C) single-query
  :func:`~flink_tensorflow_tpu.ops.flash_attention.flash_attention_decode`
  path — no ``[T, T]`` scores, no cache reshuffle, cache arrays are
  donated by the serving runner so XLA updates them in place.

Params are a plain pytree (no flax): the cache-threading signatures
above don't fit ``nn.Module.apply`` state handling, and the explicit
dict keeps the serving runner's donation boundaries obvious.  Greedy
argmax lives INSIDE the jitted methods so each step fetches one int32
per row — the d2h is 4 bytes/token, not a logits matrix.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from flink_tensorflow_tpu.models.base import ModelMethod
from flink_tensorflow_tpu.models.zoo.registry import ModelDef, register_model_def
from flink_tensorflow_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_decode,
)
from flink_tensorflow_tpu.tensors.schema import RecordSchema, TensorSpec


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _block_prefill(p, x, heads):
    """One transformer block over the full (padded) sequence.

    x: [B, C, D].  Returns (x', k, v) with k/v [B, C, H, Dh] — the
    block's cache contribution.  Causal masking via the flash kernel;
    padded positions beyond a row's true length produce garbage K/V that
    the decode path masks by length, and their outputs are never read.
    """
    b, c, d = x.shape
    hd = d // heads
    h = _rms_norm(x, p["ln1"])
    q = (h @ p["wq"]).reshape(b, c, heads, hd)
    k = (h @ p["wk"]).reshape(b, c, heads, hd)
    v = (h @ p["wv"]).reshape(b, c, heads, hd)
    o = flash_attention(q, k, v, causal=True)
    x = x + o.reshape(b, c, d) @ p["wo"]
    h = _rms_norm(x, p["ln2"])
    x = x + jax.nn.gelu(h @ p["w1"]) @ p["w2"]
    return x, k, v


def _block_decode(p, x, k_cache, v_cache, lengths, heads):
    """One block for a single new position.

    x: [B, D] (the new token's activations); k_cache/v_cache: [B, C, H,
    Dh]; lengths: [B] cache length BEFORE this token.  Scatters the new
    K/V at ``lengths`` and attends over ``lengths + 1`` positions.
    """
    b, d = x.shape
    hd = d // heads
    h = _rms_norm(x, p["ln1"])
    q = (h @ p["wq"]).reshape(b, heads, hd)
    k_new = (h @ p["wk"]).reshape(b, heads, hd)
    v_new = (h @ p["wv"]).reshape(b, heads, hd)
    rows = jnp.arange(b)
    # Out-of-capacity positions (clipped scatter would silently
    # overwrite slot C-1) are the scheduler's job to prevent; the
    # serving config rejects prompts that cannot fit.
    k_cache = k_cache.at[rows, lengths].set(k_new, mode="drop")
    v_cache = v_cache.at[rows, lengths].set(v_new, mode="drop")
    o = flash_attention_decode(q, k_cache, v_cache, lengths + 1)
    x = x + o.reshape(b, d) @ p["wo"]
    h = _rms_norm(x, p["ln2"])
    x = x + jax.nn.gelu(h @ p["w1"]) @ p["w2"]
    return x, k_cache, v_cache


@register_model_def("char_transformer")
def build(vocab_size: int = 96, embed_dim: int = 64, num_heads: int = 4,
          num_layers: int = 2, mlp_ratio: int = 4,
          capacity: int = 128) -> ModelDef:
    """``capacity`` is the KV-cache length every jitted shape is padded
    to — prompt + generated tokens must fit inside it (the serving
    scheduler enforces this at admission)."""
    if embed_dim % num_heads:
        raise ValueError(f"embed_dim {embed_dim} must divide num_heads {num_heads}")
    d, heads, layers = embed_dim, num_heads, num_layers
    mlp = mlp_ratio * d

    def init_fn(rng):
        ks = jax.random.split(rng, 2 + 6 * layers)
        def dense(key, fan_in, shape):
            return (jax.random.normal(key, shape, jnp.float32)
                    / math.sqrt(fan_in))
        params = {
            # Positional scale deliberately strong: random-param greedy
            # decoding then varies by position instead of collapsing to
            # one repeated token, which keeps the serving tests'
            # byte-identical-continuation assertions meaningful.
            "emb": dense(ks[0], 1, (vocab_size, d)) * 0.5,
            "pos": dense(ks[1], 1, (capacity, d)) * 0.8,
            "head": None,  # tied to emb below
            "ln_f": jnp.ones((d,), jnp.float32),
            "layers": [],
        }
        for i in range(layers):
            kq, kk, kv, ko, k1, k2 = ks[2 + 6 * i: 8 + 6 * i]
            params["layers"].append({
                "ln1": jnp.ones((d,), jnp.float32),
                "wq": dense(kq, d, (d, d)),
                "wk": dense(kk, d, (d, d)),
                "wv": dense(kv, d, (d, d)),
                "wo": dense(ko, d, (d, d)),
                "ln2": jnp.ones((d,), jnp.float32),
                "w1": dense(k1, d, (d, mlp)),
                "w2": dense(k2, mlp, (mlp, d)),
            })
        # Tied LM head: logits = h @ emb.T (kept as its own leaf so the
        # serving runner's donation treats params uniformly).
        params["head"] = jnp.transpose(params["emb"])
        return params

    def _logits(params, h):
        return _rms_norm(h, params["ln_f"]) @ params["head"]

    def prefill(params, inputs):
        tokens = inputs["tokens"]          # [B, C] int32, padded
        lengths = inputs["lengths"]        # [B] int32 true prompt lengths
        b, c = tokens.shape
        x = params["emb"][tokens] + params["pos"][None, :c]
        ks, vs = [], []
        for p in params["layers"]:
            x, k, v = _block_prefill(p, x, heads)
            ks.append(k)
            vs.append(v)
        # Cache layout [B, L, C, H, Dh]: slicing row b yields one
        # session's whole block — the keyed-state snapshot unit.
        k_cache = jnp.stack(ks, axis=1)
        v_cache = jnp.stack(vs, axis=1)
        last = jnp.clip(lengths - 1, 0, c - 1)
        h_last = jnp.take_along_axis(
            x, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        next_token = jnp.argmax(_logits(params, h_last), axis=-1).astype(jnp.int32)
        return {"next_token": next_token, "k_cache": k_cache, "v_cache": v_cache}

    def decode_step(params, inputs):
        token = inputs["token"]            # [B] int32 — last emitted token
        lengths = inputs["lengths"]        # [B] cache length before this token
        k_cache = inputs["k_cache"]        # [B, L, C, H, Dh]
        v_cache = inputs["v_cache"]
        c = k_cache.shape[2]
        pos = jnp.clip(lengths, 0, c - 1)
        x = params["emb"][token] + params["pos"][pos]
        new_k, new_v = [], []
        for i, p in enumerate(params["layers"]):
            x, kc, vc = _block_decode(p, x, k_cache[:, i], v_cache[:, i],
                                      lengths, heads)
            new_k.append(kc)
            new_v.append(vc)
        next_token = jnp.argmax(_logits(params, x), axis=-1).astype(jnp.int32)
        return {
            "next_token": next_token,
            "k_cache": jnp.stack(new_k, axis=1),
            "v_cache": jnp.stack(new_v, axis=1),
        }

    schema = RecordSchema({"tokens": TensorSpec((None,), np.int32)})
    methods = {
        "prefill": ModelMethod(
            name="prefill", input_schema=schema,
            output_names=("next_token", "k_cache", "v_cache"), fn=prefill,
        ),
        "decode_step": ModelMethod(
            name="decode_step", input_schema=schema,
            output_names=("next_token", "k_cache", "v_cache"), fn=decode_step,
        ),
    }
    return ModelDef(
        architecture="char_transformer",
        config={"vocab_size": vocab_size, "embed_dim": embed_dim,
                "num_heads": num_heads, "num_layers": num_layers,
                "mlp_ratio": mlp_ratio, "capacity": capacity},
        module=None,
        input_schema=schema,
        methods=methods,
        init_fn=init_fn,
    )
