"""TFSavedModelLoader — run actual TF SavedModel artifacts, XLA-native.

This is the direct counterpart of the reference's ``SavedModelLoader``
(BASELINE.json:5): it loads a real TensorFlow SavedModel by tags,
resolves a named signature (``SignatureDef``), and produces a callable.
Where the reference opens an embedded TF ``Session``, here the signature
graph is inlined into the jax computation via ``jax2tf.call_tf`` — under
``jax.jit`` the TF MLIR bridge lowers the graph to StableHLO, so the
model executes inside the same XLA executable as the rest of the step
(captured variables are baked in as constants).  On TPU this is native
MXU execution of the original TF graph — no session, no JNI, no
per-record bridge cost.

Requires tensorflow at load time (present in this image); the rest of
the framework never imports TF.

For models the MLIR bridge cannot lower (rare non-compilable ops),
fall back to weight import into a native zoo definition
(models/import_tf.py — SURVEY.md §7 hard part 1's mitigation).
"""

from __future__ import annotations

import typing

import numpy as np

from flink_tensorflow_tpu.models.base import Model, ModelMethod
from flink_tensorflow_tpu.tensors.schema import RecordSchema, TensorSpec

DEFAULT_SIGNATURE = "serving_default"


class TFSavedModelLoader:
    """Loads a TF SavedModel signature into a framework :class:`Model`."""

    def __init__(self, path: str, *, signature: str = DEFAULT_SIGNATURE,
                 tags: typing.Optional[typing.Sequence[str]] = None):
        self.path = path
        self.signature = signature
        self.tags = list(tags) if tags is not None else None

    def _load_signature(self):
        try:
            import tensorflow as tf
        except ImportError as exc:
            raise ImportError(
                "TFSavedModelLoader requires tensorflow; use the native "
                "bundle SavedModelLoader or models.import_tf weight import"
            ) from exc

        loaded = (
            tf.saved_model.load(self.path, tags=self.tags)
            if self.tags is not None else tf.saved_model.load(self.path)
        )
        try:
            sig = loaded.signatures[self.signature]
        except KeyError:
            raise KeyError(
                f"SavedModel at {self.path} has no signature "
                f"{self.signature!r}; available: {sorted(loaded.signatures)}"
            ) from None
        # Keep the loaded module alive: the ConcreteFunction holds weak
        # refs to its variables.
        sig._ftt_keepalive = loaded
        return sig

    def input_schema(self, sig=None) -> RecordSchema:
        """Per-record schema derived from the signature's structured
        input specs (batch dim stripped; None dims become dynamic)."""
        sig = sig or self._load_signature()
        fields = {}
        for name, spec in sig.structured_input_signature[1].items():
            dims = spec.shape.as_list()
            if not dims or dims[0] is not None:
                # The streaming path always feeds [B, ...] batches; a
                # signature input without a leading dynamic batch dim
                # would silently receive one extra dimension — fail
                # loudly instead (re-export the model with a batch dim).
                raise ValueError(
                    f"signature input {name!r} has shape {dims} without a "
                    "leading dynamic batch dimension; streaming inference "
                    "feeds [batch, ...] — re-export the SavedModel with "
                    "batched inputs"
                )
            fields[name] = TensorSpec(tuple(dims[1:]),
                                      np.dtype(spec.dtype.as_numpy_dtype))
        return RecordSchema(fields)

    def load(self) -> Model:
        """-> Model whose "serve" method runs the TF graph inside XLA."""
        from jax.experimental import jax2tf

        sig = self._load_signature()
        schema = self.input_schema(sig)
        output_names = tuple(sorted(sig.structured_outputs.keys()))
        # call_tf binds positionally: fix an input-name order and adapt.
        input_order = sorted(sig.structured_input_signature[1])

        def tf_positional(*args):
            return sig(**dict(zip(input_order, args)))

        call = jax2tf.call_tf(tf_positional)

        def serve(params, inputs):
            del params  # weights are baked into the lowered graph
            return dict(call(*[inputs[n] for n in input_order]))

        method = ModelMethod(
            name="serve",
            input_schema=schema,
            output_names=output_names,
            fn=serve,
        )
        name = f"tf_savedmodel:{self.path}"
        return Model(name, params={}, methods={"serve": method},
                     metadata={"source": self.path, "signature": self.signature})


class TFGraphDefLoader:
    """Loads a frozen TF ``GraphDef`` (.pb bytes or file) into a
    framework :class:`Model`.

    The reference's ``GraphLoader`` imports frozen graph bytes into a TF
    ``Graph`` and feeds/fetches named tensors through an embedded session
    (BASELINE.json:5; SURVEY.md §2 row "GraphLoader") — the artifact its
    flagship Inception example actually ships.  Here the same bytes are
    imported into a TF-v1 ``wrap_function`` graph, pruned to a
    ConcreteFunction over the requested feed/fetch tensors, and inlined
    into XLA via ``jax2tf.call_tf`` — frozen weights are constants in the
    GraphDef, so the lowered executable is fully self-contained.

    ``inputs``/``outputs`` map record-field / output names to graph
    tensor names (``"x:0"``); a bare tensor-name sequence uses the op
    names as field names.
    """

    def __init__(
        self,
        graph_def: typing.Union[bytes, str],
        *,
        inputs: typing.Union[typing.Mapping[str, str], typing.Sequence[str]],
        outputs: typing.Union[typing.Mapping[str, str], typing.Sequence[str]],
    ):
        self.graph_def = graph_def
        self.inputs = self._as_mapping(inputs)
        self.outputs = self._as_mapping(outputs)

    @staticmethod
    def _as_mapping(spec) -> typing.Dict[str, str]:
        if isinstance(spec, typing.Mapping):
            return dict(spec)
        out = {}
        for t in spec:
            key = t.split(":")[0].rsplit("/", 1)[-1]
            if key in out:
                # Two tensors sharing a basename (tower_a/logits,
                # tower_b/logits) would silently shadow each other —
                # the caller must name them explicitly.
                raise ValueError(
                    f"tensor names {out[key]!r} and {t!r} both map to field "
                    f"{key!r}; pass a mapping {{field: tensor_name}} instead"
                )
            out[key] = t
        return out

    def _graph_def_bytes(self) -> bytes:
        if isinstance(self.graph_def, bytes):
            return self.graph_def
        with open(self.graph_def, "rb") as f:
            return f.read()

    def _pruned(self):
        """Import the frozen graph and prune to feeds -> fetches."""
        try:
            import tensorflow as tf
        except ImportError as exc:
            raise ImportError(
                "TFGraphDefLoader requires tensorflow; for non-TF artifacts "
                "use models.loaders.GraphLoader (jax.export format)"
            ) from exc

        gd = tf.compat.v1.GraphDef()
        gd.ParseFromString(self._graph_def_bytes())

        def _import():
            tf.compat.v1.import_graph_def(gd, name="")

        wrapped = tf.compat.v1.wrap_function(_import, [])
        try:
            feeds = [wrapped.graph.as_graph_element(t) for t in self.inputs.values()]
            fetches = [wrapped.graph.as_graph_element(t) for t in self.outputs.values()]
        except KeyError as exc:
            names = sorted(op.name for op in wrapped.graph.get_operations())
            raise KeyError(
                f"tensor not found in frozen graph: {exc}; ops present: {names[:20]}..."
            ) from exc
        return wrapped.prune(feeds, fetches)

    def input_schema(self, pruned=None) -> RecordSchema:
        """Per-record schema from the pruned feeds (leading None batch
        dim stripped, as in :meth:`TFSavedModelLoader.input_schema`)."""
        pruned = pruned or self._pruned()
        fields = {}
        for name, tensor in zip(self.inputs, pruned.inputs):
            dims = tensor.shape.as_list()
            if not dims or dims[0] is not None:
                raise ValueError(
                    f"feed {name!r} has shape {dims} without a leading "
                    "dynamic batch dimension; streaming inference feeds "
                    "[batch, ...] — freeze the graph with batched inputs"
                )
            fields[name] = TensorSpec(tuple(dims[1:]),
                                      np.dtype(tensor.dtype.as_numpy_dtype))
        return RecordSchema(fields)

    def load(self) -> Model:
        """-> Model whose "serve" method runs the frozen graph inside XLA."""
        from jax.experimental import jax2tf

        pruned = self._pruned()
        schema = self.input_schema(pruned)
        input_order = list(self.inputs)
        output_order = list(self.outputs)
        call = jax2tf.call_tf(pruned)

        def serve(params, inputs):
            del params  # frozen weights are constants in the GraphDef
            out = call(*[inputs[n] for n in input_order])
            if not isinstance(out, (tuple, list)):
                out = (out,)
            return dict(zip(output_order, out))

        method = ModelMethod(
            name="serve",
            input_schema=schema,
            output_names=tuple(output_order),
            fn=serve,
        )
        source = self.graph_def if isinstance(self.graph_def, str) else "<bytes>"
        return Model(f"tf_graphdef:{source}", params={},
                     methods={"serve": method},
                     metadata={"source": source, "inputs": self.inputs,
                               "outputs": self.outputs})
