"""Streaming LLM serving: continuous batching + KV cache as keyed state.

The "millions of users" workload (ROADMAP): generation requests arrive
as a keyed stream (key = session id), a continuous-batching operator
admits/evicts sessions per decode step under a token budget, and each
session's KV cache lives in keyed operator state — checkpointable,
restorable mid-generation, rescalable by key group.  The model is the
zoo's char-level causal transformer (random params — the point is the
serving plane, not the prose), driving the pallas flash kernel for
prefill and the single-query decode path per token.

Run:  python examples/llm_serving_pipeline.py --records 24 --cpu
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from examples._common import base_parser, report, select_platform

#: Char vocab: printable ASCII 32..126 at ids 1..95; 0 is padding.
VOCAB = 96


def encode(text: str) -> np.ndarray:
    return np.array([max(1, min(95, ord(c) - 31)) for c in text], np.int32)


def decode(tokens) -> str:
    return "".join(chr(max(32, min(126, t + 31))) for t in tokens if t > 0)


PROMPTS = [
    "the quick brown fox",
    "streaming systems",
    "tensor processing",
    "continuous batching",
    "keyed operator state",
    "flash attention",
    "exactly once",
    "token budget",
]


def main(argv=None):
    args = base_parser(__doc__).parse_args(argv)
    select_platform(args.cpu)
    if args.smoke:
        args.records = 8

    import jax

    from flink_tensorflow_tpu import StreamExecutionEnvironment, serving
    from flink_tensorflow_tpu.models import get_model_def
    from flink_tensorflow_tpu.sources import PacedSplitSource

    mdef = get_model_def("char_transformer", vocab_size=VOCAB, embed_dim=64,
                         num_heads=4, num_layers=2, capacity=64)
    model = mdef.to_model(mdef.init_params(jax.random.PRNGKey(0)))

    n = args.records or 24
    max_new = 8 if args.smoke else 16
    requests = [
        serving.GenerateRequest(
            session_id=f"user-{i}",
            prompt=encode(PROMPTS[i % len(PROMPTS)]),
            max_new_tokens=max_new,
        )
        for i in range(n)
    ]

    env = StreamExecutionEnvironment(parallelism=args.parallelism)
    # Declared serving layout: an ABSTRACT v5e-8 mesh (data=4 x tp=2) +
    # the per-chip HBM ceiling.  Nothing at execution time touches these
    # on a CPU box — they exist so `flink-tpu-shardcheck` (and the
    # analyzer's shardcheck-* rules) can audit partitioning, donation,
    # and the static HBM budget of this plan without any TPU attached.
    from flink_tensorflow_tpu.parallel import abstract_mesh

    env.set_mesh(abstract_mesh({"data": 4, "tp": 2}))
    env.set_hbm_budget(16 * 1024**3)  # v5e: 16 GiB per chip
    events = (
        serving.continuous_batching(
            # Open-loop arrivals: sessions show up on a Poisson schedule
            # whether or not the pipeline keeps up, and each TokenEvent
            # carries meta["sched_ts"] so latency is measured against
            # the schedule (coordinated-omission-free).
            env.from_source(
                PacedSplitSource(requests, rate_hz=50.0, num_splits=4),
                name="sessions", parallelism=1,
            )
            .key_by(lambda r: r.session_id),
            model,
            config=serving.ServingConfig(
                max_active_seqs=8,       # pool slots (one decode shape)
                token_budget=256,        # sum of active cache lengths
                capacity=64,             # prompt + generated must fit
            ),
            name="continuous_batching",
            parallelism=args.parallelism,
        )
        .sink_to_list()
    )
    t0 = time.time()
    job = env.execute("llm-serving", timeout=600)

    sessions = {}
    for ev in events:
        sessions.setdefault(ev.session_id, {})[ev.index] = ev.token
    completions = {
        sid: decode([toks[i] for i in sorted(toks)])
        for sid, toks in sessions.items()
    }
    for sid in sorted(completions)[:4]:
        print(f"  {sid}: {completions[sid]!r}")
    total_tokens = sum(len(t) for t in sessions.values())
    return report("llm_serving_pipeline", job.metrics, t0, n, {
        "sessions": len(sessions),
        "tokens": total_tokens,
        "all_sessions_completed": all(
            len(t) == max_new for t in sessions.values()),
    })


if __name__ == "__main__":
    main()
