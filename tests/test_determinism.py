"""Fixed-seed numerical goldens (SURVEY.md §4: "numerical golden tests
per workload — fixed seed, tiny model, assert loss trajectory /
logits").

Rather than pinning magic constants (jaxlib upgrades would rot them),
these goldens pin the property the constants would encode: the SAME
seed and stream produce BIT-IDENTICAL results across independent runs —
the determinism that makes replay-based exactly-once meaningful.
"""

import numpy as np
import optax

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.functions import ModelWindowFunction, OnlineTrainFunction
from flink_tensorflow_tpu.models import get_model_def
from flink_tensorflow_tpu.tensors import BucketPolicy, RecordSchema, TensorValue, spec


def _lenet_job():
    import jax

    mdef = get_model_def("lenet")
    model = mdef.to_model(jax.jit(mdef.init_fn)(jax.random.key(0)))
    rng = np.random.RandomState(7)
    records = [TensorValue({"image": rng.rand(28, 28, 1).astype(np.float32)},
                           {"id": i}) for i in range(32)]
    env = StreamExecutionEnvironment(parallelism=1)
    out = (
        env.from_collection(records, parallelism=1)
        .count_window(8)
        .apply(ModelWindowFunction(model, policy=BucketPolicy(fixed_batch=8)),
               name="lenet", parallelism=1)
        .sink_to_list()
    )
    env.execute("golden-lenet", timeout=120)
    return np.stack([r["prob"] for r in sorted(out, key=lambda r: r.meta["id"])])


def _widedeep_losses():
    cfg = dict(hash_buckets=100, embed_dim=4, num_cat_slots=2,
               num_dense=4, num_wide=8, hidden=(16,))
    mdef = get_model_def("widedeep", **cfg)
    schema = RecordSchema({
        "wide": spec((cfg["num_wide"],)),
        "dense": spec((cfg["num_dense"],)),
        "cat": spec((cfg["num_cat_slots"],), np.int32),
        "label": spec((), np.int32),
    })
    rng = np.random.RandomState(3)
    records = []
    for i in range(48):
        records.append(TensorValue({
            "wide": rng.rand(cfg["num_wide"]).astype(np.float32),
            "dense": rng.rand(cfg["num_dense"]).astype(np.float32),
            "cat": rng.randint(0, 100, (cfg["num_cat_slots"],)).astype(np.int32),
            "label": np.int32(i % 2),
        }, meta={"user": i % 4}))
    env = StreamExecutionEnvironment(parallelism=1)
    out = (
        env.from_collection(records, parallelism=1)
        .key_by(lambda r: r.meta["user"])
        .process(OnlineTrainFunction(mdef, optax.adam(1e-2), train_schema=schema,
                                     mini_batch=4, seed=11),
                 name="train", parallelism=1)
        .sink_to_list()
    )
    env.execute("golden-widedeep", timeout=120)
    return np.asarray([float(r["loss"]) for r in out])


class TestFixedSeedGoldens:
    def test_lenet_inference_bit_identical_across_runs(self):
        a, b = _lenet_job(), _lenet_job()
        np.testing.assert_array_equal(a, b)

    def test_widedeep_training_trajectory_bit_identical(self):
        a, b = _widedeep_losses(), _widedeep_losses()
        assert len(a) == len(b) == 12  # 48 records / mini_batch 4
        np.testing.assert_array_equal(a, b)
        assert np.isfinite(a).all()
