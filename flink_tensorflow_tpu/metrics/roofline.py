"""Runtime roofline plane — per-jit-unit MFU/bandwidth attribution.

The runtime half of the cost model (``analysis/costmodel.py``): the
plan-time :class:`~flink_tensorflow_tpu.analysis.costmodel.CostTable`
(FLOPs / HBM bytes / collective bytes / expected h2d+d2h per call, per
jit unit per compile signature) ships to every worker via
``JobConfig.roofline``, and the model runners' measured step times join
against it to publish continuous per-operator ``roofline.*`` gauges:

- ``roofline.flops_per_s`` / ``roofline.hbm_bytes_per_s`` — achieved
  rates over wall time (cohort-summed: the aggregate device bill).
- ``roofline.mfu_pct`` / ``roofline.membw_pct`` — the same rates
  against a declared :class:`DeviceSpec` peak (cohort-max).
- ``roofline.bound`` — roofline classification code (see
  :data:`BOUND_NAMES`): host (device duty cycle below threshold), wire
  (h2d rate dominates both utilization fractions), else compute vs
  memory by the larger busy-time utilization fraction.
- ``roofline.busy_s`` — device-busy seconds attributed so far.
- ``roofline.measured_h2d_per_call`` / ``roofline.predicted_h2d_per_call``
  / ``roofline.h2d_drift_frac`` — the BENCH_r13 72 B = 72.0 B check,
  generalized into a continuous signal.
- ``roofline.compile_events`` / ``roofline.unpredicted_compiles`` —
  every runtime jit cache miss (first sight of a compile signature)
  lands on the flight recorder's ``compile`` track and the tracer's
  ``compile.events`` track with signature + trigger provenance, and is
  diffed live against the CostTable's predicted signature ladder.

Measured-vs-predicted divergence beyond tolerance and unpredicted
recompiles surface as ``roofline-drift`` / ``roofline-recompile``
findings — in the SLO rules (``metrics/health.py`` feeds the PR-12
autoscale loop), in ``flink-tpu-doctor --roofline``, and in the
``flink-tpu-roofline`` CLI's ranked headroom report, which joins any
evidence subset (metrics snapshot, Chrome trace, CostTable).

Zero-cost-when-off, repo-wide convention: runners hold ``None`` and the
hot path pays one ``is None`` test; the per-step ``observe()`` join is
a dict lookup plus a handful of integer adds (priced next to
``span_record_ns``/``flight_record_ns`` by the bench overhead probes).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import time
import typing

if typing.TYPE_CHECKING:
    from flink_tensorflow_tpu.analysis.costmodel import CostTable, OperatorCost

#: ``roofline.bound`` gauge codes.  0 = no evidence yet.
BOUND_NAMES = ("-", "compute", "memory", "host", "wire")
BOUND_NONE, BOUND_COMPUTE, BOUND_MEMORY, BOUND_HOST, BOUND_WIRE = range(5)

#: Span names whose duration counts as device-busy time when a roofline
#: report is built from a trace instead of live gauges.
COMPUTE_SPAN_NAMES = frozenset({"compute", "decode.step", "decode.prefill"})
#: Cache-movement spans (warm-tier extract/insert, paged demote/revive):
#: joined against ``cache_move`` cost entries, never against compiles.
CACHE_SPAN_NAMES = frozenset({"cache.h2d", "cache.d2h"})


# ---------------------------------------------------------------------------
# DeviceSpec — the declared hardware ceiling
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Peak rates MFU/bandwidth utilization are measured against."""

    name: str
    peak_flops_per_s: float       # bf16 systolic peak
    peak_hbm_bytes_per_s: float
    #: Host->device interconnect ceiling (PCIe gen4 x16 order) — only
    #: the wire-bound classification reads it.
    peak_h2d_bytes_per_s: float = 32e9

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def resolve(cls, spec: typing.Union[str, "DeviceSpec"]) -> "DeviceSpec":
        if isinstance(spec, cls):
            return spec
        try:
            return DEVICE_SPECS[spec]
        except KeyError:
            raise ValueError(
                f"unknown device spec {spec!r} — declare one of "
                f"{sorted(DEVICE_SPECS)} or pass a DeviceSpec") from None


#: Presets (bf16 peak / HBM bandwidth, per chip).  ``cpu-test`` declares
#: tiny deterministic peaks so CPU-only tests exercise real (non-zero,
#: non-degenerate) MFU arithmetic without pretending a CPU is a TPU.
DEVICE_SPECS: typing.Dict[str, DeviceSpec] = {
    "v4": DeviceSpec("v4", 275e12, 1228e9),
    "v5e": DeviceSpec("v5e", 197e12, 819e9),
    "v5p": DeviceSpec("v5p", 459e12, 2765e9),
    "v6e": DeviceSpec("v6e", 918e12, 1640e9),
    "cpu-test": DeviceSpec("cpu-test", 1e9, 1e9, 1e9),
}


@dataclasses.dataclass(frozen=True)
class RooflineConfig:
    """``JobConfig.roofline`` — declaring one turns the plane on.

    ``cost_table`` left ``None`` is the common path: the environment
    prices the captured plan itself at ``execute()`` (fail-soft — an
    unpriceable plan still publishes busy/duty/compile gauges, just no
    MFU).  The tolerances are the drift knobs the README documents.
    """

    device: typing.Union[str, DeviceSpec] = "v5e"
    cost_table: typing.Optional["CostTable"] = None
    #: |measured - predicted| / predicted per-call h2d beyond this
    #: fraction is a `roofline-drift` finding.
    h2d_tolerance: float = 0.25
    #: Measured MFU above this many percent of peak means the static
    #: FLOPs estimate (or the step timing) is wrong — flops drift.
    mfu_ceiling_pct: float = 105.0
    #: Device duty cycle (busy_s / elapsed) below this classifies the
    #: operator host-bound regardless of its busy-time utilization.
    host_duty_threshold: float = 0.33

    def resolved_device(self) -> DeviceSpec:
        return DeviceSpec.resolve(self.device)

    def validate(self) -> "RooflineConfig":
        self.resolved_device()  # raises on an unknown preset
        if self.h2d_tolerance <= 0:
            raise ValueError(
                f"h2d_tolerance must be > 0, got {self.h2d_tolerance}")
        if self.mfu_ceiling_pct <= 0:
            raise ValueError(
                f"mfu_ceiling_pct must be > 0, got {self.mfu_ceiling_pct}")
        if not (0.0 <= self.host_duty_threshold < 1.0):
            raise ValueError(
                "host_duty_threshold must be in [0, 1), got "
                f"{self.host_duty_threshold}")
        return self


# ---------------------------------------------------------------------------
# the live plane: one per executor, one probe per runner
# ---------------------------------------------------------------------------


class RooflinePlane:
    """Executor-owned fan-out point: holds the resolved DeviceSpec, the
    shipped CostTable, and the flight/tracer hooks compile events land
    on.  ``_wire_units`` puts it on ``ctx.roofline``; runners mint one
    :class:`RooflineProbe` per operator at ``open()``."""

    def __init__(self, config: RooflineConfig, *,
                 flight=None, tracer=None):
        self.config = config
        self.spec = config.resolved_device()
        self.table = config.cost_table
        self.flight = flight
        self.tracer = tracer

    def probe(self, node: str, *, metrics=None) -> "RooflineProbe":
        op_cost = self.table.op(node) if self.table is not None else None
        return RooflineProbe(self, node, op_cost=op_cost, metrics=metrics)


class RooflineProbe:
    """Per-operator accumulator joining measured step times against the
    static cost entries; registers the ``roofline.*`` gauges on the
    operator's metric group so they ride cohort telemetry pushes.

    Counters are plain ints (registry convention): racy increments from
    a fetch thread lose at most a step of attribution, never corrupt."""

    def __init__(self, plane: RooflinePlane, node: str, *,
                 op_cost: typing.Optional["OperatorCost"] = None,
                 metrics=None):
        self.plane = plane
        self.node = node
        self.op_cost = op_cost
        self._ladder = frozenset(
            op_cost.predicted_signatures if op_cost is not None else ())
        self._seen: typing.Set[typing.Tuple[str, typing.Optional[str]]] = set()
        self._warmup = 0
        self._t_first: typing.Optional[float] = None
        self.busy_s = 0.0
        self.flops = 0
        self.hbm_bytes = 0
        self.h2d_bytes = 0            # measured, all calls
        self.h2d_calls = 0
        #: Drift pair: measured/predicted restricted to calls the cost
        #: table actually priced — the per-call averages stay comparable.
        self.h2d_measured_paired = 0
        self.h2d_predicted_paired = 0
        self.h2d_paired_calls = 0
        self.compile_events = 0
        self.unpredicted_compiles = 0
        if metrics is not None:
            self._register_gauges(metrics)

    # -- warmup bracketing -------------------------------------------------
    def begin_warmup(self) -> None:
        """Compile-time suppression: warmup observes record their
        compile events (trigger="warmup") but no busy/flops accounting —
        compile time must not masquerade as steady-state throughput."""
        self._warmup += 1

    def end_warmup(self) -> None:
        self._warmup = max(0, self._warmup - 1)

    # -- the per-step join -------------------------------------------------
    def observe(self, unit: str, busy_s: float, *,
                signature: typing.Optional[str] = None,
                h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
        """Attribute one measured call of ``unit`` at ``signature``."""
        key = (unit, signature)
        if key not in self._seen:
            self._seen.add(key)
            if signature is not None:
                self._record_compile(unit, signature)
                if not self._warmup:
                    # The first call of a signature pays the XLA compile
                    # inside its measured time — logged as a compile
                    # event, excluded from throughput attribution (same
                    # rule as the runners' warmup metric suppression).
                    return
        if self._warmup:
            return
        now = time.monotonic()
        if self._t_first is None:
            self._t_first = now - busy_s
        self.busy_s += busy_s
        entry = (self.op_cost.entry(unit, signature)
                 if self.op_cost is not None else None)
        if entry is not None:
            self.flops += entry.flops
            self.hbm_bytes += entry.hbm_bytes
        if h2d_bytes:
            self.h2d_bytes += h2d_bytes
            self.h2d_calls += 1
            if entry is not None and entry.h2d_bytes:
                self.h2d_measured_paired += h2d_bytes
                self.h2d_predicted_paired += entry.h2d_bytes
                self.h2d_paired_calls += 1

    def observe_transfer(self, unit: str, busy_s: float, *,
                         signature: typing.Optional[str] = None,
                         h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
        """Attribute one measured cache move (warm-tier extraction,
        spilled-session revival, paged block insert).

        Transfers are NOT jit launches: no compile event is minted and
        there is no first-sight suppression — the first spill pays the
        same wire time as the hundredth, so suppressing it would bias
        the duty cycle exactly when tiering churn matters most.  Busy
        time still accrues (a runner drowning in cache moves IS
        wire-bound and :meth:`bound` should say so), and measured bytes
        pair against the plan's ``cache_move`` entries to feed the same
        drift gauges the per-step h2d feeds."""
        if self._warmup:
            return
        now = time.monotonic()
        if self._t_first is None:
            self._t_first = now - busy_s
        self.busy_s += busy_s
        moved = h2d_bytes + d2h_bytes
        if not moved:
            return
        self.h2d_bytes += moved
        self.h2d_calls += 1
        entry = (self.op_cost.entry(unit, signature)
                 if self.op_cost is not None else None)
        if entry is not None:
            # cache_move entries price both directions; pair against
            # whichever side this call actually crossed.
            predicted = (entry.h2d_bytes if h2d_bytes
                         else getattr(entry, "d2h_bytes", 0))
            if predicted:
                self.h2d_measured_paired += moved
                self.h2d_predicted_paired += predicted
                self.h2d_paired_calls += 1

    def _record_compile(self, unit: str, signature: str) -> None:
        """A jit cache miss (first sight of a signature): provenance to
        the flight recorder + trace, diffed against the predicted
        ladder."""
        self.compile_events += 1
        predicted = (signature in self._ladder) if self._ladder else None
        if predicted is False:
            self.unpredicted_compiles += 1
        args = {"node": self.node, "unit": unit, "signature": signature,
                "trigger": "warmup" if self._warmup else "steady-state",
                "predicted": predicted}
        if self.plane.flight is not None:
            self.plane.flight.record("compile", "jit_compile", args)
        if self.plane.tracer is not None:
            self.plane.tracer.instant(
                "compile.events", f"compile {self.node}:{signature}",
                args=args)

    # -- derived readings --------------------------------------------------
    def elapsed_s(self) -> float:
        if self._t_first is None:
            return 0.0
        return max(time.monotonic() - self._t_first, self.busy_s, 1e-9)

    def flops_per_s(self) -> float:
        e = self.elapsed_s()
        return self.flops / e if e else 0.0

    def hbm_bytes_per_s(self) -> float:
        e = self.elapsed_s()
        return self.hbm_bytes / e if e else 0.0

    def mfu_pct(self) -> float:
        return 100.0 * self.flops_per_s() / self.plane.spec.peak_flops_per_s

    def membw_pct(self) -> float:
        return (100.0 * self.hbm_bytes_per_s()
                / self.plane.spec.peak_hbm_bytes_per_s)

    def measured_h2d_per_call(self) -> float:
        return self.h2d_bytes / self.h2d_calls if self.h2d_calls else 0.0

    def predicted_h2d_per_call(self) -> float:
        if not self.h2d_paired_calls:
            return 0.0
        return self.h2d_predicted_paired / self.h2d_paired_calls

    def h2d_drift_frac(self) -> float:
        if not self.h2d_paired_calls or not self.h2d_predicted_paired:
            return 0.0
        measured = self.h2d_measured_paired / self.h2d_paired_calls
        predicted = self.h2d_predicted_paired / self.h2d_paired_calls
        return abs(measured - predicted) / predicted

    def bound(self) -> int:
        e = self.elapsed_s()
        if not e or not self.busy_s:
            return BOUND_NONE
        spec = self.plane.spec
        duty = self.busy_s / e
        if duty < self.plane.config.host_duty_threshold:
            return BOUND_HOST
        mfu_busy = self.flops / self.busy_s / spec.peak_flops_per_s
        membw_busy = (self.hbm_bytes / self.busy_s
                      / spec.peak_hbm_bytes_per_s)
        wire_busy = (self.h2d_bytes / self.busy_s
                     / spec.peak_h2d_bytes_per_s)
        if not self.flops and not self.hbm_bytes:
            # No compute entry joined.  Pure cache traffic (an operator
            # that only ever moved blocks) still ranks as wire-bound.
            return BOUND_WIRE if self.h2d_bytes else BOUND_NONE
        if wire_busy > max(mfu_busy, membw_busy):
            return BOUND_WIRE
        return BOUND_COMPUTE if mfu_busy >= membw_busy else BOUND_MEMORY

    def _register_gauges(self, grp) -> None:
        grp.gauge("roofline.flops_per_s", self.flops_per_s)
        grp.gauge("roofline.hbm_bytes_per_s", self.hbm_bytes_per_s)
        grp.gauge("roofline.busy_s", lambda: self.busy_s)
        grp.gauge("roofline.mfu_pct", self.mfu_pct)
        grp.gauge("roofline.membw_pct", self.membw_pct)
        grp.gauge("roofline.bound", self.bound)
        grp.gauge("roofline.measured_h2d_per_call",
                  self.measured_h2d_per_call)
        grp.gauge("roofline.predicted_h2d_per_call",
                  self.predicted_h2d_per_call)
        grp.gauge("roofline.h2d_drift_frac", self.h2d_drift_frac)
        grp.gauge("roofline.compile_events", lambda: self.compile_events)
        grp.gauge("roofline.unpredicted_compiles",
                  lambda: self.unpredicted_compiles)


# ---------------------------------------------------------------------------
# the offline join: report rows from any evidence subset
# ---------------------------------------------------------------------------


def _row(operator: str, *, busy_s: float, flops_per_s: float,
         hbm_bytes_per_s: float, spec: DeviceSpec,
         bound: typing.Optional[int] = None,
         measured_h2d: float = 0.0, predicted_h2d: float = 0.0,
         drift_frac: float = 0.0, compile_events: int = 0,
         unpredicted: int = 0) -> dict:
    mfu = 100.0 * flops_per_s / spec.peak_flops_per_s
    membw = 100.0 * hbm_bytes_per_s / spec.peak_hbm_bytes_per_s
    binding = min(1.0, max(mfu, membw) / 100.0)
    return {
        "operator": operator,
        "busy_s": round(busy_s, 6),
        "flops_per_s": flops_per_s,
        "hbm_bytes_per_s": hbm_bytes_per_s,
        "mfu_pct": round(mfu, 4),
        "membw_pct": round(membw, 4),
        "bound": BOUND_NAMES[bound if bound is not None
                             else (BOUND_COMPUTE if mfu >= membw and mfu
                                   else BOUND_MEMORY if membw
                                   else BOUND_NONE)],
        #: Seconds of device time recoverable under this operator if it
        #: ran at its binding ceiling — the ranking key.
        "headroom_s": round(busy_s * (1.0 - binding), 6),
        "measured_h2d_per_call": measured_h2d,
        "predicted_h2d_per_call": predicted_h2d,
        "h2d_drift_frac": round(drift_frac, 4),
        "compile_events": compile_events,
        "unpredicted_compiles": unpredicted,
    }


def rows_from_snapshot(snapshot: typing.Mapping[str, typing.Mapping],
                       spec: DeviceSpec) -> typing.List[dict]:
    """One report row per scope publishing ``roofline.*`` gauges."""
    rows = []
    for scope, m in sorted(snapshot.items()):
        if not isinstance(m, dict) or "roofline.busy_s" not in m:
            continue

        def g(name, default=0.0):
            v = m.get(name)
            return default if v is None else v

        rows.append(_row(
            scope,
            busy_s=float(g("roofline.busy_s")),
            flops_per_s=float(g("roofline.flops_per_s")),
            hbm_bytes_per_s=float(g("roofline.hbm_bytes_per_s")),
            spec=spec,
            bound=int(g("roofline.bound", BOUND_NONE)),
            measured_h2d=float(g("roofline.measured_h2d_per_call")),
            predicted_h2d=float(g("roofline.predicted_h2d_per_call")),
            drift_frac=float(g("roofline.h2d_drift_frac")),
            compile_events=int(g("roofline.compile_events", 0)),
            unpredicted=int(g("roofline.unpredicted_compiles", 0)),
        ))
    return rows


def rows_from_trace(events: typing.Sequence[tuple],
                    table: typing.Optional["CostTable"],
                    spec: DeviceSpec) -> typing.List[dict]:
    """Report rows joined from span events (tracer tuple form:
    ``(track, name, ph, ts, dur, args)``) against a CostTable — the
    no-live-metrics evidence path (post-hoc trace + plan artifact)."""
    from flink_tensorflow_tpu.analysis.costmodel import serving_signature

    per_op: typing.Dict[str, dict] = {}
    for ev in events:
        track, name, ph, ts, dur, args = ev[:6]
        if ph != "X" or name not in (COMPUTE_SPAN_NAMES | CACHE_SPAN_NAMES):
            continue
        node = str(track).rsplit(".", 1)[0]
        acc = per_op.setdefault(node, {
            "busy_s": 0.0, "t0": ts, "t1": ts, "flops": 0, "hbm": 0,
            "h2d": 0.0, "pred_h2d": 0.0, "calls": 0})
        acc["busy_s"] += dur
        acc["t0"] = min(acc["t0"], ts)
        acc["t1"] = max(acc["t1"], ts + dur)
        oc = table.op(node) if table is not None else None
        args = args or {}
        if name in CACHE_SPAN_NAMES:
            # Cache moves join measured bytes from the span itself and
            # predicted bytes from the plan's cache_move entries — the
            # drift pair the PR-17 deferral left open for non-runner
            # h2d attribution.
            measured = int(args.get("bytes", 0) or 0)
            if measured:
                acc["h2d"] += measured
                acc["calls"] += 1
                if oc is not None:
                    sig = (f"cache:pages:{args['pages']}"
                           if args.get("pages") else "cache:block")
                    entry = oc.entry("cache_move", sig)
                    if entry is not None:
                        acc["pred_h2d"] += (entry.h2d_bytes
                                            or entry.d2h_bytes)
            continue
        if oc is None:
            continue
        entry = None
        if name == "decode.prefill" and args.get("bucket"):
            b, t = args["bucket"]
            entry = oc.entry("prefill", serving_signature("prefill", b, t))
        elif name == "decode.step":
            entry = oc.entry("decode_step")
        elif name == "compute" and args.get("batch") is not None:
            entry = oc.entry(oc.entries[0].unit if oc.entries else "",
                             f"b{args['batch']}")
        if entry is not None:
            acc["flops"] += entry.flops
            acc["hbm"] += entry.hbm_bytes
            acc["h2d"] += entry.h2d_bytes
            acc["pred_h2d"] += entry.h2d_bytes
            acc["calls"] += 1
    rows = []
    for node, acc in sorted(per_op.items()):
        elapsed = max(acc["t1"] - acc["t0"], acc["busy_s"], 1e-9)
        rows.append(_row(
            node,
            busy_s=acc["busy_s"],
            flops_per_s=acc["flops"] / elapsed,
            hbm_bytes_per_s=acc["hbm"] / elapsed,
            spec=spec,
            measured_h2d=(acc["h2d"] / acc["calls"]) if acc["calls"] else 0.0,
            predicted_h2d=(acc["pred_h2d"] / acc["calls"])
            if acc["calls"] else 0.0,
        ))
    return rows


def drift_findings(rows: typing.Sequence[dict], *,
                   h2d_tolerance: float = 0.25,
                   mfu_ceiling_pct: float = 105.0) -> typing.List[dict]:
    """The named findings the acceptance criteria require: each one
    carries the operator and the predicted/measured pair."""
    findings = []
    for r in rows:
        if (r.get("h2d_drift_frac", 0.0) > h2d_tolerance
                and r.get("predicted_h2d_per_call")):
            findings.append({
                "rule": "roofline-drift",
                "operator": r["operator"],
                "measured_h2d_per_call": r["measured_h2d_per_call"],
                "predicted_h2d_per_call": r["predicted_h2d_per_call"],
                "drift_frac": r["h2d_drift_frac"],
                "message": (
                    f"measured h2d {r['measured_h2d_per_call']:.1f} B/call "
                    f"vs predicted {r['predicted_h2d_per_call']:.1f} B/call "
                    f"({r['h2d_drift_frac']:.0%} > "
                    f"{h2d_tolerance:.0%} tolerance) — the plan's static "
                    "transfer accounting no longer matches the runtime"),
            })
        if r.get("unpredicted_compiles"):
            findings.append({
                "rule": "roofline-recompile",
                "operator": r["operator"],
                "unpredicted_compiles": r["unpredicted_compiles"],
                "message": (
                    f"{r['unpredicted_compiles']} jit compile(s) outside "
                    "the predicted signature ladder — an unplanned shape "
                    "reached the device (recompile churn the plan did not "
                    "declare)"),
            })
        if r.get("mfu_pct", 0.0) > mfu_ceiling_pct:
            findings.append({
                "rule": "roofline-flops-drift",
                "operator": r["operator"],
                "mfu_pct": r["mfu_pct"],
                "message": (
                    f"measured MFU {r['mfu_pct']:.1f}% exceeds the "
                    f"physical ceiling ({mfu_ceiling_pct:.0f}%) — the "
                    "static FLOPs estimate or the step timing is wrong"),
            })
    return findings


def roofline_report(
    snapshot: typing.Optional[typing.Mapping] = None,
    *,
    events: typing.Sequence[tuple] = (),
    cost_table: typing.Optional["CostTable"] = None,
    device: typing.Union[str, DeviceSpec] = "v5e",
    top: typing.Optional[int] = None,
    h2d_tolerance: float = 0.25,
    mfu_ceiling_pct: float = 105.0,
) -> dict:
    """The ranked headroom report from any evidence subset: live
    ``roofline.*`` gauges in a metric snapshot when available, else
    compute spans from a trace joined against a CostTable.  Rows rank by
    recoverable headroom — "the top N seconds of recoverable headroom
    live under operator X"."""
    spec = DeviceSpec.resolve(device)
    rows = rows_from_snapshot(snapshot, spec) if snapshot else []
    if not rows and events:
        rows = rows_from_trace(events, cost_table, spec)
    rows.sort(key=lambda r: (-r["headroom_s"], r["operator"]))
    findings = drift_findings(rows, h2d_tolerance=h2d_tolerance,
                              mfu_ceiling_pct=mfu_ceiling_pct)
    if top is not None:
        rows = rows[:top]
    return {
        "kind": "flink-tpu-roofline-report",
        "device": spec.to_json(),
        "rows": rows,
        "findings": findings,
    }


def matches_scope(pattern: str, scope: str) -> bool:
    """fnmatch helper shared with the health rules' scope filters."""
    return fnmatch.fnmatch(scope, pattern)


# ---------------------------------------------------------------------------
# CLI — flink-tpu-roofline
# ---------------------------------------------------------------------------


def _load_snapshot(path: str) -> typing.Mapping:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a metric snapshot")
    if "snapshot" in doc and isinstance(doc["snapshot"], dict):
        return doc["snapshot"]
    return doc


def format_report(report: dict) -> str:
    rows = report["rows"]
    lines = [f"== flink-tpu-roofline (device: {report['device']['name']}, "
             f"peak {report['device']['peak_flops_per_s'] / 1e12:.0f} "
             "TFLOP/s) =="]
    if not rows:
        lines.append("  no roofline evidence in the inputs (run with "
                     "JobConfig.roofline set, or pass --trace + "
                     "--cost-table)")
    header = (f"  {'operator':28s} {'mfu%':>7s} {'membw%':>7s} "
              f"{'bound':>7s} {'busy_s':>9s} {'headroom_s':>11s} "
              f"{'h2d drift':>9s}")
    if rows:
        lines.append(header)
    for r in rows:
        lines.append(
            f"  {r['operator']:28s} {r['mfu_pct']:7.2f} "
            f"{r['membw_pct']:7.2f} {r['bound']:>7s} "
            f"{r['busy_s']:9.3f} {r['headroom_s']:11.3f} "
            f"{r['h2d_drift_frac']:8.1%}")
    for f in report["findings"]:
        lines.append(f"  DRIFT [{f['rule']}] {f['operator']}: "
                     f"{f['message']}")
    if rows and not report["findings"]:
        lines.append("  drift: none — measured matches the plan's "
                     "predictions within tolerance")
    return "\n".join(lines)


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="flink-tpu-roofline",
        description="Ranked per-operator MFU / bandwidth / headroom "
                    "report: joins live roofline.* gauges (metric "
                    "snapshot) or compute spans (Chrome trace) against "
                    "the plan's static CostTable and a declared "
                    "DeviceSpec peak; predicted-vs-measured divergence "
                    "surfaces as named drift findings (exit 1).",
    )
    parser.add_argument("--snapshot", default=None, metavar="SNAP.json",
                        help="metric scope tree (inspector/cohort "
                             "snapshot) carrying roofline.* gauges")
    parser.add_argument("--trace", nargs="*", default=[],
                        metavar="TRACE.json",
                        help="exported Chrome trace(s): compute spans "
                             "join against --cost-table when no "
                             "snapshot is given")
    parser.add_argument("--cost-table", default=None, metavar="TABLE.json",
                        help="static cost table "
                             "(flink-tpu-shardcheck --cost-table)")
    parser.add_argument("--device", default="v5e",
                        help=f"DeviceSpec preset ({sorted(DEVICE_SPECS)}; "
                             "default v5e)")
    parser.add_argument("--top", type=int, default=None,
                        help="rows to keep after the headroom ranking")
    parser.add_argument("--h2d-tolerance", type=float, default=0.25,
                        help="h2d drift fraction beyond which a finding "
                             "fires (default 0.25)")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as one JSON line")
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="also write the report JSON to PATH")
    args = parser.parse_args(argv)

    snapshot = None
    events: typing.List[tuple] = []
    table = None
    loaded = 0
    try:
        spec = DeviceSpec.resolve(args.device)
        if args.snapshot:
            snapshot = _load_snapshot(args.snapshot)
            loaded += 1
        if args.trace:
            from flink_tensorflow_tpu.tracing.attribution import (
                events_from_chrome,
            )

            for path in args.trace:
                with open(path) as f:
                    events.extend(events_from_chrome(json.load(f)))
                loaded += 1
        if args.cost_table:
            from flink_tensorflow_tpu.analysis.costmodel import CostTable

            with open(args.cost_table) as f:
                table = CostTable.from_json(json.load(f))
            loaded += 1
    except (OSError, ValueError) as ex:
        print(f"flink-tpu-roofline: unreadable evidence: {ex}",
              file=sys.stderr)
        return 2
    if not loaded:
        parser.error("provide at least one of --snapshot / --trace / "
                     "--cost-table")
    report = roofline_report(
        snapshot, events=events, cost_table=table, device=spec,
        top=args.top, h2d_tolerance=args.h2d_tolerance)
    print(format_report(report))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        print(f"report -> {args.out}")
    if args.json:
        print(json.dumps(report))
    return 1 if report["findings"] else 0


def cli() -> None:
    """Console-script entry point (``flink-tpu-roofline``)."""
    import sys

    sys.exit(main())


if __name__ == "__main__":  # pragma: no cover — python -m parity with cli()
    cli()
