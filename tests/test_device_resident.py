"""Device-resident dataflow (ISSUE 7): HBM-resident chained handoff.

The tentpole's contract, asserted end to end:

- a model->model chained pipeline pays exactly ONE h2d and ONE d2h per
  batch (trace-span CI guard, reusing tracing/attribution.py);
- the lazy materialization boundary forces the deferred fetch exactly
  once, at the first host-only consumer (sink / keyed shuffle / plain
  map), and user code never sees a DeviceBatch it didn't ask for;
- results are bit-compatible with the device-resident-off arm;
- a checkpoint barrier arriving mid device-resident segment snapshots
  correctly: in-flight device batches flush before the snapshot, and a
  restored run replays deterministically with no loss or duplication;
- h2d wire narrowing (bf16) halves transferred bytes within tolerance.
"""

import time

import numpy as np
import pytest

from flink_tensorflow_tpu import StreamExecutionEnvironment
from flink_tensorflow_tpu.functions import DeviceMapFunction, ModelMapFunction
from flink_tensorflow_tpu.functions.runner import CompiledMethodRunner
from flink_tensorflow_tpu.tensors import (
    BucketLadder,
    BucketPolicy,
    DeviceBatch,
    RecordSchema,
    TensorValue,
    spec,
)

DIM = 8


def _res_model(dim=DIM, name="resmlp"):
    import jax.numpy as jnp

    from flink_tensorflow_tpu.models.base import Model, ModelMethod

    schema = RecordSchema({"x": spec((dim,))})

    def serve(params, inputs):
        return {"x": jnp.tanh(inputs["x"] @ params["w"]) + inputs["x"]}

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(dim, dim).astype(np.float32) * 0.1)}
    return Model(name, params,
                 {"serve": ModelMethod("serve", schema, ("x",), serve)})


def _records(n, dim=DIM):
    return [
        TensorValue({"x": np.full(dim, i, np.float32) / (n or 1)}, {"id": i})
        for i in range(n)
    ]


def _runner(model, emit_device=False, **kw):
    r = CompiledMethodRunner(
        model, policy=BucketPolicy(batch=BucketLadder.up_to(4)), **kw)
    r.open(None)
    r.emit_device_batches = emit_device
    return r


class TestDeviceBatch:
    def test_materialize_once_and_iteration(self):
        model = _res_model()
        r = _runner(model, emit_device=True)
        try:
            out = r.run_batch(_records(3))
            assert len(out) == 1 and isinstance(out[0], DeviceBatch)
            db = out[0]
            assert db.num_records == 3 and not db.materialized
            first = db.materialize()
            assert db.materialized
            assert db.materialize() is first  # cached, fetched once
            assert [tv.meta["id"] for tv in db] == [0, 1, 2]
        finally:
            r.close()

    def test_results_match_host_path(self):
        model = _res_model()
        host = _runner(model, emit_device=False)
        dev = _runner(model, emit_device=True)
        try:
            recs = _records(4)
            expect = host.run_batch(recs)
            got = dev.run_batch(recs)[0].materialize()
            assert len(expect) == len(got) == 4
            for a, b in zip(expect, got):
                np.testing.assert_allclose(a["x"], b["x"], rtol=1e-6)
        finally:
            host.close()
            dev.close()

    def test_pickle_is_refused(self):
        import pickle

        model = _res_model()
        r = _runner(model, emit_device=True)
        try:
            db = r.run_batch(_records(2))[0]
            with pytest.raises(TypeError, match="device-resident"):
                pickle.dumps(db)
        finally:
            r.close()

    def test_dispatch_device_consumes_upstream_arrays(self):
        model = _res_model()
        up = _runner(model, emit_device=True)
        down = _runner(model, emit_device=False)
        try:
            db = up.run_batch(_records(4))[0]
            assert down.dispatch_device(db) is True
            out = down.flush()
            assert [tv.meta["id"] for tv in out] == [0, 1, 2, 3]
            # reference: the same two hops through host round trips
            mid = _runner(model)
            try:
                ref = down.run_batch(mid.run_batch(_records(4)))
            finally:
                mid.close()
            for a, b in zip(ref, out):
                np.testing.assert_allclose(a["x"], b["x"], rtol=1e-6)
        finally:
            up.close()
            down.close()

    def test_dispatch_device_schema_mismatch_falls_back(self):
        model = _res_model()
        other = _res_model(dim=DIM * 2)
        up = _runner(model, emit_device=True)
        down = _runner(other, emit_device=False)
        try:
            db = up.run_batch(_records(2))[0]
            assert down.dispatch_device(db) is False  # shape mismatch
        finally:
            up.close()
            down.close()

    def test_double_buffer_pool(self):
        model = _res_model()
        r = _runner(model)  # dispatch_lanes=1, double_buffer default on
        r2 = _runner(model, double_buffer=False)
        try:
            assert r._pool is not None and r._pool._max_workers == 2
            assert r2._pool is None
        finally:
            r.close()
            r2.close()

    def test_wire_dtype_bf16_halves_h2d_bytes(self):
        model = _res_model()
        full = _runner(model)
        narrow = _runner(model, wire_dtype="bf16")
        try:
            recs = _records(4)
            a = full.run_batch(recs)
            b = narrow.run_batch(recs)
            for x, y in zip(a, b):
                np.testing.assert_allclose(x["x"], y["x"],
                                           rtol=2 ** -6, atol=1e-3)
            batch_bytes = 4 * DIM * 4
            _, nb, saved = narrow._transfer.ship(
                __import__("flink_tensorflow_tpu.tensors.batching",
                           fromlist=["assemble"]).assemble(
                    recs, model.method("serve").input_schema,
                    narrow.policy))
            assert nb == batch_bytes // 2 and saved == batch_bytes // 2
        finally:
            full.close()
            narrow.close()

    def test_wire_dtype_int8_quarters_h2d_bytes(self):
        """PR-7 deferral closed: int8 absmax narrowing on the h2d hop —
        the field ships quantized with a companion __scale__ input, and
        the jitted call dequantizes as its first (fused) op."""
        from flink_tensorflow_tpu.tensors.batching import assemble

        model = _res_model()
        full = _runner(model)
        narrow = _runner(model, wire_dtype="int8")
        try:
            recs = _records(4)
            a = full.run_batch(recs)
            b = narrow.run_batch(recs)
            # absmax quantization: input error <= absmax/254 + rounding;
            # tanh(x@w)+x with |w|~0.1 keeps the amplification ~O(1).
            for x, y in zip(a, b):
                np.testing.assert_allclose(x["x"], y["x"], atol=0.02)
            batch_bytes = 4 * DIM * 4
            arrays, nb, saved = narrow._transfer.ship(assemble(
                recs, model.method("serve").input_schema, narrow.policy))
            # 1/4 payload + one f32 scale scalar alongside the field.
            assert nb == batch_bytes // 4 + 4
            assert saved == batch_bytes * 3 // 4
            assert "__scale__x" in arrays
        finally:
            full.close()
            narrow.close()

    def test_wire_dtype_f16_h2d_tolerance(self):
        model = _res_model()
        full = _runner(model)
        narrow = _runner(model, wire_dtype="f16")
        try:
            recs = _records(4)
            for x, y in zip(full.run_batch(recs), narrow.run_batch(recs)):
                np.testing.assert_allclose(x["x"], y["x"],
                                           rtol=2 ** -9, atol=1e-3)
        finally:
            full.close()
            narrow.close()


def _chain_env(device_resident, records, trace=False, micro=4,
               ckpt_dir=None, every_n=None, throttle=0.0):
    model = _res_model()
    env = StreamExecutionEnvironment(parallelism=1)
    env.configure(device_resident=device_resident, trace=trace)
    if ckpt_dir is not None:
        env.enable_checkpointing(ckpt_dir, every_n_records=every_n)
    env.source_throttle_s = throttle
    out = (
        env.from_collection(records)
        .map(ModelMapFunction(model, micro_batch=micro, idle_flush_s=0.005),
             name="m1")
        .map(ModelMapFunction(model, micro_batch=micro, idle_flush_s=0.005),
             name="m2")
        .sink_to_list()
    )
    return env, out


class TestChainedPipeline:
    def test_on_off_equivalence(self):
        recs = _records(12)
        env_off, off = _chain_env(False, recs)
        env_off.execute(timeout=120)
        env_on, on = _chain_env(True, recs)
        env_on.execute(timeout=120)
        assert len(off) == len(on) == 12
        assert [r.meta["id"] for r in on] == [r.meta["id"] for r in off]
        for a, b in zip(off, on):
            np.testing.assert_allclose(a["x"], b["x"], rtol=1e-6)
        rep = env_on.metric_registry.report()
        assert rep.get("m1.0.fetch_elided_batches", 0) == 3
        assert env_off.metric_registry.report().get(
            "m1.0.fetch_elided_batches", 0) == 0

    def test_host_boundary_user_code_never_sees_device_batch(self):
        """model -> plain host map (chained): the boundary materializes,
        the lambda receives TensorValues."""
        model = _res_model()
        seen = []
        env = StreamExecutionEnvironment(parallelism=1)
        env.configure(device_resident=True)
        out = (
            env.from_collection(_records(8))
            .map(ModelMapFunction(model, micro_batch=4, idle_flush_s=0.005),
                 name="m1")
            .map(lambda r: (seen.append(type(r).__name__), r)[1],
                 name="host")
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert len(out) == 8
        assert set(seen) == {"TensorValue"}

    def test_keyed_shuffle_boundary_materializes(self):
        """model -> keyed edge: Output.emit materializes before the
        partitioner needs per-record keys."""
        from flink_tensorflow_tpu.core.functions import ProcessFunction

        class Tag(ProcessFunction):
            def process_element(self, value, ctx, out):
                out.collect(value.with_meta(key=ctx.current_key))

        model = _res_model()
        env = StreamExecutionEnvironment(parallelism=1)
        env.configure(device_resident=True)
        out = (
            env.from_collection(_records(8))
            .map(ModelMapFunction(model, micro_batch=4, idle_flush_s=0.005,
                                  device_resident=True),
                 name="m1")
            .key_by(lambda r: r.meta["id"] % 2)
            .process(Tag(), parallelism=2)
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert len(out) == 8
        assert {r.meta["key"] for r in out} == {0, 1}

    def test_device_elementwise_link_stays_resident(self):
        model = _res_model()
        env = StreamExecutionEnvironment(parallelism=1)
        env.configure(device_resident=True)
        out = (
            env.from_collection(_records(8))
            .map(ModelMapFunction(model, micro_batch=4, idle_flush_s=0.005),
                 name="m1")
            .map(DeviceMapFunction(lambda arrs: {"x": arrs["x"] * 2.0}),
                 name="scale")
            .sink_to_list()
        )
        env.execute(timeout=120)
        assert len(out) == 8
        rep = env.metric_registry.report()
        assert rep.get("m1.0.fetch_elided_batches", 0) == 2
        # reference
        env2, ref = _chain_env(False, _records(8))
        env2.execute(timeout=120)


class TestTracedElisionGuard:
    """Tier-1 CI guard (not slow): in a traced model->model smoke
    pipeline, zero h2d/d2h spans between the two fused model ops —
    exactly one h2d (first model) and one d2h (second model) per batch
    end to end, with the elisions visible as instants."""

    def test_exactly_one_h2d_and_one_d2h_per_batch(self):
        from flink_tensorflow_tpu.tracing.attribution import attribution

        recs = _records(12)
        env, out = _chain_env(True, recs, trace=True)
        handle = env.execute_async()
        handle.wait(timeout=120)
        assert len(out) == 12
        tracer = handle.executor.tracer
        events = tracer.events()

        def count(track_prefix, name, ph):
            return sum(1 for e in events
                       if e[0].startswith(track_prefix) and e[1] == name
                       and e[2] == ph)

        batches = 3  # 12 records / micro_batch 4
        # First model: h2d spans only; its d2h is ELIDED per batch.
        assert count("m1", "h2d", "X") == batches
        assert count("m1", "d2h", "X") == 0
        assert count("m1", "d2h.elided", "i") == batches
        # Second model: h2d ELIDED per batch; the one real d2h lands here.
        assert count("m2", "h2d", "X") == 0
        assert count("m2", "h2d.elided", "i") == batches
        assert count("m2", "d2h", "X") == batches
        # The attribution table agrees: no h2d stage on m2, none d2h on m1.
        table = attribution(events)
        assert "h2d" not in table.get("m2", {})
        assert "d2h" not in table.get("m1", {})
        assert table["m1"]["h2d"]["count"] == batches
        assert table["m2"]["d2h"]["count"] == batches

    def test_deferred_d2h_span_lands_at_boundary(self):
        """Satellite: the fetch-block's location is asserted by a span —
        DeviceBatch.materialize records d2h(deferred=true) where the
        block actually lands (the host boundary, not the model op)."""
        model = _res_model()
        env = StreamExecutionEnvironment(parallelism=1)
        env.configure(device_resident=True, trace=True)
        out = (
            env.from_collection(_records(8))
            # device_resident=True FORCES emission even though the next
            # consumer is host-only — the auto mode would keep the fetch
            # on the background thread here (no downstream to elide for).
            .map(ModelMapFunction(model, micro_batch=4, idle_flush_s=0.005,
                                  device_resident=True),
                 name="m1")
            .sink_to_list()
        )
        handle = env.execute_async()
        handle.wait(timeout=120)
        assert len(out) == 8
        events = handle.executor.tracer.events()
        deferred = [e for e in events
                    if e[1] == "d2h" and (e[5] or {}).get("deferred")]
        assert len(deferred) == 2  # one per batch, at materialization


class TestBarrierMidSegment:
    def test_checkpoint_mid_device_segment_is_exactly_once(self, tmp_path):
        """A barrier arriving while batches are HBM-resident in flight:
        both chained models flush before snapshotting (device state is
        fetched/emitted pre-barrier), and the restored run replays the
        remainder deterministically — no record lost, none duplicated,
        values identical to an uninterrupted run."""
        n = 120
        recs = _records(n)
        ckpt = str(tmp_path / "ckpts")

        # Reference: uninterrupted, device-resident OFF.
        env_ref, ref = _chain_env(False, recs)
        env_ref.execute(timeout=120)
        by_id = {r.meta["id"]: r for r in ref}
        assert len(by_id) == n

        # Run 1: device-resident ON, checkpoint mid-stream, cancel.
        env1, out1 = _chain_env(True, recs, ckpt_dir=ckpt, throttle=0.002)
        handle = env1.execute_async()
        time.sleep(0.25)
        snaps = handle.trigger_checkpoint(timeout=30)
        offsets = [s["operator"]["offset"]
                   for s in snaps["collection"].values()]
        offset = sum(offsets)
        assert 0 < offset < n, f"want a mid-stream barrier, offsets={offsets}"
        handle.cancel()
        handle.wait(timeout=30)

        # Run 2: restore; must emit exactly records [offset, n).
        env2, out2 = _chain_env(True, recs, ckpt_dir=ckpt)
        env2.execute(restore_from=ckpt, timeout=120)
        ids2 = [r.meta["id"] for r in out2]
        assert ids2 == list(range(offset, n))
        for r in out2:
            np.testing.assert_allclose(r["x"], by_id[r.meta["id"]]["x"],
                                       rtol=1e-6)
