"""Plan-time RecordSchema propagation over the dataflow graph.

The TypeInformation role the reference delegated to Flink's job-graph
translation: sources declare the schema of the records they emit
(``Transformation.declared_schema``), every downstream operator may
declare a transform (``Transformation.schema_fn``, usually wired from
the function's optional ``output_schema(input_schema)`` hook), and this
pass walks the topological order applying them — validating without
executing, the same AOT posture as ``jax.eval_shape`` over
``RecordSchema.batched_struct``.

Propagation tracks the SET of distinct schemas flowing on each node's
output, not just one: a union of two differently-shaped streams legally
carries both signatures, and only a downstream jit boundary turns that
into recompilation churn (a lint rule's job, not propagation's).  A
``schema_fn`` that raises :class:`SchemaMismatch` produces an ERROR
diagnostic naming the exact edge the offending schema arrived on.
"""

from __future__ import annotations

import dataclasses
import typing

from flink_tensorflow_tpu.analysis.diagnostics import Diagnostic, Severity, edge_name
from flink_tensorflow_tpu.core.graph import DataflowGraph, Transformation
from flink_tensorflow_tpu.core.operators import Operator
from flink_tensorflow_tpu.tensors.schema import RecordSchema, SchemaMismatch


@dataclasses.dataclass
class SchemaFlow:
    """Propagation result.

    ``out``: node id -> the node's sole output schema, or None when it is
    unknown or ambiguous (several signatures flow).
    ``out_sets``: node id -> every distinct schema known to flow out of
    the node (empty = unknown).
    """

    out: typing.Dict[int, typing.Optional[RecordSchema]]
    out_sets: typing.Dict[int, typing.List[RecordSchema]]
    diagnostics: typing.List[Diagnostic]


def is_two_input(op: typing.Optional[Operator]) -> bool:
    """Two-input operators (connect/join) dispatch per logical edge and
    legitimately see a different schema per input."""
    if op is None:
        return False
    return type(op).process_record_from is not Operator.process_record_from


def _apply(schema_fn, input_schema):
    """A schema_fn is either a callable transform or a constant schema."""
    if isinstance(schema_fn, RecordSchema):
        return schema_fn
    return schema_fn(input_schema)


def propagate(
    graph: DataflowGraph,
    order: typing.Sequence[Transformation],
    operators: typing.Mapping[int, typing.Optional[Operator]],
) -> SchemaFlow:
    diags: typing.List[Diagnostic] = []
    # Ordered sets (dict keys) so diagnostics are deterministic.
    out_sets: typing.Dict[int, typing.Dict[RecordSchema, None]] = {}

    for t in order:
        if t.is_source:
            if t.declared_schema is None:
                diags.append(Diagnostic(
                    rule="source-schema-unknown",
                    severity=Severity.INFO,
                    message="source declares no RecordSchema; schema "
                            "propagation is disabled downstream of it "
                            "(pass schema=... to from_source/from_collection)",
                    node=t.name,
                ))
                out_sets[t.id] = {}
            else:
                out_sets[t.id] = {t.declared_schema: None}
            continue

        # Distinct incoming schemas with the direct edge each arrived on.
        incoming: typing.List[typing.Tuple[RecordSchema, str]] = []
        seen: typing.Set[RecordSchema] = set()
        for e in t.inputs:
            for s in out_sets.get(e.upstream.id, {}):
                if s not in seen:
                    seen.add(s)
                    incoming.append((s, e.upstream.name))

        outs: typing.Dict[RecordSchema, None] = {}
        if t.schema_fn is None:
            pass  # no contract declared: output unknown
        elif is_two_input(operators.get(t.id)):
            per_edge = tuple(
                next(iter(out_sets.get(e.upstream.id, {})), None)
                for e in t.inputs
            )
            try:
                r = _apply(t.schema_fn, per_edge)
                if r is not None:
                    outs[r] = None
            except SchemaMismatch as m:
                diags.append(Diagnostic(
                    rule="schema-mismatch", severity=Severity.ERROR,
                    message=str(m), node=t.name,
                    edge=edge_name(t.inputs[0].upstream.name, t.name),
                ))
            except Exception as ex:  # noqa: BLE001 - hook bugs must not kill analysis
                diags.append(Diagnostic(
                    rule="schema-hook-error", severity=Severity.WARN,
                    message=f"output_schema hook raised {ex!r}", node=t.name,
                ))
        elif not incoming:
            # Unknown input: a hook can still declare a constant output
            # (and must tolerate input_schema=None).
            try:
                r = _apply(t.schema_fn, None)
                if r is not None:
                    outs[r] = None
            except SchemaMismatch:
                pass  # nothing to validate against — stay unknown
            except Exception as ex:  # noqa: BLE001
                diags.append(Diagnostic(
                    rule="schema-hook-error", severity=Severity.WARN,
                    message=f"output_schema hook raised {ex!r}", node=t.name,
                ))
        else:
            for s, upstream_name in incoming:
                try:
                    r = _apply(t.schema_fn, s)
                    if r is not None:
                        outs.setdefault(r)
                except SchemaMismatch as m:
                    diags.append(Diagnostic(
                        rule="schema-mismatch", severity=Severity.ERROR,
                        message=str(m), node=t.name,
                        edge=edge_name(upstream_name, t.name),
                    ))
                except Exception as ex:  # noqa: BLE001
                    diags.append(Diagnostic(
                        rule="schema-hook-error", severity=Severity.WARN,
                        message=f"output_schema hook raised {ex!r}",
                        node=t.name,
                        edge=edge_name(upstream_name, t.name),
                    ))
        out_sets[t.id] = outs

    return SchemaFlow(
        out={
            tid: next(iter(s)) if len(s) == 1 else None
            for tid, s in out_sets.items()
        },
        out_sets={tid: list(s) for tid, s in out_sets.items()},
        diagnostics=diags,
    )
