"""Replay-purity bytecode scanner — the plan-time half of the pipeline
sanitizer (the runtime half is ``core/sanitizer_rt.py``).

Exactly-once recovery replays records through user functions after a
restore, and keyed/operator state is rebuilt by that replay.  A user
function that consults a wall clock, draws from a process-global RNG,
mutates module globals, captures a mutable closure, or performs I/O
computes DIFFERENT results on the replay than it did the first time —
the checkpoint's promise ("the state equals having processed the stream
once") silently breaks, with no exception anywhere.

This module walks user function BYTECODE at plan time (``dis`` over
``__code__``, nested lambdas included) and reports those impurity
sources as :class:`PurityFinding`s.  The ``replay-purity`` lint rule
(analysis/rules.py) surfaces them through ``analyze(graph)``, the
analysis CLI, and ``env.validate_plan()`` — ERROR on keyed-state paths
(where replay divergence corrupts state), WARN elsewhere.

Only USER code is scanned: code objects whose file lives inside the
``flink_tensorflow_tpu`` package are framework-sanctioned (e.g. the
paced source's open-loop clock) and skipped, so the scanner can be
strict about everything else.  Resolution is attempted through the
function's ``__globals__`` first (so ``from random import random`` and
``import numpy as anything`` are caught by object identity, not by
name), with a name-pattern fallback for unresolvable chains.
"""

from __future__ import annotations

import builtins
import dataclasses
import dis
import functools
import os
import types
import typing

#: .../flink_tensorflow_tpu — code under here is framework, not user code.
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep

_MISSING = object()

#: time-module functions that read the wall/monotonic clock.
_TIME_FUNCS = frozenset({
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "process_time", "process_time_ns", "localtime",
    "gmtime", "ctime",
})
_DATETIME_FUNCS = frozenset({"now", "utcnow", "today"})

#: numpy.random module-level constructors that produce SEEDED/owned
#: generators — using these is the recommended pattern, not a finding.
_NP_RANDOM_OK = frozenset({"RandomState", "default_rng", "Generator",
                           "SeedSequence", "PCG64", "Philox", "MT19937"})

#: modules whose use inside a streaming user function is I/O.
_IO_MODULES = frozenset({"socket", "requests", "urllib", "http", "subprocess"})
_OS_IO_FUNCS = frozenset({
    "remove", "unlink", "rename", "replace", "mkdir", "makedirs", "rmdir",
    "system", "popen", "open", "write", "truncate",
})

_MUTABLE_TYPES = (list, dict, set, bytearray)


@dataclasses.dataclass(frozen=True)
class PurityFinding:
    """One replay-purity impurity source found in user bytecode."""

    #: wall-clock | unseeded-random | global-mutation | mutable-closure | io
    kind: str
    #: The offending symbol as spelled in the code (``time.time``,
    #: ``np.random.rand``, ``global counter``, ...).
    symbol: str
    #: Qualified name of the function the finding is in.
    where: str
    #: 1-based source line when the bytecode carries one.
    line: typing.Optional[int] = None

    def describe(self) -> str:
        loc = f"{self.where}" + (f":{self.line}" if self.line else "")
        reason = {
            "wall-clock": "reads the wall clock — replay after restore sees a different time",
            "unseeded-random": "draws from a process-global RNG — replay sees a different stream",
            "global-mutation": "mutates a module global — state survives outside checkpoints",
            "mutable-closure": "captures a mutable object by closure — state survives outside checkpoints",
            "io": "performs I/O — replayed records repeat the side effect",
        }[self.kind]
        return f"{self.symbol} in {loc} {reason}"


def _is_user_code(code: types.CodeType) -> bool:
    filename = code.co_filename
    return bool(filename) and not os.path.abspath(filename).startswith(_PKG_DIR)


def _iter_code_objects(code: types.CodeType) -> typing.Iterator[types.CodeType]:
    """``code`` plus every code object nested in its constants (inner
    lambdas, comprehensions, local defs)."""
    yield code
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            yield from _iter_code_objects(const)


def _resolve_chain(
    chain: typing.Sequence[str], globals_ns: typing.Optional[dict]
) -> typing.Any:
    obj = (globals_ns or {}).get(chain[0], _MISSING)
    if obj is _MISSING:
        obj = getattr(builtins, chain[0], _MISSING)
    for attr in chain[1:]:
        if obj is _MISSING:
            return _MISSING
        obj = getattr(obj, attr, _MISSING)
    return obj


def _global_random_inst():
    import random

    return random._inst


def _np_global_state():
    try:
        import numpy as np

        return np.random.mtrand._rand
    except Exception:  # pragma: no cover - numpy always present here
        return None


def _classify_chain(
    chain: typing.Sequence[str], globals_ns: typing.Optional[dict]
) -> typing.Optional[typing.Tuple[str, str]]:
    """(kind, symbol) when the attribute chain names an impurity source."""
    symbol = ".".join(chain)
    resolved = _resolve_chain(chain, globals_ns)
    if resolved is not _MISSING:
        mod = getattr(resolved, "__module__", None)
        if mod == "time" and getattr(resolved, "__name__", "") in _TIME_FUNCS:
            return "wall-clock", symbol
        qual = getattr(resolved, "__qualname__", "")
        if mod == "datetime" and qual.split(".")[-1] in _DATETIME_FUNCS:
            return "wall-clock", symbol
        bound_self = getattr(resolved, "__self__", None)
        if bound_self is not None:
            if bound_self is _global_random_inst():
                return "unseeded-random", symbol
            if bound_self is _np_global_state():
                return "unseeded-random", symbol
        if resolved is builtins.open or resolved is builtins.input:
            return "io", symbol
        if isinstance(resolved, types.ModuleType):
            return None  # a bare module load is not a call
        root = (mod or "").split(".")[0]
        if root in _IO_MODULES:
            return "io", symbol
        if (root in ("os", "posix", "nt")
                and getattr(resolved, "__name__", "") in _OS_IO_FUNCS):
            return "io", symbol
        return None
    # Unresolvable (e.g. a method-local alias): fall back to spelling.
    if chain[0] == "time" and len(chain) > 1 and chain[1] in _TIME_FUNCS:
        return "wall-clock", symbol
    if (len(chain) >= 3 and chain[1] == "random"
            and chain[2] not in _NP_RANDOM_OK):
        return "unseeded-random", symbol
    if chain[0] in _IO_MODULES:
        return "io", symbol
    if len(chain) > 1 and chain[0] == "os" and chain[-1] in _OS_IO_FUNCS:
        return "io", symbol
    return None


#: Interprocedural depth: helpers called DIRECTLY from a scanned user
#: function are scanned, and so are THEIR helpers (two levels by
#: default — ``outer -> helper -> helper2`` provenance chains); deeper
#: callees are not.  Two levels cover the ubiquitous "map fn delegates
#: to a module helper which delegates to a shared util" split without
#: turning the scanner into a whole-program analysis; pass
#: ``max_depth=`` to :func:`scan_code` to tune it per call.
_MAX_CALL_DEPTH = 2

#: Memoized per-code local scans: id(code) -> (code, base, findings,
#: helpers).  The same helper reached from many operators (or many
#: outer functions) is disassembled ONCE; callers re-root the cached
#: findings' ``where`` onto their own provenance chain.  The value
#: holds a strong reference to the code object so its id cannot be
#: recycled while the cache entry lives.
_SCAN_CACHE: typing.Dict[int, tuple] = {}


def _helper_fn(
    chain: typing.Sequence[str], globals_ns: typing.Optional[dict]
) -> typing.Optional[types.FunctionType]:
    """The USER-DEFINED function a global attribute chain names, if any —
    the interprocedural edge.  Stdlib/framework callees resolve but live
    outside user code and are cut off here; unresolvable chains (locals,
    arguments) never form an edge."""
    resolved = _resolve_chain(chain, globals_ns)
    if resolved is _MISSING:
        return None
    fn = _unwrap(resolved)
    if fn is None or not _is_user_code(fn.__code__):
        return None
    return fn


def _scan_local(
    code: types.CodeType, globals_ns: typing.Optional[dict],
) -> typing.Tuple[str, typing.List[PurityFinding],
                  typing.List[types.FunctionType]]:
    """One code object's OWN findings (nested code included) plus the
    user-defined helpers it names — no recursion into them.  Memoized:
    findings carry a base-relative ``where`` (rooted at the code's own
    qualname) which :func:`scan_code` re-roots per caller."""
    cached = _SCAN_CACHE.get(id(code))
    if cached is not None and cached[0] is code:
        return cached[1], cached[2], cached[3]
    base = getattr(code, "co_qualname", code.co_name)
    findings: typing.List[PurityFinding] = []
    helpers: typing.List[types.FunctionType] = []
    for co in _iter_code_objects(code):
        qual = base if co is code else f"{base}.<{co.co_name}>"
        chain: typing.List[str] = []
        chain_line: typing.Optional[int] = None
        line: typing.Optional[int] = None
        for instr in dis.get_instructions(co):
            if instr.starts_line is not None:
                line = instr.starts_line
            op = instr.opname
            if op in ("LOAD_GLOBAL", "LOAD_NAME"):
                _flush(chain, chain_line, globals_ns, qual, findings, helpers)
                chain = [instr.argval]
                chain_line = line
            elif op in ("LOAD_ATTR", "LOAD_METHOD") and chain:
                chain.append(instr.argval)
            else:
                _flush(chain, chain_line, globals_ns, qual, findings, helpers)
                chain = []
                if op in ("STORE_GLOBAL", "DELETE_GLOBAL"):
                    findings.append(PurityFinding(
                        kind="global-mutation",
                        symbol=f"global {instr.argval}",
                        where=qual, line=line,
                    ))
        _flush(chain, chain_line, globals_ns, qual, findings, helpers)
    _SCAN_CACHE[id(code)] = (code, base, findings, helpers)
    return base, findings, helpers


def scan_code(
    code: types.CodeType,
    globals_ns: typing.Optional[dict] = None,
    where: typing.Optional[str] = None,
    *,
    max_depth: typing.Optional[int] = None,
    _depth: int = 0,
    _seen: typing.Optional[typing.Set[int]] = None,
) -> typing.List[PurityFinding]:
    """Purity findings for one code object (nested code included), plus
    — ``max_depth`` call levels deep (default :data:`_MAX_CALL_DEPTH`)
    — every user-defined helper it names, scanned with the same matrix
    and attributed along the full ``outer -> helper -> helper2``
    provenance chain.  Recursion is cut by a seen-set over code objects
    (the cycle guard), stdlib/framework callees by the user-code
    filter; per-code disassembly is memoized in :data:`_SCAN_CACHE`."""
    depth_cap = _MAX_CALL_DEPTH if max_depth is None else max_depth
    seen = _seen if _seen is not None else set()
    seen.add(id(code))
    top = where or getattr(code, "co_qualname", code.co_name)
    base, local, helpers = _scan_local(code, globals_ns)
    if top == base:
        findings = list(local)
    else:  # re-root the cached base-relative provenance onto this chain
        findings = [dataclasses.replace(f, where=top + f.where[len(base):])
                    for f in local]
    if _depth < depth_cap:
        for helper in helpers:
            if id(helper.__code__) in seen:
                continue  # recursion / already-scanned helper
            findings.extend(scan_code(
                helper.__code__, helper.__globals__,
                where=f"{top} -> {helper.__qualname__}",
                max_depth=depth_cap, _depth=_depth + 1, _seen=seen,
            ))
    return findings


def _flush(chain, chain_line, globals_ns, qual, findings, helpers) -> None:
    if not chain:
        return
    hit = _classify_chain(chain, globals_ns)
    if hit is not None:
        kind, symbol = hit
        findings.append(PurityFinding(kind=kind, symbol=symbol,
                                      where=qual, line=chain_line))
        return
    helper = _helper_fn(chain, globals_ns)
    if helper is not None:
        helpers.append(helper)


def _unwrap(member: typing.Any) -> typing.Optional[types.FunctionType]:
    if isinstance(member, (staticmethod, classmethod)):
        member = member.__func__
    if isinstance(member, functools.partial):
        member = member.func
    if isinstance(member, types.MethodType):
        member = member.__func__
    return member if isinstance(member, types.FunctionType) else None


def collect_user_functions(
    obj: typing.Any, _seen: typing.Optional[typing.Set[int]] = None
) -> typing.List[typing.Tuple[str, types.FunctionType]]:
    """(qualname, function) pairs of USER code reachable from ``obj``.

    ``obj`` may be a bare callable, a RichFunction/SourceFunction/
    SplitSource instance, or an operator: methods of non-framework
    classes in its MRO, plus callables stored in its instance ``__dict__``
    (where the framework's lambda wrappers keep the user's function),
    plus functions captured by closure — everything filtered to code
    objects living OUTSIDE the flink_tensorflow_tpu package.
    """
    seen = _seen if _seen is not None else set()
    out: typing.List[typing.Tuple[str, types.FunctionType]] = []
    if obj is None or id(obj) in seen:
        return out
    if _unwrap(obj) is None:  # containers dedup by id; functions in add()
        seen.add(id(obj))

    def add(name: str, fn_obj: typing.Any) -> None:
        fn = _unwrap(fn_obj)
        if fn is None or id(fn) in seen:
            return
        seen.add(id(fn))
        if not _is_user_code(fn.__code__):
            return
        out.append((name, fn))
        for cell in fn.__closure__ or ():
            try:
                captured = cell.cell_contents
            except ValueError:  # pragma: no cover - empty cell
                continue
            if isinstance(captured, types.FunctionType):
                add(f"{name}.<closure>", captured)

    direct = _unwrap(obj)
    if direct is not None:
        add(getattr(direct, "__qualname__", direct.__name__), direct)
        return out

    for cls in type(obj).__mro__:
        if cls.__module__.startswith("flink_tensorflow_tpu.") or cls is object:
            continue
        for name, member in vars(cls).items():
            add(f"{cls.__qualname__}.{name}", member)
    for name, member in vars(obj).items() if hasattr(obj, "__dict__") else ():
        if callable(member) and not isinstance(member, type):
            if _unwrap(member) is not None:
                add(f"{type(obj).__qualname__}.{name}", member)
            else:
                # A callable object stored on the instance (e.g. a user
                # function object wrapped by a framework one): recurse.
                out.extend(collect_user_functions(member, seen))
    return out


def scan_callable(obj: typing.Any) -> typing.List[PurityFinding]:
    """All purity findings for one user function/object: bytecode scan
    of every reachable user code object + mutable-closure captures."""
    findings: typing.List[PurityFinding] = []
    for name, fn in collect_user_functions(obj):
        findings.extend(scan_code(fn.__code__, fn.__globals__, where=name))
        for var, cell in zip(fn.__code__.co_freevars, fn.__closure__ or ()):
            try:
                captured = cell.cell_contents
            except ValueError:  # pragma: no cover - empty cell
                continue
            if isinstance(captured, _MUTABLE_TYPES):
                findings.append(PurityFinding(
                    kind="mutable-closure",
                    symbol=f"closure {var!r} ({type(captured).__name__})",
                    where=name,
                ))
    return findings


def scan_operator(op: typing.Any) -> typing.List[PurityFinding]:
    """Purity findings for everything user-authored an operator hosts:
    its function, key selectors, timestamp assigner, split source."""
    findings: typing.List[PurityFinding] = []
    seen_syms: typing.Set[typing.Tuple[str, str, str]] = set()
    for attr in ("function", "key_selector", "key_selector1", "key_selector2",
                 "ts_fn", "source"):
        target = getattr(op, attr, None)
        if target is None:
            continue
        for f in scan_callable(target):
            key = (f.kind, f.symbol, f.where)
            if key not in seen_syms:
                seen_syms.add(key)
                findings.append(f)
    return findings
