"""flink-tpu-shardcheck — SPMD layout, donation & HBM-budget static
analyzer for the sharded-workload arc.

The plan analyzer (PR 1) stops at the dataflow graph and the sanitizer
(PRs 5/14) at the record plane; neither ever looks INSIDE the jitted
functions where the sharded-serving/training arc lives.  This module
abstract-evaluates every jit unit a captured plan will execute —
``ModelFunction`` methods, ``OnlineTrainFunction``/``DPTrainWindowFunction``
steps, the serving operator's ``DecodeStepRunner`` prefill/decode calls —
under ``jax.eval_shape``/``jax.make_jaxpr`` against a *declared abstract
mesh* (``parallel.abstract_mesh``: shape without devices, so a CPU-only
dev box analyzes a v5e-8 layout it cannot materialize), then walks the
closed jaxprs to derive four verdicts, surfaced with operator/edge
provenance through the existing ``Diagnostic``/lint registry:

- ``shardcheck-collectives`` (INFO) — psum/all-gather/reduce-scatter/
  ppermute counts per jit unit per step, straight from the jaxpr.
- ``shardcheck-reshard`` (WARN; ERROR on device-resident chained edges)
  — an edge whose upstream declares an OUTPUT layout
  (``output_sharding_axes``) that mismatches the downstream's declared
  input sharding forces XLA to insert an implicit reshard per batch; on
  a PR-7 HBM-resident chained edge that reshard defeats the whole
  h2d-elision the chain exists for.
- ``shardcheck-donation`` (WARN) — large batch args not donated through
  the jit boundary (the KV-pool/param-buffer 2x-HBM trap), dead
  donations (donated arg with no shape-matching output to alias), and
  donations defeated by a dtype mismatch between the aliased pair.
- ``shardcheck-partition`` (ERROR) — a sharded dim (batch over
  data x fsdp, param dims over fsdp/tp per :class:`SpecLayout`) that
  does not divide its mesh-axis product: the first pjit call fails (or
  a collective hangs) after the job already started.
- ``shardcheck-hbm-budget`` (ERROR vs ``JobConfig.hbm_budget_bytes``;
  INFO summaries) — params + optimizer state + KV pool + peak
  activation liveness (linear scan over the jaxpr), per device under
  the mesh.  The admission gate of the paged-KV-economy arc.
- ``shardcheck-signatures`` (WARN unbounded / INFO bounded) — the
  static twin of the runtime recompile-churn lints: enumerate the
  compile signatures a plan can present from ``ServingConfig``
  padding-bucket ladders and runner batch/length buckets.

Everything is fail-soft: a jit unit whose abstract evaluation raises
becomes a note on the audit, never a crashed plan analysis.  Front
doors: ``analyze(graph)`` / ``env.validate_plan()`` (the rules register
at import, via analysis/rules.py), the ``flink-tpu-shardcheck`` console
script (JSON report ``flink-tpu-doctor --shardcheck`` folds in), and
``audit_plan()`` for tests/tools.
"""

from __future__ import annotations

import dataclasses
import math
import typing

from flink_tensorflow_tpu.analysis.diagnostics import Severity, edge_name

if typing.TYPE_CHECKING:
    from flink_tensorflow_tpu.analysis.rules import AnalysisContext

#: jaxpr primitives that lower to inter-device collectives (ICI/DCN
#: traffic).  ``psum_scatter`` is reduce-scatter's primitive name.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "pgather", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "reduce_scatter",
})

#: Donation findings only fire for args at least this large — donating
#: a [B] int32 vector buys nothing and the noise would drown the KV-pool
#: and param-buffer traps the checker exists for.
DONATION_MIN_BYTES = 1 << 20


# ---------------------------------------------------------------------------
# SpecLayout — the fsdp x tp parameter-placement convention
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Declarative fsdp x tp placement for a jit unit's params + batch.

    The sharded-serving arc's convention (scaling-book style): the batch
    shards over ``data`` (x ``fsdp`` when set), 2-D+ weight matrices
    shard ``(fsdp, tp)`` on their trailing two dims — flipped to
    ``(tp, fsdp)`` for output projections, whose contracting dim is the
    sharded one — and 1-D params (biases, norm scales) replicate.
    Functions/operators opt in by carrying a ``spec_layout`` attribute;
    without one, params are treated as replicated and only the batch
    divides over the declared ``sharding_axes``.
    """

    data_axis: str = "data"
    fsdp_axis: typing.Optional[str] = None
    tp_axis: typing.Optional[str] = None

    #: Param-name hints whose MATMUL places the sharded dim first
    #: (output projections: wo/w2/down_proj/out_proj/lm_head).
    out_proj_hints: typing.Tuple[str, ...] = (
        "wo", "w2", "down", "out", "head")

    def batch_axes(self) -> typing.Tuple[str, ...]:
        return tuple(a for a in (self.data_axis, self.fsdp_axis) if a)

    def param_spec(
        self, path: str, shape: typing.Sequence[int]
    ) -> typing.Tuple[typing.Optional[str], ...]:
        """Mesh axis (or None = replicated) per dim of one param leaf."""
        n = len(shape)
        if n < 2 or (self.fsdp_axis is None and self.tp_axis is None):
            return (None,) * n
        leaf = path.rsplit("/", 1)[-1].lower()
        flipped = any(h in leaf for h in self.out_proj_hints)
        spec: typing.List[typing.Optional[str]] = [None] * n
        first, second = ((self.tp_axis, self.fsdp_axis) if flipped
                         else (self.fsdp_axis, self.tp_axis))
        spec[-2], spec[-1] = first, second
        return tuple(spec)


# ---------------------------------------------------------------------------
# Audit data model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One shardcheck verdict, pre-shaped for the Diagnostic plumbing."""

    rule: str
    severity: Severity
    message: str
    node: typing.Optional[str] = None
    edge: typing.Optional[str] = None

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity.name,
                "message": self.message, "node": self.node, "edge": self.edge}


@dataclasses.dataclass
class OpAudit:
    """Everything shardcheck derived about one operator's jit unit(s)."""

    node: str
    kind: str  # model | train | serving
    #: primitive name -> occurrences per step, summed over jit units.
    collectives: typing.Dict[str, int] = dataclasses.field(default_factory=dict)
    #: per-device byte breakdown: params / optimizer / kv_pool / activations.
    hbm: typing.Dict[str, int] = dataclasses.field(default_factory=dict)
    #: bounded compile-signature count (None = unbounded/unknown).
    signatures: typing.Optional[int] = None
    #: predicted steady-state h2d bytes per decode step (serving only) —
    #: the static twin of DecodeStepRunner.step_h2d_bytes accounting.
    predicted_step_h2d_bytes: typing.Optional[int] = None
    #: why parts of the audit were skipped (fail-soft provenance).
    notes: typing.List[str] = dataclasses.field(default_factory=list)

    @property
    def hbm_total(self) -> int:
        return sum(self.hbm.values())

    def to_json(self) -> dict:
        return {
            "node": self.node, "kind": self.kind,
            "collectives": dict(self.collectives),
            "hbm_per_device_bytes": dict(self.hbm),
            "hbm_per_device_total": self.hbm_total,
            "signatures": self.signatures,
            "predicted_step_h2d_bytes": self.predicted_step_h2d_bytes,
            "notes": list(self.notes),
        }


@dataclasses.dataclass
class PlanAudit:
    """The full shardcheck result for one captured plan."""

    findings: typing.List[Finding]
    ops: typing.List[OpAudit]
    mesh_axes: typing.Optional[typing.Dict[str, int]]
    hbm_budget_bytes: typing.Optional[int]

    def op(self, node: str) -> typing.Optional[OpAudit]:
        for a in self.ops:
            if a.node == node:
                return a
        return None

    @property
    def total_hbm_per_device(self) -> int:
        return sum(a.hbm_total for a in self.ops)

    def to_json(self) -> dict:
        return {
            "mesh_axes": self.mesh_axes,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "hbm_per_device_total": self.total_hbm_per_device,
            "operators": [a.to_json() for a in self.ops],
            "findings": [f.to_json() for f in self.findings],
        }


# ---------------------------------------------------------------------------
# jaxpr walkers
# ---------------------------------------------------------------------------


def _as_jaxprs(val) -> typing.Iterator:
    """Yield every (open) Jaxpr inside one eqn-param value."""
    if hasattr(val, "jaxpr") and hasattr(val, "consts"):  # ClosedJaxpr
        yield val.jaxpr
    elif hasattr(val, "eqns") and hasattr(val, "invars"):  # Jaxpr
        yield val
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from _as_jaxprs(v)


def _iter_levels(jaxpr) -> typing.Iterator:
    """``jaxpr`` plus every nested jaxpr (pjit/scan/cond/custom calls),
    each yielded as its own level — var namespaces do not mix across
    levels, so liveness scans one level at a time."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for val in eqn.params.values():
            for sub in _as_jaxprs(val):
                yield from _iter_levels(sub)


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(math.prod(shape)) * int(dtype.itemsize)
    except TypeError:  # symbolic dims — not a concrete byte count
        return 0


def count_collectives(closed) -> typing.Dict[str, int]:
    """primitive name -> occurrences across every level of ``closed``.
    jax revs collective primitives by suffixing a digit (``psum`` became
    ``psum2``); the census strips the suffix so the names stay stable."""
    counts: typing.Dict[str, int] = {}
    for level in _iter_levels(closed.jaxpr):
        for eqn in level.eqns:
            name = eqn.primitive.name.rstrip("0123456789")
            if name in COLLECTIVE_PRIMS:
                counts[name] = counts.get(name, 0) + 1
    return counts


def _level_peak_bytes(jaxpr) -> int:
    """Peak simultaneously-live intermediate bytes at one jaxpr level,
    by linear scan: a var goes live at its defining eqn and dies after
    its last use (jaxpr outvars live to the end).  Inputs/consts are
    excluded — params and batch buffers are budgeted separately."""
    last: typing.Dict[typing.Any, int] = {}
    n = len(jaxpr.eqns)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if hasattr(v, "count"):  # Var (Literals carry no liveness)
                last[v] = i
    for v in jaxpr.outvars:
        if hasattr(v, "count"):
            last[v] = n
    live = peak = 0
    alive: typing.Dict[typing.Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            if type(v).__name__ == "DropVar":
                continue
            b = _aval_bytes(v)
            alive[v] = b
            live += b
        if live > peak:
            peak = live
        for v in [v for v, _ in alive.items() if last.get(v, -1) <= i]:
            live -= alive.pop(v)
    return peak


def peak_activation_bytes(closed) -> int:
    """Max per-level liveness peak across the whole closed jaxpr — a
    static stand-in for XLA's temp-buffer high-water mark (XLA fuses and
    rematerializes, so this is an upper-ish bound, not an exact figure;
    the predicted-vs-measured bench leg keeps it honest)."""
    return max((_level_peak_bytes(level)
                for level in _iter_levels(closed.jaxpr)), default=0)


# ---------------------------------------------------------------------------
# per-device placement math
# ---------------------------------------------------------------------------


def _param_paths(params) -> typing.List[typing.Tuple[str, typing.Any]]:
    """(slash path, leaf) pairs for a params pytree."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        parts = []
        for entry in path:
            key = getattr(entry, "key", None)
            if key is None:
                key = getattr(entry, "idx", None)
            if key is None:
                key = getattr(entry, "name", None)
            parts.append(str(key) if key is not None else "?")
        out.append(("/".join(parts) or "param", leaf))
    return out


def _leaf_shape_dtype(leaf):
    import numpy as np

    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None:
        arr = np.asarray(leaf)
        shape, dtype = arr.shape, arr.dtype
    return tuple(shape), np.dtype(dtype)


def _params_per_device(
    params, layout: SpecLayout,
    mesh_axes: typing.Optional[typing.Dict[str, int]],
    node: str, what: str,
    findings: typing.List[Finding],
) -> int:
    """Per-device bytes of a params pytree under ``layout``, emitting
    ``shardcheck-partition`` findings for indivisible sharded dims —
    each names the offending buffer and axis."""
    total = 0
    for path, leaf in _param_paths(params):
        shape, dtype = _leaf_shape_dtype(leaf)
        nbytes = int(math.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        divide = 1
        if mesh_axes:
            for dim, axis in enumerate(layout.param_spec(path, shape)):
                size = mesh_axes.get(axis, 1) if axis else 1
                if size <= 1:
                    continue
                if shape[dim] % size:
                    findings.append(Finding(
                        rule="shardcheck-partition", severity=Severity.ERROR,
                        message=(
                            f"{what} buffer {path!r} dim {dim} "
                            f"({shape[dim]}) does not divide mesh axis "
                            f"{axis!r} ({size}) — the pjit sharding is "
                            "ragged and the first call fails after the job "
                            "started; pad the dim or resize the axis"),
                        node=node))
                else:
                    divide *= size
        total += nbytes // divide
    return total


def _batch_axes_product(
    batch_axes: typing.Sequence[str],
    mesh_axes: typing.Optional[typing.Dict[str, int]],
) -> int:
    if not mesh_axes:
        return 1
    return math.prod(mesh_axes.get(a, 1) for a in batch_axes) or 1


def _check_batch_partition(
    batch: typing.Optional[int], batch_axes: typing.Sequence[str],
    mesh_axes: typing.Optional[typing.Dict[str, int]],
    node: str, findings: typing.List[Finding],
) -> int:
    """Divisibility of the batch dim over its sharding axes; returns the
    per-device divisor (1 when unsharded or indivisible)."""
    prod = _batch_axes_product(batch_axes, mesh_axes)
    if prod <= 1 or batch is None:
        return max(prod, 1)
    if batch % prod:
        findings.append(Finding(
            rule="shardcheck-partition", severity=Severity.ERROR,
            message=(
                f"batch {batch} does not divide the sharded batch axes' "
                f"device product ({'x'.join(batch_axes)} = {prod}) — "
                "per-device shards would be ragged; pick a multiple"),
            node=node))
        return 1
    return prod


# ---------------------------------------------------------------------------
# jit-unit audits
# ---------------------------------------------------------------------------


def _struct_of(pytree):
    """ShapeDtypeStruct mirror of a pytree (device-free trace input)."""
    import jax

    def conv(leaf):
        shape, dtype = _leaf_shape_dtype(leaf)
        return jax.ShapeDtypeStruct(shape, dtype)

    return jax.tree.map(conv, pytree)


def _donation_findings(
    *, donate: bool, inputs: typing.Dict[str, typing.Any],
    outputs: typing.Dict[str, typing.Any],
    node: str, where: str,
) -> typing.List[Finding]:
    """Donation verdicts for one jit unit's batch-input leaves.

    ``inputs``/``outputs`` are name -> ShapeDtypeStruct.  A donated
    input needs a shape+dtype-matching output for XLA to alias its HBM
    pages into; without donation, any such large pair holds both
    buffers live across the call — the 2x-HBM trap."""
    import numpy as np

    findings: typing.List[Finding] = []
    out_list = [(n, tuple(s.shape), np.dtype(s.dtype))
                for n, s in outputs.items()]
    for name, s in inputs.items():
        shape, dtype = tuple(s.shape), np.dtype(s.dtype)
        nbytes = int(math.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        if nbytes < DONATION_MIN_BYTES:
            continue
        exact = [o for o, osh, odt in out_list if osh == shape and odt == dtype]
        shape_only = [(o, odt) for o, osh, odt in out_list
                      if osh == shape and odt != dtype]
        mib = nbytes / 2**20
        if not donate and exact:
            findings.append(Finding(
                rule="shardcheck-donation", severity=Severity.WARN,
                message=(
                    f"{where}: arg {name!r} ({mib:.1f} MiB) has a shape/"
                    f"dtype-matching output ({exact[0]!r}) but is NOT "
                    "donated — both buffers stay live across the jitted "
                    "call (2x HBM); pass donate_inputs=True so XLA "
                    "aliases the pages"),
                node=node))
        elif donate and not exact and shape_only:
            o, odt = shape_only[0]
            findings.append(Finding(
                rule="shardcheck-donation", severity=Severity.WARN,
                message=(
                    f"{where}: donated arg {name!r} ({mib:.1f} MiB, "
                    f"{dtype}) is DEFEATED by a dtype mismatch — the "
                    f"shape-matching output {o!r} is {odt}, so XLA cannot "
                    "alias the buffer and silently keeps both; align the "
                    "dtypes to make the donation real"),
                node=node))
        elif donate and not exact:
            findings.append(Finding(
                rule="shardcheck-donation", severity=Severity.WARN,
                message=(
                    f"{where}: donated arg {name!r} ({mib:.1f} MiB) has "
                    "no shape-matching output to alias — the donation is "
                    "dead (XLA frees the buffer but reuses nothing); drop "
                    "donate_inputs or return an updated buffer"),
                node=node))
    return findings


def _signature_count(
    function, in_schema, node: str, findings: typing.List[Finding],
) -> typing.Optional[int]:
    """Bounded compile-signature count for a bucket-policied jit
    boundary, or None (unbounded) with a WARN."""
    policy = None
    hook = getattr(function, "plan_policy", None)
    if hook is not None:
        policy = hook()
    else:
        policy = getattr(function, "_policy", None)
    if policy is None:
        findings.append(Finding(
            rule="shardcheck-signatures", severity=Severity.WARN,
            message=("jit boundary has no bucket policy — every distinct "
                     "batch size compiles a fresh executable (unbounded "
                     "signature set); set a BucketPolicy"),
            node=node))
        return None
    if policy.fixed_batch is not None:
        batches = 1
    else:
        batches = len(getattr(policy.batch, "sizes", ()) or ()) or None
    if batches is None:
        findings.append(Finding(
            rule="shardcheck-signatures", severity=Severity.WARN,
            message=("batch bucket ladder is empty — the signature set is "
                     "unbounded; give the BucketPolicy a batch ladder"),
            node=node))
        return None
    dynamic = in_schema is not None and not in_schema.is_static
    if not dynamic:
        return batches
    lengths = len(getattr(policy.lengths, "sizes", ()) or ())
    if not lengths:
        findings.append(Finding(
            rule="shardcheck-signatures", severity=Severity.WARN,
            message=("dynamic input dims with no length ladder — every "
                     "observed length compiles a fresh executable "
                     "(unbounded signature set); set BucketPolicy.lengths"),
            node=node))
        return None
    return batches * lengths


def _audit_model_function(
    t, function, in_schema,
    layout: SpecLayout,
    mesh_axes: typing.Optional[typing.Dict[str, int]],
    findings: typing.List[Finding],
) -> OpAudit:
    from flink_tensorflow_tpu.analysis.chaining import sharding_axes_of
    from flink_tensorflow_tpu.models.base import Model

    audit = OpAudit(node=t.name, kind="model")
    source = getattr(function, "_source", None)
    schema = function.plan_input_schema() or in_schema
    audit.signatures = _signature_count(function, schema, t.name, findings)
    if not isinstance(source, Model):
        audit.notes.append("lazy model source — jit unit not traceable at "
                           "plan time (pass a resolved Model to analyze)")
        return audit
    try:
        method = source.method(function._method_name)
    except KeyError as ex:
        audit.notes.append(f"model method unresolvable: {ex}")
        return audit
    if schema is None:
        audit.notes.append("input schema unknown — jit unit skipped")
        return audit
    policy = function.plan_policy()
    sizes = getattr(policy.batch, "sizes", ()) or ()
    batch = policy.fixed_batch or (sizes[-1] if sizes else 1)
    axes = sharding_axes_of(function) or ()
    div = _check_batch_partition(batch, axes, mesh_axes, t.name, findings)
    audit.hbm["params"] = _params_per_device(
        source.params, layout, mesh_axes, t.name, "param", findings)
    if method.needs_lengths:
        audit.notes.append("method takes per-record lengths — abstract "
                           "trace skipped (no schema slot to trace from)")
        return audit
    try:
        import jax

        struct = schema.batched_struct(
            batch, length_bucket=function._warmup_length_bucket)
        params_struct = _struct_of(source.params)
        closed = jax.make_jaxpr(
            lambda p, x: method.fn(p, x))(params_struct, struct)
        outputs = jax.eval_shape(
            lambda p, x: method.fn(p, x), params_struct, struct)
        audit.collectives = count_collectives(closed)
        batch_bytes = sum(
            int(math.prod(s.shape)) * s.dtype.itemsize for s in struct.values())
        audit.hbm["activations"] = (
            peak_activation_bytes(closed) + batch_bytes) // div
        findings.extend(_donation_findings(
            donate=bool(getattr(function, "_donate", False)),
            inputs=struct,
            outputs={k: v for k, v in outputs.items()
                     if hasattr(v, "shape")},
            node=t.name, where=f"method {function._method_name!r}"))
    except Exception as ex:  # noqa: BLE001 - fail-soft by contract
        audit.notes.append(f"abstract trace failed: {ex!r}")
    return audit


def _audit_serving_operator(
    t, op,
    layout: SpecLayout,
    mesh_axes: typing.Optional[typing.Dict[str, int]],
    findings: typing.List[Finding],
) -> OpAudit:
    import numpy as np

    audit = OpAudit(node=t.name, kind="serving")
    cfg = op.serving_config
    sigs = cfg.compile_signatures()
    if sigs is None:
        findings.append(Finding(
            rule="shardcheck-signatures", severity=Severity.WARN,
            message=(
                "padding_buckets=False makes the serving signature set "
                "unbounded — every distinct active-set size compiles a "
                "fresh decode executable and every distinct prompt length "
                "a fresh prefill; enable padding_buckets"),
            node=t.name))
    else:
        audit.signatures = len(sigs)
    model = op.model
    audit.hbm["params"] = _params_per_device(
        model.params, layout, mesh_axes, t.name, "param", findings)
    try:
        import jax

        from flink_tensorflow_tpu.functions.runner import _build_decode_calls

        prefill = model.method("prefill")
        decode = model.method("decode_step")
        S, C = cfg.max_active_seqs, cfg.capacity
        B = cfg.bucket_admit(S)
        T = min(cfg.bucket_prompt_len(C), C)
        params_struct = _struct_of(model.params)
        tok = jax.ShapeDtypeStruct((B, T), np.int32)
        lens = jax.ShapeDtypeStruct((B,), np.int32)
        pf_out = jax.eval_shape(
            lambda p, tk, ln: prefill.fn(p, {"tokens": tk, "lengths": ln}),
            params_struct, tok, lens)
        k_like = pf_out["k_cache"]  # [B, L, T, H, Dh]
        _, layers, _, heads, hd = k_like.shape
        pool_dtype = np.dtype(k_like.dtype)
        paged = bool(getattr(cfg, "paged_kv", False))
        if paged:
            from flink_tensorflow_tpu.ops.paged_attention import (
                pages_per_session,
            )

            # The paged HBM budget is the PAGE pool, not seats x
            # capacity — oversubscription is the whole economy; the
            # overflow lives in the host/disk tiers, not in HBM.
            Pc = pages_per_session(C, cfg.page_tokens)
            P = cfg.resolved_hbm_pages()
            pool_shape = (P, layers, cfg.page_tokens, heads, hd)
        else:
            pool_shape = (S, layers, C, heads, hd)
        pool_bytes = 2 * int(math.prod(pool_shape)) * pool_dtype.itemsize
        pool_div = 1
        if mesh_axes and layout.tp_axis:
            tp = mesh_axes.get(layout.tp_axis, 1)
            if tp > 1:
                if heads % tp:
                    findings.append(Finding(
                        rule="shardcheck-partition", severity=Severity.ERROR,
                        message=(
                            f"KV pool buffer 'k_cache' heads dim ({heads}) "
                            f"does not divide mesh axis "
                            f"{layout.tp_axis!r} ({tp}) — the pool "
                            "sharding is ragged; pad heads or resize the "
                            "axis"),
                        node=t.name))
                else:
                    pool_div = tp
        audit.hbm["kv_pool"] = pool_bytes // pool_div
        # The runtime jit units, verbatim (module-level lru_cache: the
        # live runner will reuse these callables and executables).
        kc = jax.ShapeDtypeStruct(pool_shape, pool_dtype)
        s_tok = jax.ShapeDtypeStruct((S,), np.int32)
        s_len = jax.ShapeDtypeStruct((S,), np.int32)
        if paged:
            from flink_tensorflow_tpu.functions.runner import (
                _build_paged_calls,
            )

            prefill_into, step_full, _ = _build_paged_calls(
                prefill.fn, decode.fn, C, cfg.page_tokens, P)
            pf_tables = jax.ShapeDtypeStruct((B, Pc), np.int32)
            st_tables = jax.ShapeDtypeStruct((S, Pc), np.int32)
            pf_closed = jax.make_jaxpr(prefill_into)(
                params_struct, tok, lens, pf_tables, kc, kc)
            st_closed = jax.make_jaxpr(step_full)(
                params_struct, s_tok, s_len, st_tables, kc, kc)
            st_args = (params_struct, s_tok, s_len, st_tables, kc, kc)
        else:
            prefill_into, step_full, _ = _build_decode_calls(
                prefill.fn, decode.fn, C)
            slots = jax.ShapeDtypeStruct((B,), np.int32)
            mask = jax.ShapeDtypeStruct((S,), np.bool_)
            pf_closed = jax.make_jaxpr(prefill_into)(
                params_struct, tok, lens, slots, kc, kc)
            st_closed = jax.make_jaxpr(step_full)(
                params_struct, s_tok, s_len, mask, kc, kc)
            st_args = (params_struct, s_tok, s_len, mask, kc, kc)
        for closed in (pf_closed, st_closed):
            for name, n in count_collectives(closed).items():
                audit.collectives[name] = audit.collectives.get(name, 0) + n
        audit.hbm["activations"] = max(
            peak_activation_bytes(pf_closed), peak_activation_bytes(st_closed))
        # Donation by construction: the runner jits with
        # donate_argnums=(4, 5) (kc, vc) and step_full's jnp.where keeps
        # the pool shape — so the only way to lose the aliasing is a
        # dtype drift between the model's decode cache and the pool.
        step_out = jax.eval_shape(step_full, *st_args)
        out_k = step_out[1]
        if np.dtype(out_k.dtype) != pool_dtype or tuple(out_k.shape) != pool_shape:
            findings.append(Finding(
                rule="shardcheck-donation", severity=Severity.WARN,
                message=(
                    f"decode step: donated KV pool 'k_cache' "
                    f"({pool_dtype}, {pool_shape}) is DEFEATED — the step "
                    f"returns {np.dtype(out_k.dtype)} {tuple(out_k.shape)}, "
                    "so XLA cannot alias the pool pages and keeps both "
                    "copies (2x HBM); align the model's cache dtype"),
                node=t.name))
        # Predicted steady-state per-step h2d bytes — must mirror
        # DecodeStepRunner.decode_step's accounting exactly (the
        # predicted-vs-measured bench leg diffs this against the
        # runtime step_h2d_bytes counter): padding_buckets on ships
        # [S] int32 tokens + [S] int32 lengths + [S] bool mask; the
        # paged runner ships the [S, C/page_tokens] int32 block tables
        # instead of the mask (liveness rides the sentinel page id).
        if paged:
            audit.predicted_step_h2d_bytes = S * 4 + S * 4 + S * Pc * 4
        elif cfg.padding_buckets:
            audit.predicted_step_h2d_bytes = S * 4 + S * 4 + S * 1
        else:
            audit.predicted_step_h2d_bytes = None  # exact mode: varies
    except Exception as ex:  # noqa: BLE001 - fail-soft by contract
        audit.notes.append(f"abstract trace failed: {ex!r}")
    return audit


def _audit_train_function(
    t, function,
    layout: SpecLayout,
    mesh_axes: typing.Optional[typing.Dict[str, int]],
    findings: typing.List[Finding],
) -> OpAudit:
    import numpy as np

    from flink_tensorflow_tpu.analysis.chaining import sharding_axes_of

    audit = OpAudit(node=t.name, kind="train")
    batch = (getattr(function, "global_batch", None)
             or getattr(function, "mini_batch", None) or 1)
    schema = function.train_schema
    audit.signatures = _signature_count(function, schema, t.name, findings)
    axes = sharding_axes_of(function) or ()
    div = _check_batch_partition(batch, axes, mesh_axes, t.name, findings)
    try:
        import jax

        import optax
        from flink_tensorflow_tpu.parallel.dp import (
            init_train_state,
            make_train_step,
        )

        optimizer = function.optimizer or optax.sgd(0.01)
        state = jax.eval_shape(
            lambda: init_train_state(function.model_def, optimizer,
                                     jax.random.PRNGKey(0)))
        audit.hbm["params"] = _params_per_device(
            state["variables"], layout, mesh_axes, t.name, "param", findings)
        audit.hbm["optimizer"] = _params_per_device(
            state["opt_state"], layout, mesh_axes, t.name, "optimizer-state",
            findings)
        # The train batch contract of _train_batch_arrays: schema fields
        # at [B, ...] (+ <field>_len int32 for dynamic fields) + a [B]
        # f32 valid mask.
        shapes = schema.resolve_dynamic(
            getattr(function, "_warmup_length_bucket", 128))
        struct = {
            name: jax.ShapeDtypeStruct((batch, *shapes[name]),
                                       schema[name].dtype)
            for name in schema.names
        }
        for name in schema.names:
            if not schema[name].is_static:
                struct[f"{name}_len"] = jax.ShapeDtypeStruct(
                    (batch,), np.int32)
        struct["valid"] = jax.ShapeDtypeStruct((batch,), np.float32)
        step = make_train_step(function.model_def, optimizer)
        closed = jax.make_jaxpr(step)(state, struct)
        audit.collectives = count_collectives(closed)
        batch_bytes = sum(
            int(math.prod(s.shape)) * s.dtype.itemsize for s in struct.values())
        audit.hbm["activations"] = (
            peak_activation_bytes(closed) + batch_bytes) // div
        if getattr(function, "is_gang", False) and mesh_axes and len(
                [a for a, s in mesh_axes.items() if s > 1]) > 0:
            audit.notes.append(
                "gang step traced single-device (make_train_step); the DP "
                "psum over the grads is inserted by pjit at run time and "
                "is not in this count")
    except Exception as ex:  # noqa: BLE001 - fail-soft by contract
        audit.notes.append(f"abstract trace failed: {ex!r}")
    return audit


# ---------------------------------------------------------------------------
# the plan walk
# ---------------------------------------------------------------------------


def _layout_of(op, function) -> SpecLayout:
    for holder in (function, op):
        layout = getattr(holder, "spec_layout", None)
        if layout is not None:
            return layout
    return SpecLayout()


def _reshard_findings(
    ctx: "AnalysisContext", findings: typing.List[Finding],
) -> None:
    """Edge-level implicit-reshard audit: upstream declared OUTPUT layout
    vs downstream declared input sharding, escalated to ERROR on
    HBM-resident chained edges (where the reshard defeats the h2d
    elision the chain exists for)."""
    from flink_tensorflow_tpu.analysis.chaining import (
        compute_chains,
        sharding_axes_of,
    )

    plan = compute_chains(ctx.graph, operators=ctx.operators)
    resident_on = ctx.config is None or getattr(
        ctx.config, "device_resident", False)
    for t in ctx.order:
        down_fn = ctx.function_of(t)
        down_in = sharding_axes_of(down_fn)
        if down_in is None:
            continue
        for e in t.inputs:
            up_fn = ctx.function_of(e.upstream)
            if up_fn is None:
                continue
            up_out = getattr(up_fn, "output_sharding_axes", None)
            if up_out is None:
                up_out = sharding_axes_of(up_fn)
            if up_out is None or tuple(up_out) == tuple(down_in):
                continue
            resident = (resident_on
                        and (e.upstream.id, t.id) in plan.device_resident_edges)
            findings.append(Finding(
                rule="shardcheck-reshard",
                severity=Severity.ERROR if resident else Severity.WARN,
                message=(
                    f"upstream emits batches laid out over axes "
                    f"{tuple(up_out)} but this operator's pjit expects "
                    f"{tuple(down_in)} — XLA inserts an implicit reshard "
                    "(all-to-all traffic) on EVERY batch crossing this edge"
                    + ("; the edge is an HBM-resident chained hop, so the "
                       "reshard defeats the h2d elision the chain exists "
                       "for — align the layouts or cut the chain"
                       if resident else
                       "; align the upstream output_sharding_axes with the "
                       "consumer (or reshard once, upstream)")),
                node=t.name, edge=edge_name(e.upstream.name, t.name)))


def audit_plan(ctx: "AnalysisContext") -> PlanAudit:
    """Run the full shardcheck pass over an analysis context."""
    config = ctx.config
    mesh = getattr(config, "mesh", None) if config is not None else None
    mesh_axes = dict(mesh.shape) if mesh is not None else None
    budget = (getattr(config, "hbm_budget_bytes", None)
              if config is not None else None)
    findings: typing.List[Finding] = []
    ops: typing.List[OpAudit] = []
    for t in ctx.order:
        op = ctx.operators.get(t.id)
        if op is None:
            continue
        function = getattr(op, "function", None)
        layout = _layout_of(op, function)
        if getattr(op, "is_continuous_batching", False):
            ops.append(_audit_serving_operator(
                t, op, layout, mesh_axes, findings))
        elif hasattr(function, "model_def") and hasattr(function, "train_schema"):
            ops.append(_audit_train_function(
                t, function, layout, mesh_axes, findings))
        elif getattr(function, "is_jit_boundary", False) and hasattr(
                function, "plan_input_schema"):
            ops.append(_audit_model_function(
                t, function, ctx.input_schema(t), layout, mesh_axes, findings))
    _reshard_findings(ctx, findings)
    # Collective census: one INFO per jit unit that emits any.
    for a in ops:
        if a.collectives:
            census = ", ".join(f"{n}x{c}" for c, n in sorted(
                ((v, k) for k, v in a.collectives.items()), reverse=True))
            findings.append(Finding(
                rule="shardcheck-collectives", severity=Severity.INFO,
                message=f"per-step collectives in the jitted unit: {census}",
                node=a.node))
    # HBM budget: ERROR per over-budget operator, INFO summaries when a
    # mesh or budget was declared (silent otherwise — no declared target
    # means nothing to gate and the numbers would be noise).
    if budget is not None or mesh_axes is not None:
        for a in ops:
            if not a.hbm:
                continue
            breakdown = ", ".join(
                f"{k}={v / 2**20:.1f}MiB" for k, v in sorted(a.hbm.items()))
            total = a.hbm_total
            if budget is not None and total > budget:
                findings.append(Finding(
                    rule="shardcheck-hbm-budget", severity=Severity.ERROR,
                    message=(
                        f"static per-device HBM {total / 2**20:.1f} MiB "
                        f"exceeds hbm_budget_bytes "
                        f"({budget / 2**20:.1f} MiB): {breakdown} — shard "
                        "further (fsdp/tp), shrink the KV pool "
                        "(max_active_seqs/capacity), or raise the budget"),
                    node=a.node))
            else:
                findings.append(Finding(
                    rule="shardcheck-hbm-budget", severity=Severity.INFO,
                    message=(f"static per-device HBM "
                             f"{total / 2**20:.1f} MiB: {breakdown}"),
                    node=a.node))
        if budget is not None and len(ops) > 1:
            plan_total = sum(a.hbm_total for a in ops)
            findings.append(Finding(
                rule="shardcheck-hbm-budget",
                severity=(Severity.ERROR if plan_total > budget
                          else Severity.INFO),
                message=(
                    f"plan-total static per-device HBM "
                    f"{plan_total / 2**20:.1f} MiB vs budget "
                    f"{budget / 2**20:.1f} MiB (all jit units co-resident "
                    "on one device in the single-device placement)")))
    # Bounded-signature census (the unbounded WARNs were emitted inline).
    for a in ops:
        if a.signatures is not None:
            findings.append(Finding(
                rule="shardcheck-signatures", severity=Severity.INFO,
                message=(f"compile-signature set is bounded: "
                         f"{a.signatures} signature(s)"),
                node=a.node))
    return PlanAudit(findings=findings, ops=ops, mesh_axes=mesh_axes,
                     hbm_budget_bytes=budget)


def audit_of(ctx: "AnalysisContext") -> PlanAudit:
    """The per-context cached audit — six registered rules (and the
    CLI/report path) share ONE abstract-evaluation pass."""
    cached = ctx.__dict__.get("_shardcheck_audit")
    if cached is None:
        cached = audit_plan(ctx)
        ctx.__dict__["_shardcheck_audit"] = cached
    return cached


# ---------------------------------------------------------------------------
# lint registry wiring — each verdict family is its own rule id, reading
# the shared cached audit.  Registration happens via the bottom import
# in analysis/rules.py, so analyze()/validate_plan()/every CLI carries
# these without extra wiring.
# ---------------------------------------------------------------------------


def _emit_family(ctx, emit, rule_id: str) -> None:
    for f in audit_of(ctx).findings:
        if f.rule == rule_id:
            emit(f.message, node=f.node, edge=f.edge, severity=f.severity)


def _register_rules() -> None:
    from flink_tensorflow_tpu.analysis.rules import rule

    @rule("shardcheck-collectives", Severity.INFO)
    def _shardcheck_collectives(ctx, emit) -> None:
        """Collective census per jit unit: psum/all-gather/reduce-scatter/
        ppermute counts straight from the closed jaxpr — the per-step
        ICI/DCN bill the sharded arc pays, visible before any run."""
        _emit_family(ctx, emit, "shardcheck-collectives")

    @rule("shardcheck-reshard", Severity.WARN)
    def _shardcheck_reshard(ctx, emit) -> None:
        """Implicit-reshard audit: an edge whose upstream output layout
        mismatches the downstream pjit's declared input sharding makes
        XLA reshard EVERY batch; ERROR when the edge is an HBM-resident
        chained hop (the reshard defeats the h2d elision)."""
        _emit_family(ctx, emit, "shardcheck-reshard")

    @rule("shardcheck-donation", Severity.WARN)
    def _shardcheck_donation(ctx, emit) -> None:
        """Donation checker: large args not donated through a jit
        boundary (KV-pool/param-buffer 2x-HBM trap), dead donations, and
        donations defeated by dtype/shape mismatch — each finding names
        the offending buffer."""
        _emit_family(ctx, emit, "shardcheck-donation")

    @rule("shardcheck-partition", Severity.ERROR)
    def _shardcheck_partition(ctx, emit) -> None:
        """Indivisible sharded dims under the declared mesh: a batch that
        does not divide its data x fsdp product, a param/KV dim that does
        not divide its fsdp/tp axis — ragged shards fail (or hang) the
        first pjit call after the job already started."""
        _emit_family(ctx, emit, "shardcheck-partition")

    @rule("shardcheck-hbm-budget", Severity.ERROR)
    def _shardcheck_hbm_budget(ctx, emit) -> None:
        """Static per-device HBM budget: params + optimizer state + KV
        pool + peak activation liveness (jaxpr linear scan) per device
        under the mesh, gated against JobConfig.hbm_budget_bytes."""
        _emit_family(ctx, emit, "shardcheck-hbm-budget")

    @rule("shardcheck-signatures", Severity.WARN)
    def _shardcheck_signatures(ctx, emit) -> None:
        """Compile-signature enumeration: the static twin of the runtime
        recompile-churn lints — bounded counts (INFO) from
        ServingConfig/BucketPolicy ladders, WARN on unbounded sets."""
        _emit_family(ctx, emit, "shardcheck-signatures")


# ---------------------------------------------------------------------------
# report + CLI
# ---------------------------------------------------------------------------


def report_for_env(env, pipeline: typing.Optional[str] = None) -> dict:
    """The JSON shardcheck report for one captured plan — the format
    ``flink-tpu-doctor --shardcheck`` folds into its diagnosis."""
    from flink_tensorflow_tpu.analysis.analyzer import analyze  # noqa: F401 - registers rules
    from flink_tensorflow_tpu.analysis.rules import AnalysisContext
    from flink_tensorflow_tpu.analysis.schema_prop import propagate

    graph = env.graph
    order = graph.topological_order()
    operators = {}
    for t in graph.transformations:
        try:
            operators[t.id] = t.operator_factory()
        except Exception:  # noqa: BLE001 - factory-error is the analyzer's finding
            operators[t.id] = None
    flow = propagate(graph, order, operators)
    ctx = AnalysisContext(graph=graph, order=order, operators=operators,
                          schemas=flow.out, schema_sets=flow.out_sets,
                          config=env.config)
    audit = audit_of(ctx)
    report = audit.to_json()
    report["pipeline"] = pipeline
    report["errors"] = sum(
        1 for f in audit.findings if f.severity == Severity.ERROR)
    return report


def _parse_mesh(spec: str) -> typing.Dict[str, int]:
    axes: typing.Dict[str, int] = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


def main(argv=None) -> int:
    """``flink-tpu-shardcheck`` — the console script."""
    import argparse
    import dataclasses as dc
    import json
    import sys

    parser = argparse.ArgumentParser(
        prog="flink-tpu-shardcheck",
        description="SPMD layout, donation & HBM-budget static analyzer: "
                    "abstract-evaluates every jit unit of a captured plan "
                    "against a declared (abstract) mesh — no devices, no "
                    "execution.",
    )
    parser.add_argument("pipelines", nargs="+", metavar="pipeline.py",
                        help="pipeline script(s) defining main(argv)")
    parser.add_argument("--job-args", default="--smoke --cpu",
                        help="argv passed to each pipeline's main() while "
                             "building its graph (default: '--smoke --cpu')")
    parser.add_argument("--mesh", metavar="data=4,model=2",
                        help="override the job's mesh with an ABSTRACT mesh "
                             "of these axes (v5e-8 fsdp x tp: "
                             "'data=1,fsdp=4,tp=2')")
    parser.add_argument("--hbm-budget-bytes", type=int, default=None,
                        help="override JobConfig.hbm_budget_bytes "
                             "(v5e: 16 GiB/chip)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON report per pipeline")
    parser.add_argument("--out", metavar="REPORT.json",
                        help="also write the (last) JSON report here — the "
                             "file flink-tpu-doctor --shardcheck reads")
    parser.add_argument("--cost-table", metavar="TABLE.json",
                        help="also price the (last) captured plan "
                             "(analysis/costmodel: per jit unit, per "
                             "compile signature — FLOPs, HBM bytes, "
                             "collective bytes, expected h2d/d2h) and "
                             "write the CostTable here — the file "
                             "flink-tpu-roofline --cost-table reads")
    args = parser.parse_args(argv)

    from flink_tensorflow_tpu.analysis.capture import capture_pipeline_file

    job_args = args.job_args.split()
    exit_code = 0
    report = None
    last_env = None
    for path in args.pipelines:
        try:
            env = capture_pipeline_file(path, job_args)
        except Exception as ex:  # noqa: BLE001 - report and keep going
            print(f"{path}: capture failed: {ex}", file=sys.stderr)
            exit_code = max(exit_code, 2)
            continue
        config = env.config
        if args.mesh:
            from flink_tensorflow_tpu.parallel.mesh import abstract_mesh

            config = dc.replace(config, mesh=abstract_mesh(_parse_mesh(args.mesh)))
        if args.hbm_budget_bytes is not None:
            config = dc.replace(config, hbm_budget_bytes=args.hbm_budget_bytes)
        env.config = config
        last_env = env
        report = report_for_env(env, pipeline=path)
        if args.json:
            print(json.dumps(report))
        else:
            mesh = report["mesh_axes"]
            print(f"== {path} (mesh: {mesh or 'none declared'}, "
                  f"budget: {report['hbm_budget_bytes'] or 'none'}) ==")
            for a in report["operators"]:
                line = f"  [{a['kind']}] {a['node']}"
                if a["hbm_per_device_total"]:
                    line += (f"  hbm/device="
                             f"{a['hbm_per_device_total'] / 2**20:.1f}MiB")
                if a["signatures"] is not None:
                    line += f"  signatures={a['signatures']}"
                if a["collectives"]:
                    line += f"  collectives={a['collectives']}"
                print(line)
                for note in a["notes"]:
                    print(f"      note: {note}")
            for f in report["findings"]:
                where = f" [{f['edge'] or f['node'] or 'plan'}]"
                print(f"  {f['severity']:5s} {f['rule']}{where}: "
                      f"{f['message']}")
        if report["errors"]:
            exit_code = max(exit_code, 1)
    if args.out and report is not None:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2)
    if args.cost_table and last_env is not None:
        from flink_tensorflow_tpu.analysis.costmodel import cost_table_for_env

        table = cost_table_for_env(last_env)
        with open(args.cost_table, "w") as fh:
            json.dump(table.to_json(), fh, indent=2)
        print(f"cost table -> {args.cost_table}")
    return exit_code


if __name__ == "__main__":
    import sys

    sys.exit(main())
